#!/usr/bin/env python
"""Run BOTH test lanes (default + slow) and record the counts.

VERDICT r3 weak #7 / next #9: the default lane deselects the deepest kernel
parity tests (`pytest.ini` addopts `-m "not slow"`); this runner makes the
full sweep one command and leaves a machine-readable artifact
(TESTS_LANES.json) that bench.py folds into the bench output so every round's
artifact shows both lanes' counts.

Exit code is non-zero if EITHER lane fails.
"""

import json
import re
import subprocess
import sys
import time


def run_lane(name: str, marker_args):
    t0 = time.time()
    proc = subprocess.run([sys.executable, "-m", "pytest", "tests/", "-q", *marker_args],
                          capture_output=True, text=True)
    dt = time.time() - t0
    tail = (proc.stdout.strip().splitlines() or [""])[-1]
    counts = {k: int(v) for v, k in re.findall(r"(\d+) (passed|failed|error|skipped|deselected)", tail)}
    print(f"[{name}] {tail}  ({dt:.0f}s)")
    if proc.returncode != 0:
        print(proc.stdout[-4000:])
        print(proc.stderr[-2000:], file=sys.stderr)
    return {"name": name, "rc": proc.returncode, "seconds": round(dt, 1),
            "summary": tail, **counts}


def main():
    lanes = [run_lane("default", []), run_lane("slow", ["-m", "slow"])]
    out = {"lanes": lanes, "ok": all(l["rc"] == 0 for l in lanes)}
    with open("TESTS_LANES.json", "w") as fh:
        json.dump(out, fh, indent=1)
    print(json.dumps({"lanes": {l["name"]: l.get("passed", 0) for l in lanes}, "ok": out["ok"]}))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
