#!/usr/bin/env python
"""Run BOTH test lanes (default + slow) and record the counts.

VERDICT r3 weak #7 / next #9: the default lane deselects the deepest kernel
parity tests (`pytest.ini` addopts `-m "not slow"`); this runner makes the
full sweep one command and leaves a machine-readable artifact
(TESTS_LANES.json) that bench.py folds into the bench output so every round's
artifact shows both lanes' counts.

Exit code is non-zero if EITHER lane fails.
"""

import json
import re
import subprocess
import sys
import time


def telemetry_smoke():
    """CI smoke for the unified telemetry subsystem (ISSUE 1 acceptance): a
    3-step CPU train loop with wall_clock_breakdown + telemetry enabled must
    produce 3 well-formed JSONL records (loss, step_time_ms, samples_per_sec,
    tokens_per_sec, mfu, hbm — hbm null-safe on CPU) and jax.profiler trace
    files under the configured dir."""
    import os
    import tempfile
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax
    import numpy as np

    import deepspeed_tpu

    rng = np.random.default_rng(0)
    hidden = 16

    def loss_fn(params, batch, _rng):
        import jax.numpy as jnp
        h = jnp.maximum(batch["x"] @ params["w0"], 0.0)
        pred = h @ params["w1"]
        return jnp.mean((pred - batch["y"]) ** 2)

    params = {"w0": rng.standard_normal((hidden, hidden)).astype("float32") * 0.1,
              "w1": rng.standard_normal((hidden, hidden)).astype("float32") * 0.1}
    tmp = tempfile.mkdtemp(prefix="dstpu_telemetry_smoke_")
    jsonl = os.path.join(tmp, "telemetry.jsonl")
    tracedir = os.path.join(tmp, "traces")
    engine, _, _, _ = deepspeed_tpu.initialize(
        loss_fn=loss_fn,
        model_parameters=params,
        config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
            "wall_clock_breakdown": True,
            "telemetry": {"jsonl_path": jsonl,
                          "profile_step_start": 1, "profile_step_stop": 2,
                          "profile_dir": tracedir,
                          # pinned so MFU is a real number on the CPU backend
                          "peak_flops_per_chip": 1e12},
        })
    for step in range(3):
        batch = {"x": rng.standard_normal((engine.train_batch_size, hidden)).astype("float32"),
                 "y": rng.standard_normal((engine.train_batch_size, hidden)).astype("float32")}
        engine.train_batch(batch)
    engine.telemetry.close()

    with open(jsonl) as fh:
        records = [json.loads(line) for line in fh]
    steps = [r for r in records if r.get("kind") == "train_step"]
    assert len(steps) >= 3, f"expected >=3 train_step records, got {len(steps)}"
    required = ("loss", "step_time_ms", "samples_per_sec", "tokens_per_sec", "mfu", "hbm")
    for r in steps:
        missing = [k for k in required if k not in r]
        assert not missing, f"record {r['step']} missing fields {missing}"
        assert r["loss"] is not None and np.isfinite(r["loss"])
        assert r["step_time_ms"] > 0 and r["samples_per_sec"] > 0 and r["tokens_per_sec"] > 0
        assert set(r["hbm"]) == {"bytes_in_use", "peak_bytes_in_use", "bytes_limit"}
    assert steps[-1]["mfu"] is not None and steps[-1]["mfu"] > 0, "mfu did not resolve"
    trace_files = [os.path.join(root, f)
                   for root, _, files in os.walk(tracedir) for f in files]
    assert trace_files, f"no jax.profiler trace files under {tracedir}"
    print(json.dumps({"telemetry_smoke": "ok", "records": len(steps),
                      "trace_files": len(trace_files), "jsonl": jsonl}))
    return 0


def resilience_smoke():
    """CI smoke for the checkpoint resilience layer (ISSUE 2 acceptance):
    kill a save mid-write, prove ``latest`` still names the previous complete
    checkpoint, resume a FRESH engine from it with fallback_to_valid, and
    verify loss continuity — three post-resume steps reproduce the original
    run's losses exactly (fp32)."""
    import os
    import tempfile
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.runtime.checkpointing import TMP_PREFIX, get_latest_tag, is_valid_tag
    from tests.unit.fault_injection import FaultyCheckpointEngine, SimulatedCrash
    from tests.unit.simple_model import init_mlp_params, mlp_loss_fn, random_batch

    hidden = 16
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
        "bf16": {"enabled": False},  # fp32: exact loss continuity
        "steps_per_print": 100,
        "checkpoint": {"save_retries": 2, "retry_backoff_secs": 0.0},
    }

    def build():
        params = init_mlp_params(jax.random.PRNGKey(0), hidden=hidden)
        engine, _, _, _ = deepspeed_tpu.initialize(loss_fn=mlp_loss_fn,
                                                   model_parameters=params, config=config)
        return engine

    def step(engine, seed):
        batch = random_batch(engine.train_batch_size, hidden=hidden, seed=seed)
        return float(engine.train_batch(batch).loss)

    ckdir = tempfile.mkdtemp(prefix="dstpu_resilience_smoke_")
    engine = build()
    for s in range(3):
        step(engine, seed=s)
    good_tag = engine.save_checkpoint(ckdir)
    ref_losses = [step(engine, seed=100 + s) for s in range(3)]

    # preemption strikes the next save mid-write
    engine._ckpt_engine = FaultyCheckpointEngine(kill_after_bytes=1500)
    crashed = False
    try:
        engine.save_checkpoint(ckdir, tag="doomed")
    except SimulatedCrash:
        crashed = True
    assert crashed, "fault injection did not fire"
    assert get_latest_tag(ckdir) == good_tag, "crashed save moved 'latest'"
    assert not os.path.isdir(os.path.join(ckdir, "doomed")), "partial tag was published"
    assert is_valid_tag(ckdir, good_tag, verify_integrity=True)

    # a fresh process resumes from the intact checkpoint and replays identically
    engine2 = build()
    loaded_tag, _ = engine2.load_checkpoint(ckdir, fallback_to_valid=True)
    assert loaded_tag == good_tag, f"resumed from {loaded_tag!r}, wanted {good_tag!r}"
    resumed_losses = [step(engine2, seed=100 + s) for s in range(3)]
    np.testing.assert_allclose(resumed_losses, ref_losses, rtol=0, atol=0)

    # the next healthy save sweeps the crashed staging dir
    engine2.save_checkpoint(ckdir)
    stale = [d for d in os.listdir(ckdir) if d.startswith(TMP_PREFIX)]
    assert not stale, f"staging dirs not swept: {stale}"

    print(json.dumps({"resilience_smoke": "ok", "good_tag": good_tag,
                      "resumed_losses": resumed_losses, "ckdir": ckdir}))
    return 0


def serving_resilience_smoke():
    """CI smoke for the serving resilience layer (ISSUE 4 acceptance): a
    fault-injected mixed-arrival continuous-batching run on CPU — probabilistic
    KV-allocator failures plus throttled admission (requests flow out of the
    bounded queue in waves as the pool frees) — must finish every request with
    an ``ok`` status, zero stalls, and the KV pool fully reclaimed."""
    import os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax
    import numpy as np

    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    from deepspeed_tpu.models import llama
    from tests.unit.fault_injection_serving import FaultyBlockedAllocator

    cfg = llama.LlamaConfig.tiny(vocab=128, hidden=64, layers=2, heads=4, kv_heads=2, seq=256)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    eng = InferenceEngineV2(llama, cfg, params,
                            config={"dtype": "float32",
                                    "serving_resilience": {"max_live_seqs": 3,
                                                           "stall_watchdog_steps": 50}},
                            num_blocks=48, block_size=8, max_blocks_per_seq=8,
                            token_budget=32, max_seqs_per_step=4)
    eng.manager.allocator = FaultyBlockedAllocator(48, fail_rate=0.25, seed=11)
    initial_free = eng.manager.allocator.free_blocks
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 128, int(n)).tolist() for n in rng.integers(3, 24, 8)]
    results = eng.generate(prompts, max_new_tokens=6, strict=False)
    statuses = [r.status for r in results]
    assert all(s == "ok" for s in statuses), f"non-ok statuses: {statuses}"
    health = eng.health()
    assert health["stalls_total"] == 0, "watchdog tripped during the run"
    assert health["live_seqs"] == 0 and health["queue_depth"] == 0
    assert eng.manager.allocator.free_blocks == initial_free, "KV blocks leaked"
    assert eng.manager.allocator.injected_failures > 0, "fault injection never fired"
    print(json.dumps({"serving_resilience_smoke": "ok", "requests": len(results),
                      "injected_failures": eng.manager.allocator.injected_failures,
                      "preempted_total": health["preempted_total"],
                      "scheduler_steps": health["scheduler_steps"]}))
    return 0


def serving_fastpath_smoke():
    """CI smoke for the serving fast path (ISSUE 5 acceptance), CPU-deterministic
    counter/invariant assertions — never wall-clock: a mixed-arrival serve must
    (a) keep host syncs bounded by serve-loop iterations + wave-boundary
    flushes (steady-state decode pays <=1 sync per iteration), (b) emit most
    tokens through fused decode bursts, (c) add ZERO compiled programs on an
    identical warm rerun (the compile-count invariant behind stable p95), and
    (d) produce byte-identical tokens to a ``serving_fastpath.enabled=False``
    reference run.  The same invariants then rerun SHARDED (ISSUE 15): a
    tp=2 engine over the 8-device host mesh must match the slow-path oracle
    AND the single-chip tokens with the identical counter bounds."""
    import os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        # 8 host devices BEFORE the first jax import: the tp=2 leg below
        # needs a real multi-device mesh (same trick as tests/conftest.py)
        os.environ["XLA_FLAGS"] = \
            (flags + " --xla_force_host_platform_device_count=8").strip()

    import jax
    import numpy as np

    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    from deepspeed_tpu.models import llama
    from deepspeed_tpu.parallel import MeshTopology

    cfg = llama.LlamaConfig.tiny(vocab=128, hidden=64, layers=2, heads=4, kv_heads=2, seq=256)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    kw = dict(num_blocks=64, block_size=8, max_blocks_per_seq=8,
              token_budget=32, max_seqs_per_step=8)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 128, int(n)).tolist() for n in rng.integers(4, 16, 6)]

    fast = InferenceEngineV2(llama, cfg, params, config={"dtype": "float32"}, **kw)
    ref = InferenceEngineV2(llama, cfg, params,
                            config={"dtype": "float32",
                                    "serving_fastpath": {"enabled": False}}, **kw)
    out_fast = fast.generate(prompts, max_new_tokens=8)
    out_ref = ref.generate(prompts, max_new_tokens=8)
    assert out_fast == out_ref, "fast path diverged from the reference loop's tokens"

    c1 = fast.counters.snapshot()
    assert c1["host_syncs"] <= c1["loop_iterations"] + c1["flushes"], c1
    assert c1["burst_tokens"] > c1["step_tokens"], c1  # decode fusion dominates
    tokens_emitted = c1["burst_tokens"] + c1["step_tokens"]
    assert c1["host_syncs"] < tokens_emitted, c1  # strictly sub-1-sync-per-token

    # an identical second serve must hit only cached programs (no mid-wave
    # recompiles: the p95 stability the bucket hysteresis + prewarm buy)
    out2 = fast.generate(prompts, max_new_tokens=8)
    assert out2 == out_fast, "warm rerun diverged"
    c2 = fast.counters.delta_since(c1)
    assert c2["compiles"] == 0, f"identical warm scenario recompiled: {c2}"

    # ---- the same invariants, SHARDED (ISSUE 15): tp=2 over the 8-device
    # host mesh.  Byte-identical to the sharded slow-path oracle AND to the
    # single-chip fast path, <=1 host sync per steady iteration, zero warm
    # recompiles — the fast path no longer falls back under TP.
    topo = MeshTopology.from_axis_dict({"tensor": 2, "data": -1})
    fast_tp = InferenceEngineV2(llama, cfg, params, topology=topo,
                                config={"dtype": "float32"}, **kw)
    ref_tp = InferenceEngineV2(llama, cfg, params, topology=topo,
                               config={"dtype": "float32",
                                       "serving_fastpath": {"enabled": False}}, **kw)
    out_tp = fast_tp.generate(prompts, max_new_tokens=8)
    assert out_tp == ref_tp.generate(prompts, max_new_tokens=8), \
        "tp=2 fast path diverged from the sharded reference loop"
    assert out_tp == out_fast, "tp=2 serving diverged from single-chip tokens"
    ct1 = fast_tp.counters.snapshot()
    assert ct1["host_syncs"] <= ct1["loop_iterations"] + ct1["flushes"], ct1
    assert ct1["burst_tokens"] > ct1["step_tokens"], ct1
    assert out_tp == fast_tp.generate(prompts, max_new_tokens=8), \
        "tp=2 warm rerun diverged"
    ct2 = fast_tp.counters.delta_since(ct1)
    assert ct2["compiles"] == 0, f"tp=2 warm scenario recompiled: {ct2}"
    hp = fast_tp.health()["fastpath"]
    assert hp["tp"] == 2 and hp["mesh_shape"]["tensor"] == 2, hp

    print(json.dumps({"serving_fastpath_smoke": "ok",
                      "host_syncs": c1["host_syncs"],
                      "loop_iterations": c1["loop_iterations"],
                      "flushes": c1["flushes"],
                      "compiled_programs": c1["compiles"],
                      "burst_tokens": c1["burst_tokens"],
                      "step_tokens": c1["step_tokens"],
                      "warm_rerun_compiles": c2["compiles"],
                      "tp2_host_syncs": ct1["host_syncs"],
                      "tp2_loop_iterations": ct1["loop_iterations"],
                      "tp2_compiled_programs": ct1["compiles"],
                      "tp2_warm_rerun_compiles": ct2["compiles"]}))
    return 0


def tracing_smoke():
    """CI smoke for request-lifecycle tracing (ISSUE 6 acceptance): a
    mixed-arrival serve with ``serving_tracing.enabled`` must (a) yield a
    complete JSONL span chain for every admitted request whose terminal event
    matches its ``RequestResult`` status, (b) fill the TTFT/TBT/e2e/queue-wait
    histograms, and (c) leave the serving fast path's host-link counters
    IDENTICAL to a tracing-off run of the same scenario — tracing observes,
    it never adds device syncs or recompiles."""
    import os
    import tempfile
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax
    import numpy as np

    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    from deepspeed_tpu.models import llama
    from deepspeed_tpu.monitor.telemetry import TelemetryCollector
    from deepspeed_tpu.runtime.config import TelemetryConfig

    cfg = llama.LlamaConfig.tiny(vocab=128, hidden=64, layers=2, heads=4, kv_heads=2, seq=256)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    kw = dict(num_blocks=64, block_size=8, max_blocks_per_seq=8,
              token_budget=32, max_seqs_per_step=8)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 128, int(n)).tolist() for n in rng.integers(4, 16, 6)]
    # one over-cap prompt rides along so a shed terminal appears in the traces
    prompts.append(list(range(1, 100)))

    tmp = tempfile.mkdtemp(prefix="dstpu_tracing_smoke_")
    jsonl = os.path.join(tmp, "traces.jsonl")
    collector = TelemetryCollector(config=TelemetryConfig(jsonl_path=jsonl,
                                                          jsonl_flush_every=8))
    traced = InferenceEngineV2(llama, cfg, params, telemetry=collector,
                               config={"dtype": "float32",
                                       "serving_tracing": {"enabled": True}}, **kw)
    plain = InferenceEngineV2(llama, cfg, params, config={"dtype": "float32"}, **kw)
    results = {r.uid: r for r in traced.generate(prompts, max_new_tokens=8, strict=False)}
    plain_results = {r.uid: r for r in plain.generate(prompts, max_new_tokens=8,
                                                      strict=False)}
    collector.close()

    # tokens and statuses byte-identical to the untraced engine
    assert {u: r.tokens for u, r in results.items()} == \
        {u: r.tokens for u, r in plain_results.items()}, "tracing changed the tokens"
    # fastpath invariants unchanged: the host-link counters of both runs match
    c_on, c_off = traced.counters.snapshot(), plain.counters.snapshot()
    assert c_on == c_off, f"tracing disturbed the host-link counters: {c_on} vs {c_off}"
    assert c_on["host_syncs"] <= c_on["loop_iterations"] + c_on["flushes"], c_on

    with open(jsonl) as fh:
        records = [json.loads(line) for line in fh]
    traces = {r["uid"]: r for r in records if r["kind"] == "trace"}
    assert set(traces) == set(results), \
        f"missing traces for {set(results) - set(traces)}"
    for uid, r in results.items():
        tr = traces[uid]
        assert tr["status"] == r.status, f"uid {uid}: trace terminal {tr['status']} " \
            f"!= result status {r.status}"
        assert tr["events"] and tr["events"][-1][0] in (r.status, "shed"), tr["events"]
        if r.status == "ok":  # complete span chain, every span closed
            names = [s["name"] for s in tr["spans"]]
            assert names[0] == "queue_wait" and "prefill" in names and "decode" in names
            assert all(s["end"] is not None for s in tr["spans"]), tr["spans"]
            assert tr["ttft_s"] is not None and tr["e2e_s"] >= tr["ttft_s"] >= 0
    h = traced.health()
    for metric in ("ttft", "tbt", "e2e", "queue_wait"):
        assert h["latency"][metric]["count"] > 0, f"{metric} histogram is empty"
        assert h["latency"][metric]["p50"] is not None
    assert h["flight_recorder"], "flight recorder is empty"
    n_ok = sum(1 for r in results.values() if r.status == "ok")
    print(json.dumps({"tracing_smoke": "ok", "requests": len(results),
                      "ok": n_ok, "shed": len(results) - n_ok,
                      "trace_records": len(traces),
                      "ttft_p50_s": round(h["latency"]["ttft"]["p50"], 5),
                      "host_syncs": c_on["host_syncs"]}))
    return 0


def ops_smoke():
    """CI smoke for the ops plane (ISSUE 11 acceptance): a mixed-arrival
    serve with the ops server ON must (a) answer /metrics scrapes MID-SERVE
    and after with valid Prometheus 0.0.4 text (validated by the in-tree
    strict parser) exposing the shed/preempt/fastpath counters and the
    TTFT/TBT/e2e histograms, (b) mirror ``health()`` on /healthz, and
    (c) add ZERO host-link cost — the fastpath ``ServeCounters`` snapshots
    are byte-identical with the server on vs off, and the tokens match
    (the same guarantee style as the tracing/journal smokes)."""
    import os
    import threading
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax
    import numpy as np

    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    from deepspeed_tpu.models import llama
    from deepspeed_tpu.monitor.exposition import parse_exposition
    from deepspeed_tpu.monitor.ops_server import scrape

    cfg = llama.LlamaConfig.tiny(vocab=128, hidden=64, layers=2, heads=4,
                                 kv_heads=2, seq=256)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    kw = dict(num_blocks=64, block_size=8, max_blocks_per_seq=8,
              token_budget=32, max_seqs_per_step=8)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 128, int(n)).tolist() for n in rng.integers(4, 16, 6)]

    on = InferenceEngineV2(llama, cfg, params,
                           config={"dtype": "float32",
                                   "serving_tracing": {"enabled": True},
                                   "ops_server": {"enabled": True,
                                                  "refresh_interval_s": 0.0}},
                           **kw)
    off = InferenceEngineV2(llama, cfg, params,
                            config={"dtype": "float32",
                                    "serving_tracing": {"enabled": True}}, **kw)
    url = on.ops.url

    # ---- (a) mid-serve scrapes from a concurrent thread: every response
    # must strict-parse; the handler serves cached strings, so a scrape can
    # never sync a device or race the loop
    mid_serve = {"metrics": 0, "healthz": 0, "errors": []}
    stop = threading.Event()

    def scraper():
        while not stop.is_set():
            try:
                parse_exposition(scrape(url("/metrics")))
                mid_serve["metrics"] += 1
                json.loads(scrape(url("/healthz")))
                mid_serve["healthz"] += 1
            except Exception as exc:  # a single bad payload fails the smoke
                mid_serve["errors"].append(repr(exc))
                return

    thread = threading.Thread(target=scraper, daemon=True)
    thread.start()
    out_on = on.generate(prompts, max_new_tokens=8)
    stop.set()
    thread.join(timeout=10.0)
    assert not mid_serve["errors"], f"mid-serve scrape failed: {mid_serve['errors']}"
    assert mid_serve["metrics"] > 0, "no successful mid-serve scrape"

    # ---- post-serve: the acceptance families with correct values
    body = scrape(url("/metrics"))
    fams = parse_exposition(body)
    counter = lambda name: fams[name]["samples"][0][2]
    assert counter("dstpu_serving_shed_total") == on.admission.shed_total
    assert counter("dstpu_serving_preempted_total") == on.scheduler.preempted_total
    assert counter("dstpu_serving_completed_total") == len(prompts)
    assert counter("dstpu_fastpath_host_syncs_total") == on.counters.host_syncs
    for name in ("dstpu_request_ttft_seconds", "dstpu_request_tbt_seconds",
                 "dstpu_request_e2e_seconds"):
        assert fams[name]["type"] == "histogram"
        bucket_inf = [v for n, l, v in fams[name]["samples"]
                      if n.endswith("_bucket") and l.get("le") == "+Inf"]
        assert bucket_inf and bucket_inf[0] > 0, f"{name} histogram is empty"
    health = json.loads(scrape(url("/healthz")))
    assert health == json.loads(json.dumps(on.health())), \
        "/healthz does not mirror health()"
    statez = json.loads(scrape(url("/statez")))
    assert statez["flight_recorder"], "statez missing the flight-recorder tail"

    # ---- (c) zero added host-link cost: counters byte-identical on vs off
    out_off = off.generate(prompts, max_new_tokens=8)
    assert out_on == out_off, "ops server changed the served tokens"
    c_on, c_off = on.counters.snapshot(), off.counters.snapshot()
    assert c_on == c_off, \
        f"ops server disturbed the host-link counters: {c_on} vs {c_off}"

    on.close_ops()
    print(json.dumps({"ops_smoke": "ok", "requests": len(prompts),
                      "mid_serve_scrapes": mid_serve["metrics"],
                      "families": len(fams),
                      "ttft_count": int(on.tracer.ttft.count),
                      "host_syncs": c_on["host_syncs"]}))
    return 0


def ops_stress():
    """Dynamic validation of the conventions the threadcheck lint encodes
    (ISSUE 18): hammer /metrics + /healthz + direct ``health()`` calls from
    N concurrent threads for the WHOLE duration of a mixed serve and assert
    (a) every response strict-parses (no torn reads of the published cache
    strings — the atomic-publish contract observed dynamically), (b) zero
    exceptions escape any hammer thread, and (c) the fastpath
    ``ServeCounters`` snapshot is byte-identical to an unscraped run — the
    scrape plane added no host-link traffic (the handler-holds-engine
    contract observed dynamically)."""
    import os
    import threading
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax
    import numpy as np

    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    from deepspeed_tpu.models import llama
    from deepspeed_tpu.monitor.exposition import parse_exposition
    from deepspeed_tpu.monitor.ops_server import scrape

    N_SCRAPERS = 4   # /metrics + /healthz hammer threads
    N_HEALTH = 2     # direct engine.health() hammer threads

    cfg = llama.LlamaConfig.tiny(vocab=128, hidden=64, layers=2, heads=4,
                                 kv_heads=2, seq=256)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    kw = dict(num_blocks=64, block_size=8, max_blocks_per_seq=8,
              token_budget=32, max_seqs_per_step=8)
    rng = np.random.default_rng(1)
    prompts = [rng.integers(1, 128, int(n)).tolist()
               for n in rng.integers(4, 16, 8)]

    on = InferenceEngineV2(llama, cfg, params,
                           config={"dtype": "float32",
                                   "serving_tracing": {"enabled": True},
                                   "ops_server": {"enabled": True,
                                                  "refresh_interval_s": 0.0}},
                           **kw)
    off = InferenceEngineV2(llama, cfg, params,
                            config={"dtype": "float32",
                                    "serving_tracing": {"enabled": True}}, **kw)
    url = on.ops.url

    stop = threading.Event()
    stats = {"metrics": 0, "healthz": 0, "health": 0}
    stats_lock = threading.Lock()
    errors = []  # (worker label, repr(exc)) — any entry fails the stress

    def scraper(idx):
        try:
            while not stop.is_set():
                fams = parse_exposition(scrape(url("/metrics")))
                assert "dstpu_serving_completed_total" in fams
                hz = json.loads(scrape(url("/healthz")))
                assert isinstance(hz, dict)
                with stats_lock:
                    stats["metrics"] += 1
                    stats["healthz"] += 1
        except BaseException as exc:
            errors.append((f"scraper-{idx}", repr(exc)))

    def health_hammer(idx):
        try:
            while not stop.is_set():
                h = on.health()
                # health() must always be a complete, JSON-renderable view
                json.dumps(h)
                assert "latency" in h
                with stats_lock:
                    stats["health"] += 1
        except BaseException as exc:
            errors.append((f"health-{idx}", repr(exc)))

    threads = [threading.Thread(target=scraper, args=(i,), daemon=True)
               for i in range(N_SCRAPERS)]
    threads += [threading.Thread(target=health_hammer, args=(i,), daemon=True)
                for i in range(N_HEALTH)]
    for t in threads:
        t.start()
    out_on = on.generate(prompts, max_new_tokens=8)
    stop.set()
    for t in threads:
        t.join(timeout=10.0)
    assert not any(t.is_alive() for t in threads), "hammer thread hung"
    assert not errors, f"hammer thread failures: {errors}"
    assert stats["metrics"] > 0 and stats["health"] > 0, \
        f"stress produced no load: {stats}"

    # the scrape plane must not have perturbed the serve: tokens AND
    # host-link counters byte-identical to the unscraped engine
    out_off = off.generate(prompts, max_new_tokens=8)
    assert out_on == out_off, "stress changed the served tokens"
    c_on, c_off = on.counters.snapshot(), off.counters.snapshot()
    assert c_on == c_off, \
        f"stress disturbed the host-link counters: {c_on} vs {c_off}"

    on.close_ops()
    print(json.dumps({"ops_stress": "ok", "requests": len(prompts),
                      "threads": len(threads), **stats,
                      "host_syncs": c_on["host_syncs"]}))
    return 0


def kv_obs_smoke():
    """CI smoke for KV-pool observability (ISSUE 12 acceptance): (a) a
    shared-prefix serve must report a NON-ZERO counterfactual prefix-cache
    win (duplicate blocks, hit-rate, prefill tokens saved) and expose the
    ``serving_kv_*`` Prometheus families through /metrics (strict-parsed by
    the in-tree exposition parser); (b) the census-vs-allocator partition
    invariant must hold through a fault-injected serve (25% probabilistic
    allocator failures — every alloc/free/preempt/rollback path exercised);
    (c) zero added host-link cost — the fastpath ``ServeCounters`` are
    byte-identical with kv observability on vs off, and the tokens match."""
    import os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax
    import numpy as np

    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    from deepspeed_tpu.models import llama
    from deepspeed_tpu.monitor.exposition import parse_exposition
    from deepspeed_tpu.monitor.ops_server import scrape
    from tests.unit.fault_injection_serving import FaultyBlockedAllocator

    cfg = llama.LlamaConfig.tiny(vocab=128, hidden=64, layers=2, heads=4,
                                 kv_heads=2, seq=256)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    kw = dict(num_blocks=64, block_size=8, max_blocks_per_seq=8,
              token_budget=32, max_seqs_per_step=8)
    rng = np.random.default_rng(0)
    header = rng.integers(1, 128, 24).tolist()  # 3 full shared blocks
    prompts = [header + rng.integers(1, 128, 4).tolist() for _ in range(6)]

    # ---- (a) shared-prefix serve: counterfactual win + /metrics families
    on = InferenceEngineV2(llama, cfg, params,
                           config={"dtype": "float32",
                                   "ops_server": {"enabled": True,
                                                  "refresh_interval_s": 0.0}},
                           **kw)
    out_on = on.generate(prompts, max_new_tokens=8)
    kv = on.health()["kv"]
    assert kv["enabled"], kv
    pfx = kv["prefix"]
    assert pfx["duplicate_blocks_total"] > 0, pfx
    assert pfx["prefill_tokens_saved_total"] > 0, pfx
    assert pfx["last_pass"]["hit_rate"] > 0.0, pfx
    assert kv["census"]["blocks_allocated_total"] == \
        kv["census"]["blocks_freed_total"], kv["census"]  # pool fully reclaimed
    on.check_kv_invariant()
    fams = parse_exposition(scrape(on.ops.url("/metrics")))
    value = lambda name: fams[name]["samples"][0][2]
    assert value("dstpu_serving_kv_prefix_tokens_saved_total") == \
        pfx["prefill_tokens_saved_total"]
    # the deprecated aliases (serving_free_kv_blocks /
    # scheduler_kv_block_utilization) served their one release and are gone
    assert "dstpu_serving_free_kv_blocks" not in fams
    assert "dstpu_scheduler_kv_block_utilization" not in fams
    for name in ("dstpu_serving_kv_free_blocks", "dstpu_serving_kv_utilization",
                 "dstpu_serving_kv_fragmentation_tokens",
                 "dstpu_serving_kv_under_pressure",
                 "dstpu_serving_kv_block_utilization"):
        assert name in fams, f"missing /metrics family {name}"
    for name in ("dstpu_serving_kv_block_age_steps",
                 "dstpu_serving_kv_blocks_per_request"):
        assert fams[name]["type"] == "histogram", name
    on.close_ops()

    # ---- (c) byte-identical ServeCounters + tokens, kv observability off
    off = InferenceEngineV2(llama, cfg, params,
                            config={"dtype": "float32",
                                    "serving_kv_observability": {"enabled": False}},
                            **kw)
    out_off = off.generate(prompts, max_new_tokens=8)
    assert out_on == out_off, "kv observability changed the served tokens"
    c_on, c_off = on.counters.snapshot(), off.counters.snapshot()
    assert c_on == c_off, \
        f"kv observability disturbed the host-link counters: {c_on} vs {c_off}"
    assert off.health()["kv"] == {"enabled": False}

    # ---- (b) census invariant under injected allocator faults (the PR-4
    # double-free guard as a continuously-checked pool invariant)
    faulty = InferenceEngineV2(llama, cfg, params,
                               config={"dtype": "float32",
                                       "serving_resilience": {"max_live_seqs": 3,
                                                              "stall_watchdog_steps": 50}},
                               num_blocks=48, block_size=8, max_blocks_per_seq=8,
                               token_budget=32, max_seqs_per_step=4)
    faulty.manager.allocator = FaultyBlockedAllocator(48, fail_rate=0.25, seed=11)
    mixed = [rng.integers(1, 128, int(n)).tolist() for n in rng.integers(3, 24, 8)]
    results = faulty.generate(mixed, max_new_tokens=6, strict=False)
    assert all(r.status == "ok" for r in results), [r.status for r in results]
    assert faulty.manager.allocator.injected_failures > 0, "faults never fired"
    faulty.check_kv_invariant()  # owned-set/free-list partition held throughout
    census = faulty.health()["kv"]["census"]
    assert census["allocated_blocks"] == 0 and \
        census["blocks_allocated_total"] == census["blocks_freed_total"], census

    print(json.dumps({"kv_obs_smoke": "ok", "requests": len(prompts),
                      "duplicate_blocks_total": pfx["duplicate_blocks_total"],
                      "hit_rate": round(pfx["last_pass"]["hit_rate"], 4),
                      "prefill_tokens_saved": pfx["prefill_tokens_saved_total"],
                      "injected_failures": faulty.manager.allocator.injected_failures,
                      "invariant_checks":
                          faulty.health()["kv"]["invariant_checks_total"],
                      "host_syncs": c_on["host_syncs"]}))
    return 0


def prefix_cache_smoke():
    """CI smoke for copy-on-write prefix caching (ISSUE 13 acceptance): a
    shared-prefix arrival run must (a) realize a prefix hit-rate > 0 with
    prefill tokens saved EQUAL to the PrefixObservatory's counterfactual
    prediction, (b) serve generated tokens byte-identical cache on vs off,
    (c) fully reclaim the pool AND drain the tree at the end (weak entries:
    sharing never pins capacity), with the refcount/census invariants clean
    — including under 25% injected allocator faults — and (d) cost nothing
    when there is nothing to share (fastpath ``ServeCounters`` byte-identical
    cache on vs off on a no-sharing workload)."""
    import os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax
    import numpy as np

    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    from deepspeed_tpu.models import llama
    from tests.unit.fault_injection_serving import FaultyBlockedAllocator

    cfg = llama.LlamaConfig.tiny(vocab=128, hidden=64, layers=2, heads=4,
                                 kv_heads=2, seq=256)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    kw = dict(num_blocks=64, block_size=8, max_blocks_per_seq=8,
              token_budget=64, max_seqs_per_step=8)
    rng = np.random.default_rng(0)
    header = rng.integers(1, 128, 24).tolist()  # 3 full shared blocks
    prompts = [header + rng.integers(1, 128, 4).tolist() for _ in range(6)]

    def engine(enabled, **over):
        merged = dict(kw)
        merged.update(over)
        return InferenceEngineV2(
            llama, cfg, params,
            config={"dtype": "float32",
                    "serving_prefix_cache": {"enabled": enabled}}, **merged)

    # ---- (a) realized savings == the observatory's counterfactual
    on = engine(True)
    out_on = on.generate(prompts, max_new_tokens=8)
    pc = on.health()["prefix_cache"]
    obs = on.health()["kv"]["prefix"]
    assert pc["realized_hit_rate"] > 0.0, pc
    assert pc["tokens_saved_total"] == obs["prefill_tokens_saved_total"], (pc, obs)
    assert pc["hit_blocks_total"] == obs["duplicate_blocks_total"], (pc, obs)
    # ---- (c) pool AND tree fully reclaimed at drain; invariants clean
    on.check_kv_invariant()
    assert on.manager.allocator.free_blocks == kw["num_blocks"] - 1
    assert pc["entries"] == 0, pc

    # ---- (b) byte-identical outputs cache on vs off
    off = engine(False)
    out_off = off.generate(prompts, max_new_tokens=8)
    assert out_on == out_off, "prefix caching changed the served tokens"

    # ---- invariants under 25% injected allocator faults + preemption pressure
    faulty = engine(True, num_blocks=40, token_budget=32, max_seqs_per_step=4)
    faulty.manager.allocator = FaultyBlockedAllocator(40, fail_rate=0.25, seed=11)
    results = faulty.generate(prompts, max_new_tokens=6, strict=False)
    assert all(r.status == "ok" for r in results), [r.status for r in results]
    assert faulty.manager.allocator.injected_failures > 0, "faults never fired"
    faulty.check_kv_invariant()
    assert faulty.manager.allocator.free_blocks == 39
    assert faulty.health()["prefix_cache"]["hits_total"] > 0

    # ---- (d) zero cost with nothing to share: counters byte-identical
    distinct = [rng.integers(1, 128, int(n)).tolist()
                for n in rng.integers(3, 30, 6)]
    snaps = {}
    for enabled in (True, False):
        e = engine(enabled)
        o = e.generate(distinct, max_new_tokens=6)
        snaps[enabled] = (e.counters.snapshot(), o)
    assert snaps[True] == snaps[False], \
        "an idle prefix cache disturbed the host-link counters"

    print(json.dumps({"prefix_cache_smoke": "ok", "requests": len(prompts),
                      "realized_hit_rate": round(pc["realized_hit_rate"], 4),
                      "prefill_tokens_saved": pc["tokens_saved_total"],
                      "counterfactual_tokens": obs["prefill_tokens_saved_total"],
                      "deferrals": pc["deferrals_total"],
                      "byte_identical": out_on == out_off,
                      "injected_failures":
                          faulty.manager.allocator.injected_failures}))
    return 0


def elastic_smoke():
    """CI smoke for elastic training fault tolerance (ISSUE 7 acceptance):
    a 4-worker CPU run under the elastic agent with TWO injected faults —
    kill one rank mid-step in generation 0, then hang another (stamped
    'entered all_reduce', detectable only by heartbeat staleness) in the next
    generation — asserting: rescale to elastic-valid worlds, every generation
    resumed from the agent-pinned consensus tag, exact loss continuity vs an
    uninterrupted reference run, the hang dump naming the stuck collective,
    and zero orphaned worker processes."""
    import os
    import signal
    import tempfile
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from deepspeed_tpu.elasticity import DSElasticAgent

    # overall deadline: this smoke TESTS hang detection, so a regression in
    # it must fail the lane, not wedge CI forever waiting on a poll loop
    # that never indicts the injected hang
    def _deadline(signum, frame):
        raise TimeoutError("elastic_smoke exceeded its 480s deadline — the "
                           "agent's hang detection may have regressed")

    signal.signal(signal.SIGALRM, _deadline)
    signal.alarm(480)

    root = os.path.dirname(os.path.abspath(__file__))
    worker_cmd = [sys.executable, "-u", os.path.join(root, "tests", "unit", "elastic_worker.py")]
    steps = 6

    def worker_env(tmp, faults):
        env = dict(os.environ, ELASTIC_TMP=tmp, ELASTIC_STEPS=str(steps),
                   ELASTIC_FAULTS=json.dumps(faults))
        env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
        return env

    # uninterrupted reference: one rank, no faults, same model/batches — the
    # continuity oracle (every rank trains the SAME deterministic fp32 MLP)
    ref_tmp = tempfile.mkdtemp(prefix="dstpu_elastic_ref_")
    rc = DSElasticAgent(worker_cmd, world_size=1, poll_interval=0.1,
                        env=worker_env(ref_tmp, [])).run()
    assert rc == 0, f"reference run failed rc={rc}"
    ref_loss = {}
    with open(os.path.join(ref_tmp, "loss.rank0.jsonl")) as fh:
        for line in fh:
            rec = json.loads(line)
            ref_loss[rec["step"]] = rec["loss"]
    assert sorted(ref_loss) == list(range(1, steps + 1))

    # the faulty run: crash rank 2 in gen 0, hang rank 1 in gen 1.  The crash
    # awaits global_step1 in EVERY rank dir first, so the post-crash consensus
    # always has a common tag (cross-rank startup skew would otherwise race
    # the first saves and legitimately yield a fresh start)
    tmp = tempfile.mkdtemp(prefix="dstpu_elastic_smoke_")
    faults = [{"mode": "crash", "rank": 2, "step": 2, "gen": 0,
               "await_tag": "global_step1"},
              {"mode": "hang", "rank": 1, "step": 1, "gen": 1}]
    agent = DSElasticAgent(
        worker_cmd, world_size=4,
        elastic_config={"max_train_batch_size": 8, "micro_batch_sizes": [1, 2],
                        "min_gpus": 1, "max_gpus": 4},
        max_restarts=3, poll_interval=0.1, env=worker_env(tmp, faults),
        checkpoint_dir=os.path.join(tmp, "ckpt"), per_rank_checkpoints=True,
        heartbeat_dir=os.path.join(tmp, "hb"), heartbeat_timeout_s=5.0,
        heartbeat_interval_s=0.1, startup_grace_s=180.0, term_grace_secs=10.0)
    rc = agent.run()
    assert rc == 0, f"elastic run failed rc={rc}: {agent.state_snapshot()}"

    events = agent.recorder.tail()
    by_kind = {}
    for e in events:
        by_kind.setdefault(e["event"], []).append(e)

    # both failure modes seen, both recovered, worlds rescaled validly
    assert agent.restart_count == 2, f"expected 2 restarts: {by_kind.keys()}"
    assert by_kind["worker_failed"][0]["rank"] == 2
    hang = by_kind["hang_detected"][0]
    assert hang["ranks"] == [1] and hang["collectives"] == {1: "all_reduce"}
    assert "blocked in collective 'all_reduce'" in hang["report"]
    rescales = [(e["from_world"], e["to_world"]) for e in by_kind["rescale"]]
    assert rescales == [(4, 2), (2, 1)], rescales

    # resume-tag consensus: every rank of each restarted generation loaded
    # EXACTLY the tag the agent pinned
    assert agent.resume_tags[0] is None and None not in agent.resume_tags[1:]
    for gen in (1, 2):
        world = {1: 2, 2: 1}[gen]
        seen = set()
        for rank in range(world):
            marker = os.path.join(tmp, f"resume.gen{gen}.rank{rank}")
            if os.path.exists(marker):  # a rank at the target step loads nothing
                seen.add(open(marker).read().strip())
        assert seen <= {agent.resume_tags[gen]}, (gen, seen, agent.resume_tags)

    # loss continuity: EVERY step logged by ANY rank in ANY generation —
    # including steps re-executed after a resume — matches the uninterrupted
    # reference bit-exactly (fp32 determinism contract of elastic_worker)
    compared = 0
    for name in os.listdir(tmp):
        if not name.startswith("loss.rank"):
            continue
        with open(os.path.join(tmp, name)) as fh:
            for line in fh:
                rec = json.loads(line)
                assert rec["loss"] == ref_loss[rec["step"]], (name, rec)
                compared += 1
    assert compared >= steps, "loss logs suspiciously empty"

    # zero orphans: every worker pid ever spawned is gone
    pids = os.listdir(os.path.join(tmp, "pids"))
    orphans = [p for p in pids if os.path.exists(f"/proc/{p}")]
    assert not orphans, f"orphaned workers: {orphans}"
    assert os.path.exists(os.path.join(tmp, f"done.gen2.rank0"))

    signal.alarm(0)
    print(json.dumps({"elastic_smoke": "ok", "restarts": agent.restart_count,
                      "rescales": rescales, "resume_tags": agent.resume_tags,
                      "losses_compared": compared, "workers_spawned": len(pids),
                      "orphans": 0}))
    return 0


def _timed_pass(eng, prompts, max_new_tokens: int = 16) -> float:
    t0 = time.perf_counter()
    eng.generate(prompts, max_new_tokens=max_new_tokens)
    return time.perf_counter() - t0


def _journal_stream_cost(path: str, prompts, emitted, tok_frames: int,
                         iterations: int = 300) -> float:
    """Directly time one serve pass's worth of journal work: the admits,
    the OBSERVED number of wave-boundary token flushes (each carrying its
    share of the emitted tokens — fused bursts batch many tokens into one
    frame), and the terminals — i.e. the record stream the journaled serve
    of this workload actually appended."""
    from deepspeed_tpu.inference.v2 import RequestJournal
    journal = RequestJournal(path, fsync_every=0)
    waves = max(tok_frames, 1)

    def one_pass():
        for uid, prompt in enumerate(prompts):
            journal.record_admit(uid, prompt, max_new_tokens=16)
        for w in range(waves):
            for uid, toks in enumerate(emitted):
                share = toks[w * len(toks) // waves:(w + 1) * len(toks) // waves]
                if share:
                    journal.note_tokens(uid, share)
            journal.flush()
        for uid, toks in enumerate(emitted):
            journal.record_terminal(uid, "ok", finish_reason="max_new_tokens",
                                    n_tokens=len(toks))

    one_pass()
    # min over many small rounds: the journal's work is deterministic, so
    # its true cost is the floor — a CI load spike during one timing window
    # must not masquerade as journal cost
    cost = float("inf")
    rounds, per_round = 15, max(iterations // 15, 10)
    for _ in range(rounds):
        t0 = time.perf_counter()
        for _ in range(per_round):
            one_pass()
        cost = min(cost, (time.perf_counter() - t0) / per_round)
    journal.close()
    return cost


def serving_recovery_smoke():
    """CI smoke for serving fault tolerance (ISSUE 8 acceptance): (a) kill a
    real serving worker mid-decode (fault-injected at journal-flush wave 2);
    after supervised restart + journal replay — through a torn journal tail
    left at the restart boundary — every request reaches a terminal
    ``RequestResult``, recovered token streams are byte-identical to an
    uninterrupted seeded run, and zero worker processes are orphaned;
    (b) restart-budget exhaustion degrades to drain-only mode with every
    journaled request finalized as a structured ``failed`` (no hang);
    (c) a hung worker (stamps once, then silence) is indicted by heartbeat
    staleness, not by luck; (d) the journaling durability tax stays under
    3% tok/s on the CPU tiny-config bench scenario."""
    import os
    import signal
    import tempfile
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax
    from deepspeed_tpu.inference.v2 import (InferenceEngineV2, RequestJournal,
                                            ServingSupervisor)
    from deepspeed_tpu.models import llama
    from tests.unit.inference.serving_crash_worker import workload

    def _deadline(signum, frame):
        raise TimeoutError("serving_recovery_smoke exceeded its 600s deadline — "
                           "supervised restart or hang detection may have "
                           "regressed into a wedge")

    signal.signal(signal.SIGALRM, _deadline)
    signal.alarm(600)

    root = os.path.dirname(os.path.abspath(__file__))
    worker_cmd = [sys.executable, "-u",
                  os.path.join(root, "tests", "unit", "inference",
                               "serving_crash_worker.py")]
    cfg = llama.LlamaConfig.tiny(vocab=128, hidden=64, layers=2, heads=4,
                                 kv_heads=2, seq=256)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    kw = dict(num_blocks=64, block_size=8, max_blocks_per_seq=8,
              token_budget=32, max_seqs_per_step=8)
    prompts = workload()

    # uninterrupted seeded reference: the token-identity oracle
    ref = InferenceEngineV2(llama, cfg, params, config={"dtype": "float32"}, **kw)
    ref_out = ref.generate(prompts, max_new_tokens=8)

    # ---- (a) crash mid-decode at gen 0 + torn journal tail at gen-1 startup
    tmp = tempfile.mkdtemp(prefix="dstpu_serving_recovery_")
    faults = [{"mode": "crash", "gen": 0, "flush_n": 2},
              {"mode": "torn_tail", "gen": 1}]
    env = {"SERVING_TMP": tmp, "SERVING_FAULTS": json.dumps(faults),
           "PYTHONPATH": root + os.pathsep + os.environ.get("PYTHONPATH", "")}
    sup = ServingSupervisor(
        journal_path=os.path.join(tmp, "requests.wal"),
        config={"max_restarts": 3, "hang_timeout_s": 60.0,
                "startup_grace_s": 300.0, "poll_interval_s": 0.1,
                "heartbeat_interval_s": 0.1})
    report = sup.supervise_command(worker_cmd, env=env,
                                   heartbeat_base=os.path.join(tmp, "hb"))
    assert report["restarts"] == 1, report
    assert not report["degraded"]
    state = report["state"]
    assert not state.incomplete(), [e.uid for e in state.incomplete()]
    results = report["results"]
    assert set(results) == set(range(len(prompts))), sorted(results)
    for uid, r in sorted(results.items()):
        assert r.status == "ok", (uid, r.status, r.reason)
        assert r.tokens == ref_out[uid], \
            f"uid {uid}: recovered stream diverged from the uninterrupted run"
    recovered = [e for e in state.entries.values()
                 if e.admits > 1 and e.prefix_len > 0]
    assert recovered, "no request was actually recovered with an emitted prefix"
    pids = os.listdir(os.path.join(tmp, "pids"))
    orphans = [p for p in pids if os.path.exists(f"/proc/{p}")]
    assert not orphans, f"orphaned serving workers: {orphans}"
    assert len(pids) == 2, f"expected gen0+gen1 workers, saw {len(pids)}"

    # ---- (b) restart-budget exhaustion: drain-only degradation, no hang
    tmp2 = tempfile.mkdtemp(prefix="dstpu_serving_budget_")
    jp2 = os.path.join(tmp2, "requests.wal")
    seed_journal = RequestJournal(jp2)
    seed_journal.record_admit(0, [1, 2, 3], max_new_tokens=8)
    seed_journal.note_tokens(0, [5])
    seed_journal.flush()
    seed_journal.close()
    sup2 = ServingSupervisor(
        journal_path=jp2,
        config={"max_restarts": 1, "hang_timeout_s": 5.0,
                "startup_grace_s": 30.0, "poll_interval_s": 0.02})
    rep2 = sup2.supervise_command([sys.executable, "-c", "import sys; sys.exit(3)"],
                                  heartbeat_base=os.path.join(tmp2, "hb"))
    assert rep2["degraded"], rep2
    assert not rep2["state"].incomplete()
    r0 = rep2["results"][0]
    assert r0.status == "failed" and r0.retryable, r0
    ev2 = [e["event"] for e in sup2.recorder.tail()]
    assert "degraded" in ev2 and "finalized" in ev2, ev2

    # ---- (c) hang detection: one stamp, then silence -> heartbeat staleness
    tmp3 = tempfile.mkdtemp(prefix="dstpu_serving_hang_")
    hang_script = (
        "import json,os,time; d=os.environ['DSTPU_HEARTBEAT_DIR'];"
        "os.makedirs(d, exist_ok=True);"
        "open(os.path.join(d,'hb.rank0.json'),'w').write("
        "json.dumps({'rank':0,'time':time.time(),'step':1}));"
        "time.sleep(600)")
    sup3 = ServingSupervisor(
        journal_path=os.path.join(tmp3, "requests.wal"),
        config={"max_restarts": 0, "hang_timeout_s": 1.0,
                "startup_grace_s": 30.0, "poll_interval_s": 0.05})
    rep3 = sup3.supervise_command([sys.executable, "-c", hang_script],
                                  heartbeat_base=os.path.join(tmp3, "hb"))
    ev3 = [e["event"] for e in sup3.recorder.tail()]
    assert "hang_detected" in ev3, ev3
    assert rep3["degraded"] and rep3["generations"] == 2, rep3

    # ---- (d) journaling durability tax < 3% tok/s (CPU tiny-config bench),
    # at fsync_every=0 (buffered appends — the throughput deploy setting;
    # fsync_every>=1 buys per-record durability at the price of one disk
    # barrier per record, by design).  Two-part gate, both deterministic:
    #   1. device-side cost is ZERO — the fastpath ServeCounters of a
    #      journaled serve are byte-identical to an unjournaled one (the
    #      journal only appends host bytes; it never adds a sync, dispatch,
    #      upload, or compile), and the tokens match;
    #   2. the journal's host cost — its ACTUAL record stream for this
    #      workload, timed directly (min over rounds of a tight loop, so a
    #      CI load spike can't masquerade as journal cost) — stays under 3%
    #      of the TYPICAL serve pass (median over 9 passes).
    # An end-to-end wall-clock A/B delta is deliberately NOT the meter: two
    # IDENTICAL engines measure ±10% apart under CI load, an order of
    # magnitude above the journal's true cost; bench.py reports the
    # end-to-end serving_mixed_journal_overhead_pct on quiet bench hosts.
    on = InferenceEngineV2(
        llama, cfg, params,
        config={"dtype": "float32",
                "serving_fault_tolerance": {
                    "enabled": True, "fsync_every": 0,
                    "journal_path": os.path.join(tmp, "bench.wal")}}, **kw)
    off = InferenceEngineV2(llama, cfg, params,
                            config={"dtype": "float32"}, **kw)
    records_before = on.journal.records_written
    out_on = on.generate(prompts, max_new_tokens=16)
    pass_records = on.journal.records_written - records_before
    out_off = off.generate(prompts, max_new_tokens=16)
    assert out_on == out_off, "journaling changed the served tokens"
    assert on.counters.snapshot() == off.counters.snapshot(), \
        f"journaling disturbed the host-link counters: " \
        f"{on.counters.snapshot()} vs {off.counters.snapshot()}"

    import statistics
    serve_typical = statistics.median(
        _timed_pass(on, prompts) for _ in range(9))
    emitted = [o[len(p):] for o, p in zip(out_on, prompts)]
    # the observed pass = admits + terminals + its tok frames
    tok_frames = max(pass_records - 2 * len(prompts), 1)
    journal_cost = _journal_stream_cost(os.path.join(tmp, "stream.wal"),
                                        prompts, emitted, tok_frames)
    overhead_pct = journal_cost / serve_typical * 100.0
    assert overhead_pct < 3.0, \
        f"journaling host cost {journal_cost*1e6:.0f}us/pass is " \
        f"{overhead_pct:.2f}% of the {serve_typical*1e3:.1f}ms typical serve (>= 3%)"

    signal.alarm(0)
    print(json.dumps({"serving_recovery_smoke": "ok",
                      "requests": len(prompts),
                      "restarts": report["restarts"],
                      "recovered_with_prefix": len(recovered),
                      "budget_degraded": rep2["degraded"],
                      "hang_detected": "hang_detected" in ev3,
                      "journal_overhead_pct": round(overhead_pct, 2),
                      "orphans": 0}))
    return 0


def perf_smoke():
    """CI smoke for the serving perf observatory (ISSUE 16 acceptance): a
    3-wave mixed-arrival serve with the observatory ON must (a) fill EVERY
    phase family (admission_pump .. other) with spans that sum to the
    measured iteration wall, (b) report ZERO warm recompiles across all
    three waves (the steady-state no-recompile guarantee, runtime twin of
    dslint's recompile-risk rule), (c) carry full roofline cost coverage
    (no uncosted dispatches) with finite gauges, (d) strict-parse the new
    serving_phase/compiles/recompiles/roofline families off a live /metrics
    scrape, and (e) add ZERO cost — tokens and the fastpath ``ServeCounters``
    byte-identical with the observatory off."""
    import os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax
    import numpy as np

    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    from deepspeed_tpu.models import llama
    from deepspeed_tpu.monitor.exposition import parse_exposition
    from deepspeed_tpu.monitor.ops_server import scrape
    from deepspeed_tpu.monitor.perf import PHASES

    cfg = llama.LlamaConfig.tiny(vocab=128, hidden=64, layers=2, heads=4,
                                 kv_heads=2, seq=256)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    kw = dict(num_blocks=64, block_size=8, max_blocks_per_seq=8,
              token_budget=32, max_seqs_per_step=8)
    rng = np.random.default_rng(0)
    # three arrival waves of mixed prompt lengths: wave 2/3 revisit wave 1's
    # compiled buckets, so any recompile is a warm one the ledger must flag
    waves = [[rng.integers(1, 128, int(n)).tolist()
              for n in rng.integers(4, 16, 5)] for _ in range(3)]

    on = InferenceEngineV2(llama, cfg, params,
                           config={"dtype": "float32",
                                   "serving_tracing": {"enabled": True},
                                   "serving_perf": {"enabled": True},
                                   "ops_server": {"enabled": True,
                                                  "refresh_interval_s": 0.0}},
                           **kw)
    off = InferenceEngineV2(llama, cfg, params,
                            config={"dtype": "float32"}, **kw)
    toks_on = [on.generate(w, max_new_tokens=8) for w in waves]
    toks_off = [off.generate(w, max_new_tokens=8) for w in waves]

    # ---- (a) every phase family non-empty, spans sum to the wall
    prof = on.phase_profiler
    empty = [p for p in PHASES if prof.hists[p].count == 0]
    assert not empty, f"phase families never sampled: {empty}"
    assert abs(sum(prof.totals.values()) - prof.wall_s) < 1e-6, \
        "phase spans do not sum to the iteration wall"

    # ---- (b) zero warm recompiles over the 3-wave scenario
    led = on.ledger.snapshot()
    assert led["warm_total"] == 0, f"warm recompiles in steady state: {led}"
    assert on.counters.compiles == led["total"], \
        "ledger/counter compile attribution drift"

    # ---- (c) full roofline cost coverage, finite gauges
    roof = on.health()["perf"]["roofline"]
    assert roof["uncosted_dispatches"] == 0, roof
    assert roof["costed_buckets"] > 0 and roof["hbm_bytes"] > 0
    for name, v in roof["gauges"].items():
        assert v == v and abs(v) != float("inf"), f"{name} not finite: {v}"

    # ---- (d) the new families strict-parse off a live /metrics scrape
    fams = parse_exposition(scrape(on.ops.url("/metrics")))
    phase_samples = fams["dstpu_serving_phase_seconds"]["samples"]
    phases_seen = {l.get("phase") for _, l, _ in phase_samples if l.get("phase")}
    assert set(PHASES) <= phases_seen, f"missing phase series: {set(PHASES) - phases_seen}"
    assert any(l.get("site") == "fwd"
               for _, l, _ in fams["dstpu_serving_compiles_total"]["samples"])
    recomp = fams["dstpu_serving_recompiles_total"]["samples"]
    assert recomp and all(v == 0.0 for _, _, v in recomp), recomp
    for name in ("dstpu_serving_roofline_fraction",
                 "dstpu_serving_hbm_bytes_per_token"):
        assert name in fams, f"missing family {name}"

    # ---- (e) byte-identity: observatory adds zero cost
    assert toks_on == toks_off, "observatory changed the served tokens"
    c_on, c_off = on.counters.snapshot(), off.counters.snapshot()
    assert c_on == c_off, \
        f"observatory disturbed the host-link counters: {c_on} vs {c_off}"

    on.close_ops()
    print(json.dumps({"perf_smoke": "ok", "waves": len(waves),
                      "iterations": prof.iterations,
                      "phases": {p: prof.hists[p].count for p in PHASES},
                      "compiles": led["total"], "warm_recompiles": 0,
                      "costed_buckets": roof["costed_buckets"],
                      "roofline_fraction": roof["gauges"]["serving_roofline_fraction"]}))
    return 0


def fleet_smoke():
    """CI smoke for the serving fleet (ISSUE 17 acceptance): three in-process
    supervised replicas behind the health-gated ``FleetRouter`` on a mixed
    workload with shared prompt headers; one replica is crash-injected
    mid-decode (the crash worker's count-to-N idiom, in-process) until its
    restart budget exhausts.  The router must drain it and migrate its
    journaled in-flight work to a healthy replica such that (a) every request
    reaches a terminal ``ok`` result, (b) migrated token streams are
    byte-identical to an uninterrupted seeded single-engine run, (c) the
    merged /metrics text strict-parses and every fleet counter is monotone
    across the failover, (d) prefix affinity realizes actual KV prefix hits
    on the home replica, and (e) zero requests are orphaned: every admit
    journaled anywhere is terminal somewhere, and ``lost_total == 0``."""
    import os
    import signal
    import tempfile
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax
    from deepspeed_tpu.inference.v2 import FleetRouter, InferenceEngineV2
    from deepspeed_tpu.inference.v2.journal import replay_journal
    from deepspeed_tpu.models import llama
    from deepspeed_tpu.monitor.exposition import parse_exposition
    from tests.unit.inference.serving_crash_worker import workload

    def _deadline(signum, frame):
        raise TimeoutError("fleet_smoke exceeded its 600s deadline — fleet "
                           "failover or shed re-routing may have regressed "
                           "into a wedge")

    signal.signal(signal.SIGALRM, _deadline)
    signal.alarm(600)

    cfg = llama.LlamaConfig.tiny(vocab=128, hidden=64, layers=2, heads=4,
                                 kv_heads=2, seq=256)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    kw = dict(num_blocks=64, block_size=8, max_blocks_per_seq=8,
              token_budget=32, max_seqs_per_step=8)

    # mixed workload: the crash worker's seeded prompts plus two requests
    # sharing one FULL 8-token header block — with block_size=8 and
    # affinity_blocks=1 that header is exactly the affinity home key AND a
    # realizable prefix-cache block
    header = [7, 11, 13, 17, 19, 23, 29, 31]
    base = workload()
    mixed = base[:3] + [header + [41, 43, 47], header + [53, 59]] + base[3:]
    wave1, wave2 = mixed[:5], mixed[5:]

    # uninterrupted seeded reference: the byte-identity oracle (greedy decode
    # is per-sequence deterministic, so batch composition cannot matter)
    ref = InferenceEngineV2(llama, cfg, params, config={"dtype": "float32"},
                            **kw)
    ref_out = ref.generate(mixed, max_new_tokens=8)

    # the in-process analog of the crash worker's flush-count fault: once
    # armed, replica 0's engines die right AFTER their first non-empty decode
    # burst of every generation — the burst epilogue has just journaled and
    # flushed the emitted tokens, so the crash leaves durable in-flight
    # prefixes with no terminals (exactly what failover must migrate)
    fault = {"armed": False}

    def _arm_crash(engine):
        # count "productive" serve events (a dispatched step or a non-empty
        # burst) and die on the third — by then at least one step's tokens
        # have been absorbed into the journal (the burst epilogue and the
        # supervisor's close-on-crash both flush), so every generation dies
        # with durable in-flight prefixes and no terminals
        events = {"n": 0}

        def _productive():
            events["n"] += 1
            if events["n"] >= 2:
                raise RuntimeError("fleet_smoke: injected mid-decode crash")

        real_burst = engine.decode_burst

        def burst(k, *args, **kwargs):
            # clamp the fused window so the crash lands MID-stream: an
            # unclamped first burst can emit the whole remaining stream,
            # leaving the restart generation nothing to do (complete journal
            # streams are adopted, the budget never exhausts, and there is
            # no failover to exercise)
            out = real_burst(min(int(k), 2), *args, **kwargs)
            if out:
                _productive()
            return out

        real_dispatch = engine._dispatch_step

        def dispatch(*args, **kwargs):
            out = real_dispatch(*args, **kwargs)
            if out is not None:
                _productive()
            return out

        engine.decode_burst = burst
        engine._dispatch_step = dispatch
        return engine

    def _factory(index):
        def build():
            eng = InferenceEngineV2(llama, cfg, params,
                                    config={"dtype": "float32"}, **kw)
            if index == 0 and fault["armed"]:
                _arm_crash(eng)
            return eng
        return build

    tmp = tempfile.mkdtemp(prefix="dstpu_fleet_smoke_")
    # health_stale_s is wide open here: on CPU a single XLA compile takes
    # longer than the 5s production horizon, so real-clock staleness would
    # gate replicas arbitrarily (the staleness gate itself is unit-tested
    # with fake clocks in test_serving_fleet.py)
    router = FleetRouter([_factory(r) for r in range(3)], journal_dir=tmp,
                         config={"replicas": 3, "affinity_blocks": 1,
                                 "health_stale_s": 600.0},
                         ft_config={"enabled": True, "max_restarts": 1,
                                    "fsync_every": 1},
                         block_size=8)
    home = router._affinity_home(header + [41, 43, 47])

    # ---- wave 1: all replicas healthy; the shared-header pair homes
    out1 = router.serve(wave1, uids=list(range(len(wave1))),
                        max_new_tokens=8)
    for uid, r in enumerate(out1):
        assert r.status == "ok", (uid, r.status, r.reason)
        assert r.tokens == ref_out[uid], \
            f"uid {uid}: fleet stream diverged from the uninterrupted run"
    assert router.affinity_routed_total >= 2, router.affinity_routed_total

    scrape1 = parse_exposition(router.metrics_text())
    hits = [(labels, v) for name, labels, v
            in scrape1["dstpu_serving_kv_prefix_hits_total"]["samples"]
            if labels.get("rank") == str(home)]
    assert hits and max(v for _, v in hits) > 0, \
        f"no realized prefix hits on home replica {home}: {hits}"

    def _counters(families):
        flat = {}
        for fam, body in families.items():
            if body["type"] != "counter":
                continue
            for name, labels, value in body["samples"]:
                flat[(name, tuple(sorted(labels.items())))] = value
        return flat

    before = _counters(scrape1)

    # ---- wave 2: arm the fault; replica 0 (least-loaded tie, lowest index)
    # takes the non-affinity traffic, crashes past its budget, and the router
    # must migrate its journaled in-flight work to a healthy replica
    fault["armed"] = True
    out2 = router.serve(wave2, uids=list(range(len(wave1), len(mixed))),
                        max_new_tokens=8)
    for i, r in enumerate(out2):
        uid = len(wave1) + i
        assert r.status == "ok", (uid, r.status, r.reason)
        assert r.tokens == ref_out[uid], \
            f"uid {uid}: migrated stream diverged from the uninterrupted run"

    assert router.migrations_total == 1, router.migrations_total
    assert router.migrated_requests_total >= 1, router.migrated_requests_total
    assert router.lost_total == 0, router.lost_total
    assert router.replicas[0].drained
    migrations = [e for e in router.recorder.tail() if e["event"] == "migrate"]
    inflight = [e for e in migrations if e["emitted"] > 0]
    assert inflight, \
        "no migrated request carried a journaled emitted prefix — the " \
        "failover exercised only fresh re-admission, not true continuation"

    fleet_health = router.health()
    assert fleet_health["healthy_replicas"] == 2, fleet_health

    # ---- merged metrics stay strict-parseable and monotone across failover
    scrape2 = parse_exposition(router.metrics_text())
    after = _counters(scrape2)
    regressed = {k: (before[k], after[k]) for k in before
                 if k in after and after[k] < before[k] - 1e-9}
    assert not regressed, \
        f"fleet counters went backwards across the failover: {regressed}"
    assert after[("dstpu_router_migrations_total", ())] == 1.0

    # ---- zero orphans: every uid admitted in ANY journal is terminal in
    # SOME journal (the drained replica's in-flight entries must have
    # reached terminals on their migration targets)
    admitted, terminal = set(), set()
    for replica in router.replicas:
        if not os.path.exists(replica.journal_path):
            continue
        state = replay_journal(replica.journal_path, truncate=False)
        admitted.update(state.entries)
        terminal.update(u for u, e in state.entries.items() if e.done)
    orphans = sorted(admitted - terminal)
    assert not orphans, f"journaled requests with no terminal anywhere: {orphans}"

    # ---- the drained replica is routed around, not resurrected
    routed0 = router.routed_total[0]
    out3 = router.serve([[3, 1, 4, 1, 5]], uids=[99], max_new_tokens=4)
    assert out3[0].status == "ok", out3[0]
    assert router.routed_total[0] == routed0, \
        "post-drain traffic reached the drained replica"

    router.close()
    signal.alarm(0)
    print(json.dumps({"fleet_smoke": "ok", "requests": len(mixed) + 1,
                      "home_replica": home,
                      "affinity_routed": router.affinity_routed_total,
                      "prefix_hits_on_home": max(v for _, v in hits),
                      "migrations": router.migrations_total,
                      "migrated_requests": router.migrated_requests_total,
                      "migrated_with_prefix": len(inflight),
                      "lost": router.lost_total, "orphans": 0}))
    return 0


def qos_smoke():
    """CI smoke for multi-tenant QoS (ISSUE 19 acceptance): an adversarial
    noisy-neighbor run on CPU.  A batch-class flood tenant slams the engine
    with long prompts against a tight token-rate quota while an interactive
    tenant trickles short requests — all under 25% probabilistic KV-allocator
    faults.  Must hold: (a) the interactive tenant's TTFT p95 stays within
    2x its flood-free baseline measured on the SAME warm engine (compile
    time cancels out), (b) every flood shed is the structured retryable
    ``quota_exceeded``/``queue_full`` with a finite ``retry_after_s`` (the
    quota is ENFORCED, fault injection notwithstanding), (c) zero watchdog
    stalls and every interactive request ``ok``, (d) the KV pool is fully
    reclaimed, and (e) the ``serving_tenant_*`` families strict-parse from
    the rendered registry with the per-tenant SLO histograms populated."""
    import os
    import signal
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax

    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    from deepspeed_tpu.models import llama
    from deepspeed_tpu.monitor.exposition import parse_exposition, render
    from deepspeed_tpu.monitor.metrics import MetricsRegistry, populate_from_engine
    from tests.unit.fault_injection_serving import FaultyBlockedAllocator

    def _deadline(signum, frame):
        raise TimeoutError("qos_smoke exceeded its 600s deadline — weighted-"
                           "fair dequeue or quota shedding may have wedged")

    signal.signal(signal.SIGALRM, _deadline)
    signal.alarm(600)

    cfg = llama.LlamaConfig.tiny(vocab=128, hidden=64, layers=2, heads=4,
                                 kv_heads=2, seq=256)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    # flood tenant quota: burst covers ONE 20-token prompt; refilling 8 tok/s
    # against a burst of back-to-back submissions means every flood request
    # after the first sheds quota_exceeded with an exact bucket-refill hint
    eng = InferenceEngineV2(
        llama, cfg, params,
        config={"dtype": "float32",
                "serving_tracing": {"enabled": True},
                "serving_qos": {"enabled": True,
                                "tenants": {"flood": {"tokens_per_s": 8.0,
                                                      "token_burst": 24.0,
                                                      "max_kv_blocks": 16}}}},
        num_blocks=64, block_size=8, max_blocks_per_seq=8,
        token_budget=32, max_seqs_per_step=8)
    # the whole run — warmup, baseline and flood — rides 25% allocator
    # faults (the serving_resilience injection idiom): quotas and fairness
    # must hold while the pool itself is misbehaving
    eng.manager.allocator = FaultyBlockedAllocator(64, fail_rate=0.25, seed=11)
    initial_free = eng.manager.allocator.free_blocks

    interactive = [[5, 9, 2, 14, 3, 8], [21, 4, 17, 6], [33, 7, 12, 25, 9],
                   [41, 2, 19, 30, 5, 11]]
    flood = [[(60 + i + j) % 120 + 1 for j in range(20)] for i in range(10)]

    # warmup: pay the XLA compiles for both prompt shapes and the baseline
    # batch composition OUTSIDE the timed passes (default tenant — its
    # histograms are keyed separately)
    eng.generate([list(p) for p in interactive], max_new_tokens=6,
                 strict=False)
    eng.generate([list(p) for p in interactive] + [list(flood[0])],
                 max_new_tokens=6, strict=False)

    # ---- flood-free baseline: the interactive trickle alone
    base_res = eng.generate([list(p) for p in interactive], max_new_tokens=6,
                            strict=False,
                            tenants=["int_base"] * len(interactive),
                            service_classes=["interactive"] * len(interactive))
    assert all(r.status == "ok" for r in base_res), \
        f"baseline statuses: {[r.status for r in base_res]}"
    base_hist = eng.tracer.tenant_histograms()[("int_base", "ttft")]
    base_p95 = base_hist.percentiles()["p95"]

    # ---- the noisy-neighbor pass: flood FIRST (it heads the queue), the
    # interactive trickle behind it — one call, one admission wave
    prompts = [list(p) for p in flood] + [list(p) for p in interactive]
    tenants = ["flood"] * len(flood) + ["int_live"] * len(interactive)
    classes = ["batch"] * len(flood) + ["interactive"] * len(interactive)
    mixed = eng.generate(prompts, max_new_tokens=6, strict=False,
                         tenants=tenants, service_classes=classes)
    flood_res = mixed[:len(flood)]
    int_res = mixed[len(flood):]

    # every interactive request finished despite the flood
    assert all(r.status == "ok" for r in int_res), \
        f"interactive statuses under flood: {[r.status for r in int_res]}"

    # the flood was QUOTA-shed, not starved out or failed: structured,
    # retryable, finite retry hints
    sheds = [r for r in flood_res if r.status == "shed"]
    assert sheds, "the flood was never shed — the tenant quota did not bite"
    for r in sheds:
        assert r.shed_code in ("quota_exceeded", "queue_full"), \
            f"unexpected shed code {r.shed_code!r}: {r.reason}"
        assert r.retryable, f"quota shed must be retryable: {r.reason}"
        assert r.retry_after_s is not None and 0 < r.retry_after_s < 120, \
            f"non-finite retry hint on {r.reason}"
    quota_sheds = [r for r in sheds if r.shed_code == "quota_exceeded"]
    assert quota_sheds, "no quota_exceeded shed among the flood sheds"
    assert any(r.status == "ok" for r in flood_res), \
        "the flood tenant was starved outright — quota, not blackout"

    # noisy-neighbor isolation: interactive TTFT p95 within 2x flood-free
    # (baseline floored at 50ms so CPU scheduling jitter on a sub-ms
    # baseline can't make the band tighter than the clock can resolve)
    live_hist = eng.tracer.tenant_histograms()[("int_live", "ttft")]
    live_p95 = live_hist.percentiles()["p95"]
    floor = max(base_p95, 0.05)
    assert live_p95 <= 2.0 * floor, \
        (f"interactive TTFT p95 {live_p95:.3f}s breached 2x its flood-free "
         f"baseline {base_p95:.3f}s — noisy-neighbor isolation regressed")

    # zero stalls, pool reclaimed, faults actually fired
    health = eng.health()
    assert health["stalls_total"] == 0, "watchdog tripped during the run"
    assert health["live_seqs"] == 0 and health["queue_depth"] == 0
    assert eng.manager.allocator.free_blocks == initial_free, "KV blocks leaked"
    assert eng.manager.allocator.injected_failures > 0, \
        "fault injection never fired"

    # per-tenant accounting reached the ledger
    assert eng.qos.admitted_by_tenant.get(("int_live", "interactive")) \
        == len(interactive), eng.qos.admitted_by_tenant
    assert eng.qos.shed_by_tenant.get(("flood", "quota_exceeded"), 0) \
        == len(quota_sheds), eng.qos.shed_by_tenant

    # ---- the serving_tenant_* families strict-parse and carry the tenants
    reg = MetricsRegistry()
    populate_from_engine(reg, eng)
    fams = parse_exposition(render(reg))

    def _samples(family):
        return {tuple(sorted(labels.items())): v
                for _, labels, v in fams[family]["samples"]}

    admitted = _samples("dstpu_serving_tenant_admitted_total")
    assert admitted[(("class", "interactive"), ("tenant", "int_live"))] \
        == float(len(interactive)), admitted
    shed_fam = _samples("dstpu_serving_tenant_shed_total")
    assert shed_fam[(("code", "quota_exceeded"), ("tenant", "flood"))] \
        == float(len(quota_sheds)), shed_fam
    ttft_counts = {labels.get("tenant"): v
                   for name, labels, v
                   in fams["dstpu_serving_tenant_ttft_seconds"]["samples"]
                   if name.endswith("_count")}
    assert ttft_counts.get("int_live") == float(len(interactive)), ttft_counts
    assert "dstpu_serving_tenant_retry_after_seconds" in fams

    signal.alarm(0)
    print(json.dumps({
        "qos_smoke": "ok",
        "interactive_ok": len(int_res),
        "flood_admitted": sum(1 for r in flood_res if r.status == "ok"),
        "flood_quota_sheds": len(quota_sheds),
        "injected_failures": eng.manager.allocator.injected_failures,
        "ttft_p95_base_s": round(base_p95, 4),
        "ttft_p95_under_flood_s": round(live_p95, 4)}))
    return 0


def spec_decode_smoke():
    """CI smoke for speculative decoding (ISSUE 20 acceptance): distribution
    parity is PROVED, not assumed, while the allocator misbehaves.  Must
    hold: (a) greedy spec-on tokens are byte-identical to the spec-off
    engine under 25% probabilistic KV-allocator faults (a rejected fault
    round falls back to the plain burst mid-stream and the streams still
    match), with the KV pool fully reclaimed and speculation demonstrably
    engaged; (b) the same identity holds with per-request deadlines expiring
    mid-decode on a fake clock — partial token lists and statuses match; (c)
    at T>0 the on-device rejection sampler's empirical marginal over many
    rng draws matches direct sampling from the filtered target distribution
    within a total-variation band (the Leviathan guarantee, measured); (d)
    the spec_decode health section and serving_spec_* families strict-parse
    and agree with the engine's counters."""
    import os
    import signal
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepspeed_tpu.inference.engine import _filter_logits
    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    from deepspeed_tpu.inference.v2.spec_decode import rejection_select
    from deepspeed_tpu.models import llama
    from deepspeed_tpu.monitor.exposition import parse_exposition, render
    from deepspeed_tpu.monitor.metrics import MetricsRegistry, populate_from_engine
    from tests.unit.fault_injection_serving import FakeClock, FaultyBlockedAllocator

    def _deadline(signum, frame):
        raise TimeoutError("spec_decode_smoke exceeded its 600s deadline — "
                           "draft/verify dispatch or the fallback path may "
                           "have wedged")

    signal.signal(signal.SIGALRM, _deadline)
    signal.alarm(600)

    cfg = llama.LlamaConfig.tiny(vocab=128, hidden=64, layers=2, heads=4,
                                 kv_heads=2, seq=256)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    prompts = [[1, 2, 3, 4, 5], [7, 8, 9], [11, 12, 13, 14, 15, 16, 17],
               [20, 21]]

    def mk(spec: bool, **kw):
        conf = {"dtype": "float32"}
        if spec:
            conf["serving_spec_decode"] = {"enabled": True, "k": 4}
        return InferenceEngineV2(llama, cfg, params, config=conf,
                                 num_blocks=64, block_size=8,
                                 max_blocks_per_seq=8, token_budget=32,
                                 max_seqs_per_step=8, **kw)

    # ---- (a) greedy byte-identity under 25% injected allocator faults
    def faulted(spec: bool):
        eng = mk(spec)
        eng.manager.allocator = FaultyBlockedAllocator(64, fail_rate=0.25,
                                                       seed=7)
        free0 = eng.manager.allocator.free_blocks
        res = eng.generate(prompts, max_new_tokens=12, strict=False)
        assert eng.manager.allocator.injected_failures > 0, \
            "fault injection never fired"
        assert eng.manager.allocator.free_blocks == free0, "KV blocks leaked"
        assert eng.health()["stalls_total"] == 0
        return [(r.status, r.tokens) for r in res], eng

    spec_res, spec_eng = faulted(True)
    ref_res, _ = faulted(False)
    assert spec_res == ref_res, \
        f"greedy spec-on diverged from spec-off under faults:\n" \
        f"spec: {spec_res}\nref:  {ref_res}"
    spec_health = spec_eng.health()["spec_decode"]
    assert spec_health["enabled"] and spec_health["rounds_total"] > 0, \
        f"speculation never engaged: {spec_health}"
    healthy = mk(True).generate(prompts, max_new_tokens=12)
    assert [t for _, t in spec_res] == healthy, \
        "faulted spec run diverged from the healthy spec run"

    # ---- (b) byte-identity with deadlines expiring mid-decode
    def expiring(spec: bool):
        eng = mk(spec, clock=FakeClock(tick=0.05))
        res = eng.generate([[1, 2, 3, 4, 5], [7, 8, 9]], max_new_tokens=64,
                           strict=False, ttl_s=0.4)
        return [(r.uid, r.status, r.tokens) for r in res]

    assert expiring(True) == expiring(False), \
        "deadline-expiry partials diverged between spec-on and spec-off"

    # ---- (c) measured distribution parity at T>0: rejection_select's
    # marginal over the FIRST emitted position vs direct categorical
    # sampling from the same filtered logits, many rng draws, small-V
    sample_cfg = (0.9, 0, 1.0)
    v, k, draws = 24, 3, 4000
    lrng = np.random.default_rng(3)
    base_logits = jnp.asarray(lrng.normal(0.0, 1.5, size=(1, k + 1, v)),
                              jnp.float32)
    logits = jnp.tile(base_logits, (draws, 1, 1))
    draft = jnp.tile(jnp.asarray([[1, 2, 3]], jnp.int32), (draws, 1))
    packed, _ = rejection_select(logits, draft, jax.random.PRNGKey(0),
                                 sample_cfg=sample_cfg)
    first = np.asarray(packed)[:, 1]
    spec_freq = np.bincount(first, minlength=v) / draws
    filt = _filter_logits(base_logits[0, :1], temperature=sample_cfg[0],
                          top_k=sample_cfg[1], top_p=sample_cfg[2])
    target_p = np.asarray(jax.nn.softmax(filt[0]))
    tv = 0.5 * float(np.abs(spec_freq - target_p).sum())
    # TV between an empirical 4000-draw histogram and its own source
    # distribution concentrates around ~sqrt(V/(2*pi*N)) ~= 0.03; 0.08 is
    # a >5-sigma band — failures mean the sampler is biased, not unlucky
    assert tv < 0.08, \
        f"rejection-sampler marginal drifted from the filtered target: TV={tv:.4f}"

    # ---- (d) health section + serving_spec_* families agree with counters
    reg = MetricsRegistry()
    populate_from_engine(reg, spec_eng)
    fams = parse_exposition(render(reg))
    val = lambda name: fams[name]["samples"][0][2]
    assert val("dstpu_serving_spec_proposed_total") == float(
        spec_eng.counters.spec_proposed)
    assert val("dstpu_serving_spec_accepted_total") == float(
        spec_eng.counters.spec_accepted)
    assert 0.0 <= val("dstpu_serving_spec_acceptance") <= 1.0
    tpv_count = sum(v for n, _, v
                    in fams["dstpu_serving_spec_tokens_per_verify"]["samples"]
                    if n.endswith("_count"))
    assert tpv_count == float(sum(
        spec_health["tokens_per_verify"].values())), \
        (tpv_count, spec_health["tokens_per_verify"])
    # spec OFF keeps the exposition byte-identical: no spec families at all
    reg_off = MetricsRegistry()
    populate_from_engine(reg_off, mk(False))
    assert not any("spec" in name for name in reg_off.families), \
        [n for n in reg_off.families if "spec" in n]

    signal.alarm(0)
    print(json.dumps({
        "spec_decode_smoke": "ok",
        "spec_rounds": spec_health["rounds_total"],
        "acceptance_rate": spec_health["acceptance_rate"],
        "injected_failures": spec_eng.manager.allocator.injected_failures,
        "sampler_tv_distance": round(tv, 4)}))
    return 0


def run_bench_diff_lane():
    """bench regression gate (ISSUE 16): the committed BENCH_r04->r05 pair
    must pass (timed-out r04 carries zero metrics -> all-missing verdicts,
    never a failure), and an injected-regression fixture must exit 1 — both
    via the standalone bin/dstpu-benchdiff CLI (same loading discipline as
    the lint lane: works even when the library is broken at import time)."""
    import os
    import tempfile
    t0 = time.time()
    root = os.path.dirname(os.path.abspath(__file__))
    cli = os.path.join(root, "bin", "dstpu-benchdiff")
    committed = subprocess.run(
        [sys.executable, cli, os.path.join(root, "BENCH_r04.json"),
         os.path.join(root, "BENCH_r05.json"),
         "--policy", os.path.join(root, "benchtrack.json")],
        capture_output=True, text=True)
    # injected regression: candidate = r05's metrics with the serving
    # throughput cut 30% — must trip the gate
    from deepspeed_tpu.tools.benchtrack.diffcore import load_bench
    metrics = dict(load_bench(os.path.join(root, "BENCH_r05.json"))["metrics"])
    degraded = dict(metrics)
    degraded["serving_mixed_tok_s"] = metrics.get("serving_mixed_tok_s", 100.0) * 0.7
    tmp = tempfile.mkdtemp(prefix="dstpu_benchdiff_")
    base_p = os.path.join(tmp, "base.json")
    cand_p = os.path.join(tmp, "degraded.json")
    json.dump(metrics, open(base_p, "w"))
    json.dump(degraded, open(cand_p, "w"))
    injected = subprocess.run(
        [sys.executable, cli, base_p, cand_p,
         "--policy", os.path.join(root, "benchtrack.json")],
        capture_output=True, text=True)
    dt = time.time() - t0
    ok = committed.returncode == 0 and injected.returncode == 1
    tail = (f"committed pair rc={committed.returncode} (want 0), "
            f"injected regression rc={injected.returncode} (want 1)")
    print(f"[bench_diff] {tail}  ({dt:.0f}s)")
    if not ok:
        print(committed.stdout[-2000:])
        print(injected.stdout[-2000:])
        print(committed.stderr[-1000:], file=sys.stderr)
        print(injected.stderr[-1000:], file=sys.stderr)
    return {"name": "bench_diff", "rc": 0 if ok else 1, "seconds": round(dt, 1),
            "summary": tail}


def run_smoke_lane(name: str, flag: str):
    """Run one of the smoke entry points as its own recorded lane (subprocess:
    each smoke pins its own env and must not contaminate the pytest lanes)."""
    t0 = time.time()
    proc = subprocess.run([sys.executable, __file__, flag], capture_output=True, text=True)
    dt = time.time() - t0
    tail = (proc.stdout.strip().splitlines() or [""])[-1]
    print(f"[{name}] {tail}  ({dt:.0f}s)")
    if proc.returncode != 0:
        print(proc.stdout[-2000:])
        print(proc.stderr[-2000:], file=sys.stderr)
    return {"name": name, "rc": proc.returncode, "seconds": round(dt, 1), "summary": tail}


def run_lane(name: str, marker_args):
    t0 = time.time()
    # --continue-on-collection-errors matches the tier-1 verify invocation:
    # a module that won't import (e.g. jax API drift) is counted as an error
    # without dead-stopping the whole lane
    proc = subprocess.run([sys.executable, "-m", "pytest", "tests/", "-q",
                           "--continue-on-collection-errors", *marker_args],
                          capture_output=True, text=True)
    dt = time.time() - t0
    tail = (proc.stdout.strip().splitlines() or [""])[-1]
    counts = {k: int(v) for v, k in re.findall(r"(\d+) (passed|failed|error|skipped|deselected)", tail)}
    print(f"[{name}] {tail}  ({dt:.0f}s)")
    if proc.returncode != 0:
        print(proc.stdout[-4000:])
        print(proc.stderr[-2000:], file=sys.stderr)
    return {"name": name, "rc": proc.returncode, "seconds": round(dt, 1),
            "summary": tail, **counts}


def run_lint_lane():
    """dslint over the whole package AND tests/ (ISSUE 3 + ISSUE 10): fails CI
    on any non-baselined finding.  tests/ is scanned by the test-scoped rules
    only (direct-shimmed-import), so a drifted test import is a lint error
    instead of a silent collection failure.  Subprocesses bin/dstpu-lint (which
    loads the pure-AST analyzer standalone, never through
    deepspeed_tpu/__init__) so the lint lane still reports when the library
    itself is broken at import time — exactly when a static check is most
    wanted."""
    import os
    t0 = time.time()
    root = os.path.dirname(os.path.abspath(__file__))
    proc = subprocess.run([sys.executable, os.path.join(root, "bin", "dstpu-lint"),
                           os.path.join(root, "deepspeed_tpu"),
                           os.path.join(root, "tests"), "--root", root,
                           "--format", "json"],
                          capture_output=True, text=True)
    dt = time.time() - t0
    try:
        s = json.loads(proc.stdout)["summary"]
        tail = (f"{s['findings']} finding(s), {s['baselined']} baselined, "
                f"{s['suppressed']} suppressed over {s['files_checked']} files")
        counts = {"findings": s["findings"], "baselined": s["baselined"],
                  "suppressed": s["suppressed"]}
    except (ValueError, KeyError):
        tail = f"dstpu-lint did not produce JSON (rc={proc.returncode})"
        counts = {}
        print(proc.stdout[-2000:])
        print(proc.stderr[-2000:], file=sys.stderr)
    print(f"[lint] {tail}  ({dt:.0f}s)")
    if proc.returncode != 0 and counts:
        for f in json.loads(proc.stdout)["findings"]:
            print(f"{f['path']}:{f['line']}: [{f['rule']}] {f['message']}")
    return {"name": "lint", "rc": proc.returncode, "seconds": round(dt, 1),
            "summary": tail, **counts}


# The test files of the kernel/onebit/TP/sequence families that jax-0.4.37
# drift (shard_map / CompilerParams / axis_size / memories API) failed
# WHOLESALE before the compat/ shim (ISSUE 10).  This lane gates them
# HARD-GREEN — no "failure set identical to seed" allowance — because these
# are exactly the sharded kernels and TP paths the multichip ROADMAP items
# must regress against.
DRIFT_FAMILY_FILES = [
    "tests/unit/ops/test_flash_attention.py",
    "tests/unit/ops/test_sparse_attention.py",
    "tests/unit/ops/test_quantizer.py",
    "tests/unit/test_onebit.py",
    "tests/unit/test_sequence_parallel.py",
    "tests/unit/test_pipeline.py",
    "tests/unit/test_zeropp.py",
    "tests/unit/test_comm.py",
    "tests/unit/test_aux_subsystems.py",
    "tests/unit/test_activation_checkpointing.py",
    "tests/unit/test_multiprocess.py",
    "tests/unit/test_model_families.py",
    "tests/unit/test_tensor_parallel.py",
    "tests/unit/test_compat.py",
    "tests/unit/inference/test_inference_v1.py",
    "tests/unit/inference/test_inference_v2_tp.py",
]


def run_drift_families_lane():
    """Hard-green gate over the previously-drifted families: any failure or
    collection error here is a regression in code the compat shim re-greened
    (kernels, onebit, TP, sequence, pipeline, ZeRO++, multiprocess)."""
    t0 = time.time()
    proc = subprocess.run([sys.executable, "-m", "pytest", *DRIFT_FAMILY_FILES,
                           "-q", "-m", "not slow"],
                          capture_output=True, text=True)
    dt = time.time() - t0
    tail = (proc.stdout.strip().splitlines() or [""])[-1]
    counts = {k: int(v) for v, k in re.findall(r"(\d+) (passed|failed|error|skipped|deselected)", tail)}
    print(f"[drift_families] {tail}  ({dt:.0f}s)")
    if proc.returncode != 0:
        print(proc.stdout[-4000:])
        print(proc.stderr[-2000:], file=sys.stderr)
    return {"name": "drift_families", "rc": proc.returncode,
            "seconds": round(dt, 1), "summary": tail, **counts}


def main():
    lanes = [run_lint_lane(),
             run_smoke_lane("serving_resilience_smoke", "--serving-resilience-smoke"),
             run_smoke_lane("serving_fastpath_smoke", "--serving-fastpath-smoke"),
             run_smoke_lane("tracing_smoke", "--tracing-smoke"),
             run_smoke_lane("ops_smoke", "--ops-smoke"),
             run_smoke_lane("ops_stress", "--ops-stress-smoke"),
             run_smoke_lane("kv_obs_smoke", "--kv-obs-smoke"),
             run_smoke_lane("prefix_cache_smoke", "--prefix-cache-smoke"),
             run_smoke_lane("serving_recovery_smoke", "--serving-recovery-smoke"),
             run_smoke_lane("elastic_smoke", "--elastic-smoke"),
             run_smoke_lane("perf_smoke", "--perf-smoke"),
             run_smoke_lane("fleet_smoke", "--fleet-smoke"),
             run_smoke_lane("qos_smoke", "--qos-smoke"),
             run_smoke_lane("spec_decode_smoke", "--spec-decode-smoke"),
             run_bench_diff_lane(),
             run_drift_families_lane(),
             run_lane("default", []), run_lane("slow", ["-m", "slow"])]
    out = {"lanes": lanes, "ok": all(l["rc"] == 0 for l in lanes)}
    with open("TESTS_LANES.json", "w") as fh:
        json.dump(out, fh, indent=1)
    print(json.dumps({"lanes": {l["name"]: l.get("passed", 0) for l in lanes}, "ok": out["ok"]}))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    if "--telemetry-smoke" in sys.argv:
        sys.exit(telemetry_smoke())
    if "--resilience-smoke" in sys.argv:
        sys.exit(resilience_smoke())
    if "--serving-resilience-smoke" in sys.argv:
        sys.exit(serving_resilience_smoke())
    if "--serving-fastpath-smoke" in sys.argv:
        sys.exit(serving_fastpath_smoke())
    if "--tracing-smoke" in sys.argv:
        sys.exit(tracing_smoke())
    if "--ops-smoke" in sys.argv:
        sys.exit(ops_smoke())
    if "--ops-stress-smoke" in sys.argv:
        sys.exit(ops_stress())
    if "--kv-obs-smoke" in sys.argv:
        sys.exit(kv_obs_smoke())
    if "--prefix-cache-smoke" in sys.argv:
        sys.exit(prefix_cache_smoke())
    if "--serving-recovery-smoke" in sys.argv:
        sys.exit(serving_recovery_smoke())
    if "--elastic-smoke" in sys.argv:
        sys.exit(elastic_smoke())
    if "--perf-smoke" in sys.argv:
        sys.exit(perf_smoke())
    if "--fleet-smoke" in sys.argv:
        sys.exit(fleet_smoke())
    if "--qos-smoke" in sys.argv:
        sys.exit(qos_smoke())
    if "--spec-decode-smoke" in sys.argv:
        sys.exit(spec_decode_smoke())
    if "--bench-diff" in sys.argv:
        sys.exit(run_bench_diff_lane()["rc"])
    if "--lint" in sys.argv:
        sys.exit(run_lint_lane()["rc"])
    if "--drift-families" in sys.argv:
        sys.exit(run_drift_families_lane()["rc"])
    sys.exit(main())
