"""Communication logging.

Analog of ``CommsLogger`` (deepspeed/utils/comms_logging.py:67): per-(op, message
size) count / latency / algorithmic-bw / bus-bw accounting, summarized via
``log_summary``.  Two data sources feed it:

- host-level ops (outside jit): wall-clock latency measured around the call;
- traced collectives (inside jit/shard_map): recorded at trace time with message
  volume only (XLA schedules them; latency comes from the profiler, not here).
"""

from collections import defaultdict

from .logging import logger


def calc_bw_log(comm_op: str, size_bytes: int, duration_s: float, n: int):
    """Algorithmic and bus bandwidth in Gbps — formulas match the reference
    (utils/comms_logging.py:13 ``calc_bw_log``): busbw scales algbw by the
    ring-collective traffic factor (n-1)/n for allgather/reduce-scatter/allreduce×2."""
    duration_s = max(duration_s, 1e-12)
    tput = size_bytes / duration_s  # bytes/s
    if comm_op in ("all_gather", "reduce_scatter", "all_to_all"):
        busbw = tput * ((n - 1) / max(n, 1))
    elif comm_op == "all_reduce":
        busbw = tput * (2 * (n - 1) / max(n, 1))
    else:  # pt2pt, broadcast
        busbw = tput
    # convert to Gbps
    return tput * 8 / 1e9, busbw * 8 / 1e9


class CommsLogger:
    """Per-op/size stats store (reference utils/comms_logging.py:67)."""

    def __init__(self, enabled=False, verbose=False, prof_all=True, prof_ops=None, debug=False):
        self.enabled = enabled
        self.verbose = verbose
        self.prof_all = prof_all
        self.prof_ops = prof_ops or []
        self.debug = debug
        # comms_dict[op_name][size] = [count, [latencies], [algbw], [busbw]]
        self.comms_dict = defaultdict(lambda: defaultdict(lambda: [0, [], [], []]))
        # traced_dict[op_name][size] = trace-time occurrence count
        self.traced_dict = defaultdict(lambda: defaultdict(int))

    def configure(self, config):
        self.enabled = config.enabled
        self.verbose = config.verbose
        self.prof_all = config.prof_all
        self.prof_ops = list(config.prof_ops)
        self.debug = config.debug

    def should_profile(self, op_name: str) -> bool:
        if not self.enabled:
            return False
        return self.prof_all or op_name in self.prof_ops

    def append(self, raw_name: str, record_name: str, latency_s: float, msg_size: int, world: int):
        algbw, busbw = calc_bw_log(raw_name, msg_size, latency_s, world)
        entry = self.comms_dict[record_name][msg_size]
        entry[0] += 1
        entry[1].append(latency_s * 1000.0)
        entry[2].append(algbw)
        entry[3].append(busbw)
        if self.verbose:
            logger.info(f"comm op: {record_name} | time (ms): {latency_s*1000:.2f} | "
                        f"msg size: {msg_size} | algbw (Gbps): {algbw:.2f} | busbw (Gbps): {busbw:.2f}")

    def record_traced(self, op_name: str, msg_size: int):
        self.traced_dict[op_name][msg_size] += 1

    def as_events(self, step: int):
        """Summarize per-op stats as monitor ``(tag, value, step)`` events —
        the comms-logger → MonitorMaster bridge (the reference only prints its
        summary; here it also flows into the telemetry event stream).  One
        count/avg-latency/avg-busbw triple per op, aggregated over sizes, plus
        trace-time counts for in-graph collectives."""
        events = []
        for record_name, sizes in sorted(self.comms_dict.items()):
            count = sum(entry[0] for entry in sizes.values())
            lats = [l for entry in sizes.values() for l in entry[1]]
            bus = [b for entry in sizes.values() for b in entry[3]]
            events.append((f"Comms/{record_name}/count", float(count), step))
            if lats:
                events.append((f"Comms/{record_name}/avg_latency_ms",
                               sum(lats) / len(lats), step))
            if bus:
                events.append((f"Comms/{record_name}/avg_busbw_gbps",
                               sum(bus) / len(bus), step))
        for op, sizes in sorted(self.traced_dict.items()):
            events.append((f"Comms/traced/{op}/count",
                           float(sum(sizes.values())), step))
        return events

    def log_summary(self, show_straggler=False):
        lines = [f"{'Comm. Op':<20}{'Message Size':<20}{'Count':<10}"
                 f"{'Total Latency(ms)':<20}{'Avg Latency(ms)':<20}{'tput_avg (Gbps)':<20}{'busbw_avg (Gbps)':<20}"]
        for record_name, sizes in sorted(self.comms_dict.items()):
            lines.append(record_name)
            for size, (count, lats, alg, bus) in sorted(sizes.items()):
                total = sum(lats)
                avg = total / max(count, 1)
                lines.append(f"{'':<20}{size:<20}{count:<10}{total:<20.2f}{avg:<20.2f}"
                             f"{sum(alg)/max(len(alg),1):<20.2f}{sum(bus)/max(len(bus),1):<20.2f}")
        if self.traced_dict:
            lines.append("traced (in-graph) collectives — counts at trace time:")
            for op, sizes in sorted(self.traced_dict.items()):
                for size, count in sorted(sizes.items()):
                    lines.append(f"{'':<4}{op:<16}{size:<20}{count:<10}")
        summary = "\n".join(lines)
        logger.info("\n" + summary)
        return summary


_COMMS_LOGGER = None


def get_comms_logger() -> CommsLogger:
    global _COMMS_LOGGER
    if _COMMS_LOGGER is None:
        _COMMS_LOGGER = CommsLogger()
    return _COMMS_LOGGER
