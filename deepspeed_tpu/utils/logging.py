"""Rank-aware logging.

TPU-native analog of the reference's ``deepspeed/utils/logging.py`` (``logger``,
``log_dist``).  Rank filtering uses ``jax.process_index()`` instead of
``torch.distributed.get_rank()``.
"""

import functools
import logging
import os
import sys

LOG_LEVEL = os.environ.get("DSTPU_LOG_LEVEL", "INFO").upper()

log_levels = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


class _NoDuplicateFilter(logging.Filter):
    """Filter out exact-duplicate warn-once style records."""

    def __init__(self):
        super().__init__()
        self._seen = set()

    def filter(self, record):
        if getattr(record, "once", False):
            key = (record.levelno, record.getMessage())
            if key in self._seen:
                return False
            self._seen.add(key)
        return True


def _create_logger(name="deepspeed_tpu", level=None):
    logger_ = logging.getLogger(name)
    if logger_.handlers:
        return logger_
    level = level if level is not None else log_levels.get(LOG_LEVEL.lower(), logging.INFO)
    logger_.setLevel(level)
    logger_.propagate = False
    handler = logging.StreamHandler(stream=sys.stdout)
    handler.setFormatter(
        logging.Formatter(
            "[%(asctime)s] [%(levelname)s] [%(name)s:%(lineno)d] %(message)s",
            datefmt="%Y-%m-%d %H:%M:%S",
        ))
    logger_.addHandler(handler)
    logger_.addFilter(_NoDuplicateFilter())
    return logger_


logger = _create_logger()


def _env_rank():
    return int(os.environ.get("RANK", os.environ.get("JAX_PROCESS_INDEX", "0")))


# Overridden by comm.init_distributed once the backend is up; reading the env
# before then avoids forcing jax backend initialization from a log call (and
# avoids caching a pre-init rank for the process lifetime).
_rank_provider = _env_rank


def set_rank_provider(fn):
    global _rank_provider
    _rank_provider = fn


def _process_index():
    try:
        return _rank_provider()
    except Exception:
        return 0


def log_dist(message, ranks=None, level=logging.INFO):
    """Log ``message`` only on the given process indices (None or [-1] = all).

    Mirrors the reference ``log_dist`` contract (deepspeed/utils/logging.py:108):
    rank filtering against the distributed rank; here the host process index.
    """
    my_rank = _process_index()
    if ranks is None or -1 in ranks or my_rank in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")


def warning_once(message):
    logger.warning(message, extra={"once": True})


def print_rank_0(message):
    if _process_index() == 0:
        logger.info(message)
