from .logging import logger, log_dist, print_rank_0, warning_once
from .memory import device_memory_stats, live_array_census, see_memory_usage
from .tensor_fragment import (safe_get_full_fp32_param, safe_get_full_grad,
                              safe_get_full_optimizer_state, safe_set_full_fp32_param)
