"""Tensor fragment API — stable access to (possibly sharded) optimizer state.

Analog of the reference tensor-fragment helpers
(deepspeed/utils/tensor_fragment.py: safe_get_full_fp32_param:101,
safe_set_full_fp32_param:117, safe_get_full_grad:168, local variants :189-204):
the reference walks ZeRO partitions and flat buffers; here state lives as a
sharded pytree, so "full" access is a gather via replicated out-sharding and
"set" is a device_put back with the leaf's own sharding.  Paths use the
dotted checkpoint key convention (e.g. "layers.attn.wq").
"""

from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec


def _resolve(tree, dotted: str):
    node = tree
    for part in dotted.split("."):
        if isinstance(node, tuple) and hasattr(node, "_fields") and part in node._fields:
            node = getattr(node, part)  # NamedTuple states (optimizer moments)
        elif isinstance(node, (list, tuple)):
            node = node[int(part)]
        elif isinstance(node, dict):
            if part not in node:
                raise KeyError(f"path component '{part}' not in {sorted(node)}")
            node = node[part]
        else:
            node = getattr(node, part)
    return node


def _set(tree, dotted: str, value):
    parts = dotted.split(".")
    node = tree
    for part in parts[:-1]:
        node = node[int(part)] if isinstance(node, (list, tuple)) else node[part]
    last = parts[-1]
    if isinstance(node, list):
        node[int(last)] = value
    else:
        node[last] = value


def _gather_full(leaf) -> np.ndarray:
    if isinstance(leaf, jax.Array) and len(leaf.sharding.device_set) > 1:
        rep = NamedSharding(leaf.sharding.mesh, PartitionSpec())
        leaf = jax.device_put(leaf, rep)
    return np.asarray(leaf)  # dslint: disable=sharding-dropped-at-boundary  # deliberate collapse: the debug/API contract of safe_get_* is a full host ndarray — replicate-then-fetch is the point


def safe_get_full_fp32_param(engine, param_path: str) -> Optional[np.ndarray]:
    """Gather one fp32 master parameter to host (reference :101)."""
    if engine.offload_device is not None:
        return _resolve(engine._offload_host_state()["params"], param_path)
    return _gather_full(_resolve(engine.state.params, param_path))


def safe_set_full_fp32_param(engine, param_path: str, value) -> None:
    """Overwrite one fp32 master parameter, preserving its sharding (reference :117)."""
    value = np.asarray(value, np.float32)
    if engine.offload_device is not None:
        key = param_path
        flat = engine._offload_state.params
        if key not in flat:
            raise KeyError(f"{key} not in offloaded params: {sorted(flat)[:8]}...")
        flat[key][...] = value.ravel()
        engine._push_compute_params()
        return
    leaf = _resolve(engine.state.params, param_path)
    if tuple(np.shape(leaf)) != value.shape:
        raise ValueError(f"shape mismatch for {param_path}: {value.shape} vs {np.shape(leaf)}")
    new_leaf = jax.device_put(value, leaf.sharding)
    params = jax.tree_util.tree_map(lambda x: x, engine.state.params)  # shallow copy tree
    _set(params, param_path, new_leaf)
    engine.state = engine.state._replace(params=params)


def safe_get_full_optimizer_state(engine, param_path: str, state_name: str) -> Optional[np.ndarray]:
    """Gather one optimizer moment ('exp_avg'/'exp_avg_sq') (reference :134).

    Quantized optimizers return the DEQUANTIZED fp32 moment in the param's
    shape — the reference API contract is a torch-tensor-shaped moment, not
    the raw storage (ADVICE r3 #1): fused_adam8bit's int8 (groups, group_size)
    blocks decode through ops/adam/adam8bit.dequantize_moments (v is stored in
    the sqrt domain and squared back here)."""
    if engine.offload_device is not None:
        sd = engine._offload_state.state_dict()
        key = {"exp_avg": "m", "exp_avg_sq": "v"}[state_name]
        return sd[key][param_path].copy()
    opt_state = engine.state.opt_state
    if type(opt_state).__name__ == "Adam8bitState" and state_name in ("exp_avg", "exp_avg_sq"):
        from ..ops.adam.adam8bit import dequantize_moments
        param = _resolve(engine.state.params, param_path)
        n = int(np.prod(np.shape(param))) if np.shape(param) else 1
        m8 = _gather_full(_resolve(opt_state.exp_avg, param_path))
        v8 = _gather_full(_resolve(opt_state.exp_avg_sq, param_path))
        sm = _gather_full(_resolve(opt_state.scale_m, param_path))
        sv = _gather_full(_resolve(opt_state.scale_v, param_path))
        m, v = dequantize_moments(jax.numpy.asarray(m8), jax.numpy.asarray(v8),
                                  jax.numpy.asarray(sm), jax.numpy.asarray(sv), n)
        out = m if state_name == "exp_avg" else v
        return np.asarray(out).reshape(np.shape(param))
    moments = _resolve(opt_state, state_name)
    return _gather_full(_resolve(moments, param_path))


def safe_get_full_grad(engine, param_path: str) -> Optional[np.ndarray]:
    """Reference :168 — gradients are transient inside the compiled step, so
    this exposes the LAST step's gradient only when grad capture is enabled via
    engine config (see Engine.capture_grads)."""
    grads = getattr(engine, "_last_grads", None)
    if grads is None:
        return None
    return _gather_full(_resolve(grads, param_path))
