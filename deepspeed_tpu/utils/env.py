"""Tolerant environment-variable number parsing.

The elastic agent drives its workers through an env contract
(``DSTPU_HEARTBEAT_INTERVAL_S``, ``DSTPU_COLLECTIVE_TIMEOUT_S``,
``DSTPU_INIT_RETRIES``, ...).  Every consumer wants the same semantics: unset
or empty means "use the default", garbage means "warn once and use the
default" — a malformed env var must degrade supervision, never crash a
worker.  One helper so the parse sites can't drift apart.
"""

from typing import Callable, Optional, TypeVar

from .logging import warning_once

T = TypeVar("T")


def _env_number(name: str, default: Optional[T], cast: Callable[[str], T],
                warn: bool) -> Optional[T]:
    import os
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return cast(raw)
    except ValueError:
        if warn:
            warning_once(f"env: bad {name}={raw!r} (not a {cast.__name__}); "
                         f"using default {default!r}")
        return default


def env_float(name: str, default: Optional[float] = None,
              warn: bool = True) -> Optional[float]:
    return _env_number(name, default, float, warn)


def env_int(name: str, default: Optional[int] = None,
            warn: bool = True) -> Optional[int]:
    return _env_number(name, default, int, warn)
