"""Device-memory introspection.

Analog of ``see_memory_usage`` (deepspeed/runtime/utils.py:835): the reference
reads torch.cuda allocator counters; here the source of truth is the device's
``memory_stats()`` (HBM bytes_in_use / peak_bytes_in_use / bytes_limit) plus a
``jax.live_arrays()`` census standing in for the reference's "MA/CA" allocator
split — on XLA the live-array view is the part of HBM the *framework* can name,
the rest is compiler temp/fragmentation.

Null-safe on backends without memory instrumentation (CPU ``memory_stats()``
returns None): stats fields come back as None and the census still reports.
"""

from typing import Any, Dict, Optional

from .logging import log_dist

# memory_stats() keys surfaced in telemetry records and see_memory_usage lines
HBM_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")


def device_memory_stats(device_index: int = 0) -> Dict[str, Optional[int]]:
    """HBM counters for one local device, with every key present and None where
    the backend has no instrumentation (CPU) — callers never need to branch."""
    import jax
    try:
        raw = jax.local_devices()[device_index].memory_stats() or {}
    except Exception:
        raw = {}
    return {k: (int(raw[k]) if k in raw else None) for k in HBM_KEYS}


def live_array_census() -> Dict[str, int]:
    """Count and total bytes of arrays the framework holds alive (the analog of
    the reference's torch 'memory allocated'; XLA temps are invisible here)."""
    import jax
    count = 0
    nbytes = 0
    for a in jax.live_arrays():
        count += 1
        nbytes += int(getattr(a, "nbytes", 0) or 0)
    return {"live_arrays": count, "live_array_bytes": nbytes}


def _gb(n: Optional[int]) -> str:
    return "n/a" if n is None else f"{n / 2**30:.2f}GB"


def see_memory_usage(message: str, force: bool = True, device_index: int = 0) -> Dict[str, Any]:
    """Log a one-line memory snapshot tagged ``message`` (reference
    see_memory_usage prints MA/Max_MA/CA/Max_CA) and return it as a dict:
    ``{bytes_in_use, peak_bytes_in_use, bytes_limit, live_arrays,
    live_array_bytes}``."""
    snap: Dict[str, Any] = dict(device_memory_stats(device_index))
    snap.update(live_array_census())
    if force:
        log_dist(
            f"{message} | HBM in_use={_gb(snap['bytes_in_use'])} "
            f"peak={_gb(snap['peak_bytes_in_use'])} limit={_gb(snap['bytes_limit'])} "
            f"| live arrays: {snap['live_arrays']} ({_gb(snap['live_array_bytes'])})",
            ranks=[0])
    return snap
