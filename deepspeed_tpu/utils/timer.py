"""Wall-clock and throughput timers.

Analog of deepspeed/utils/timer.py (``SynchronizedWallClockTimer:43``,
``ThroughputTimer:198``, ``NoopTimer:163``).  The reference synchronizes CUDA
events; XLA dispatch is async so we synchronize by blocking on a trivial device
computation before reading the clock.
"""

import time
from typing import Dict, List, Optional

from .logging import log_dist, warning_once


def _device_sync():
    try:
        import jax
        import jax.numpy as jnp
        jnp.zeros(()).block_until_ready()
    except Exception as exc:  # no backend: timers read the clock unsynchronized
        warning_once(f"timer: device sync unavailable ({exc!r}); wall-clock "
                     f"readings will not include in-flight device work")


class _Timer:

    def __init__(self, name):
        self.name = name
        self.started = False
        self.start_time = 0.0
        self.elapsed_ms = 0.0
        self.count = 0

    def start(self, sync=False):
        if sync:
            _device_sync()
        self.start_time = time.perf_counter()
        self.started = True

    def stop(self, sync=False):
        if not self.started:
            return
        if sync:
            _device_sync()
        self.elapsed_ms += (time.perf_counter() - self.start_time) * 1000.0
        self.count += 1
        self.started = False

    def elapsed(self, reset=True):
        value = self.elapsed_ms
        if reset:
            self.elapsed_ms = 0.0
            self.count = 0
        return value

    def mean(self):
        return self.elapsed_ms / max(self.count, 1)


class SynchronizedWallClockTimer:
    """Named-timer registry (reference utils/timer.py:43)."""

    def __init__(self):
        self.timers: Dict[str, _Timer] = {}

    def __call__(self, name) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def log(self, names: List[str], normalizer: float = 1.0, reset: bool = True):
        parts = []
        for name in names:
            if name in self.timers:
                ms = self.timers[name].elapsed(reset=reset) / max(normalizer, 1e-9)
                parts.append(f"{name}: {ms:.2f}")
        if parts:
            log_dist("time (ms) | " + " | ".join(parts), ranks=[0])


class NoopTimer:

    class _N:

        def start(self, *a, **k):
            pass

        def stop(self, *a, **k):
            pass

        def elapsed(self, *a, **k):
            return 0.0

    def __call__(self, name):
        return self._N()

    def log(self, *a, **k):
        pass


class ThroughputTimer:
    """Samples/sec tracker (reference utils/timer.py:198)."""

    def __init__(self, batch_size: int, start_step: int = 2):
        self.batch_size = max(batch_size, 1)
        self.start_step = start_step
        self.step_count = 0
        self.total_elapsed = 0.0
        self._t0: Optional[float] = None

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self) -> Optional[float]:
        if self._t0 is None:
            return None
        _device_sync()
        dt = time.perf_counter() - self._t0
        self._t0 = None
        self.step_count += 1
        if self.step_count > self.start_step:  # skip compile-dominated steps
            self.total_elapsed += dt
        return dt

    def avg_samples_per_sec(self) -> float:
        counted = self.step_count - self.start_step
        if counted <= 0 or self.total_elapsed == 0:
            return 0.0
        return counted * self.batch_size / self.total_elapsed
