"""Durable-IO primitives + CRC-framed write-ahead-log helpers.

One implementation of the crash-safety idioms two subsystems share:

- ``runtime/checkpointing.py`` (PR 2): atomic text writes (stage + fsync +
  rename), file/dir fsync, and whole-file CRC32 for the per-leaf manifest.
- ``inference/v2/journal.py`` (PR 8): an append-only request WAL whose frames
  carry their own length + CRC32, so a reader can replay a journal that died
  mid-append by truncating at the first bad frame instead of refusing the
  whole file.

Frame layout (little-endian): ``MAGIC(4) | payload_len u32 | crc32 u32 |
payload``.  A frame is valid iff the magic matches, the payload is fully
present, and its CRC32 matches.  The FIRST invalid frame ends the scan —
everything after a torn/corrupt frame is unreachable by construction (frame
boundaries can't be re-synchronized reliably once one length field is
garbage), which is exactly the semantics an append-only log wants: the tail
that wasn't durably written never happened.

All host-side stdlib; nothing here imports jax/numpy.
"""

import os
import struct
import zlib
from typing import Iterator, List, Optional, Tuple

FRAME_MAGIC = b"DSWL"
_HEADER = struct.Struct("<4sII")  # magic, payload length, payload crc32
HEADER_SIZE = _HEADER.size


# --------------------------------------------------------------- durable IO
def fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # fs without directory fds (or non-POSIX); rename is still atomic
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_text(path: str, text: str) -> None:
    """Stage + fsync + rename so readers never observe a partial file."""
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(text)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path) or ".")


def file_crc32(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as fh:
        while True:
            block = fh.read(chunk)
            if not block:
                return crc
            crc = zlib.crc32(block, crc)


# ------------------------------------------------------------------- frames
def encode_frame(payload: bytes) -> bytes:
    """One self-validating frame: header (magic + length + CRC32) + payload."""
    return _HEADER.pack(FRAME_MAGIC, len(payload), zlib.crc32(payload)) + payload


def append_frame(fh, payload: bytes) -> int:
    """Append one frame to an open binary file object; returns bytes written.
    The caller owns flush/fsync policy (a WAL batches those per its own
    durability knob)."""
    data = encode_frame(payload)
    fh.write(data)
    return len(data)


def iter_frames(data: bytes) -> Iterator[Tuple[bytes, int]]:
    """Yield ``(payload, end_offset)`` for each valid frame prefix of
    ``data``; stops silently at the first invalid frame (torn tail, bit
    flip, foreign bytes)."""
    off = 0
    n = len(data)
    while off + HEADER_SIZE <= n:
        magic, length, crc = _HEADER.unpack_from(data, off)
        if magic != FRAME_MAGIC:
            return
        end = off + HEADER_SIZE + length
        if end > n:
            return  # torn tail: the payload never fully landed
        payload = data[off + HEADER_SIZE:end]
        if zlib.crc32(payload) != crc:
            return  # bit flip / partial overwrite inside the payload
        yield payload, end
        off = end


def scan_frames(path: str) -> Tuple[List[bytes], int, Optional[str]]:
    """Read every valid frame of ``path``.

    Returns ``(payloads, good_size, tail_error)``: ``good_size`` is the byte
    offset just past the last valid frame, and ``tail_error`` describes the
    invalid tail (None when the file ends exactly on a frame boundary).
    A missing file reads as an empty log.
    """
    try:
        with open(path, "rb") as fh:
            data = fh.read()
    except FileNotFoundError:
        return [], 0, None
    payloads: List[bytes] = []
    good = 0
    for payload, end in iter_frames(data):
        payloads.append(payload)
        good = end
    if good == len(data):
        return payloads, good, None
    bad = len(data) - good
    if bad < HEADER_SIZE:
        detail = f"{bad} trailing byte(s) — torn header"
    else:
        magic = data[good:good + 4]
        detail = ("torn or corrupt frame" if magic == FRAME_MAGIC
                  else f"bad magic {magic!r}")
    return payloads, good, f"{detail} at offset {good} ({bad} byte(s) dropped)"


def truncate_torn_tail(path: str) -> Optional[str]:
    """Physically truncate ``path`` at the last valid frame boundary (the
    PR-2 resume-from-latest-valid move applied to a log file): a writer
    reopening the journal in append mode then extends a clean prefix instead
    of burying the torn bytes under new frames — which would make every
    later record unreachable to scans.  Returns the tail description when a
    truncation happened, None when the file was already clean/missing."""
    _, good, tail_error = scan_frames(path)
    if tail_error is None:
        return None
    with open(path, "rb+") as fh:
        fh.truncate(good)
        fh.flush()
        os.fsync(fh.fileno())
    return tail_error
