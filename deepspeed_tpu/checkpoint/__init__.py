"""Checkpoint conversion tools (reference deepspeed/checkpoint/)."""
from .universal import ds_to_universal, load_universal, zero_to_fp32
