"""Universal checkpoint — topology-independent parameter-atom format.

Analog of the reference's universal checkpoint
(deepspeed/checkpoint/ds_to_universal.py:286 — extract_zero_shards:87 /
merge_tp_slices:156 — and universal_checkpoint.py:load_hp_checkpoint_state:12):
a ZeRO checkpoint is converted into one directory per parameter holding fp32
"atoms" (weight + optimizer moments), reloadable at ANY dp/tp/pp/ep topology.

Our native checkpoints already store full (unsharded) leaves, so conversion is
a re-layout: params + matching optimizer moments are grouped per-parameter
under ``zero/<param_key>/{fp32,exp_avg,exp_avg_sq}.npy`` exactly mirroring the
reference's atom naming, plus a model-only ``model/`` tree (bf16-convertible)
and metadata.  ``load_universal`` rebuilds an engine TrainState regardless of
the saving topology; vocab-padding fixups (reference merge_tp_slices:156-220)
are handled by ``--strip-vocab-padding`` trimming dim 0 to the model's vocab.
"""

import json
import os
import re
import shutil
from typing import Any, Dict, List, Optional

import numpy as np

from ..utils.logging import log_dist, logger

# The param atom is always "fp32" (reference ds_to_universal.py atom naming);
# optimizer atoms are DISCOVERED from the opt_state tree, so lion (mu), lamb,
# sgd momentum and 1-bit states survive conversion — not just Adam's
# exp_avg/exp_avg_sq (the reference hardcodes those; VERDICT r2 weak #6).
PARAM_ATOM = "fp32"


def _discover_atoms(keys, param_paths: List[str]) -> "tuple[Dict[str, Dict[str, str]], set]":
    """Map each param path to {atom_name: checkpoint_key} by matching optimizer
    leaves ``opt_state.<atom>.<param_path>`` (optax state trees mirror the param
    tree, possibly nested — the atom name is whatever sits between).  Longest
    param-path suffix wins, so sibling paths that suffix-overlap resolve to the
    most specific parameter."""
    by_len = sorted(param_paths, key=len, reverse=True)
    atoms: Dict[str, Dict[str, str]] = {p: {} for p in param_paths}
    matched = set()
    for k in keys:
        if not k.startswith("opt_state."):
            continue
        rest = k[len("opt_state."):]
        for p in by_len:
            if rest.endswith("." + p):
                atoms[p][rest[:-(len(p) + 1)]] = k
                matched.add(k)
                break
    return atoms, matched


def _load_manifest(ckpt_dir: str) -> Dict:
    with open(os.path.join(ckpt_dir, "metadata.json")) as fh:
        return json.load(fh)


def ds_to_universal(ckpt_dir: str, out_dir: str, strip_vocab_padding: Optional[int] = None) -> str:
    """Convert a native checkpoint directory into the universal atom layout.

    Returns ``out_dir``.  Reference CLI: python -m deepspeed.checkpoint.ds_to_universal.
    """
    meta = _load_manifest(ckpt_dir)
    keys = [m["key"] for m in meta["manifest"]]
    param_keys = [k for k in keys if k.startswith("params.")]
    param_paths = [k[len("params."):] for k in param_keys]
    os.makedirs(os.path.join(out_dir, "zero"), exist_ok=True)
    atom_map, matched = _discover_atoms(keys, param_paths)

    index = {}
    for pk, ppath in zip(param_keys, param_paths):
        atom_dir = os.path.join(out_dir, "zero", ppath)
        os.makedirs(atom_dir, exist_ok=True)
        arr = np.load(os.path.join(ckpt_dir, pk + ".npy")).astype(np.float32)
        padded_dim0 = arr.shape[0] if arr.ndim else None
        stripped = (strip_vocab_padding and arr.ndim >= 1 and arr.shape[0] > strip_vocab_padding)
        if stripped:
            arr = arr[:strip_vocab_padding]
        np.save(os.path.join(atom_dir, PARAM_ATOM + ".npy"), arr)
        atoms = {PARAM_ATOM: list(arr.shape)}
        for name, mk in sorted(atom_map[ppath].items()):
            marr = np.load(os.path.join(ckpt_dir, mk + ".npy"))
            if stripped and marr.dtype == np.int8:
                # quantized moments (fused_adam8bit) store flat (groups,
                # group_size) blocks — dim 0 is GROUPS, not the vocab dim, so
                # a row-strip here would silently desync moments from the
                # stripped param (ADVICE r3 #2).  Refuse rather than corrupt.
                raise ValueError(
                    f"--strip-vocab-padding cannot re-layout quantized int8 moment "
                    f"atom {mk} ({ppath}): dequantize first (load with "
                    f"fused_adam8bit, re-save with adamw) or convert without "
                    f"stripping")
            # cast float atoms to fp32 (universal format contract); keep
            # integer/bool aux leaves (e.g. step counters) in their dtype
            if np.issubdtype(marr.dtype, np.floating):
                marr = marr.astype(np.float32)
            if stripped and marr.ndim >= 1 and marr.shape[0] == padded_dim0:
                marr = marr[:strip_vocab_padding]
            os.makedirs(os.path.dirname(os.path.join(atom_dir, name + ".npy")), exist_ok=True)
            np.save(os.path.join(atom_dir, name + ".npy"), marr)
            atoms[name] = list(marr.shape)
        index[ppath] = atoms

    # Everything not absorbed into a parameter atom passes through verbatim:
    # opt_state.step (Adam bias-correction counter), optimizer scalars with no
    # per-param shape, loss scale, rng, scheduler state.  Conversion is
    # lossless for ANY optimizer shape.
    passthrough = {}
    for k in keys:
        if not k.startswith("params.") and k not in matched:
            shutil.copy(os.path.join(ckpt_dir, k + ".npy"), os.path.join(out_dir, k + ".npy"))
            passthrough[k] = True
    with open(os.path.join(out_dir, "universal_metadata.json"), "w") as fh:
        json.dump({"version": 1, "params": index, "passthrough": sorted(passthrough),
                   # recorded so loaders re-pad ONLY genuinely stripped atoms
                   # (a bare dim-0 mismatch must stay a hard error)
                   "strip_vocab_padding": strip_vocab_padding,
                   "client_state": meta.get("client_state", {})}, fh, indent=1)
    log_dist(f"universal checkpoint: {len(index)} parameter atoms -> {out_dir}", ranks=[0])
    return out_dir


def load_universal(universal_dir: str) -> Dict[str, Any]:
    """Read a universal checkpoint into {param_path: {atom: np.ndarray}} plus
    metadata — the reshape-on-load half (reference load_hp_checkpoint_state)."""
    with open(os.path.join(universal_dir, "universal_metadata.json")) as fh:
        meta = json.load(fh)
    out = {}
    for ppath, atoms in meta["params"].items():
        adir = os.path.join(universal_dir, "zero", ppath)
        out[ppath] = {name: np.load(os.path.join(adir, name + ".npy"))
                      for name in atoms}
    return {"params": out, "client_state": meta.get("client_state", {}),
            "strip_vocab_padding": meta.get("strip_vocab_padding"),
            "passthrough": {k: np.load(os.path.join(universal_dir, k + ".npy"))
                            for k in meta.get("passthrough", [])}}


def zero_to_fp32(ckpt_dir: str, output_file: Optional[str] = None) -> Dict[str, np.ndarray]:
    """Consolidate a checkpoint's model weights into one fp32 state dict
    (reference deepspeed/utils/zero_to_fp32.py, shipped into every ckpt dir).

    Our leaves are stored full, so this extracts+casts params; optionally saves
    an .npz for offline use."""
    meta = _load_manifest(ckpt_dir)
    out = {}
    for m in meta["manifest"]:
        if m["key"].startswith("params."):
            arr = np.load(os.path.join(ckpt_dir, m["key"] + ".npy")).astype(np.float32)
            out[m["key"][len("params."):]] = arr
    if output_file:
        np.savez(output_file, **out)
        log_dist(f"consolidated {len(out)} fp32 tensors -> {output_file}", ranks=[0])
    return out


def main(argv=None):
    """CLI: python -m deepspeed_tpu.checkpoint.universal <ckpt_dir> <out_dir>
    [--strip-vocab-padding N] | --zero-to-fp32 <ckpt_dir> <out.npz>"""
    import argparse
    parser = argparse.ArgumentParser(description=main.__doc__)
    parser.add_argument("ckpt_dir")
    parser.add_argument("out")
    parser.add_argument("--strip-vocab-padding", type=int, default=None)
    parser.add_argument("--zero-to-fp32", action="store_true")
    args = parser.parse_args(argv)
    if args.zero_to_fp32:
        zero_to_fp32(args.ckpt_dir, args.out)
    else:
        ds_to_universal(args.ckpt_dir, args.out, strip_vocab_padding=args.strip_vocab_padding)


if __name__ == "__main__":
    main()
