"""Fallback implementations for shimmed symbols with no old-jax spelling.

Most drifted symbols are pure renames (``TPUCompilerParams`` →
``CompilerParams``) and resolve to whichever attribute the installed jax
ships.  A few NEW symbols have no importable pre-drift equivalent at all —
for those, ``SHIMMED_SYMBOLS`` lists this module as the last candidate, so
resolution degrades to a behavior-compatible reimplementation instead of an
ImportError.  Keep each fallback tiny and written against the OLD jax only
(the new jax never reaches it: its native spelling resolves first).
"""

from jax import lax


def axis_size(axis_name):
    """``jax.lax.axis_size`` for pre-0.6 jax: the canonical ``psum(1, axis)``
    idiom — constant-folds to a static int under shard_map, so callers can
    keep using the result in shapes/reshapes."""
    return lax.psum(1, axis_name)


class _SpaceMeta(type):
    """Lazy members: resolving a memory kind queries the backend's devices,
    which must not happen at import time (tests pin JAX_PLATFORMS after
    import; eager resolution would initialize the wrong backend)."""

    _cache = {}

    def _kind(cls, want, fallback_to_default):
        key = (want, fallback_to_default)
        if key not in cls._cache:
            import jax
            dev = jax.local_devices()[0]
            kinds = {m.kind for m in dev.addressable_memories()}
            kind = want if want in kinds else dev.default_memory().kind
            from jax._src.sharding_impls import TransferToMemoryKind
            cls._cache[key] = TransferToMemoryKind(kind)
        return cls._cache[key]

    @property
    def Host(cls):
        return cls._kind("pinned_host", True)

    @property
    def Device(cls):
        return cls._kind("device", True)


class Space(metaclass=_SpaceMeta):
    """``jax.memory.Space`` for pre-memories-API jax: ``Host``/``Device``
    resolve to ``TransferToMemoryKind`` placements — legal as ``device_put``
    targets INSIDE jit only (old jax's restriction), which is exactly where
    activation offload runs (the engine's train step is jitted; an eager
    ``device_put(x, Space.Host)`` raises on old jax).  On backends with a
    single memory space (CPU: only ``unpinned_host``) both members resolve to
    the same kind, so offload degrades to a pass-through copy with identical
    math."""
