"""Versioned-import shim over the drifted jax/Pallas API surface.

The reference DeepSpeed survives CUDA/torch version skew through its
accelerator + op_builder indirection (SURVEY §L0): kernels never import a
vendor API directly, they ask the abstraction layer.  This package is the
jax_graft equivalent for the *jax* API surface: every symbol whose import
path or signature has drifted across the jax versions we support is exported
from here, resolved against whatever the installed jax actually ships, and
**dslint enforces** (rule ``direct-shimmed-import``) that nothing outside
``compat/`` spells the underlying paths — so the next upstream rename lands
as one edit to ``SHIMMED_SYMBOLS`` plus one lint report naming call sites,
instead of 41 red tests across the kernel/onebit/TP/sequence families.

Shimmed today (jax 0.4.x ←→ 0.5/0.6+):

- ``shard_map`` — moved from ``jax.experimental.shard_map`` to top-level
  ``jax.shard_map``; the replication-check kwarg was renamed
  ``check_rep`` → ``check_vma``.  Exported as a signature-normalizing
  wrapper: call it with ``check_vma=`` everywhere and the shim translates
  for whichever implementation resolved.
- ``CompilerParams`` — Pallas-TPU compiler params, renamed from
  ``pltpu.TPUCompilerParams`` to ``pltpu.CompilerParams``.
- ``axis_size`` — ``jax.lax.axis_size`` is new-jax-only; old jax falls back
  to the behavior-compatible ``psum(1, axis)`` reimplementation in
  ``compat/_fallbacks.py``.
- ``Space`` — the ``jax.memory.Space`` memories enum; old jax falls back to
  lazily-resolved ``TransferToMemoryKind`` placements (see
  ``compat/_fallbacks.py``).

How to add a shimmed symbol (see README "Compatibility & drift policy"):

1. add a ``SHIMMED_SYMBOLS`` entry: exported name → tuple of
   ``"module:attr"`` candidates, NEWEST spelling first (first hit wins);
2. export it below (plain ``resolve_symbol`` binding, or a wrapper when the
   *signature* drifted too, like ``shard_map``);
3. port the call sites — ``dstpu-lint`` now flags every direct spelling of
   any candidate path, inside ``deepspeed_tpu/`` and ``tests/`` alike;
4. add resolution tests to ``tests/unit/test_compat.py`` covering both the
   new-name and old-name branches (module monkeypatching, no jax upgrade
   needed).

``SHIMMED_SYMBOLS`` doubles as the machine-readable registry dslint reads —
by AST parse of this file, never by importing it — so the lint rule can never
go stale relative to what the shim actually covers.
"""

import importlib
import inspect
from typing import Any, Dict, Tuple

# exported name -> ordered "module:attr" candidates, newest spelling FIRST.
# dslint's direct-shimmed-import rule bans every candidate spelling outside
# compat/ (both directions: the old name must not linger, the new name must
# not be imported around the shim).  Keep values as literal tuples of literal
# strings: the rule reads this assignment from the AST.
SHIMMED_SYMBOLS: Dict[str, Tuple[str, ...]] = {
    "shard_map": (
        "jax:shard_map",
        "jax.experimental.shard_map:shard_map",
    ),
    "CompilerParams": (
        "jax.experimental.pallas.tpu:CompilerParams",
        "jax.experimental.pallas.tpu:TPUCompilerParams",
    ),
    "axis_size": (
        "jax.lax:axis_size",
        "deepspeed_tpu.compat._fallbacks:axis_size",
    ),
    "Space": (
        "jax.memory:Space",
        "deepspeed_tpu.compat._fallbacks:Space",
    ),
}


class CompatResolutionError(ImportError):
    """No candidate spelling of a shimmed symbol exists in the installed jax."""


_cache: Dict[str, Tuple[Any, str]] = {}


def _resolve_uncached(name: str) -> Tuple[Any, str]:
    try:
        candidates = SHIMMED_SYMBOLS[name]
    except KeyError:
        raise CompatResolutionError(
            f"'{name}' is not a shimmed symbol; known: {', '.join(SHIMMED_SYMBOLS)}")
    tried = []
    for spec in candidates:
        mod_name, _, attr = spec.partition(":")
        try:
            mod = importlib.import_module(mod_name)
        except ImportError:
            tried.append(f"{spec} (module not importable)")
            continue
        obj = getattr(mod, attr, None)
        if obj is not None:
            return obj, spec
        tried.append(f"{spec} (attribute absent)")
    raise CompatResolutionError(
        f"compat: no installed spelling of '{name}' — tried {'; '.join(tried)}. "
        f"The installed jax has drifted past every candidate in "
        f"SHIMMED_SYMBOLS['{name}']; add its current path as the first entry.")


def resolve_symbol(name: str, refresh: bool = False) -> Any:
    """The object behind a shimmed name under the installed jax (cached).

    ``refresh=True`` re-runs resolution — the seam the compat unit tests use
    to exercise both the new-name and old-name branches via monkeypatched
    modules without reinstalling jax.
    """
    if refresh or name not in _cache:
        _cache[name] = _resolve_uncached(name)
    return _cache[name][0]


def resolved_source(name: str) -> str:
    """Which candidate spelling ``resolve_symbol`` bound (for diagnostics)."""
    resolve_symbol(name)
    return _cache[name][1]


# --------------------------------------------------------------- shard_map
def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None,
              axis_names=None, **kwargs):
    """``jax.shard_map`` across the rename AND the kwarg drift.

    Call with the NEW spellings everywhere; the shim translates for whichever
    implementation resolved:

    - ``check_vma=`` → ``check_rep=`` on the pre-rename
      ``jax.experimental.shard_map.shard_map`` (a ``check_rep=`` kwarg is
      likewise forwarded under whichever name the implementation accepts, so
      the shim never strands a caller mid-migration);
    - ``axis_names={...}`` (the set of mesh axes the body is MANUAL over) →
      the old API's complementary ``auto=`` set (the mesh axes left
      automatic), computed against ``mesh.axis_names``.
    """
    impl = resolve_symbol("shard_map")
    params = inspect.signature(impl).parameters
    flag = kwargs.pop("check_rep", check_vma)
    if flag is not None:
        kwargs["check_vma" if "check_vma" in params else "check_rep"] = flag
    if axis_names is not None:
        if "axis_names" in params:
            kwargs["axis_names"] = set(axis_names)
        else:
            # the old API spells partial-manual as the complementary ``auto=``
            # set — but its XLA lowering hard-ABORTS the process on real auto
            # axes (spmd_partitioner IsManualSubgroup check), so refuse with a
            # debuggable Python error instead.  Size-1 leftover axes are
            # semantically manual==auto and simply fold into manual.
            auto = {a for a in mesh.axis_names
                    if a not in frozenset(axis_names) and mesh.shape[a] > 1}
            if auto:
                raise NotImplementedError(
                    f"compat.shard_map: partial-manual over {sorted(axis_names)} "
                    f"with automatic axes {sorted(auto)} is not runnable on this "
                    f"jax ({resolved_source('shard_map')}): the old 'auto=' "
                    f"lowering aborts in XLA's SPMD partitioner. Gate the caller "
                    f"on compat.supports_partial_manual() and fall back to a "
                    f"fully-manual or fully-automatic formulation.")
    return impl(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)


def ensure_cpu_multiprocess_collectives() -> bool:
    """Align old jax with the new default for cross-process CPU collectives.

    New jax runs multiprocess CPU programs out of the box (its
    ``jax_cpu_collectives_implementation`` defaults to ``gloo``); old jax
    defaults the same option to ``none``, so the first cross-process
    computation — even ``multihost_utils.sync_global_devices`` — dies with
    "Multiprocess computations aren't implemented on the CPU backend".
    Select gloo when the option exists and nothing was chosen explicitly.
    Must run BEFORE the CPU client is created (comm.init_distributed calls
    it ahead of ``jax.distributed.initialize``).  Returns False only when a
    collectives implementation could not be arranged."""
    import jax
    try:
        # the option is defined at xla_bridge import, which plain `import jax`
        # defers — force it so the probe reads the real default
        import jax._src.xla_bridge  # noqa: F401
    except ImportError:
        pass
    try:
        # flag-style options aren't attribute-readable on old jax — _read is
        # the accessor that works across versions
        current = jax.config._read("jax_cpu_collectives_implementation")
    except (AttributeError, KeyError, ValueError):
        return True  # option retired: this jax defaults to a working impl
    if current in (None, "none"):
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            return False
    return True


def supports_partial_manual() -> bool:
    """Whether ``shard_map`` can leave some mesh axes automatic
    (``axis_names=`` subset).  Only the new top-level ``jax.shard_map``
    supports this reliably — the experimental API's ``auto=`` crashes XLA's
    SPMD partitioner on real (size>1) auto axes, so callers of hierarchical
    manual/auto programs (e.g. stage-3 ZeRO++) must gate on this and degrade
    to a formulation the installed jax can run."""
    impl = resolve_symbol("shard_map")
    return "axis_names" in inspect.signature(impl).parameters


# --------------------------------------------------- plain renamed exports
# Resolved LAZILY via module __getattr__ (PEP 562): `from compat import
# CompilerParams` resolves at the importer's import time, but importers that
# only need shard_map/the probes (comm, the runtime engine) never trigger a
# Pallas-TPU import — eager resolution here would couple the whole package's
# import surface to jax.experimental.pallas.tpu being importable.
def __getattr__(name: str):
    if name in SHIMMED_SYMBOLS:
        return resolve_symbol(name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = ["SHIMMED_SYMBOLS", "CompatResolutionError", "resolve_symbol",
           "resolved_source", "shard_map", "supports_partial_manual",
           "ensure_cpu_multiprocess_collectives",
           "CompilerParams", "axis_size", "Space"]
