from .abstract_accelerator import Accelerator
from .tpu_accelerator import TpuAccelerator, get_accelerator
