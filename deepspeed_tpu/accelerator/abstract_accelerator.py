"""Accelerator abstraction.

TPU-native analog of ``DeepSpeedAccelerator`` (accelerator/abstract_accelerator.py:10).
The reference defines ~80 abstract methods over torch devices/streams/memory; in a
JAX world most of that surface collapses: streams/events become implicit in XLA's
async dispatch, memory stats come from device memory_stats(), and op-builder
resolution disappears (kernels are Pallas functions, JIT-compiled by XLA).  We keep
the subset that the runtime, tests, and tooling actually consume, with the same
method names so a reference user can orient quickly.
"""

import abc


class Accelerator(abc.ABC):
    """Minimal device abstraction consumed by the engine/runtime."""

    @abc.abstractmethod
    def device_name(self, device_index=None) -> str:
        ...

    @abc.abstractmethod
    def device_count(self) -> int:
        ...

    @abc.abstractmethod
    def current_device(self):
        ...

    @abc.abstractmethod
    def synchronize(self):
        ...

    @abc.abstractmethod
    def is_available(self) -> bool:
        ...

    @abc.abstractmethod
    def is_bf16_supported(self) -> bool:
        ...

    @abc.abstractmethod
    def is_fp16_supported(self) -> bool:
        ...

    @abc.abstractmethod
    def supported_dtypes(self):
        ...

    @abc.abstractmethod
    def memory_allocated(self, device_index=None) -> int:
        ...

    @abc.abstractmethod
    def total_memory(self, device_index=None) -> int:
        ...

    @abc.abstractmethod
    def available_memory(self, device_index=None) -> int:
        ...

    @abc.abstractmethod
    def communication_backend_name(self) -> str:
        ...

    @abc.abstractmethod
    def random_seed(self, seed: int):
        ...
