"""TPU (and CPU-simulated) accelerator implementation.

Analog of accelerator/cuda_accelerator.py — but for JAX backends.  One class
covers TPU and the CPU host-simulation used by the test harness, since JAX
abstracts both behind the same device API.
"""

from typing import Optional

import jax
import jax.numpy as jnp

from .abstract_accelerator import Accelerator
from ..utils.logging import logger


class TpuAccelerator(Accelerator):

    def __init__(self):
        self._name = None

    # -- identity -------------------------------------------------------------
    def _platform(self) -> str:
        return jax.devices()[0].platform

    def device_name(self, device_index=None) -> str:
        if device_index is None:
            return self._platform()
        return f"{self._platform()}:{device_index}"

    def device(self, device_index=None):
        return jax.devices()[device_index or 0]

    def current_device(self):
        return jax.devices()[0]

    def current_device_name(self) -> str:
        return self.device_name(0)

    def device_count(self) -> int:
        return jax.local_device_count()

    def global_device_count(self) -> int:
        return jax.device_count()

    def is_available(self) -> bool:
        try:
            return len(jax.devices()) > 0
        except RuntimeError:
            return False

    # -- synchronization ------------------------------------------------------
    def synchronize(self, device_index=None):
        # XLA dispatch is async; block_until_ready on a trivial transfer drains it.
        jnp.zeros(()).block_until_ready()

    # -- dtype support --------------------------------------------------------
    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        # fp16 compute is supported on TPU but bf16 is the native fast path.
        return True

    def is_triton_supported(self) -> bool:
        return False

    def supported_dtypes(self):
        return [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int8, jnp.int32]

    # -- memory ---------------------------------------------------------------
    def _stats(self, device_index=None) -> dict:
        dev = jax.local_devices()[device_index or 0]
        try:
            return dev.memory_stats() or {}
        except Exception:
            return {}

    def memory_allocated(self, device_index=None) -> int:
        return int(self._stats(device_index).get("bytes_in_use", 0))

    def max_memory_allocated(self, device_index=None) -> int:
        return int(self._stats(device_index).get("peak_bytes_in_use", 0))

    def total_memory(self, device_index=None) -> int:
        return int(self._stats(device_index).get("bytes_limit", 0))

    def available_memory(self, device_index=None) -> int:
        stats = self._stats(device_index)
        return int(stats.get("bytes_limit", 0)) - int(stats.get("bytes_in_use", 0))

    def empty_cache(self):
        pass  # XLA owns allocation; no-op (reference empties the CUDA cache)

    # -- communication --------------------------------------------------------
    def communication_backend_name(self) -> str:
        return "xla"

    # -- rng ------------------------------------------------------------------
    def random_seed(self, seed: int):
        return jax.random.PRNGKey(seed)

    def on_accelerator(self, array) -> bool:
        return isinstance(array, jax.Array)


_ACCELERATOR: Optional[TpuAccelerator] = None


def get_accelerator() -> TpuAccelerator:
    """Analog of real_accelerator.get_accelerator (accelerator/real_accelerator.py:51).
    There is a single backend family (JAX), so no DS_ACCELERATOR probing."""
    global _ACCELERATOR
    if _ACCELERATOR is None:
        _ACCELERATOR = TpuAccelerator()
    return _ACCELERATOR
