"""TPU (and CPU-simulated) accelerator implementation.

Analog of accelerator/cuda_accelerator.py — but for JAX backends.  One class
covers TPU and the CPU host-simulation used by the test harness, since JAX
abstracts both behind the same device API.
"""

from typing import Optional

import jax
import jax.numpy as jnp

from .abstract_accelerator import Accelerator
from ..utils.logging import logger


class TpuAccelerator(Accelerator):

    def __init__(self):
        self._name = None

    # -- identity -------------------------------------------------------------
    def _platform(self) -> str:
        return jax.devices()[0].platform

    def device_name(self, device_index=None) -> str:
        if device_index is None:
            return self._platform()
        return f"{self._platform()}:{device_index}"

    def device(self, device_index=None):
        return jax.devices()[device_index or 0]

    def current_device(self):
        return jax.devices()[0]

    def current_device_name(self) -> str:
        return self.device_name(0)

    def device_count(self) -> int:
        return jax.local_device_count()

    def global_device_count(self) -> int:
        return jax.device_count()

    def is_available(self) -> bool:
        try:
            return len(jax.devices()) > 0
        except RuntimeError:
            return False

    # -- synchronization ------------------------------------------------------
    def synchronize(self, device_index=None):
        # XLA dispatch is async; block_until_ready on a trivial transfer drains it.
        jnp.zeros(()).block_until_ready()

    # -- dtype support --------------------------------------------------------
    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        # fp16 compute is supported on TPU but bf16 is the native fast path.
        return True

    def is_triton_supported(self) -> bool:
        return False

    def supported_dtypes(self):
        return [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int8, jnp.int32]

    # -- memory ---------------------------------------------------------------
    def _stats(self, device_index=None) -> dict:
        dev = jax.local_devices()[device_index or 0]
        try:
            return dev.memory_stats() or {}
        except Exception:
            return {}

    def memory_allocated(self, device_index=None) -> int:
        return int(self._stats(device_index).get("bytes_in_use", 0))

    def max_memory_allocated(self, device_index=None) -> int:
        return int(self._stats(device_index).get("peak_bytes_in_use", 0))

    def total_memory(self, device_index=None) -> int:
        return int(self._stats(device_index).get("bytes_limit", 0))

    def available_memory(self, device_index=None) -> int:
        stats = self._stats(device_index)
        return int(stats.get("bytes_limit", 0)) - int(stats.get("bytes_in_use", 0))

    def empty_cache(self):
        pass  # XLA owns allocation; no-op (reference empties the CUDA cache)

    # -- streams / events -----------------------------------------------------
    # XLA dispatch is a single async stream per device; Stream is an ordering
    # no-op and Event timestamps by draining it (the reference's CudaEventTimer
    # contract, utils/timer.py:31 — elapsed() returns milliseconds).
    class Stream:
        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return False

        def synchronize(self):
            jnp.zeros(()).block_until_ready()

    class Event:
        def __init__(self, enable_timing: bool = True):
            self._t = None

        def record(self, stream=None):
            import time as _time
            jnp.zeros(()).block_until_ready()  # drain dispatch first
            self._t = _time.perf_counter()

        def synchronize(self):
            jnp.zeros(()).block_until_ready()

        def elapsed_time(self, end_event) -> float:
            if self._t is None or end_event._t is None:
                raise RuntimeError("elapsed_time needs both events recorded")
            return (end_event._t - self._t) * 1e3

    def stream(self, stream=None):
        return self.Stream()

    def current_stream(self, device_index=None):
        return self.Stream()

    def default_stream(self, device_index=None):
        return self.Stream()

    # -- graph capture --------------------------------------------------------
    # jit IS the graph capture: create returns a callable cache, capture
    # compiles, replay calls the compiled function (reference
    # create_graph/capture_to_graph/replay_graph).
    def create_graph(self):
        return {}

    def capture_to_graph(self, graph, fn, *args, **kwargs):
        graph["fn"] = jax.jit(fn)
        graph["out"] = graph["fn"](*args, **kwargs)
        return graph["out"]

    def replay_graph(self, graph, *args, **kwargs):
        return graph["fn"](*args, **kwargs)

    # -- pinned memory --------------------------------------------------------
    def pin_memory(self, array):
        """Host-resident contiguous staging buffer (the reference pins CUDA
        host memory; XLA's host->TPU DMA path wants contiguous numpy)."""
        import numpy as _np
        return _np.ascontiguousarray(array)

    def is_pinned(self, array) -> bool:
        import numpy as _np
        return isinstance(array, _np.ndarray) and array.flags["C_CONTIGUOUS"]

    # -- profiler ranges ------------------------------------------------------
    def range_push(self, name: str):
        self._ranges = getattr(self, "_ranges", [])
        ann = jax.profiler.TraceAnnotation(name)
        ann.__enter__()
        self._ranges.append(ann)

    def range_pop(self):
        if getattr(self, "_ranges", []):
            self._ranges.pop().__exit__(None, None, None)

    # -- device properties ----------------------------------------------------
    def get_device_properties(self, device_index=None) -> dict:
        dev = jax.local_devices()[device_index or 0]
        return {"name": getattr(dev, "device_kind", self._platform()),
                "platform": dev.platform,
                "total_memory": self.total_memory(device_index),
                "num_cores": getattr(dev, "num_cores", 1)}

    # -- communication --------------------------------------------------------
    def communication_backend_name(self) -> str:
        return "xla"

    # -- op builders (reference accelerator op_builder resolution) -------------
    def op_builder_dir(self) -> str:
        return "deepspeed_tpu.ops.op_builder"

    def get_op_builder(self, class_name: str):
        from ..ops import op_builder
        return getattr(op_builder, class_name)

    def create_op_builder(self, class_name: str):
        return self.get_op_builder(class_name)()

    # -- rng ------------------------------------------------------------------
    def random_seed(self, seed: int):
        return jax.random.PRNGKey(seed)

    def get_rng_state(self, key):
        """JAX rng is an explicit key, not hidden device state; the 'state' IS
        the key array (reference get_rng_state returns the CUDA RNG blob)."""
        import numpy as _np
        return _np.asarray(key)

    def set_rng_state(self, state):
        return jnp.asarray(state, jnp.uint32)

    def on_accelerator(self, array) -> bool:
        return isinstance(array, jax.Array)


_ACCELERATOR: Optional[TpuAccelerator] = None


def get_accelerator() -> TpuAccelerator:
    """Analog of real_accelerator.get_accelerator (accelerator/real_accelerator.py:51).
    There is a single backend family (JAX), so no DS_ACCELERATOR probing."""
    global _ACCELERATOR
    if _ACCELERATOR is None:
        _ACCELERATOR = TpuAccelerator()
    return _ACCELERATOR
