"""Inference engine (v1) — TP-sharded generation with a jitted prefill/decode split.

Analog of the reference InferenceEngine (deepspeed/inference/engine.py:39): the
reference injects CUDA kernels into a HF module tree and shards weights over a
TP process group; here the model is a pure function + params pytree, TP is a
mesh axis with AutoTP-derived shardings (auto_tp.py), and the CUDA-graph
capture step (engine.py:524) is subsumed by jit compilation of two programs:

  prefill(params, ids, cache)        -> (logits, cache)   # full prompt
  decode(params, last_token, cache)  -> (logits, cache)   # one token, reused

Generation loops decode on-device state; only sampled tokens come back to host.
"""

import functools
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..parallel.mesh import MeshTopology, TENSOR_AXIS
from ..runtime.zero.sharding import ShardingPlan
from ..utils.logging import log_dist
from .auto_tp import auto_tp_rules
from .config import DTYPES as _DTYPES, InferenceConfig, load_inference_config

class InferenceEngine:
    """Serve a model-family module (models.llama-style: needs forward_with_cache
    + init_cache) with TP sharding and incremental decoding."""

    def __init__(self, model_module, model_config, params,
                 config: Optional[Dict] = None,
                 topology: Optional[MeshTopology] = None,
                 tp_rules: Optional[Callable] = None,
                 attention_fn: Optional[Callable] = None):
        self.config = load_inference_config(config)
        self.model = model_module
        self.model_config = model_config
        tp_size = self.config.tensor_parallel.tp_size
        # wildcard data axis soaks up remaining local devices (replicated serve)
        self.topology = topology or MeshTopology.from_axis_dict({TENSOR_AXIS: tp_size, "data": -1})
        self.dtype = _DTYPES[self.config.dtype]
        self.attention_fn = attention_fn
        rules = tp_rules if tp_rules is not None else (
            getattr(model_module, "tp_rules", None) or auto_tp_rules)
        # ZeRO stage 0 plan: TP rules only, everything else replicated
        class _NoZero:
            stage = 0
            param_persistence_threshold = 0
        from ..runtime.zero.sharding import build_sharding_plan
        self.plan = build_sharding_plan(_NoZero(), self.topology, tp_rules=rules)

        self._quantized = self.config.quant.enabled
        if self._quantized:
            if self.topology.axis_size(TENSOR_AXIS) > 1:
                log_dist("WARNING: quant.enabled serves weights REPLICATED — "
                         "packed layouts do not yet follow the TP sharding plan; "
                         "tp_size > 1 buys no memory here", ranks=[0])
            # real WOQ: weights live PACKED (int8/int4 + scales) in device
            # memory; the jitted forward dequantizes per layer on the fly
            # (inference/quantization.py).  Packed leaves replicate — TP
            # sharding of packed layouts composes later.
            from .quantization import is_woq_leaf, quantize_tree
            params = quantize_tree(params, bits=self.config.quant.bits,
                                   group_size=self.config.quant.group_size)
            # non-packed leaves (norms, biases) still serve in the configured
            # dtype — otherwise fp32 norms silently promote the whole forward
            params = jax.tree_util.tree_map(
                lambda x: x if is_woq_leaf(x) else jnp.asarray(x, self.dtype),
                params, is_leaf=is_woq_leaf)
            # replicate over the WHOLE topology mesh: a bare device_put would
            # leave packed leaves committed to the default device only, and the
            # jitted forward then fails (or silently serves one chip) when
            # combined with mesh-placed cache/inputs
            self.params = jax.device_put(params, self.topology.replicated())
        else:
            self.params = self._shard_params(params)
        self._prefill = None
        self._decode = None
        self._samplers = {}
        log_dist(f"InferenceEngine: tp={self.topology.axis_size(TENSOR_AXIS)} "
                 f"dtype={self.config.dtype}", ranks=[0])

    # ----------------------------------------------------------------- setup
    def _shard_params(self, params):
        cast = jax.tree_util.tree_map(lambda x: jnp.asarray(x, self.dtype), params)
        shardings = self.plan.param_shardings(cast)
        return jax.jit(lambda p: p, out_shardings=shardings)(cast)

    # ------------------------------------------------------------ compiled fns
    def _build(self, batch: int, max_seq: int):
        model, cfg = self.model, self.model_config
        attn = self.attention_fn
        if self._quantized:
            from .quantization import dequantize_tree
            dtype = self.dtype
            unpack = lambda p: dequantize_tree(p, dtype)  # inside jit: fused
        else:
            unpack = lambda p: p

        def prefill(params, ids, cache):
            return model.forward_with_cache(cfg, unpack(params), ids, cache, attention_fn=attn)

        def decode(params, last, cache):
            return model.forward_with_cache(cfg, unpack(params), last, cache, attention_fn=attn)

        self._prefill = jax.jit(prefill)
        self._decode = jax.jit(decode)

    # ---------------------------------------------------------------- forward
    def forward(self, input_ids):
        """One full forward returning logits (reference engine.forward:584)."""
        ids = jnp.asarray(input_ids)
        cache = self.model.init_cache(self.model_config, ids.shape[0], ids.shape[1],
                                      dtype=self.dtype)
        if self._prefill is None:
            self._build(ids.shape[0], cache["k"].shape[2])
        logits, _ = self._prefill(self.params, ids, cache)
        return logits

    __call__ = forward

    # --------------------------------------------------------------- generate
    def generate(self, input_ids, max_new_tokens: Optional[int] = None,
                 temperature: Optional[float] = None, top_k: Optional[int] = None,
                 top_p: Optional[float] = None, eos_token_id: Optional[int] = None,
                 seed: Optional[int] = None):
        """Autoregressive generation (reference hybrid/generate paths).

        input_ids: [B, S] prompt tokens. Returns np.ndarray [B, S + new]."""
        ids = jnp.asarray(np.asarray(input_ids))
        b, s = ids.shape
        new = max_new_tokens if max_new_tokens is not None else self.config.max_out_tokens
        if new <= 0:
            return np.asarray(ids)
        temperature = self.config.temperature if temperature is None else temperature
        top_k = self.config.top_k if top_k is None else top_k
        top_p = self.config.top_p if top_p is None else top_p
        model_max = getattr(self.model_config, "max_seq_len", None)
        max_seq = self.config.max_seq_len or (s + new)
        if model_max is not None:
            max_seq = min(max_seq, model_max)
        if s + new > max_seq:
            raise ValueError(f"prompt ({s}) + max_new_tokens ({new}) exceeds max_seq_len {max_seq} "
                             f"(model rotary table covers {model_max} positions)")

        cache = self.model.init_cache(self.model_config, b, max_seq, dtype=self.dtype)
        if self._prefill is None:
            self._build(b, max_seq)
        rng = jax.random.PRNGKey(self.config.seed if seed is None else seed)

        logits, cache = self._prefill(self.params, ids, cache)
        skey = (temperature, top_k, top_p)
        if skey not in self._samplers:
            self._samplers[skey] = jax.jit(
                functools.partial(_sample, temperature=temperature, top_k=top_k, top_p=top_p))
        sample = self._samplers[skey]
        tok, rng = sample(logits[:, -1], rng)
        out = [np.asarray(tok)]
        for _ in range(new - 1):
            logits, cache = self._decode(self.params, tok[:, None], cache)
            tok, rng = sample(logits[:, -1], rng)
            out.append(np.asarray(tok))
            if eos_token_id is not None and bool(np.all(out[-1] == eos_token_id)):
                break
        gen = np.stack(out, axis=1)
        return np.concatenate([np.asarray(ids), gen], axis=1)


def _filter_logits(logits, *, temperature, top_k, top_p):
    """Temperature scaling + top-k / top-p masking in fp32 — the ONE filtered
    target distribution behind both :func:`_sample` and the spec-decode
    rejection sampler (inference/v2/spec_decode.py): acceptance probabilities
    and resampling must see byte-identical masking to what the plain sampled
    path draws from, or spec mode would silently shift the distribution it is
    proving it preserves.  ``temperature == 0`` must be handled by the caller
    (greedy argmax, no filtering)."""
    logits = logits.astype(jnp.float32)
    logits = logits / jnp.maximum(temperature, 1e-6)
    # top_k/top_p are Python scalars statically bound before jit at every call
    # site (_sample binds via functools.partial; the spec verify program bakes
    # its sample_cfg into the compile key), so these branches specialize traces
    if top_k and top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -1e30, logits)
    return logits


def _sample(logits, rng, *, temperature, top_k, top_p):
    """Temperature / top-k / top-p sampling on-device; greedy at T=0."""
    # temperature/top_k/top_p are Python scalars bound via functools.partial
    # BEFORE jit at every call site (engine.generate, engine_v2 pick/burst), so
    # these branches specialize the trace; only logits/rng are traced values
    if temperature == 0.0:  # dslint: disable=traced-control-flow  # statically bound via functools.partial at every jit site
        return jnp.argmax(logits.astype(jnp.float32), axis=-1).astype(jnp.int32), rng
    logits = _filter_logits(logits, temperature=temperature, top_k=top_k, top_p=top_p)
    rng, sub = jax.random.split(rng)
    tok = jax.random.categorical(sub, logits, axis=-1).astype(jnp.int32)
    return tok, rng


def init_inference(model_module=None, model_config=None, params=None, config=None,
                   hf_model=None, **kwargs) -> InferenceEngine:
    """deepspeed.init_inference analog (reference __init__.py:263).

    Either pass (model_module, model_config, params) explicitly, or a HF
    LlamaForCausalLM/MistralForCausalLM via ``hf_model`` — converted with
    models.llama.from_hf_state_dict (load_checkpoint.py analog).
    """
    if hf_model is not None:
        from ..models import llama
        model_module = llama
        model_config = llama.config_from_hf(hf_model.config)
        params = llama.from_hf_state_dict(model_config, hf_model.state_dict())
    if model_module is None or params is None:
        raise ValueError("init_inference needs (model_module, model_config, params) or hf_model")
    return InferenceEngine(model_module, model_config, params, config=config, **kwargs)
