"""Speculative decoding for the v2 serving engine — draft, verify, accept.

The fused decode burst (fastpath.py / engine_v2.decode_burst) already
collapses host round-trips: k tokens per sync.  But every one of those k
tokens still costs a full target-model forward, and decode is
HBM-bandwidth-bound — the weights stream from HBM once PER TOKEN.
Speculative decoding (Leviathan et al., "Fast Inference from Transformers
via Speculative Decoding") amortizes that stream k-for-1: a cheap DRAFTER
proposes k tokens per sequence, the target model scores all k in ONE
batched forward over the paged KV pool (positions ride the existing block
tables), and on-device rejection sampling accepts the longest valid prefix
plus one corrected token — between 1 and k+1 tokens per verify, with the
output distribution provably the target's.

This module owns the pieces that are independent of the engine's dispatch
machinery:

- :func:`rejection_select` — the on-device accept/reject kernel.  For the
  deterministic drafters below the proposal distribution is a delta, so the
  exact residual-sampling rule simplifies: accept ``d_i`` with probability
  ``p_i(d_i)`` under the FILTERED target distribution (the same
  temperature/top-k/top-p masking ``_sample`` applies — shared via
  ``engine._filter_logits`` so spec and plain sampling can never diverge),
  and on the first rejection resample from ``p_i`` with ``d_i`` masked out
  (the normalized residual ``max(p - q, 0)`` of a delta proposal).  Greedy
  decode degenerates to "accept while argmax agrees, then emit argmax" —
  token-identical to spec-off greedy decode.  Everything stays on device;
  the packed ``[n, k+2]`` result (accept count + emitted run) rides the
  round's ONE wave-boundary materialize.
- :class:`NgramDrafter` — the zero-weight prompt-lookup fallback: propose
  the continuation of the longest recent n-gram matching the sequence's
  suffix (pure host python over token ids the host already owns; no second
  model, no device work).
- :class:`ModelDrafter` — a small draft model from the model zoo running
  greedily against its OWN paged pool (catch-up prefill + k-step draft scan
  in one compiled program; proposals never visit the host — the device
  array feeds the verify program directly).
- :class:`AdaptiveKController` — EWMA-of-acceptance k controller restricted
  to a small static ladder so every verify width is a prewarmable bucket;
  at the k=1 floor the engine degrades to the plain burst path and the
  controller re-probes periodically.
- :class:`SpecDecodeStats` — proposed/accepted/emitted counters and the
  tokens-per-verify histogram behind ``serving_spec_*`` metrics and
  ``health()["spec_decode"]``.

Zero-host-sync contract: accept/reject accumulation stays on device until
the engine's wave-boundary ``fastpath.materialize()`` — dslint's
``host-sync-in-hot-path`` rule scans this WHOLE file (module level
included) with the full explicit-fetch set, same as kv_metrics.py.
"""

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def spec_k_ladder(k_max: int) -> Tuple[int, ...]:
    """The static draft-length ladder: 1 (the degrade-to-burst floor) then
    pow2-1 rungs capped at the configured k, so verify widths k+1 stay powers
    of two as long as the cap itself is one.  A static ladder is what lets
    the prewarm enumerate every verify program ahead of serving — an
    unconstrained adaptive k would recompile on every drift."""
    rungs = {min(int(k_max), v) for v in (1, 3, 7, 15, 31, 63)}
    return tuple(sorted(rungs))


def rejection_select(logits, draft, rng, *, sample_cfg):
    """On-device accept/reject for one verify round (traced into the engine's
    fused verify program — never called eagerly).

    ``logits``: [n, k+1, V] target logits over (input token + k draft
    tokens); position i is conditioned on the draft prefix d_0..d_{i-1}.
    ``draft``: [n, k] proposed tokens.  ``sample_cfg``: None for greedy,
    else (temperature, top_k, top_p) — the engine's live sampling knobs.

    Returns ``(packed, rng)`` with packed [n, k+2] int32 rows
    ``[count, e_0, ..., e_k]``: the row emits ``e_0..e_{count-1}``
    (1 <= count <= k+1).  Accepted positions satisfy e_i == d_i; the final
    emitted token is the corrected/bonus sample and becomes the sequence's
    next pending input.

    Exactness (deterministic drafter => delta proposal q = δ(d_i)):
    accept d_i with prob p̃_i(d_i); the residual max(p̃ - q, 0)/Z is p̃ with
    d_i zeroed, so the correction resamples from p̃_i masked at d_i; if all
    k accept, the bonus samples p̃_k unmasked.  The marginal of each emitted
    token is exactly p̃ — the same filtered distribution ``_sample`` draws
    from, so spec on/off are distribution-identical (and token-identical
    under greedy, where acceptance is argmax agreement).
    """
    n, kp1, vocab = logits.shape
    k = kp1 - 1
    # sample_cfg is a static Python tuple bound before jit at the verify
    # compile seam, so this branch specializes the trace
    if sample_cfg is None or sample_cfg[0] == 0.0:
        tgt = jnp.argmax(logits.astype(jnp.float32), axis=-1).astype(jnp.int32)
        acc = (draft == tgt[:, :k]).astype(jnp.int32)
        count = 1 + jnp.sum(jnp.cumprod(acc, axis=1), axis=1)
        packed = jnp.concatenate([count[:, None].astype(jnp.int32), tgt], axis=1)
        return packed, rng
    from ..engine import _filter_logits
    temperature, top_k, top_p = sample_cfg
    filt = _filter_logits(logits.reshape(n * kp1, vocab), temperature=temperature,
                          top_k=top_k, top_p=top_p).reshape(n, kp1, vocab)
    logp = jax.nn.log_softmax(filt, axis=-1)
    lp_draft = jnp.take_along_axis(logp[:, :k], draft[..., None], axis=-1)[..., 0]
    rng, ku, kr = jax.random.split(rng, 3)
    u = jax.random.uniform(ku, (n, k))
    # log-space compare; the 1e-38 floor keeps a u=0 draw (prob ~2^-23 per
    # element, NOT negligible over a serve) from accepting a top-k/top-p
    # MASKED draft token through log(0) = -inf < -1e30
    acc = (jnp.log(jnp.maximum(u, 1e-38)) < lp_draft).astype(jnp.int32)
    a = jnp.sum(jnp.cumprod(acc, axis=1), axis=1)          # leading accepts, 0..k
    count = a + 1
    # correction/bonus sample at position a: residual of the delta proposal
    # (mask d_a) below k; the bonus position a == k samples p̃_k unmasked
    row = jnp.take_along_axis(filt, a[:, None, None], axis=1)[:, 0]  # [n, V]
    d_pad = jnp.concatenate([draft, draft[:, :1]], axis=1)  # [n, k+1]; col k unused
    d_at_a = jnp.take_along_axis(d_pad, a[:, None], axis=1)[:, 0]
    mask = (jnp.arange(vocab, dtype=jnp.int32)[None, :] == d_at_a[:, None]) \
        & (a < k)[:, None]
    row = jnp.where(mask, -jnp.inf, row)
    fix = jax.random.categorical(kr, row, axis=-1).astype(jnp.int32)
    pos = jnp.arange(kp1, dtype=jnp.int32)[None, :]
    emitted = jnp.where(pos == a[:, None], fix[:, None], d_pad)
    packed = jnp.concatenate([count[:, None].astype(jnp.int32), emitted], axis=1)
    return packed, rng


class NgramDrafter:
    """Zero-weight prompt-lookup drafter (the no-second-model fallback).

    Proposes the continuation of the rightmost earlier occurrence of the
    sequence's longest suffix n-gram — pure host python over token ids the
    host already owns (spec rounds run at wave boundaries, so every token is
    materialized), zero device work, proposals ride the verify upload.
    Effective exactly where cheap speculation should be: repetitive /
    templated continuations, copy spans, and the short cycles greedy decode
    falls into; elsewhere acceptance collapses and the adaptive-k controller
    degrades the engine back to the plain burst."""

    #: bound the suffix-match scan to the most recent history — proposal cost
    #: must stay O(window), not O(sequence length)
    WINDOW = 256

    def __init__(self, ngram_max: int = 3, ngram_min: int = 1):
        self.ngram_max = max(int(ngram_max), int(ngram_min))
        self.ngram_min = max(1, int(ngram_min))

    def propose(self, tokens: List[int], k: int) -> List[int]:
        """Exactly k proposed tokens for one sequence's token history."""
        hist = tokens[-self.WINDOW:]
        m_len = len(hist)
        for m in range(self.ngram_max, self.ngram_min - 1, -1):
            if m_len <= m:
                continue
            suffix = hist[m_len - m:]
            for j in range(m_len - m - 1, -1, -1):
                if hist[j:j + m] == suffix:
                    cont = hist[j + m:j + m + k]
                    if cont:
                        out = list(cont)
                        while len(out) < k:
                            out.append(out[-1])
                        return out
        return [hist[-1]] * k  # no match: propose a repeat run

    def propose_batch(self, seqs, k: int, pad_to: int, counters=None):
        """[pad_to, k] int32 host proposals, row i for seqs[i] (padded rows
        zero — they decode into the trash block and are never read)."""
        out = np.zeros((pad_to, k), np.int32)
        for i, seq in enumerate(seqs):
            out[i, :] = self.propose(seq.tokens, k)
        return out


class ModelDrafter:
    """A small draft model from the model zoo proposing greedily against its
    OWN paged KV pool.

    The drafter mirrors the target's paged-attention contract
    (``forward_paged`` + block tables) over a private pool: each round it
    catches up on tokens the target accepted since its last draft (their
    positions simply overwrite whatever rejected-draft KV was left there —
    paged attention never reads past ``start_pos + n_tokens``, the same
    argument that makes the target's own rejected positions harmless), then
    drafts k tokens in one compiled catch-up-plus-scan program.  Proposals
    stay ON DEVICE — the [n, k] array feeds the engine's verify program
    directly, so drafting adds dispatches but zero host syncs.

    Under a TP mesh the drafter runs fully replicated (params, pool and
    batch all ``PartitionSpec()``): a draft model small enough to be worth
    drafting with is small enough to replicate, and replication keeps the
    proposal array consumable by the shard_mapped verify without resharding.
    """

    def __init__(self, model_module, model_config, params, *, num_blocks: int,
                 block_size: int, max_blocks_per_seq: int, dtype=jnp.float32,
                 mesh=None, ledger=None):
        self.model = model_module
        self.cfg = model_config
        self.block_size = int(block_size)
        self.max_blocks_per_seq = int(max_blocks_per_seq)
        self._ledger = ledger
        self._replicated = None
        # construction-time host->device upload of draft weights (not a fetch)
        params = jax.tree_util.tree_map(lambda x: jnp.asarray(x, dtype), params)
        kv = model_module.init_paged_cache(model_config, num_blocks, block_size,
                                           dtype=dtype)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            self._replicated = NamedSharding(mesh, PartitionSpec())
            params = jax.device_put(params, self._replicated)
            kv = jax.device_put(kv, self._replicated)
        self.params = params
        self.kv = kv
        # trivial private allocator: the last block is the trash slot padded
        # rows decode into (same convention as the ragged manager's pool)
        self.trash_block = num_blocks - 1
        self._free: List[int] = list(range(num_blocks - 1))
        self._state: Dict[int, Dict] = {}  # uid -> {"blocks": [...], "seen": int}
        self._fns: Dict = {}

    # ------------------------------------------------------------ bookkeeping
    def _gc(self, live_uids) -> None:
        for uid in [u for u in self._state if u not in live_uids]:
            self._free.extend(self._state.pop(uid)["blocks"])

    def _ensure_blocks(self, st: Dict, upto_tokens: int) -> bool:
        need = min(-(-upto_tokens // self.block_size), self.max_blocks_per_seq)
        grow = need - len(st["blocks"])
        if grow > len(self._free):
            return False
        for _ in range(max(0, grow)):
            st["blocks"].append(self._free.pop())
        return True

    def _compiled_draft(self, n: int, t: int, b: int, k: int):
        key = (n, t, b, k)
        fn = self._fns.get(key)
        if fn is None:
            model, cfg, bs = self.model, self.cfg, self.block_size
            ones = jnp.ones((n,), jnp.int32)

            def draft(params, kv, tokens, nt, start, tables):
                logits, kv = model.forward_paged(cfg, params, tokens, nt, start,
                                                 tables, kv, block_size=bs)
                last = jnp.maximum(nt - 1, 0)
                row = jnp.take_along_axis(logits, last[:, None, None], axis=1)[:, 0]
                d0 = jnp.argmax(row, axis=-1).astype(jnp.int32)
                if k == 1:  # static Python int baked into the compile key
                    return kv, d0[:, None]

                def body(carry, _):
                    kv, tok, pos = carry
                    lg, kv = model.forward_paged(cfg, params, tok[:, None], ones,
                                                 pos, tables, kv, block_size=bs)
                    nxt = jnp.argmax(lg[:, 0], axis=-1).astype(jnp.int32)
                    return (kv, nxt, pos + 1), nxt

                (kv, _, _), rest = jax.lax.scan(body, (kv, d0, start + nt), None,
                                                length=k - 1)
                return kv, jnp.concatenate([d0[:, None], rest.T], axis=1)

            if self._replicated is not None:
                rep = self._replicated
                self._fns[key] = jax.jit(  # dslint: disable=donation-after-use  # call-site contract: propose_batch reassigns self.kv from the result in the same statement
                    draft, donate_argnums=(1,), out_shardings=(rep, rep))
            else:
                self._fns[key] = jax.jit(draft, donate_argnums=(1,))  # dslint: disable=donation-after-use  # call-site contract: propose_batch reassigns self.kv from the result in the same statement
            fn = self._fns[key]
            if self._ledger is not None:
                self._ledger.record("draft", key)
        return fn

    # ---------------------------------------------------------------- propose
    def propose_batch(self, seqs, k: int, pad_to: int, counters=None):
        """Draft k tokens per sequence; returns a DEVICE [pad_to, k] int32
        array (row i for seqs[i]) or None when the private pool can't cover
        the round (the engine falls back to the plain burst)."""
        self._gc({s.uid for s in seqs})
        n = pad_to
        rows: List[Tuple[Dict, List[int]]] = []
        t_max = 1
        for s in seqs:
            st = self._state.setdefault(s.uid, {"blocks": [], "seen": 0})
            pending = s.tokens[st["seen"]:]
            if not pending:  # catch-up must feed >= 1 token; re-feed the last
                st["seen"] -= 1
                pending = s.tokens[-1:]
            if not self._ensure_blocks(st, len(s.tokens) + k):
                return None
            rows.append((st, pending))
            t_max = max(t_max, len(pending))
        t = 1
        while t < t_max:
            t *= 2
        b = 1
        while b < max(len(st["blocks"]) for st, _ in rows):
            b *= 2
        tokens = np.zeros((n, t), np.int32)
        nt = np.zeros((n,), np.int32)
        start = np.zeros((n,), np.int32)
        tables = np.full((n, b), self.trash_block, np.int32)
        for i, (st, pending) in enumerate(rows):
            tokens[i, :len(pending)] = pending
            nt[i] = len(pending)
            start[i] = st["seen"]
            tables[i, :len(st["blocks"])] = st["blocks"]
            # positions < len(tokens) now hold real-token KV; drafted
            # positions beyond are junk the NEXT catch-up overwrites
            st["seen"] = st["seen"] + len(pending)
        fn = self._compiled_draft(n, t, b, k)
        if counters is not None:
            counters.dispatches += 1
            counters.uploads += 4
            counters.upload_ints += int(tokens.size + nt.size + start.size
                                        + tables.size)
        up = (lambda a: jax.device_put(a, self._replicated)) \
            if self._replicated is not None else jnp.asarray
        self.kv, draft = fn(self.params, self.kv, up(tokens), up(nt), up(start),
                            up(tables))
        return draft


class AdaptiveKController:
    """EWMA-of-acceptance draft-length controller over the static ladder.

    ``note_round`` folds one verify round's acceptance fraction into the
    EWMA; the live k steps UP one rung when the EWMA clears
    ``raise_threshold`` and DOWN one rung below ``lower_threshold`` — never
    off-ladder, so every verify width the controller can pick is already a
    compiled bucket.  At the k=1 floor speculation isn't worth a drafter
    call: :meth:`next_k` returns 1 and the engine runs the plain burst
    (zero spec overhead, zero recompiles); every ``probe_every`` floored
    rounds the controller re-probes the lowest speculative rung so a
    regime change (e.g. the decode entering a repetitive span) can win k
    back."""

    def __init__(self, cfg):
        self.cfg = cfg
        self.ladder = spec_k_ladder(cfg.k)
        self._idx = len(self.ladder) - 1  # start optimistic, at the cap
        self.ewma: Optional[float] = None
        self._floor_rounds = 0

    @property
    def k(self) -> int:
        return self.ladder[self._idx]

    def next_k(self) -> int:
        """The draft length to use for the NEXT fused round."""
        if not self.cfg.adaptive_k:
            return self.cfg.k
        if self.ladder[self._idx] <= 1:
            self._floor_rounds += 1
            if self._floor_rounds >= self.cfg.probe_every and len(self.ladder) > 1:
                self._floor_rounds = 0
                self._idx = 1  # re-probe the lowest speculative rung
        return self.ladder[self._idx]

    def note_round(self, proposed: int, accepted: int) -> None:
        if not self.cfg.adaptive_k or proposed <= 0:
            return
        rate = accepted / proposed
        a = self.cfg.ewma_alpha
        self.ewma = rate if self.ewma is None else a * rate + (1 - a) * self.ewma
        if self.ewma >= self.cfg.raise_threshold:
            self._idx = min(self._idx + 1, len(self.ladder) - 1)
        elif self.ewma <= self.cfg.lower_threshold:
            self._idx = max(self._idx - 1, 0)

    def snapshot(self) -> Dict[str, object]:
        return {"k": self.k, "ladder": list(self.ladder),
                "acceptance_ewma": (round(self.ewma, 4)
                                    if self.ewma is not None else None)}


class SpecDecodeStats:
    """Host-side spec-decode accounting behind ``serving_spec_*`` metrics
    and ``health()["spec_decode"]`` — proposed/accepted lifetime counters,
    emitted totals, and the tokens-per-verify histogram (bounded: a verify
    of k emits between 1 and k+1 tokens per sequence)."""

    def __init__(self):
        self.rounds_total = 0
        self.proposed_total = 0
        self.accepted_total = 0
        self.emitted_total = 0
        self.fallback_rounds_total = 0  # fused rounds that ran the plain burst
        self.tokens_per_verify: Dict[int, int] = {}

    def note_round(self, proposed: int, accepted: int,
                   run_lengths: List[int]) -> None:
        self.rounds_total += 1
        self.proposed_total += int(proposed)
        self.accepted_total += int(accepted)
        self.emitted_total += int(sum(run_lengths))
        for r in run_lengths:
            self.tokens_per_verify[int(r)] = self.tokens_per_verify.get(int(r), 0) + 1

    def acceptance_rate(self) -> float:
        return self.accepted_total / max(self.proposed_total, 1)

    def snapshot(self) -> Dict[str, object]:
        return {"rounds_total": self.rounds_total,
                "proposed_total": self.proposed_total,
                "accepted_total": self.accepted_total,
                "emitted_total": self.emitted_total,
                "fallback_rounds_total": self.fallback_rounds_total,
                "acceptance_rate": round(self.acceptance_rate(), 4),
                "tokens_per_verify": {str(c): n for c, n in
                                      sorted(self.tokens_per_verify.items())}}
