"""FastGen-style ragged/continuous-batching serving (reference deepspeed/inference/v2/)."""
from .admission import (AdmissionQueue, RecoveredRequest, RequestResult,
                        ServingStalledError, ShedReason, REQUEST_STATUSES)
from .blocked_allocator import BlockedAllocator, KVAllocationError
from .engine_factory import build_engine, build_hf_engine
from .engine_v2 import InferenceEngineV2
from .fastpath import PENDING_TOKEN, DeferredTokens, DeviceBatchState, ServeCounters
from .journal import JournalEntry, JournalState, RequestJournal, replay_journal
from .kv_metrics import (BlockCensus, CapacityForecaster, CensusInvariantError,
                         KVObservability, PrefixObservatory, block_hashes)
from .ragged_manager import (EmptyPromptError, PrefixCache, PrefixEntry,
                             RaggedStateManager, SequenceDescriptor,
                             UnknownSequenceError)
from .router import FleetRouter, ReplicaHandle
from .scheduler import ScheduledChunk, SplitFuseScheduler
from .supervisor import (RecoveryPlan, ServeSpec, ServingSupervisor,
                         plan_recovery, recover_and_serve)
