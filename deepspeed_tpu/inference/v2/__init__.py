"""FastGen-style ragged/continuous-batching serving (reference deepspeed/inference/v2/)."""
from .admission import (AdmissionQueue, RequestResult, ServingStalledError, ShedReason,
                        REQUEST_STATUSES)
from .blocked_allocator import BlockedAllocator, KVAllocationError
from .engine_factory import build_engine, build_hf_engine
from .engine_v2 import InferenceEngineV2
from .fastpath import PENDING_TOKEN, DeferredTokens, DeviceBatchState, ServeCounters
from .ragged_manager import (EmptyPromptError, RaggedStateManager, SequenceDescriptor,
                             UnknownSequenceError)
from .scheduler import ScheduledChunk, SplitFuseScheduler
