"""FastGen-style ragged/continuous-batching serving (reference deepspeed/inference/v2/)."""
from .blocked_allocator import BlockedAllocator
from .engine_factory import build_engine, build_hf_engine
from .engine_v2 import InferenceEngineV2
from .ragged_manager import RaggedStateManager, SequenceDescriptor
from .scheduler import ScheduledChunk, SplitFuseScheduler
