"""Serving fast path — device-resident batch state + deferred host syncs.

The v2 ragged engine's serve loop used to rebuild its whole padded batch on
the host every step (one ``np`` rebuild + four ``jnp.asarray`` uploads) and
then block on ``np.asarray(toks)`` before it could schedule the next step —
pure orchestration overhead that left a ~20x gap between the fused decode
burst and the continuous-batching loop (BENCH_r05: 1907 vs 90.4 tok/s).
This module holds the three host-link levers the engine composes:

- :class:`DeviceBatchState` — persistent donated device buffers per
  ``(n_seqs, chunk, table_width)`` bucket (tokens / n_tokens / start_pos /
  block tables), updated by ONE jitted scatter of the rows that actually
  changed since the previous step (admissions, retirements, new tokens), so
  steady-state steps move O(changed seqs) ints across the host link instead
  of re-uploading the full padded batch.
- :class:`DeferredTokens` — the sanctioned deferred-sync handle for sampled
  tokens.  The engine appends :data:`PENDING_TOKEN` placeholders at dispatch
  time and patches them when the handle is materialized — one step later in
  the pipelined serve loop, immediately in the synchronous ``step()`` API.
  :func:`materialize` is the ONE place v2 serving code converts a device
  value to host; dslint's ``host-sync-in-hot-path`` rule flags any direct
  ``np.asarray`` on step results elsewhere under ``inference/v2/``.
- :class:`ServeCounters` — host-sync / dispatch / upload / compile counters
  that make the win provable (the fastpath tests assert <=1 host sync per
  serve-loop iteration in steady-state decode and a bounded compile count
  across a mixed-arrival scenario; bench.py reports syncs-per-token).

Nothing here schedules or owns sequences — that stays in the scheduler and
the ragged manager; this is purely the host<->device traffic layer.

Sharded serving (ISSUE 15): given the engine's mesh, :class:`DeviceBatchState`
places its buffers REPLICATED over it (``NamedSharding(mesh,
PartitionSpec())``) and pins replicated ``out_shardings`` on the donated
scatter/feed programs, so the same ≤1-sync loop drives a shard_mapped
forward under TP×DP meshes — the delta is broadcast once, never gathered.
"""

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

# host-side placeholder for a sampled-but-not-yet-fetched token.  Negative so
# it can never collide with a real vocab id; it only ever appears as the LAST
# entry of a live sequence's token list between dispatch and materialize.
PENDING_TOKEN = -1

# device-side mirror sentinel for a token slot that is fed on-device from the
# previous step's sampled tokens (the host never knows the value, so the
# mirror records "fed" instead of a real id and the diff never tries to
# re-upload it)
FED_SENTINEL = np.int32(-(2**31) + 1)


class ServeCounters:
    """Lifetime counters for the serve loop's host-link behavior.

    ``host_syncs``   device->host materializations (the expensive round-trips)
    ``dispatches``   device program launches (forward / pick / burst / scatter)
    ``uploads``      host->device transfers issued
    ``upload_ints``  int32 elements moved host->device by those transfers
    ``compiles``     distinct compiled programs (bucket shapes) built so far
    ``loop_iterations`` serve-loop iterations observed
    ``step_tokens`` / ``burst_tokens``  tokens emitted via stepwise vs fused
    ``flushes``      pipeline flushes forced by wave boundaries
    ``spec_rounds``  speculative draft/verify rounds dispatched (ISSUE 20)
    ``spec_proposed`` / ``spec_accepted``  draft tokens proposed vs accepted
    by the target's rejection sampler — their ratio is the acceptance rate
    behind the adaptive-k controller and the ``serving_spec_*`` metric
    families.  All three stay zero with spec decode off (the default), so
    the pre-spec counter fields keep their exact pre-spec values.
    """

    FIELDS = ("host_syncs", "dispatches", "uploads", "upload_ints", "compiles",
              "loop_iterations", "step_tokens", "burst_tokens", "flushes",
              "spec_rounds", "spec_proposed", "spec_accepted")

    def __init__(self):
        for f in self.FIELDS:
            setattr(self, f, 0)

    def snapshot(self) -> Dict[str, int]:
        return {f: int(getattr(self, f)) for f in self.FIELDS}

    def delta_since(self, snap: Dict[str, int]) -> Dict[str, int]:
        return {f: int(getattr(self, f)) - snap.get(f, 0) for f in self.FIELDS}


def materialize(dev_array, counters: Optional[ServeCounters] = None) -> np.ndarray:
    """THE sanctioned device->host sync for v2 serving step results.

    Every fetch of sampled tokens / done masks funnels through here so the
    cost is (a) counted and (b) statically auditable — dslint's
    host-sync-in-hot-path rule treats this helper as the one legal idiom and
    flags direct ``np.asarray`` on step results anywhere else in
    ``inference/v2/``.
    """
    if counters is not None:
        counters.host_syncs += 1
    # no suppression needed: the rule itself recognizes materialize() as the
    # sanctioned deferred-sync helper (tools/staticcheck/rules.py)
    return np.asarray(dev_array)


@dataclasses.dataclass
class DeferredTokens:
    """Handle to one dispatched step's sampled tokens still on device.

    ``emits``  [(uid, position_in_seq_tokens, batch_row)] for every sequence
    that produced a next token this step (finished prefill or decoded) — the
    positions hold :data:`PENDING_TOKEN` until :meth:`wait` patches them.
    ``row_of`` maps uid -> batch row for on-device feeding of the NEXT step's
    input tokens (the value never visits the host).

    ``tracer`` (monitor/tracing.py RequestTracer): the first :meth:`patch`
    is the moment this step's tokens become host-visible — exactly where
    per-request TTFT/TBT marks belong (ISSUE 6).  Reported once even though
    patch() itself is idempotent (the burst path pre-patches the in-flight
    handle and the serve loop settles it again).

    ``journal`` (inference/v2/journal.py RequestJournal): the same
    host-visibility moment is where emitted tokens enter the durable request
    WAL's buffer (ISSUE 8) — tokens the journal never saw die with a crash
    and are regenerated identically from the journaled prefix, so buffering
    at this seam adds zero device syncs and zero extra fetches.
    """
    toks_dev: object
    emits: List[Tuple[int, int, int]]
    row_of: Dict[int, int]
    counters: Optional[ServeCounters] = None
    tracer: Optional[object] = None
    journal: Optional[object] = None
    _cached: Optional[np.ndarray] = None
    _trace_reported: bool = False

    def wait(self) -> np.ndarray:
        """Materialize the sampled tokens (idempotent)."""
        if self._cached is None:
            self._cached = materialize(self.toks_dev, self.counters)
        return self._cached

    def patch(self, manager) -> Dict[int, int]:
        """Write the real token values over the placeholders and return the
        ``{uid: token}`` map of sequences that emitted this step.

        Sequences that vanished (retired/evicted mid-flight) are skipped;
        sequences whose placeholder was already truncated (finish overshoot)
        are skipped too — the patch keys on the recorded position still
        holding :data:`PENDING_TOKEN`.
        """
        toks = self.wait()
        out: Dict[int, int] = {}
        for uid, pos, row in self.emits:
            seq = manager.seqs.get(uid)
            if seq is None:
                continue
            tok = int(toks[row])
            if pos < len(seq.tokens) and seq.tokens[pos] == PENDING_TOKEN:
                seq.tokens[pos] = tok
            out[uid] = tok
        if not self._trace_reported and (self.tracer is not None
                                         or self.journal is not None):
            self._trace_reported = True  # patch() is idempotent; marks are not
            if self.tracer is not None:
                self.tracer.event("absorb", tokens=len(out))
                self.tracer.on_tokens_map(out)
            if self.journal is not None:
                self.journal.note_token_map(out)
        return out

    def drop_emit(self, uid: int) -> None:
        """Forget a uid's pending emit (its overshoot token was truncated)."""
        self.emits = [e for e in self.emits if e[0] != uid]
        self.row_of.pop(uid, None)


@dataclasses.dataclass
class DeferredRuns:
    """Handle to one speculative verify round's packed accept runs still on
    device (ISSUE 20) — the variable-length sibling of :class:`DeferredTokens`.

    ``packed_dev`` holds ``[n, k+2]`` int32 rows ``[count | e_0 .. e_k]``
    from the fused verify program's rejection sampler: row i emits its first
    ``count`` tokens (1 <= count <= k+1 — the accepted draft prefix plus one
    corrected/bonus token).  The count and the run ride the SAME array, so
    absorbing a whole verify round costs the one wave-boundary
    :func:`materialize` the burst path already pays — per-sequence
    acceptance-length variance never adds a second sync.

    ``uids`` maps batch row -> sequence uid for the live rows; padded rows
    beyond ``len(uids)`` carry garbage runs and are never read.
    """
    packed_dev: object
    uids: List[int]
    counters: Optional[ServeCounters] = None
    _cached: Optional[np.ndarray] = None

    def wait(self) -> np.ndarray:
        """Materialize the packed accept runs (idempotent)."""
        if self._cached is None:
            self._cached = materialize(self.packed_dev, self.counters)
        return self._cached

    def runs(self) -> Dict[int, List[int]]:
        """``{uid: emitted tokens}`` — each row truncated to its accept
        count.  Emitted runs are VERIFIED output (accepted prefix + the
        resampled token); unverified draft tails never leave this handle, so
        downstream seams (journal frames, tracer marks) can never observe a
        token the target model did not endorse."""
        packed = self.wait()
        out: Dict[int, List[int]] = {}
        for i, uid in enumerate(self.uids):
            count = int(packed[i, 0])
            out[uid] = [int(t) for t in packed[i, 1:1 + count]]
        return out


@dataclasses.dataclass
class _Slot:
    """One bucket's persistent device arrays plus their host mirror."""
    tokens: object          # device [n, t] int32
    n_tokens: object        # device [n] int32
    start_pos: object       # device [n] int32
    tables: object          # device [n, b] int32
    mirror: np.ndarray      # host [n, 1 + t + 2 + b] packed rows
    active_rows: int = 0


def round_up_pow2(n: int) -> int:
    """Next power of two >= n — the ONE bucketing primitive shared by batch
    shapes (engine ``_bucket``) and scatter-row padding, so the two can never
    silently diverge and multiply compiled shapes."""
    b = 1
    while b < n:
        b *= 2
    return b


class DeviceBatchState:
    """Per-bucket persistent batch buffers with incremental scatter updates.

    Rows are packed host-side as ``[tokens(t) | n_tokens | start_pos |
    tables(b)]`` so the per-step delta is ONE ``[m, 3 + t + b]`` int32 upload
    (changed-row indices ride in column 0) and ONE donated scatter dispatch,
    instead of four full-batch uploads.  The host mirror tracks exactly what
    the device holds, so shrinking batches neutralize their stale rows
    (n_tokens=0, tables=trash) without ever re-uploading unchanged ones —
    a stale row left live would write KV into blocks the allocator may have
    handed to another sequence.

    With a ``mesh`` (TP/DP-sharded serving, ISSUE 15) the persistent buffers
    live REPLICATED over the whole mesh — every device sees the full padded
    batch while params/KV carry the sharded dims, so the shard_mapped ragged
    forward consumes them with zero resharding.  The delta upload is placed
    replicated too, and the scatter/feed programs pin replicated
    ``out_shardings`` so donation still aliases in place (XLA only aliases a
    donated buffer when input and output shardings agree).  The per-step
    host-link cost is unchanged: O(changed seqs) ints, broadcast once.
    """

    def __init__(self, counters: ServeCounters, mesh=None, ledger=None):
        self.counters = counters
        # compile ledger (ISSUE 16): when attached, scatter/feed shape builds
        # are recorded there (site + key + class) and the ledger bumps
        # counters.compiles — the counter's values are unchanged, its units
        # just gain provenance; without a ledger the direct bump remains
        self._ledger = ledger
        self._replicated = (NamedSharding(mesh, PartitionSpec())
                            if mesh is not None else None)
        self._slots: Dict[Tuple[int, int, int], _Slot] = {}
        self._scatter_shapes: set = set()
        self._feed_shapes: set = set()
        if mesh is not None:
            rep = self._replicated
            self._scatter = jax.jit(self._scatter_impl, donate_argnums=(0, 1, 2, 3),
                                    out_shardings=(rep, rep, rep, rep))
            self._feed = jax.jit(self._feed_impl, donate_argnums=(0,),
                                 out_shardings=rep)
        else:
            self._scatter = jax.jit(self._scatter_impl, donate_argnums=(0, 1, 2, 3))
            self._feed = jax.jit(self._feed_impl, donate_argnums=(0,))

    def _device(self, arr: np.ndarray):
        """Host->device upload: replicated over the mesh under sharded
        serving (a committed single-device array would be rejected by the
        shard_mapped forward), default placement otherwise."""
        if self._replicated is not None:
            return jax.device_put(arr, self._replicated)
        return jnp.asarray(arr)

    @staticmethod
    def _scatter_impl(tokens, n_tokens, start_pos, tables, packed):
        t = tokens.shape[1]
        idx = packed[:, 0]
        return (tokens.at[idx].set(packed[:, 1:1 + t]),
                n_tokens.at[idx].set(packed[:, 1 + t]),
                start_pos.at[idx].set(packed[:, 2 + t]),
                tables.at[idx].set(packed[:, 3 + t:]))

    @staticmethod
    def _feed_impl(tokens, toks_prev, pairs):
        # pairs [m, 2]: (dst_row, src_row) — the next step's input token IS
        # the previous step's sampled token; it never visits the host
        return tokens.at[pairs[:, 0], 0].set(toks_prev[pairs[:, 1]])

    # ------------------------------------------------------------------ slots
    def slot(self, key: Tuple[int, int, int], trash_block: int) -> _Slot:
        s = self._slots.get(key)
        if s is None:
            n, t, b = key
            mirror = np.zeros((n, 3 + t + b), np.int32)
            mirror[:, 0] = np.arange(n)
            mirror[:, 3 + t:] = trash_block
            s = _Slot(tokens=self._device(np.zeros((n, t), np.int32)),
                      n_tokens=self._device(np.zeros((n,), np.int32)),
                      start_pos=self._device(np.zeros((n,), np.int32)),
                      tables=self._device(np.full((n, b), trash_block, np.int32)),
                      mirror=mirror)
            self._slots[key] = s
        return s

    # ----------------------------------------------------------------- update
    def update(self, key: Tuple[int, int, int], rows: List[Tuple[int, np.ndarray]],
               n_active: int, trash_block: int) -> _Slot:
        """Scatter ``rows`` ([(row_index, packed_row)]) into the bucket's
        device buffers, neutralizing any previously-active row beyond
        ``n_active``.  Unchanged rows (mirror match) cost nothing."""
        s = self.slot(key, trash_block)
        n, t, b = key
        changed: List[np.ndarray] = []
        for i, packed in rows:
            if not np.array_equal(packed[1:], s.mirror[i, 1:]):
                changed.append(packed)
                s.mirror[i, 1:] = packed[1:]
        neutral = None
        for i in range(n_active, s.active_rows):
            if neutral is None:
                neutral = np.zeros(3 + t + b, np.int32)
                neutral[3 + t:] = trash_block
            if not np.array_equal(neutral[1:], s.mirror[i, 1:]):
                row = neutral.copy()
                row[0] = i
                changed.append(row)
                s.mirror[i, 1:] = row[1:]
        s.active_rows = n_active
        if changed:
            m = len(changed)
            m_pad = round_up_pow2(m)
            # pad with a repeat of the last row: duplicate scatter indices
            # carry identical values, so the write order cannot matter
            changed.extend([changed[-1]] * (m_pad - m))
            packed = np.stack(changed)
            sig = (key, m_pad)
            if sig not in self._scatter_shapes:
                self._scatter_shapes.add(sig)
                if self._ledger is not None:
                    self._ledger.record("scatter", sig)
                else:
                    self.counters.compiles += 1
            self.counters.uploads += 1
            self.counters.upload_ints += int(packed.size)
            self.counters.dispatches += 1
            s.tokens, s.n_tokens, s.start_pos, s.tables = self._scatter(
                s.tokens, s.n_tokens, s.start_pos, s.tables, self._device(packed))
        return s

    def feed(self, key: Tuple[int, int, int], toks_prev,
             pairs: List[Tuple[int, int]]) -> None:
        """Feed previous-step sampled tokens into this step's input slots
        entirely on device (``pairs``: (dst_row, src_row))."""
        if not pairs:
            return
        s = self._slots[key]
        m_pad = round_up_pow2(len(pairs))
        arr = np.empty((m_pad, 2), np.int32)
        arr[:len(pairs)] = pairs
        arr[len(pairs):] = pairs[-1]  # duplicate writes carry identical values
        sig = (key, int(toks_prev.shape[0]), m_pad)
        if sig not in self._feed_shapes:
            self._feed_shapes.add(sig)
            if self._ledger is not None:
                self._ledger.record("feed", sig)
            else:
                self.counters.compiles += 1
        self.counters.uploads += 1
        self.counters.upload_ints += int(arr.size)
        self.counters.dispatches += 1
        s.tokens = self._feed(s.tokens, toks_prev, self._device(arr))

    def forget(self) -> None:
        """Drop every slot (tests / bucket-policy changes)."""
        self._slots.clear()
