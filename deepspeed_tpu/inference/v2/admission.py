"""Serving admission control — bounded queue, deadlines, load shedding.

The overload front door of the v2 ragged engine (the serving-side analog of
the reference's request rejection in DeepSpeed-FastGen / MII: a request the
pool cannot or should not take is turned away with a structured reason BEFORE
any KV allocation, instead of detonating the whole batch mid-step).

Three layers live here:

- :class:`AdmissionQueue` — a bounded, priority-aware queue between ``put``
  and the scheduler.  ``submit`` applies the load-shedding policy
  (:class:`ShedReason` with a retryable/fatal verdict) and stamps each ticket
  with its deadline; the engine pumps tickets into the
  ``RaggedStateManager`` only while the KV pool has headroom.
- :class:`RequestResult` — the per-request outcome ``generate(strict=False)``
  returns: every request ends in exactly one terminal status instead of the
  first failure raising away everyone else's tokens.
- :class:`ServingStalledError` — raised by the engine's progress watchdog in
  place of an unbounded ``while`` loop; carries a full state snapshot (live
  uids, block-table occupancy, allocator free count) for postmortems.

Thresholds come from ``ServingResilienceConfig`` (runtime/config.py
``serving_resilience`` section).  All host-side; nothing here touches jax.
"""

import dataclasses
import heapq
import time
from typing import Any, Dict, List, Optional, Tuple

# ----------------------------------------------------------- request statuses
OK = "ok"
SHED = "shed"
DEADLINE_EXPIRED = "deadline_expired"
PREEMPT_REQUEUED_EXHAUSTED = "preempt_requeued_exhausted"
FAILED = "failed"

REQUEST_STATUSES = (OK, SHED, DEADLINE_EXPIRED, PREEMPT_REQUEUED_EXHAUSTED, FAILED)


@dataclasses.dataclass
class RequestResult:
    """Terminal outcome of one served request.

    ``tokens`` is prompt + generated for any request that reached the model
    (possibly partial for evicted ones), empty for requests shed at admission.
    ``retryable`` tells the client whether resubmitting later can succeed
    (queue full / pool pressure / stall) or never will (over-cap prompt).
    """
    uid: int
    status: str
    tokens: List[int] = dataclasses.field(default_factory=list)
    finish_reason: Optional[str] = None  # eos | max_new_tokens | length_capped
    reason: Optional[str] = None         # failure/shed/eviction detail
    retryable: bool = False
    queue_wait_s: float = 0.0
    preemptions: int = 0
    # structured backpressure (ISSUE 17): the shed's retry_after_s hint,
    # carried through so a fleet router (or client) can back off for the
    # admission door's own pressure estimate instead of guessing
    retry_after_s: Optional[float] = None
    # machine-readable shed code (ISSUE 19): routers must distinguish a
    # per-tenant quota shed (rerouting to a sibling cannot help — the quota
    # is tenant-global) from replica-local pressure without parsing `reason`
    shed_code: Optional[str] = None

    @property
    def ok(self) -> bool:
        return self.status == OK


@dataclasses.dataclass(frozen=True)
class ShedReason:
    """Structured admission rejection, decided before any KV allocation.

    ``retry_after_s`` (retryable sheds only) is the admission door's own
    estimate of how long the pressure that caused the shed takes to clear —
    queue sheds scale with the configured depth cap, KV-pressure sheds with
    the utilization overshoot.  It turns every shed site into structured
    backpressure a fleet router can honor instead of re-hammering the same
    replica on a generic exponential clock.  None on fatal sheds (no wait
    will ever make an over-cap prompt fit).
    """
    code: str      # empty_prompt | prompt_over_cap | queue_full | kv_pressure
    detail: str
    retryable: bool
    retry_after_s: Optional[float] = None

    def __str__(self):
        kind = "retryable" if self.retryable else "fatal"
        hint = (f"; retry in ~{self.retry_after_s:.2f}s"
                if self.retry_after_s is not None else "")
        return f"[{self.code}/{kind}] {self.detail}{hint}"


class ServingStalledError(RuntimeError):
    """The serving loop was live but unschedulable for the watchdog window.

    Replaces the former spin-forever failure mode of ``generate()``
    (engine_v2: ``while len(done) < len(uids)``) with a diagnosis:
    ``snapshot`` holds live uids, per-sequence progress and block-table
    occupancy, the allocator free count, and queue depth at trip time.
    """

    def __init__(self, message: str, snapshot: Dict[str, Any]):
        super().__init__(message)
        self.snapshot = snapshot


@dataclasses.dataclass
class AdmissionTicket:
    uid: int
    prompt: List[int]
    priority: int = 0                  # lower pops first; ties are FIFO
    deadline: Optional[float] = None   # absolute clock() time; None = no TTL
    enqueue_t: float = 0.0
    # crash-recovery re-admission provenance (ISSUE 8): tokens this request
    # already emitted in a previous engine life, replayed from the durable
    # request journal.  The pump admits ``prompt + prefix`` as the sequence's
    # token history with ``prompt_len`` pinned to the ORIGINAL prompt, so the
    # recovered decode continues from where it died (the prefix counts as
    # generated output, not prompt) instead of restarting from scratch.
    prefix: List[int] = dataclasses.field(default_factory=list)
    recovered: bool = False
    # multi-tenant QoS identity (ISSUE 19): who this request belongs to and
    # which service class it rides — carried end-to-end (ticket → sequence →
    # journal → recovery) so policy decisions always see the same identity
    tenant: str = "default"
    service_class: str = "interactive"

    @property
    def token_cost(self) -> int:
        """Full token history — the DRR/quota charging unit."""
        return len(self.prompt) + len(self.prefix)


@dataclasses.dataclass
class RecoveredRequest:
    """One re-admission unit for supervised crash recovery
    (inference/v2/supervisor.py → ``engine.serve_recovered``): a journaled
    request plus its already-emitted token prefix and the REMAINING TTL
    budget (computed on the original wall-clock admit stamp, so a restart
    never refreshes a deadline).  ``prefix=[]`` re-admits a request that
    never emitted (or a brand-new one riding the same call)."""
    uid: int
    prompt: List[int]
    prefix: List[int] = dataclasses.field(default_factory=list)
    priority: int = 0
    ttl_s: Optional[float] = None      # remaining TTL; None = no deadline
    pin_ttl: bool = False              # True: ttl_s is authoritative AS-IS
    # (None = genuinely deadline-free) — a recovered request whose original
    # life had no TTL must not be handed one by the new engine's
    # default_ttl_s.  False (new requests): ttl_s=None falls through to the
    # config default exactly like generate().
    # QoS identity (ISSUE 19): replayed from the journal admit record, so a
    # restart can neither launder a best-effort request into interactive
    # nor strip a tenant of its quota accounting
    tenant: str = "default"
    service_class: str = "interactive"


class AdmissionQueue:
    """Bounded, priority-aware admission queue with structured load shedding.

    ``submit`` either enqueues a ticket (stamped with its TTL deadline) or
    returns the :class:`ShedReason` that turned it away — the caller decides
    whether that raises (strict) or becomes a ``shed`` RequestResult.  The
    shedding policy runs against queue depth and the CALLER-OBSERVED KV
    utilization, so rejection happens before the request ever owns a block.

    ``clock`` is injectable (fault tests drive a fake clock); defaults to
    ``time.monotonic``.  ``tracer`` (monitor/tracing.py RequestTracer) hears
    about every intake decision: a ``queue_wait`` span opens at submit, a
    shed becomes a terminal trace event, and shed/submit land in the
    always-on flight recorder — the request-lifecycle chain starts at this
    front door (ISSUE 6).
    """

    def __init__(self, config=None, *, clock=time.monotonic, tracer=None,
                 qos=None):
        from ...runtime.config import ServingResilienceConfig
        self.config = config if config is not None else ServingResilienceConfig()
        self.clock = clock
        self.tracer = tracer
        self._heap: List[Tuple[int, int, AdmissionTicket]] = []
        self._seq = 0  # FIFO tiebreak within a priority class
        self.submitted_total = 0
        self.shed_total = 0
        # per-code shed accounting (ISSUE 17): lifetime counts plus the last
        # retry_after_s hint issued per code — exported as the labeled
        # Prometheus shed families next to the unlabeled shed_total
        self.shed_by_code: Dict[str, int] = {}
        self.last_retry_after: Dict[str, float] = {}
        # multi-tenant QoS (ISSUE 19): with an enabled policy the single
        # priority heap becomes per-service-class heaps drained by
        # deficit-round-robin on token cost; quota sheds happen in submit.
        # qos=None keeps every code path below byte-identical to PR-4.
        self.qos = qos if (qos is not None and qos.enabled) else None
        self._drr = self.qos.make_drr() if self.qos is not None else None
        self._classes: Dict[str, List[Tuple[int, int, AdmissionTicket]]] = {}

    def __len__(self) -> int:
        if self._drr is not None:
            return sum(len(h) for h in self._classes.values())
        return len(self._heap)

    # ------------------------------------------------------------- shedding
    def shed_reason(self, prompt_len: int, *, kv_utilization: Optional[float] = None,
                    token_cap: Optional[int] = None) -> Optional[ShedReason]:
        """The policy verdict for a prospective request; None = admit."""
        if prompt_len <= 0:
            return ShedReason("empty_prompt", "prompt has no tokens — a zero-pending "
                              "sequence can never be scheduled or retired", retryable=False)
        if token_cap is not None and prompt_len > token_cap:
            return ShedReason("prompt_over_cap",
                              f"prompt of {prompt_len} tokens exceeds the per-sequence "
                              f"KV cap of {token_cap} tokens", retryable=False)
        depth_cap = self.config.max_queue_depth
        if depth_cap and len(self) >= depth_cap:
            # retry hint ~ time to drain a full queue: scale with the depth
            # cap (a deeper queue takes longer to clear), clamped to a
            # [0.05s, 2s] band so the hint is always a sane client backoff
            return ShedReason("queue_full",
                              f"admission queue at max_queue_depth={depth_cap}",
                              retryable=True,
                              retry_after_s=min(2.0, max(0.05, 0.025 * depth_cap)))
        shed_at = self.config.shed_kv_utilization
        if kv_utilization is not None and shed_at < 1.0 and kv_utilization >= shed_at:
            # retry hint grows with the overshoot past the shed threshold: a
            # pool 1% over the line frees a block soon; one pinned at 100%
            # needs requests to retire first
            return ShedReason("kv_pressure",
                              f"KV utilization {kv_utilization:.3f} >= shed threshold "
                              f"{shed_at} (pool pressure)", retryable=True,
                              retry_after_s=min(2.0, 0.1 + 4.0 * (kv_utilization - shed_at)))
        return None

    # --------------------------------------------------------------- intake
    def submit(self, uid: int, prompt: List[int], *, priority: int = 0,
               ttl_s: Optional[float] = None, kv_utilization: Optional[float] = None,
               token_cap: Optional[int] = None, prefix: Optional[List[int]] = None,
               apply_default_ttl: bool = True, recovered: bool = False,
               tenant: Optional[str] = None,
               service_class: Optional[str] = None) -> Optional[ShedReason]:
        """Admit-or-shed.  Returns None on admission, else the ShedReason.

        ``prefix``/``recovered`` carry crash-recovery provenance (ISSUE 8):
        the shedding policy sees the FULL token history (prompt + prefix) —
        a recovered request whose history no longer fits the per-sequence KV
        cap is a genuine rejection, not an accounting accident.
        ``apply_default_ttl=False`` pins ``ttl_s`` as authoritative
        (None = deadline-free) so a re-admission never refreshes or invents
        a deadline the original request didn't have.

        ``tenant``/``service_class`` (ISSUE 19): with a QoS policy armed the
        structural checks run first (an over-cap prompt is fatal no matter
        whose it is), then the tenant's token-rate/KV quotas — a quota
        violation is a retryable ``quota_exceeded`` shed whose
        ``retry_after_s`` is the bucket's exact refill time.  Recovered
        requests bypass quota enforcement: their cost was charged in the
        life that admitted them, and recovery must not double-charge (or
        shed) work the journal already accepted."""
        self.submitted_total += 1
        prefix = list(prefix) if prefix else []
        tenant = str(tenant) if tenant else "default"
        if self.qos is not None:
            service_class = self.qos.service_class(service_class)
        elif service_class is None:
            service_class = "interactive"
        reason = self.shed_reason(len(prompt) + len(prefix),
                                  kv_utilization=kv_utilization,
                                  token_cap=token_cap)
        if reason is None and self.qos is not None and not recovered:
            reason = self.qos.admission_check(tenant, service_class,
                                              len(prompt) + len(prefix))
        if reason is not None:
            self.shed_total += 1
            self.shed_by_code[reason.code] = self.shed_by_code.get(reason.code, 0) + 1
            if reason.retry_after_s is not None:
                self.last_retry_after[reason.code] = reason.retry_after_s
            if self.qos is not None:
                self.qos.note_shed(tenant, reason.code, reason.retry_after_s)
            if self.tracer is not None:
                if self.tracer.enabled:
                    # sheds never reach the ticket stamp below, so span
                    # tracing pays one clock read here — otherwise a fresh
                    # engine's shed records carry the stale last-ticked value
                    self.tracer.tick(self.clock())
                self.tracer.event("shed", uid=int(uid), code=reason.code)
                self.tracer.on_shed(int(uid), reason.code, retryable=reason.retryable,
                                    detail=reason.detail)
            return reason
        now = self.clock()
        if ttl_s is not None or not apply_default_ttl:
            ttl = ttl_s
        else:
            ttl = self.config.default_ttl_s
        # `is not None`, not truthiness: an explicit ttl of 0.0 (a spent
        # budget) means "already expired", not "no deadline"
        ticket = AdmissionTicket(uid=int(uid), prompt=list(prompt), priority=int(priority),
                                 deadline=(now + ttl) if ttl is not None else None,
                                 enqueue_t=now, prefix=prefix,
                                 recovered=bool(recovered),
                                 tenant=tenant, service_class=service_class)
        if self._drr is not None:
            heapq.heappush(self._classes.setdefault(service_class, []),
                           (ticket.priority, self._seq, ticket))
            self.qos.note_admit(tenant, service_class, ticket.token_cost)
        else:
            heapq.heappush(self._heap, (ticket.priority, self._seq, ticket))
        self._seq += 1
        if self.tracer is not None:
            # the queue_wait span opens on the SAME clock value the ticket
            # was stamped with — tracing adds no clock reads at this seam
            self.tracer.tick(now)
            self.tracer.event("submit", uid=ticket.uid, priority=ticket.priority)
            self.tracer.on_submit(ticket.uid, now,
                                  prompt_len=len(ticket.prompt) + len(ticket.prefix),
                                  priority=ticket.priority,
                                  tenant=(tenant if self.qos is not None else None))
        return None

    # ---------------------------------------------------------------- drain
    def pop_ready(self) -> Tuple[Optional[AdmissionTicket], List[AdmissionTicket]]:
        """Pop the next ticket whose deadline has not passed.

        Returns ``(ticket_or_none, expired)`` — tickets that died waiting in
        the queue come back in ``expired`` so the engine can finalize them as
        ``deadline_expired`` (they never owned KV blocks).
        """
        expired: List[AdmissionTicket] = []
        now = self.clock()
        if self.tracer is not None:
            self.tracer.tick(now)  # donate the already-read clock value
        if self._drr is not None:
            return self._pop_fair(now, expired), expired
        while self._heap:
            _, _, ticket = heapq.heappop(self._heap)
            if ticket.deadline is not None and now >= ticket.deadline:
                expired.append(ticket)
                continue
            return ticket, expired
        return None, expired

    def _pop_fair(self, now: float,
                  expired: List[AdmissionTicket]) -> Optional[AdmissionTicket]:
        """Weighted-fair pop: sweep each class's expired heads (they never
        reach the DRR — a dead ticket must not charge its class's deficit),
        then let the DRR pick among the live heads by token cost."""
        head_costs: Dict[str, int] = {}
        for cls, heap in list(self._classes.items()):
            while heap:
                ticket = heap[0][2]
                if ticket.deadline is not None and now >= ticket.deadline:
                    expired.append(heapq.heappop(heap)[2])
                    continue
                head_costs[cls] = max(1, ticket.token_cost)
                break
            if not heap:
                del self._classes[cls]
        cls = self._drr.select(head_costs)
        if cls is None:
            return None
        ticket = heapq.heappop(self._classes[cls])[2]
        if not self._classes[cls]:
            del self._classes[cls]
        return ticket

    def _entries(self) -> List[Tuple[int, int, AdmissionTicket]]:
        if self._drr is not None:
            return [e for heap in self._classes.values() for e in heap]
        return self._heap

    def queued_stats(self) -> Tuple[int, int]:
        """(depth, longest queued prompt) without mutating the queue — the
        serve-time compile-cache prewarm sizes its candidate buckets from
        what is actually waiting to be admitted."""
        entries = self._entries()
        if not entries:
            return 0, 0
        return len(entries), max(len(e[2].prompt) + len(e[2].prefix)
                                 for e in entries)

    def drain(self) -> List[AdmissionTicket]:
        """Remove and return every queued ticket (stall cleanup), in pop order."""
        out = [entry[2] for entry in sorted(self._entries(),
                                            key=lambda e: (e[0], e[1]))]
        self._heap = []
        self._classes = {}
        return out
