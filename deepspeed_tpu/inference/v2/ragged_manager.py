"""Ragged state manager — sequence tracking + block-table bookkeeping.

Analog of DSStateManager / DSSequenceDescriptor (inference/v2/ragged/
ragged_manager.py:19, sequence_descriptor.py): tracks live sequences, grows
their block tables as tokens are scheduled, and frees blocks at retirement.
All host-side (numpy); the device sees only the padded block-table array.

Resilience hooks (ISSUE 4): sequences carry admission metadata (arrival order,
priority, deadline, preemption count), :meth:`RaggedStateManager.preempt`
rolls a prefilling victim back to a block boundary so its KV blocks can rescue
starved decodes, and the intake/retire edges validate loudly —
:class:`EmptyPromptError` for a request that could never be scheduled,
:class:`UnknownSequenceError` (with the uid's actual history) instead of a
bare ``KeyError`` on a bad retire.

Copy-on-write prefix caching (ISSUE 13): :class:`PrefixCache` is the prefix
tree PR 12's ``PrefixObservatory`` measured the counterfactual for — keyed on
the SAME chained token-block hashes (:func:`kv_metrics.block_hashes`), so the
realized win lands against the metric that predicted it.  An admitted request
whose leading full prompt blocks match live, fully-computed blocks maps them
READ-ONLY (allocator refcount +1 per mapping) and only prefills its divergent
tail into freshly allocated private blocks; a prompt cached to its last token
copies the final block (copy-on-write — the engine provides the device block
copy) so the one recomputed position writes a private block, never a shared
one.  Entries are weak: the tree serves a block only while some sequence
still maps it (the allocator's free() reports refcount-zero releases and the
tree drops those entries), so a drained pool is a fully-reclaimed pool and
sharing reaches exactly as far as the observatory's live-set counterfactual.
"""

import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .blocked_allocator import BlockedAllocator, KVAllocationError
from .kv_metrics import block_hashes, tenant_namespace

# finish reasons that mark an EVICTION (the request did not run to a useful
# completion); retire() excludes them from completed_requests even when the
# caller flushes through the default completed=True path
EVICTED_FINISH_REASONS = frozenset({"deadline_expired", "preempt_requeued_exhausted"})


class EmptyPromptError(ValueError):
    """A request arrived with zero prompt tokens.  Such a sequence has
    ``pending_tokens == 0`` forever: the scheduler never picks it, it never
    retires, and ``generate()`` would spin on it — reject at intake."""

    def __init__(self, uid: int):
        super().__init__(f"uid {uid}: empty prompt — a sequence with no pending "
                         f"tokens can never be scheduled or retired")
        self.uid = uid


class UnknownSequenceError(KeyError):
    """Retire/lookup of a uid the manager does not track, with its history
    (already retired / failed-and-flushed / never added) in the message."""

    def __init__(self, uid: int, detail: str):
        super().__init__(f"uid {uid} is not tracked by RaggedStateManager ({detail})")
        self.uid = uid


@dataclasses.dataclass
class SequenceDescriptor:
    uid: int
    tokens: List[int]  # full known token ids (prompt + generated)
    seen_tokens: int = 0  # tokens already in the KV cache
    blocks: List[int] = dataclasses.field(default_factory=list)
    done: bool = False
    # --- admission / resilience metadata (inference/v2/admission.py) ---
    prompt_len: int = 0        # len(tokens) at intake; generated = len(tokens) - prompt_len
    arrival: int = 0           # monotonic intake order; preemption evicts the newest
    priority: int = 0          # lower = more urgent (admission-queue order)
    deadline: Optional[float] = None  # absolute clock time; engine evicts past it
    queue_wait_s: float = 0.0  # time spent in the admission queue
    preemptions: int = 0       # times this sequence was preempted-and-requeued
    finish_reason: Optional[str] = None  # eos | max_new_tokens | length_capped | ...
    # --- prefix-cache state (ISSUE 13) ---
    # chained hashes of the FULL blocks of the prompt portion (computed once
    # at intake when the cache is armed; never covers generated tokens)
    prefix_hashes: Optional[List[bytes]] = None
    # prompt blocks already offered to the tree (mapped-from-cache blocks
    # count immediately; self-computed ones as prefill completes them) —
    # preemption rolls this back with the block table
    prefix_registered: int = 0
    # prefill tokens this sequence skipped by mapping shared blocks
    prefix_cached_tokens: int = 0
    # --- multi-tenant QoS identity (ISSUE 19) ---
    # owner tenant + service class, carried from the admission ticket: the
    # prefix-cache keying folds the tenant in (cross-tenant sharing is
    # impossible) and KV-pressure preemption prefers over-quota /
    # lower-class victims
    tenant: str = "default"
    service_class: str = "interactive"

    @property
    def pending_tokens(self) -> int:
        return len(self.tokens) - self.seen_tokens

    @property
    def in_prefill(self) -> bool:
        return self.seen_tokens < len(self.tokens) - 1

    @property
    def generated_tokens(self) -> int:
        return len(self.tokens) - self.prompt_len


@dataclasses.dataclass
class PrefixEntry:
    """One shareable, fully-computed prompt block.  ``tokens`` (the block's
    actual token ids) and ``parent`` (the previous block's chained hash) are
    stored so a lookup VERIFIES content, never trusts a hash alone — a
    colliding hash must not map one request onto another's KV."""
    block: int
    tokens: Tuple[int, ...]
    parent: bytes


class PrefixCache:
    """The copy-on-write prefix tree over the paged KV pool (ISSUE 13).

    Keyed on the chained token-block hashes of :func:`kv_metrics.block_hashes`
    — block ``i``'s hash covers its tokens AND its ancestry, so a flat
    ``hash -> entry`` dict IS the tree (matching a node implies matching the
    whole path to the root).  Entries are weak: a block is served only while
    at least one sequence still maps it; :meth:`invalidate_blocks` (driven by
    the allocator's refcount-zero releases at the manager's one reclaim seam)
    drops dead entries, so a drained pool leaves an empty tree and the pool
    is always fully reclaimed.

    ``defer_shared_prefill``: the scheduler skips a prefill chunk for one
    step when another SCHEDULED sequence is computing the exact block it
    needs — next step the block is computed and maps as a hit, converting
    same-wave duplicate prefill into a one-step delay plus a cache hit
    (realized savings match the observatory's same-intake counterfactual).

    All counters are host ints (JSON-safe); nothing here touches jax — the
    one device action (the CoW block copy) is a callable the engine installs
    on the manager.
    """

    def __init__(self, block_size: int, *, cow: bool = True,
                 defer_shared_prefill: bool = True):
        self.block_size = int(block_size)
        self.cow = bool(cow)
        self.defer_shared_prefill = bool(defer_shared_prefill)
        self.entries: Dict[bytes, PrefixEntry] = {}
        self._by_block: Dict[int, bytes] = {}
        # realized-savings counters (the observatory's counterfactual twins)
        self.hits_total = 0              # blocks mapped read-only from the tree
        self.cow_copies_total = 0        # fully-cached prompts served via block copy
        self.misses_total = 0            # full prompt blocks computed by their own request
        self.tokens_saved_total = 0      # prefill tokens skipped (realized)
        self.registered_total = 0        # distinct entries ever inserted
        self.evicted_total = 0           # entries dropped because the block was freed
        self.collision_rejects_total = 0  # hash matched, token ids/ancestry did not
        self.deferrals_total = 0         # prefill chunks deferred one step onto a
        # block another scheduled sequence is computing

    def __len__(self) -> int:
        return len(self.entries)

    def register(self, h: bytes, parent: bytes, block: int,
                 tokens: Tuple[int, ...]) -> bool:
        """Offer a fully-computed prompt block to the tree.  First writer
        wins: an existing entry for ``h`` is kept (two same-step co-prefills
        of the same content both stay valid; only one is served)."""
        if h in self.entries:
            return False
        self.entries[h] = PrefixEntry(block=int(block), tokens=tuple(tokens),
                                      parent=bytes(parent))
        self._by_block[int(block)] = h
        self.registered_total += 1
        return True

    def lookup(self, h: bytes, parent: bytes,
               tokens: Tuple[int, ...]) -> Optional[int]:
        """Block id for ``h`` IF the entry's actual token ids and ancestry
        match (hash-collision safety); None on miss or verification failure."""
        entry = self.entries.get(h)
        if entry is None:
            return None
        if entry.tokens != tuple(tokens) or entry.parent != bytes(parent):
            self.collision_rejects_total += 1
            return None
        return entry.block

    def invalidate_blocks(self, blocks: List[int]) -> None:
        """Drop entries whose block went back to the free list (refcount hit
        zero) — its KV is about to belong to someone else."""
        for b in blocks:
            h = self._by_block.pop(int(b), None)
            if h is not None and self.entries.pop(h, None) is not None:
                self.evicted_total += 1

    @property
    def hit_blocks_total(self) -> int:
        """Blocks the tree served instead of a prefill — read-only shared
        mappings plus CoW copies.  THE definition of a 'hit block'; every
        exporter (gauges, /metrics, bench) reads this one spelling."""
        return self.hits_total + self.cow_copies_total

    def realized_hit_rate(self) -> float:
        """Shared-or-copied blocks over all full prompt blocks that entered
        the pool — directly comparable to the observatory's counterfactual
        ``hit_rate``."""
        total = self.hit_blocks_total + self.misses_total
        return self.hit_blocks_total / total if total else 0.0

    def snapshot(self) -> Dict[str, object]:
        return {
            "enabled": True,
            "entries": len(self.entries),
            "hit_blocks_total": self.hit_blocks_total,
            "hits_total": self.hits_total,
            "cow_copies_total": self.cow_copies_total,
            "misses_total": self.misses_total,
            "tokens_saved_total": self.tokens_saved_total,
            "registered_total": self.registered_total,
            "evicted_total": self.evicted_total,
            "collision_rejects_total": self.collision_rejects_total,
            "deferrals_total": self.deferrals_total,
            "realized_hit_rate": self.realized_hit_rate(),
        }


class RaggedStateManager:

    def __init__(self, num_blocks: int, block_size: int, max_blocks_per_seq: int,
                 prefix_cache: Optional[PrefixCache] = None):
        self.allocator = BlockedAllocator(num_blocks)
        self.block_size = block_size
        self.max_blocks_per_seq = max_blocks_per_seq
        # block census (inference/v2/kv_metrics.BlockCensus) — attached by the
        # engine when kv observability is on.  Hooks fire at the manager's
        # ONE alloc seam (ensure_blocks) and ONE reclaim seam (_reclaim), so
        # every path that moves a block keeps the census exact; pure host
        # bookkeeping, never a device touch.
        self.census = None
        # copy-on-write prefix tree (ISSUE 13) — None disables sharing; the
        # engine installs ``cow_copy`` (the ONE device action: duplicate a
        # shared block's KV into a private block) next to it
        self.prefix_cache = prefix_cache
        self.cow_copy: Optional[Callable[[int, int], None]] = None
        self.seqs: Dict[int, SequenceDescriptor] = {}
        self.failures: Dict[int, str] = {}
        # uid history for descriptive retire errors; a bounded recency window
        # (insertion-ordered dict) so a long-lived server doesn't grow it
        # forever — uids older than the window degrade to "never added"
        self.retired_uids: Dict[int, None] = {}
        self._retired_window = 4096
        # lifetime counters feeding the telemetry gauges (requests/sec is the
        # collector-side rate over completed_requests)
        self.total_requests = 0
        self.completed_requests = 0
        self.failed_requests = 0
        self._arrivals = 0

    @property
    def trash_block(self) -> int:
        return self.allocator.trash_block

    def add_sequence(self, uid: int, prompt_tokens: List[int], *, priority: int = 0,
                     deadline: Optional[float] = None,
                     queue_wait_s: float = 0.0,
                     prompt_len: Optional[int] = None,
                     tenant: str = "default",
                     service_class: str = "interactive") -> SequenceDescriptor:
        """``prompt_len`` pins where prompt ends and generated output begins
        when it differs from ``len(prompt_tokens)`` — crash recovery re-admits
        ``prompt + already-emitted-prefix`` as the token history (the prefill
        rebuilds their KV in one pass) while the prefix keeps counting as
        GENERATED tokens for budgets, results, and gauges."""
        if uid in self.seqs:
            raise ValueError(f"uid {uid} already tracked")
        if not prompt_tokens:
            raise EmptyPromptError(uid)
        if prompt_len is None:
            prompt_len = len(prompt_tokens)
        elif not 0 < prompt_len <= len(prompt_tokens):
            raise ValueError(f"uid {uid}: prompt_len={prompt_len} outside "
                             f"(0, {len(prompt_tokens)}]")
        seq = SequenceDescriptor(uid=uid, tokens=list(prompt_tokens),
                                 prompt_len=int(prompt_len), arrival=self._arrivals,
                                 priority=priority, deadline=deadline,
                                 queue_wait_s=queue_wait_s,
                                 tenant=str(tenant) if tenant else "default",
                                 service_class=service_class)
        if self.prefix_cache is not None:
            # the tree's keying, computed once per life: chained hashes over
            # the PROMPT portion only (a recovered request's replayed prefix
            # is generated output — never shareable read-only).  The chain
            # is seeded with the tenant namespace (ISSUE 19): cross-tenant
            # prompts hash to disjoint chains, so the cache STRUCTURALLY
            # cannot share a block across tenants; the default tenant keeps
            # the legacy empty seed (single-tenant keying unchanged)
            seq.prefix_hashes = block_hashes(seq.tokens[:seq.prompt_len],
                                             self.block_size,
                                             tenant_namespace(seq.tenant))
        self._arrivals += 1
        self.seqs[uid] = seq
        self.total_requests += 1
        return seq

    def ensure_blocks(self, seq: SequenceDescriptor, upto_tokens: int) -> None:
        """Grow the block table to cover ``upto_tokens`` cache positions."""
        need = (upto_tokens + self.block_size - 1) // self.block_size
        if need > self.max_blocks_per_seq:
            raise RuntimeError(f"uid {seq.uid}: {upto_tokens} tokens exceeds "
                               f"max_blocks_per_seq={self.max_blocks_per_seq}")
        if need > len(seq.blocks):
            grown = self.allocator.allocate(need - len(seq.blocks))
            seq.blocks.extend(grown)
            if self.census is not None:
                self.census.on_alloc(seq.uid, grown)

    def _reclaim(self, uid: int, blocks: List[int]) -> List[int]:
        """THE reclaim seam: every block leaving a sequence releases its
        mapping here, with the census kept in lock-step.  Shared blocks only
        decrement; the prefix tree drops entries exactly for the blocks whose
        refcount reached zero (their KV is about to belong to someone else).
        Returns the blocks that actually went back to the free list."""
        released = self.allocator.free(blocks)
        if self.census is not None:
            self.census.on_free(uid, blocks)
        if self.prefix_cache is not None and released:
            self.prefix_cache.invalidate_blocks(released)
        return released

    # ------------------------------------------------- prefix caching (ISSUE 13)
    def map_prefix(self, seq: SequenceDescriptor) -> int:
        """Map as many of ``seq``'s leading full prompt blocks as the tree
        can serve, advancing ``seen_tokens`` past the cached KV.  Returns the
        number of prefill tokens skipped.

        Mapping is read-only (allocator refcount +1; census gains an owner)
        and only proceeds while the sequence sits exactly at a block boundary
        with no private progress — the first divergent or missing block stops
        it, and everything after is prefilled into freshly allocated private
        blocks, so decode always writes a private tail block.

        A prompt cached to its LAST token is the copy-on-write case: mapping
        the final block read-only would leave nothing pending (no position to
        produce first-token logits from), and recomputing its last position
        would WRITE into the shared block.  Instead the final block's KV is
        copied into a private block (``cow_copy``, the engine's one-dispatch
        device copy), ``seen_tokens`` lands at ``prompt_len - 1``, and the
        single recomputed position rewrites its identical KV into the private
        copy.  Without a copy seam (bare-manager tests, cow disabled) the
        final block is simply recomputed — correct, one block less saved.

        Called at admit time (the engine's pump / ``put``) and again by the
        scheduler before each prefill chunk, so a block computed AFTER this
        sequence was admitted — by an earlier request of the same wave, or by
        the pre-crash life a journal-replayed request is rejoining — still
        maps (late binding).  Idempotent and cheap on a miss: one dict probe.
        """
        cache = self.prefix_cache
        if cache is None or seq.done or not seq.prefix_hashes:
            return 0
        bs = self.block_size
        saved = 0
        while True:
            i = len(seq.blocks)
            if seq.seen_tokens != i * bs or i >= len(seq.prefix_hashes):
                break  # private progress past the boundary, or past the prompt
            if seq.prefix_hashes[i] not in cache.entries:
                break  # miss — probe before building the token tuple
            parent = (seq.prefix_hashes[i - 1] if i
                      else tenant_namespace(seq.tenant))
            block = cache.lookup(seq.prefix_hashes[i], parent,
                                 tuple(seq.tokens[i * bs:(i + 1) * bs]))
            if block is None:
                break  # collision/verification reject
            if (i + 1) * bs >= seq.prompt_len:
                saved += self._cow_map_final(seq, block)
                break
            self.allocator.incref(block)
            if self.census is not None:
                self.census.on_share(seq.uid, block)
            seq.blocks.append(block)
            seq.prefix_registered = len(seq.blocks)
            seq.seen_tokens += bs
            cache.hits_total += 1
            saved += bs
        if saved:
            cache.tokens_saved_total += saved
            seq.prefix_cached_tokens += saved
        return saved

    def _cow_map_final(self, seq: SequenceDescriptor, src: int) -> int:
        """Copy-on-write for a fully-cached prompt: duplicate ``src``'s KV
        into a private block, map the copy, and leave exactly one prompt
        position pending (its recompute writes identical KV into the COPY,
        never the shared block).  Declines — the block is recomputed instead
        — when no copy seam is installed or the pool can't spare the block."""
        cache = self.prefix_cache
        if self.cow_copy is None or not cache.cow:
            return 0
        try:
            dst = self.allocator.allocate(1)[0]
        except KVAllocationError:
            return 0  # pool-tight/injected fault: recompute instead
        self.cow_copy(src, dst)
        if self.census is not None:
            self.census.on_alloc(seq.uid, [dst])
        seq.blocks.append(dst)
        seq.prefix_registered = len(seq.blocks)
        seq.seen_tokens = seq.prompt_len - 1
        cache.cow_copies_total += 1
        return self.block_size - 1

    def next_prefix_hash(self, seq: SequenceDescriptor) -> Optional[bytes]:
        """The hash of the next full prompt block ``seq`` needs, or None when
        it has private progress / is past its prompt.  After
        :meth:`map_prefix` this is by construction a TREE MISS — the
        scheduler defers the chunk one step iff another scheduled sequence is
        computing exactly this block."""
        if self.prefix_cache is None or not seq.prefix_hashes:
            return None
        i = len(seq.blocks)
        if seq.seen_tokens != i * self.block_size or i >= len(seq.prefix_hashes):
            return None
        return seq.prefix_hashes[i]

    def register_prefix_blocks(self, seq: SequenceDescriptor) -> int:
        """Offer ``seq``'s newly COMPLETED full prompt blocks to the tree
        (called after every ``seen_tokens`` advance; mapped-from-cache blocks
        were marked registered at mapping, so only self-computed blocks — the
        misses — walk here).  Returns how many blocks were offered."""
        cache = self.prefix_cache
        if cache is None or not seq.prefix_hashes:
            return 0
        bs = self.block_size
        n_complete = min(min(seq.seen_tokens, seq.prompt_len) // bs,
                         len(seq.prefix_hashes), len(seq.blocks))
        offered = 0
        while seq.prefix_registered < n_complete:
            i = seq.prefix_registered
            cache.register(seq.prefix_hashes[i],
                           (seq.prefix_hashes[i - 1] if i
                            else tenant_namespace(seq.tenant)),
                           seq.blocks[i],
                           tuple(seq.tokens[i * bs:(i + 1) * bs]))
            cache.misses_total += 1
            seq.prefix_registered = i + 1
            offered += 1
        return offered

    def over_cap(self, upto_tokens: int) -> bool:
        return (upto_tokens + self.block_size - 1) // self.block_size > self.max_blocks_per_seq

    def fail(self, uid: int, reason: str) -> None:
        self.failures[uid] = reason
        self.failed_requests += 1
        seq = self.seqs.get(uid)
        if seq is not None:
            seq.done = True
            self._reclaim(uid, seq.blocks)  # reclaim the KV pool immediately
            seq.blocks = []

    def evict(self, seq: SequenceDescriptor, finish_reason: str) -> int:
        """End a sequence WITHOUT completion: done + finish reason + KV blocks
        reclaimed in place.  The single primitive behind deadline expiry and
        preemption-budget exhaustion, so reason-aware accounting (retire()
        excludes EVICTED_FINISH_REASONS from completed_requests) has one seam.
        Returns the blocks ACTUALLY released to the pool (shared mappings only
        decrement)."""
        seq.done = True
        seq.finish_reason = finish_reason
        released = 0
        if seq.blocks:
            released = len(self._reclaim(seq.uid, seq.blocks))
            seq.blocks = []
        return released

    def preempt(self, seq: SequenceDescriptor, keep_blocks: int = 0) -> int:
        """Preempt-and-requeue support: free the sequence's trailing KV blocks
        and roll ``seen_tokens`` back to the kept-block boundary.  The prefix
        KV in the kept blocks stays valid (prefill wrote those positions and
        they are never rewritten); the dropped positions are simply recomputed
        when the sequence is rescheduled.  Returns the number of blocks
        ACTUALLY released to the pool — dropping a SHARED mapping returns no
        capacity, and the scheduler's rescue policy keys on this."""
        released = self.rollback_blocks(seq, keep_blocks)
        seq.seen_tokens = min(seq.seen_tokens, len(seq.blocks) * self.block_size)
        return released

    def rollback_blocks(self, seq: SequenceDescriptor, keep_blocks: int) -> int:
        """Free a sequence's trailing blocks past ``keep_blocks`` WITHOUT
        touching its progress — the burst pre-allocation rollback (a failed
        mid-grab returns exactly the blocks it took) and the lower half of
        :meth:`preempt`.  Returns the number of blocks actually released to
        the pool (mappings of shared blocks only decrement the refcount)."""
        keep_blocks = max(0, min(int(keep_blocks), len(seq.blocks)))
        dropped = seq.blocks[keep_blocks:]
        released = 0
        if dropped:
            released = len(self._reclaim(seq.uid, dropped))
            seq.blocks = seq.blocks[:keep_blocks]
            # dropped prompt blocks must be re-offered (or re-mapped) when
            # the sequence resumes — the registration watermark rolls back
            # with the table
            seq.prefix_registered = min(seq.prefix_registered, keep_blocks)
        return released

    def releasable_blocks(self, seq: SequenceDescriptor, keep_blocks: int) -> int:
        """How many of ``seq``'s trailing blocks past ``keep_blocks`` would
        ACTUALLY return to the pool if dropped — blocks mapped by another
        sequence too only lose a refcount.  The scheduler's preemption rescue
        uses this to pick victims whose rollback reclaims real capacity
        instead of burning a shared-prefix victim's budget for nothing."""
        keep_blocks = max(0, min(int(keep_blocks), len(seq.blocks)))
        return sum(1 for b in seq.blocks[keep_blocks:]
                   if self.allocator.refcount(b) == 1)

    def can_allocate(self, n_blocks: int) -> bool:
        return self.allocator.free_blocks >= n_blocks

    def blocks_needed(self, seq: SequenceDescriptor, upto_tokens: int) -> int:
        need = (upto_tokens + self.block_size - 1) // self.block_size
        return max(0, need - len(seq.blocks))

    def block_table_row(self, seq: SequenceDescriptor,
                        width: Optional[int] = None) -> np.ndarray:
        """Padded block-table row for the device batch; ``width`` bounds it to
        the step's bucketed table width (the fast path packs rows at exactly
        the compiled width instead of building max_blocks_per_seq and
        slicing)."""
        width = self.max_blocks_per_seq if width is None else width
        row = np.full(width, self.trash_block, np.int32)
        row[:len(seq.blocks)] = seq.blocks
        return row

    def retire(self, uid: int, *, completed: bool = True) -> None:
        """Drop a sequence and reclaim its blocks.  ``completed=False`` marks
        an eviction (deadline/shed/stall) so it doesn't count as a completion.
        Unknown uids raise :class:`UnknownSequenceError` naming what actually
        happened to the uid instead of a bare ``KeyError``."""
        seq = self.seqs.pop(uid, None)
        if seq is None:
            if uid in self.failures:
                detail = f"it failed ({self.failures[uid]!r})"
                if uid in self.retired_uids:
                    detail += " and was already flushed"
            elif uid in self.retired_uids:
                detail = "it was already retired"
            else:
                detail = "it was never added"
            raise UnknownSequenceError(uid, detail)
        self.retired_uids.pop(uid, None)  # re-adding refreshes recency
        self.retired_uids[uid] = None
        while len(self.retired_uids) > self._retired_window:
            self.retired_uids.pop(next(iter(self.retired_uids)))
        self._reclaim(uid, seq.blocks)
        seq.blocks = []
        if self.census is not None:
            self.census.on_terminal(uid)
        # neither a flushed failure nor an evicted request is a completion
        if (completed and uid not in self.failures
                and seq.finish_reason not in EVICTED_FINISH_REASONS):
            self.completed_requests += 1

    def live_uids(self) -> List[int]:
        # list copy first (GIL-atomic): health() threads call this while the
        # serve thread admits/retires sequences; the comprehension's per-item
        # bytecode would otherwise crash on a concurrent insert
        return [uid for uid, s in list(self.seqs.items()) if not s.done]

    def kv_utilization(self) -> float:
        """Fraction of the usable KV pool currently allocated (trash block
        excluded) — the paged-attention memory-pressure gauge."""
        usable = self.allocator.num_blocks - 1
        return (usable - self.allocator.free_blocks) / max(usable, 1)

    def tenant_blocks(self, tenant: str) -> int:
        """Resident KV blocks mapped by ``tenant``'s live sequences — the
        QoS layer's KV-quota denominator.  Shared (prefix) blocks count
        once per mapper: a tenant pays for every mapping it holds, which
        is exactly what its eviction would release pressure on.  List copy
        first (GIL-atomic) for the same concurrent-mutation reason as
        :meth:`live_uids`."""
        return sum(len(s.blocks) for s in list(self.seqs.values())
                   if not s.done and s.tenant == tenant)

    def tenant_block_usage(self) -> Dict[str, int]:
        """{tenant: resident blocks} over live sequences (gauge export)."""
        out: Dict[str, int] = {}
        for s in list(self.seqs.values()):
            if not s.done and s.blocks:
                out[s.tenant] = out.get(s.tenant, 0) + len(s.blocks)
        return out
