"""Ragged state manager — sequence tracking + block-table bookkeeping.

Analog of DSStateManager / DSSequenceDescriptor (inference/v2/ragged/
ragged_manager.py:19, sequence_descriptor.py): tracks live sequences, grows
their block tables as tokens are scheduled, and frees blocks at retirement.
All host-side (numpy); the device sees only the padded block-table array.

Resilience hooks (ISSUE 4): sequences carry admission metadata (arrival order,
priority, deadline, preemption count), :meth:`RaggedStateManager.preempt`
rolls a prefilling victim back to a block boundary so its KV blocks can rescue
starved decodes, and the intake/retire edges validate loudly —
:class:`EmptyPromptError` for a request that could never be scheduled,
:class:`UnknownSequenceError` (with the uid's actual history) instead of a
bare ``KeyError`` on a bad retire.
"""

import dataclasses
from typing import Dict, List, Optional

import numpy as np

from .blocked_allocator import BlockedAllocator

# finish reasons that mark an EVICTION (the request did not run to a useful
# completion); retire() excludes them from completed_requests even when the
# caller flushes through the default completed=True path
EVICTED_FINISH_REASONS = frozenset({"deadline_expired", "preempt_requeued_exhausted"})


class EmptyPromptError(ValueError):
    """A request arrived with zero prompt tokens.  Such a sequence has
    ``pending_tokens == 0`` forever: the scheduler never picks it, it never
    retires, and ``generate()`` would spin on it — reject at intake."""

    def __init__(self, uid: int):
        super().__init__(f"uid {uid}: empty prompt — a sequence with no pending "
                         f"tokens can never be scheduled or retired")
        self.uid = uid


class UnknownSequenceError(KeyError):
    """Retire/lookup of a uid the manager does not track, with its history
    (already retired / failed-and-flushed / never added) in the message."""

    def __init__(self, uid: int, detail: str):
        super().__init__(f"uid {uid} is not tracked by RaggedStateManager ({detail})")
        self.uid = uid


@dataclasses.dataclass
class SequenceDescriptor:
    uid: int
    tokens: List[int]  # full known token ids (prompt + generated)
    seen_tokens: int = 0  # tokens already in the KV cache
    blocks: List[int] = dataclasses.field(default_factory=list)
    done: bool = False
    # --- admission / resilience metadata (inference/v2/admission.py) ---
    prompt_len: int = 0        # len(tokens) at intake; generated = len(tokens) - prompt_len
    arrival: int = 0           # monotonic intake order; preemption evicts the newest
    priority: int = 0          # lower = more urgent (admission-queue order)
    deadline: Optional[float] = None  # absolute clock time; engine evicts past it
    queue_wait_s: float = 0.0  # time spent in the admission queue
    preemptions: int = 0       # times this sequence was preempted-and-requeued
    finish_reason: Optional[str] = None  # eos | max_new_tokens | length_capped | ...

    @property
    def pending_tokens(self) -> int:
        return len(self.tokens) - self.seen_tokens

    @property
    def in_prefill(self) -> bool:
        return self.seen_tokens < len(self.tokens) - 1

    @property
    def generated_tokens(self) -> int:
        return len(self.tokens) - self.prompt_len


class RaggedStateManager:

    def __init__(self, num_blocks: int, block_size: int, max_blocks_per_seq: int):
        self.allocator = BlockedAllocator(num_blocks)
        self.block_size = block_size
        self.max_blocks_per_seq = max_blocks_per_seq
        # block census (inference/v2/kv_metrics.BlockCensus) — attached by the
        # engine when kv observability is on.  Hooks fire at the manager's
        # ONE alloc seam (ensure_blocks) and ONE reclaim seam (_reclaim), so
        # every path that moves a block keeps the census exact; pure host
        # bookkeeping, never a device touch.
        self.census = None
        self.seqs: Dict[int, SequenceDescriptor] = {}
        self.failures: Dict[int, str] = {}
        # uid history for descriptive retire errors; a bounded recency window
        # (insertion-ordered dict) so a long-lived server doesn't grow it
        # forever — uids older than the window degrade to "never added"
        self.retired_uids: Dict[int, None] = {}
        self._retired_window = 4096
        # lifetime counters feeding the telemetry gauges (requests/sec is the
        # collector-side rate over completed_requests)
        self.total_requests = 0
        self.completed_requests = 0
        self.failed_requests = 0
        self._arrivals = 0

    @property
    def trash_block(self) -> int:
        return self.allocator.trash_block

    def add_sequence(self, uid: int, prompt_tokens: List[int], *, priority: int = 0,
                     deadline: Optional[float] = None,
                     queue_wait_s: float = 0.0,
                     prompt_len: Optional[int] = None) -> SequenceDescriptor:
        """``prompt_len`` pins where prompt ends and generated output begins
        when it differs from ``len(prompt_tokens)`` — crash recovery re-admits
        ``prompt + already-emitted-prefix`` as the token history (the prefill
        rebuilds their KV in one pass) while the prefix keeps counting as
        GENERATED tokens for budgets, results, and gauges."""
        if uid in self.seqs:
            raise ValueError(f"uid {uid} already tracked")
        if not prompt_tokens:
            raise EmptyPromptError(uid)
        if prompt_len is None:
            prompt_len = len(prompt_tokens)
        elif not 0 < prompt_len <= len(prompt_tokens):
            raise ValueError(f"uid {uid}: prompt_len={prompt_len} outside "
                             f"(0, {len(prompt_tokens)}]")
        seq = SequenceDescriptor(uid=uid, tokens=list(prompt_tokens),
                                 prompt_len=int(prompt_len), arrival=self._arrivals,
                                 priority=priority, deadline=deadline,
                                 queue_wait_s=queue_wait_s)
        self._arrivals += 1
        self.seqs[uid] = seq
        self.total_requests += 1
        return seq

    def ensure_blocks(self, seq: SequenceDescriptor, upto_tokens: int) -> None:
        """Grow the block table to cover ``upto_tokens`` cache positions."""
        need = (upto_tokens + self.block_size - 1) // self.block_size
        if need > self.max_blocks_per_seq:
            raise RuntimeError(f"uid {seq.uid}: {upto_tokens} tokens exceeds "
                               f"max_blocks_per_seq={self.max_blocks_per_seq}")
        if need > len(seq.blocks):
            grown = self.allocator.allocate(need - len(seq.blocks))
            seq.blocks.extend(grown)
            if self.census is not None:
                self.census.on_alloc(seq.uid, grown)

    def _reclaim(self, uid: int, blocks: List[int]) -> None:
        """THE reclaim seam: every block leaving a sequence returns to the
        allocator here, with the census kept in lock-step."""
        self.allocator.free(blocks)
        if self.census is not None:
            self.census.on_free(uid, blocks)

    def over_cap(self, upto_tokens: int) -> bool:
        return (upto_tokens + self.block_size - 1) // self.block_size > self.max_blocks_per_seq

    def fail(self, uid: int, reason: str) -> None:
        self.failures[uid] = reason
        self.failed_requests += 1
        seq = self.seqs.get(uid)
        if seq is not None:
            seq.done = True
            self._reclaim(uid, seq.blocks)  # reclaim the KV pool immediately
            seq.blocks = []

    def evict(self, seq: SequenceDescriptor, finish_reason: str) -> None:
        """End a sequence WITHOUT completion: done + finish reason + KV blocks
        reclaimed in place.  The single primitive behind deadline expiry and
        preemption-budget exhaustion, so reason-aware accounting (retire()
        excludes EVICTED_FINISH_REASONS from completed_requests) has one seam."""
        seq.done = True
        seq.finish_reason = finish_reason
        if seq.blocks:
            self._reclaim(seq.uid, seq.blocks)
            seq.blocks = []

    def preempt(self, seq: SequenceDescriptor, keep_blocks: int = 0) -> int:
        """Preempt-and-requeue support: free the sequence's trailing KV blocks
        and roll ``seen_tokens`` back to the kept-block boundary.  The prefix
        KV in the kept blocks stays valid (prefill wrote those positions and
        they are never rewritten); the dropped positions are simply recomputed
        when the sequence is rescheduled.  Returns the number of freed blocks."""
        dropped = self.rollback_blocks(seq, keep_blocks)
        seq.seen_tokens = min(seq.seen_tokens, len(seq.blocks) * self.block_size)
        return dropped

    def rollback_blocks(self, seq: SequenceDescriptor, keep_blocks: int) -> int:
        """Free a sequence's trailing blocks past ``keep_blocks`` WITHOUT
        touching its progress — the burst pre-allocation rollback (a failed
        mid-grab returns exactly the blocks it took) and the lower half of
        :meth:`preempt`.  Returns the number of freed blocks."""
        keep_blocks = max(0, min(int(keep_blocks), len(seq.blocks)))
        dropped = seq.blocks[keep_blocks:]
        if dropped:
            self._reclaim(seq.uid, dropped)
            seq.blocks = seq.blocks[:keep_blocks]
        return len(dropped)

    def can_allocate(self, n_blocks: int) -> bool:
        return self.allocator.free_blocks >= n_blocks

    def blocks_needed(self, seq: SequenceDescriptor, upto_tokens: int) -> int:
        need = (upto_tokens + self.block_size - 1) // self.block_size
        return max(0, need - len(seq.blocks))

    def block_table_row(self, seq: SequenceDescriptor,
                        width: Optional[int] = None) -> np.ndarray:
        """Padded block-table row for the device batch; ``width`` bounds it to
        the step's bucketed table width (the fast path packs rows at exactly
        the compiled width instead of building max_blocks_per_seq and
        slicing)."""
        width = self.max_blocks_per_seq if width is None else width
        row = np.full(width, self.trash_block, np.int32)
        row[:len(seq.blocks)] = seq.blocks
        return row

    def retire(self, uid: int, *, completed: bool = True) -> None:
        """Drop a sequence and reclaim its blocks.  ``completed=False`` marks
        an eviction (deadline/shed/stall) so it doesn't count as a completion.
        Unknown uids raise :class:`UnknownSequenceError` naming what actually
        happened to the uid instead of a bare ``KeyError``."""
        seq = self.seqs.pop(uid, None)
        if seq is None:
            if uid in self.failures:
                detail = f"it failed ({self.failures[uid]!r})"
                if uid in self.retired_uids:
                    detail += " and was already flushed"
            elif uid in self.retired_uids:
                detail = "it was already retired"
            else:
                detail = "it was never added"
            raise UnknownSequenceError(uid, detail)
        self.retired_uids.pop(uid, None)  # re-adding refreshes recency
        self.retired_uids[uid] = None
        while len(self.retired_uids) > self._retired_window:
            self.retired_uids.pop(next(iter(self.retired_uids)))
        self._reclaim(uid, seq.blocks)
        seq.blocks = []
        if self.census is not None:
            self.census.on_terminal(uid)
        # neither a flushed failure nor an evicted request is a completion
        if (completed and uid not in self.failures
                and seq.finish_reason not in EVICTED_FINISH_REASONS):
            self.completed_requests += 1

    def live_uids(self) -> List[int]:
        return [uid for uid, s in self.seqs.items() if not s.done]

    def kv_utilization(self) -> float:
        """Fraction of the usable KV pool currently allocated (trash block
        excluded) — the paged-attention memory-pressure gauge."""
        usable = self.allocator.num_blocks - 1
        return (usable - self.allocator.free_blocks) / max(usable, 1)
