"""Ragged state manager — sequence tracking + block-table bookkeeping.

Analog of DSStateManager / DSSequenceDescriptor (inference/v2/ragged/
ragged_manager.py:19, sequence_descriptor.py): tracks live sequences, grows
their block tables as tokens are scheduled, and frees blocks at retirement.
All host-side (numpy); the device sees only the padded block-table array.
"""

import dataclasses
from typing import Dict, List, Optional

import numpy as np

from .blocked_allocator import BlockedAllocator


@dataclasses.dataclass
class SequenceDescriptor:
    uid: int
    tokens: List[int]  # full known token ids (prompt + generated)
    seen_tokens: int = 0  # tokens already in the KV cache
    blocks: List[int] = dataclasses.field(default_factory=list)
    done: bool = False

    @property
    def pending_tokens(self) -> int:
        return len(self.tokens) - self.seen_tokens

    @property
    def in_prefill(self) -> bool:
        return self.seen_tokens < len(self.tokens) - 1


class RaggedStateManager:

    def __init__(self, num_blocks: int, block_size: int, max_blocks_per_seq: int):
        self.allocator = BlockedAllocator(num_blocks)
        self.block_size = block_size
        self.max_blocks_per_seq = max_blocks_per_seq
        self.seqs: Dict[int, SequenceDescriptor] = {}
        self.failures: Dict[int, str] = {}
        # lifetime counters feeding the telemetry gauges (requests/sec is the
        # collector-side rate over completed_requests)
        self.total_requests = 0
        self.completed_requests = 0
        self.failed_requests = 0

    @property
    def trash_block(self) -> int:
        return self.allocator.trash_block

    def add_sequence(self, uid: int, prompt_tokens: List[int]) -> SequenceDescriptor:
        if uid in self.seqs:
            raise ValueError(f"uid {uid} already tracked")
        seq = SequenceDescriptor(uid=uid, tokens=list(prompt_tokens))
        self.seqs[uid] = seq
        self.total_requests += 1
        return seq

    def ensure_blocks(self, seq: SequenceDescriptor, upto_tokens: int) -> None:
        """Grow the block table to cover ``upto_tokens`` cache positions."""
        need = (upto_tokens + self.block_size - 1) // self.block_size
        if need > self.max_blocks_per_seq:
            raise RuntimeError(f"uid {seq.uid}: {upto_tokens} tokens exceeds "
                               f"max_blocks_per_seq={self.max_blocks_per_seq}")
        if need > len(seq.blocks):
            seq.blocks.extend(self.allocator.allocate(need - len(seq.blocks)))

    def over_cap(self, upto_tokens: int) -> bool:
        return (upto_tokens + self.block_size - 1) // self.block_size > self.max_blocks_per_seq

    def fail(self, uid: int, reason: str) -> None:
        self.failures[uid] = reason
        self.failed_requests += 1
        seq = self.seqs.get(uid)
        if seq is not None:
            seq.done = True
            self.allocator.free(seq.blocks)  # reclaim the KV pool immediately
            seq.blocks = []

    def can_allocate(self, n_blocks: int) -> bool:
        return self.allocator.free_blocks >= n_blocks

    def blocks_needed(self, seq: SequenceDescriptor, upto_tokens: int) -> int:
        need = (upto_tokens + self.block_size - 1) // self.block_size
        return max(0, need - len(seq.blocks))

    def block_table_row(self, seq: SequenceDescriptor) -> np.ndarray:
        row = np.full(self.max_blocks_per_seq, self.trash_block, np.int32)
        row[:len(seq.blocks)] = seq.blocks
        return row

    def retire(self, uid: int) -> None:
        seq = self.seqs.pop(uid)
        self.allocator.free(seq.blocks)
        if uid not in self.failures:  # a flushed failure is not a completion
            self.completed_requests += 1

    def live_uids(self) -> List[int]:
        return [uid for uid, s in self.seqs.items() if not s.done]

    def kv_utilization(self) -> float:
        """Fraction of the usable KV pool currently allocated (trash block
        excluded) — the paged-attention memory-pressure gauge."""
        usable = self.allocator.num_blocks - 1
        return (usable - self.allocator.free_blocks) / max(usable, 1)
