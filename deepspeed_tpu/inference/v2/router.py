"""Serving fleet front-end: health-gated routing over N supervised replicas
with journaled failover and zero lost requests.

``FleetRouter`` closes the last single-point-of-failure PR 9 left in the
serving stack: one :class:`ServingSupervisor` can restart its own engine, but
when its restart budget runs out the whole service degrades to drain-only —
every queued request is finalized ``failed`` because there is nowhere else
for the journaled work to go.  The router owns N replicas (each a supervisor
+ its own request journal) and composes the seams the stack already ships:

- **Health-gated admission.**  Each request goes to the least-loaded
  *healthy* replica, scored from the engine's own ``health()`` gauges —
  queue depth, KV-pool utilization, and the capacity forecaster's
  steps-to-exhaustion (a replica forecasting imminent KV exhaustion is
  steered away from BEFORE it starts shedding).  A snapshot older than
  ``health_stale_s`` (by the injectable-clock ``generated_at`` stamp the
  engine embeds) marks the replica unhealthy: a frozen replica's last-good
  gauges must not attract traffic.
- **Prefix affinity.**  Requests sharing a prompt header hash to the same
  home replica (the chained ``block_hashes`` key the prefix cache itself
  uses), so each replica's CoW prefix tree stays hot instead of every
  replica cold-building the same shared header.  Affinity is a preference,
  not a pin: an unhealthy home falls back to least-loaded.
- **Shed backoff.**  A retryable shed is NOT surfaced to the caller: the
  router re-routes it to a different replica after backing off for the
  shed's own ``retry_after_s`` hint (or exponential backoff when the hint
  is absent), up to ``max_reroutes`` rounds.  Only a shed that exhausts its
  reroute budget — or is non-retryable — reaches the caller.
- **Journaled failover.**  A replica that exhausts its restart budget is
  drained and its journal replayed: already-terminal work is adopted as-is,
  and every in-flight entry is TRANSPLANTED into a healthy replica's
  journal — original prompt, emitted-token prefix, and the original
  ttl/wall pair, so the deadline keeps ticking on the request's own clock.
  The target's normal recovery path (``serve_recovered`` emitted-prefix
  re-admission) then continues each decode byte-identically from where the
  dead replica left it.  Zero lost requests, and the migrated work is
  durable on the TARGET before it is served — a second crash mid-migration
  loses nothing either.
- **One merged ops surface.**  A :class:`FleetAggregator` absorbs every
  replica generation (rank = replica index, generation bumps carry counter
  totals), so fleet TTFT/TBT/e2e SLO histograms and monotone fleet counters
  come out of ONE ``/metrics`` endpoint no matter how many times any
  replica restarted.

Clock discipline: monotonic reads flow through the injectable ``clock``
seam, wall-clock through ``wall_clock``, and backoff through ``sleep`` —
all bound to the ``time`` functions as DEFAULTS (the dslint
``raw-clock-in-serving`` contract) so fleet tests drive fake time
deterministically.  This module is host-side only (dslint scans the whole
file as zero-device-sync): it reads health dicts and journal files, never a
device value.
"""

import dataclasses
import json
import os
import time
from typing import (Any, Callable, Dict, Iterable, List, Optional, Sequence,
                    Set, Tuple)

from ...monitor.tracing import FlightRecorder
from ...runtime.config import (OpsServerConfig, ServingFaultToleranceConfig,
                               ServingFleetConfig)
from ...utils.logging import logger
from .admission import FAILED, SHED, RequestResult
from .journal import RequestJournal, replay_journal
from .kv_metrics import block_hashes, tenant_namespace
from .qos import QUOTA_EXCEEDED
from .supervisor import ServeSpec, ServingSupervisor, result_from_entry

UNROUTABLE_REASON = ("fleet: every replica is drained (all restart budgets "
                     "exhausted) — request finalized by the router; resubmit "
                     "once capacity returns")

# a replica forecasting KV exhaustion within the steering horizon is scored
# as-if carrying this much extra load: effectively last-resort, still legal
EXHAUSTION_PENALTY = 1000.0


@dataclasses.dataclass
class ReplicaHandle:
    """One fleet member: a supervised engine plus the router's view of it."""
    index: int
    supervisor: ServingSupervisor
    journal_path: str
    drained: bool = False            # restart budget exhausted; never routed to
    health: Optional[Dict[str, Any]] = None   # last observed health() snapshot
    observed_at: Optional[float] = None       # router-clock stamp of observe()


class FleetRouter:
    """Front-end over N supervised serving replicas (module docstring).

    ``engine_factories`` is a sequence of zero-arg engine builders, one per
    replica, OR a single callable replicated ``config.replicas`` times (each
    invocation must build a FRESH engine).  Each replica gets its own journal
    (``journal_paths[i]`` or ``journal_dir/replica<i>.journal``) and its own
    :class:`ServingSupervisor` built from ``ft_config``.

    Uids are a fleet-wide namespace: one router instance serves one workload
    namespace, and re-serving a uid the fleet already resolved would adopt
    the journaled terminal instead of serving (the recovery contract working
    as designed) — the router therefore refuses uid reuse across its
    lifetime.
    """

    def __init__(self, engine_factories, *,
                 journal_dir: Optional[str] = None,
                 journal_paths: Optional[Sequence[str]] = None,
                 config=None, ft_config=None, block_size: int = 16,
                 telemetry=None, ops_server=None,
                 clock: Callable[[], float] = time.monotonic,
                 wall_clock: Callable[[], float] = time.time,
                 sleep: Callable[[float], None] = time.sleep):
        if config is None:
            config = ServingFleetConfig()
        elif isinstance(config, dict):
            config = ServingFleetConfig(**config)
        self.cfg = config
        if callable(engine_factories):
            engine_factories = [engine_factories] * self.cfg.replicas
        factories = list(engine_factories)
        if not factories:
            raise ValueError("FleetRouter needs at least one engine factory")
        if journal_paths is None:
            if journal_dir is None:
                raise ValueError("FleetRouter needs journal_paths or journal_dir")
            journal_paths = [os.path.join(journal_dir, f"replica{r}.journal")
                             for r in range(len(factories))]
        if len(journal_paths) != len(factories):
            raise ValueError(f"{len(factories)} engine factories but "
                             f"{len(journal_paths)} journal paths")
        self.block_size = int(block_size)
        self.telemetry = telemetry
        self._clock = clock
        self._wall = wall_clock
        self._sleep = sleep
        if isinstance(ft_config, ServingFaultToleranceConfig):
            ft_config = ft_config.to_dict()
        self.replicas: List[ReplicaHandle] = []
        for r, (factory, path) in enumerate(zip(factories, journal_paths)):
            # each replica owns its own WAL: journal_path is spelled into the
            # per-replica fault-tolerance section so enabled=True validates
            replica_ft = dict(ft_config, journal_path=path) \
                if ft_config is not None else None
            sup = ServingSupervisor(factory, journal_path=path,
                                    config=replica_ft, telemetry=telemetry,
                                    clock=clock, wall_clock=wall_clock,
                                    sleep=sleep)
            self.replicas.append(ReplicaHandle(index=r, supervisor=sup,
                                               journal_path=path))
        # ---- routing / failover counters (host ints; populate_from_router
        # exports them, FleetAggregator merges them with replica counters)
        self.routed_total: List[int] = [0] * len(self.replicas)
        self.affinity_routed_total = 0       # home replica took the request
        self.affinity_overridden_total = 0   # home existed but was unhealthy
        self.reroutes_total = 0              # retryable sheds sent elsewhere
        self.backoff_seconds_total = 0.0
        self.migrations_total = 0            # replicas drained + migrated
        self.migrated_requests_total = 0     # entries transplanted
        self.adopted_from_journal_total = 0  # dead-journal terminals adopted
        self.lost_total = 0                  # the zero-lost-requests invariant
        # per-tenant fleet counters (ISSUE 19): placement and quota sheds by
        # tenant — a quota shed is tenant-global (rerouting to a sibling
        # cannot help), so it surfaces here instead of in reroutes_total
        self.routed_by_tenant: Dict[str, int] = {}
        self.quota_sheds_by_tenant: Dict[str, int] = {}
        self.recorder = FlightRecorder(256)
        self._served_uids: Set[int] = set()
        # ---- merged fleet ops surface: aggregator always on (host dicts are
        # cheap); the HTTP listener only when an ops_server config asks
        from ...monitor.metrics import FleetAggregator
        self.aggregator = FleetAggregator()
        self.ops = None
        self._ops_cache = None
        if ops_server is not None:
            ops_cfg = ops_server if isinstance(ops_server, OpsServerConfig) \
                else OpsServerConfig(**dict(ops_server))
            if ops_cfg.enabled:
                from ...monitor.ops_server import OpsCache, try_start_ops_server
                self._ops_cache = OpsCache()
                self.ops = try_start_ops_server(self._ops_cache,
                                                host=ops_cfg.host,
                                                port=ops_cfg.port,
                                                owner="fleet router")
                self._refresh_ops()

    # ------------------------------------------------------------- accounting
    def _event(self, event: str, **fields) -> None:
        self.recorder.record(event, t=self._wall(), **fields)
        if self.telemetry is not None:
            self.telemetry.record_resilience(f"fleet_{event}", **fields)

    # ---------------------------------------------------------- health gating
    def observe(self, index: int, health: Dict[str, Any]) -> None:
        """Record a replica's ``health()`` snapshot (absorbed automatically
        after every serve generation; tests inject synthetic ones)."""
        replica = self.replicas[index]
        replica.health = health
        replica.observed_at = self._clock()

    def _is_healthy(self, index: int, now: float) -> bool:
        """Routable AND trustworthy: not drained, supervisor not degraded,
        and the last health snapshot (if any) is inside the staleness
        horizon.  A never-observed replica is healthy-unknown — a fresh
        fleet must be routable before its first serve."""
        replica = self.replicas[index]
        if replica.drained or replica.supervisor.degraded:
            return False
        if replica.health is None:
            return True
        stamp = replica.health.get("generated_at", replica.observed_at)
        if stamp is None:
            return True
        return (now - float(stamp)) <= self.cfg.health_stale_s

    def _load_score(self, index: int) -> float:
        """Weighted load from the engine's own gauges: queue depth + KV
        utilization, plus a steering penalty when the capacity forecaster
        predicts exhaustion within ``exhaustion_steer_steps`` — the router
        moves traffic away BEFORE the replica starts shedding."""
        h = self.replicas[index].health
        if h is None:
            return 0.0
        score = (float(h.get("queue_depth", 0)) * self.cfg.queue_weight
                 + float(h.get("kv_utilization", 0.0)) * self.cfg.kv_weight)
        forecast = h.get("kv", {}).get("forecast", {}) or {}
        steps = forecast.get("steps_to_exhaustion")
        steer = self.cfg.exhaustion_steer_steps
        if steps is not None and float(steps) < steer:
            score += EXHAUSTION_PENALTY * (1.0 + (steer - float(steps)) / steer)
        return score

    def healthy_indices(self) -> List[int]:
        now = self._clock()
        return [r.index for r in self.replicas if self._is_healthy(r.index, now)]

    # --------------------------------------------------------------- routing
    def _affinity_home(self, prompt: Sequence[int],
                       tenant: str = "default") -> Optional[int]:
        """Home replica for a prompt header: the chained block hash at depth
        ``affinity_blocks`` (the SAME key the prefix cache indexes by, so
        prompts that would share cached blocks share a home).  The tenant
        namespace seeds the chain exactly as the cache's own key does
        (ISSUE 19) — two tenants with byte-identical prompts get independent
        homes, so placement leaks nothing across the tenant boundary either.
        None when the prompt has no full block or affinity is off."""
        if self.cfg.affinity_blocks <= 0:
            return None
        depth = self.cfg.affinity_blocks * self.block_size
        hashes = block_hashes(list(prompt)[:depth], self.block_size,
                              tenant_namespace(tenant))
        if not hashes:
            return None
        return int.from_bytes(hashes[-1][:8], "big") % len(self.replicas)

    def route(self, prompt: Sequence[int], *,
              exclude: Iterable[int] = (),
              tenant: str = "default") -> Optional[int]:
        """Pick a replica for one prompt: the healthy affinity home when it
        has one, else the least-loaded healthy replica; when NO replica is
        healthy, any undrained one (best-effort beats refusal — staleness
        may be a probe gap, drain is definitive).  None only when every
        replica outside ``exclude`` is drained."""
        now = self._clock()
        excluded = set(exclude)
        candidates = [r.index for r in self.replicas
                      if not r.drained and r.index not in excluded]
        if not candidates:
            return None
        healthy = [i for i in candidates if self._is_healthy(i, now)]
        home = self._affinity_home(prompt, tenant)
        if home is not None and home in healthy \
                and self._load_score(home) < EXHAUSTION_PENALTY:
            self.affinity_routed_total += 1
            return home
        if home is not None and home in candidates:
            self.affinity_overridden_total += 1
        pool = healthy or candidates
        return min(pool, key=lambda i: (self._load_score(i), i))

    # ---------------------------------------------------------------- serving
    def serve(self, prompts: Sequence[Sequence[int]], *,
              uids: Optional[Sequence[int]] = None,
              max_new_tokens: int = 32, eos_token_id: Optional[int] = None,
              greedy: bool = True,
              priorities: Optional[Sequence[int]] = None,
              ttl_s: Optional[Sequence[Optional[float]]] = None,
              tenants: Optional[Sequence[str]] = None,
              service_classes: Optional[Sequence[str]] = None
              ) -> List[RequestResult]:
        """Serve a workload across the fleet; one terminal result per prompt,
        in input order.  Every request reaches exactly one terminal — sheds
        are re-routed with backoff, exhausted replicas are drained and their
        journaled in-flight work migrated — and the router never hangs: when
        the LAST replica drains, whatever is left is finalized ``failed``
        (and counted in ``lost_total``, which staying zero is the point)."""
        if uids is None:
            base = (max(self._served_uids) + 1) if self._served_uids else 0
            uids = list(range(base, base + len(prompts)))
        uid_list = [int(u) for u in uids]
        if len(uid_list) != len(prompts):
            raise ValueError(f"{len(prompts)} prompts but {len(uid_list)} uids")
        dupes = self._served_uids.intersection(uid_list)
        if len(set(uid_list)) != len(uid_list) or dupes:
            raise ValueError(
                f"fleet uids must be unique across the router's lifetime "
                f"(journals adopt prior terminals for reused uids); "
                f"clashing: {sorted(dupes) or 'within this call'}")
        self._served_uids.update(uid_list)
        specs = [ServeSpec(uid=uid, prompt=list(prompt),
                           priority=(int(priorities[i]) if priorities else 0),
                           ttl_s=(ttl_s[i] if ttl_s else None),
                           tenant=(str(tenants[i]) if tenants is not None
                                   and tenants[i] else "default"),
                           service_class=(str(service_classes[i])
                                          if service_classes is not None
                                          and service_classes[i]
                                          else "interactive"))
                 for i, (uid, prompt) in enumerate(zip(uid_list, prompts))]
        spec_by_uid = {s.uid: s for s in specs}
        results: Dict[int, RequestResult] = {}
        # which replicas already shed a uid: re-routes avoid them (their
        # journal holds a shed terminal that recovery would adopt)
        shed_at: Dict[int, Set[int]] = {}
        assignment: Dict[int, List[ServeSpec]] = {}
        for spec in specs:
            target = self.route(spec.prompt, tenant=spec.tenant)
            if target is None:
                results[spec.uid] = self._lost(spec.uid)
                continue
            assignment.setdefault(target, []).append(spec)
            self.routed_total[target] += 1
            self.routed_by_tenant[spec.tenant] = \
                self.routed_by_tenant.get(spec.tenant, 0) + 1
            self._event("route", uid=spec.uid, replica=target)

        attempt = 0
        while assignment:
            next_assignment: Dict[int, List[ServeSpec]] = {}
            retry_hints: List[float] = []
            rerouted_shed = False
            for index in sorted(assignment):
                replica = self.replicas[index]
                batch = assignment[index]
                got, exhausted = replica.supervisor.serve_specs(
                    batch, max_new_tokens=max_new_tokens,
                    eos_token_id=eos_token_id, greedy=greedy,
                    on_generation=lambda eng, gen, _i=index:
                        self._absorb(_i, eng, gen))
                if exhausted:
                    # the supervisor stopped INSIDE its budget contract: drain
                    # this replica and move the journaled in-flight work
                    replica.drained = True
                    self.migrations_total += 1
                    self._event("replica_exhausted", replica=index,
                                restarts=replica.supervisor.restarts_total)
                    logger.warning(f"fleet: replica {index} exhausted its "
                                   f"restart budget — draining and migrating "
                                   f"journaled work")
                    unresolved = [s for s in batch if s.uid not in got]
                    adopted, regrouped, lost = self._migrate(index, unresolved)
                    results.update(adopted)
                    results.update(lost)
                    for target, moved in regrouped.items():
                        next_assignment.setdefault(target, []).extend(moved)
                        self.routed_total[target] += len(moved)
                    results.update({u: r for u, r in got.items()})
                    continue
                for uid, result in got.items():
                    spec = spec_by_uid.get(uid)
                    if spec is None:
                        continue
                    if result.status == SHED \
                            and result.shed_code == QUOTA_EXCEEDED:
                        # a quota shed is TENANT-global, not replica-local:
                        # every sibling enforces the same per-tenant budget,
                        # so rerouting would just burn its admission door
                        # (and journal a second shed terminal that recovery
                        # would adopt).  Surface it to the caller with the
                        # quota-derived retry_after_s — the client backs off
                        # for the tenant's own refill window
                        self.quota_sheds_by_tenant[spec.tenant] = \
                            self.quota_sheds_by_tenant.get(spec.tenant, 0) + 1
                        if result.retry_after_s is not None:
                            # the quota window still floors THIS round's
                            # backoff: reroutes sharing the round must not
                            # land before the tenant's bucket can refill
                            retry_hints.append(float(result.retry_after_s))
                        self._event("quota_shed", uid=uid, replica=index,
                                    tenant=spec.tenant,
                                    retry_after_s=result.retry_after_s)
                        results[uid] = result
                        continue
                    if result.status == SHED and result.retryable \
                            and attempt < self.cfg.max_reroutes:
                        shed_at.setdefault(uid, set()).add(index)
                        target = self.route(spec.prompt,
                                            exclude=shed_at[uid],
                                            tenant=spec.tenant)
                        if target is not None:
                            next_assignment.setdefault(target, []).append(spec)
                            self.routed_total[target] += 1
                            self.reroutes_total += 1
                            rerouted_shed = True
                            if result.retry_after_s is not None:
                                retry_hints.append(float(result.retry_after_s))
                            self._event("reroute", uid=uid, shed_by=index,
                                        replica=target,
                                        retry_after_s=result.retry_after_s)
                            continue
                    results[uid] = result
            # migration rounds continue immediately; only shed re-routes wait
            # out the pressure that caused them
            if rerouted_shed:
                delay = self._backoff_delay(attempt, retry_hints)
                if delay > 0.0:
                    self.backoff_seconds_total += delay
                    self._event("backoff", delay_s=delay, attempt=attempt,
                                pending=sum(len(v) for v in
                                            next_assignment.values()))
                    self._sleep(delay)
            assignment = next_assignment
            attempt += 1
        self._refresh_ops()
        return [results[uid] for uid in uid_list]

    def _backoff_delay(self, attempt: int, hints: List[float]) -> float:
        """Honor the sheds' own ``retry_after_s`` estimates when present
        (the admission door knows its pressure better than a fixed curve),
        floor at exponential backoff, cap at ``backoff_max_s``."""
        base = self.cfg.backoff_base_s * (2.0 ** attempt)
        return min(self.cfg.backoff_max_s, max([base] + hints))

    def _lost(self, uid: int) -> RequestResult:
        self.lost_total += 1
        self._event("unroutable", uid=uid)
        return RequestResult(uid=uid, status=FAILED, retryable=True,
                             reason=UNROUTABLE_REASON)

    # --------------------------------------------------------------- failover
    def _migrate(self, dead_index: int, specs: Sequence[ServeSpec]
                 ) -> Tuple[Dict[int, RequestResult],
                            Dict[int, List[ServeSpec]],
                            Dict[int, RequestResult]]:
        """Replay the drained replica's journal and move every unresolved
        request: journaled terminals are adopted as results, in-flight
        entries are transplanted — prompt, emitted prefix, and the ORIGINAL
        ttl/wall pair — into per-target journals (durably, fsync-per-record)
        before any target serves them.  Returns (adopted, {target: specs},
        lost); ``lost`` is non-empty only when no undrained replica exists."""
        dead = self.replicas[dead_index]
        # read-only replay: the dead journal stays as forensic truth (the
        # work is not terminal THERE — it moved); truncation is for writers
        state = replay_journal(dead.journal_path, truncate=False)
        adopted: Dict[int, RequestResult] = {}
        regrouped: Dict[int, List[ServeSpec]] = {}
        lost: Dict[int, RequestResult] = {}
        writers: Dict[int, RequestJournal] = {}
        for spec in specs:
            entry = state.entries.get(spec.uid)
            if entry is not None and entry.done:
                adopted[spec.uid] = result_from_entry(entry)
                self.adopted_from_journal_total += 1
                continue
            target = self.route(spec.prompt, exclude={dead_index},
                                tenant=spec.tenant)
            if target is None:
                lost[spec.uid] = self._lost(spec.uid)
                continue
            journal = writers.get(target)
            if journal is None:
                journal = writers[target] = RequestJournal(
                    self.replicas[target].journal_path, fsync_every=1,
                    wall_clock=self._wall)
            if entry is not None:
                # identity migrates AS JOURNALED: the target's recovery reads
                # tenant/class from this record, never from the spec
                journal.record_admit(
                    spec.uid, entry.prompt, priority=entry.priority,
                    ttl_s=entry.ttl_s, max_new_tokens=entry.max_new_tokens,
                    eos_token_id=entry.eos_token_id, greedy=entry.greedy,
                    admit_wall=entry.admit_wall, tenant=entry.tenant,
                    service_class=entry.service_class)
                if entry.emitted:
                    journal.note_tokens(spec.uid, list(entry.emitted))
            # entry None = the replica died before durably admitting it:
            # nothing to transplant — the target admits it fresh
            regrouped.setdefault(target, []).append(spec)
            self.migrated_requests_total += 1
            self._event("migrate", uid=spec.uid, src=dead_index, dst=target,
                        emitted=len(entry.emitted) if entry is not None else 0)
        for journal in writers.values():
            journal.flush()
            journal.close()
        if dead.supervisor.ops is not None:
            dead.supervisor.close_ops()
        return adopted, regrouped, lost

    # ------------------------------------------------------------- ops plane
    def _absorb(self, index: int, engine, generation: int) -> None:
        """Fold one replica generation into the fleet aggregator (rank =
        replica index; generation bumps carry counters) and refresh the
        router's health view from the same engine."""
        try:
            from ...monitor.metrics import MetricsRegistry, populate_from_engine
            reg = MetricsRegistry(namespace=self.cfg.namespace,
                                  generation=generation)
            populate_from_engine(reg, engine)
            self.aggregator.absorb(index, reg.snapshot())
            self.observe(index, engine.health())
        except Exception as exc:   # a crashed engine's gauges must never
            self._event("absorb_failed", replica=index,   # unwind serving
                        detail=f"{type(exc).__name__}: {exc}")

    def registry(self):
        """The merged fleet registry: every replica's carried counters and
        rank-blind-merged histograms, plus the router's own families."""
        from ...monitor.metrics import populate_from_router
        reg = self.aggregator.registry(namespace=self.cfg.namespace)
        populate_from_router(reg, self)
        return reg

    def metrics_text(self) -> str:
        from ...monitor.exposition import render
        return render(self.registry(), collect=False)

    def health(self) -> Dict[str, Any]:
        """Fleet-level /healthz: per-replica state plus routing totals."""
        now = self._clock()
        return {
            "replicas": [{
                "index": r.index,
                "drained": r.drained,
                "degraded": r.supervisor.degraded,
                "healthy": self._is_healthy(r.index, now),
                "load_score": self._load_score(r.index),
                "generations": r.supervisor.generations,
                "restarts_total": r.supervisor.restarts_total,
            } for r in self.replicas],
            "healthy_replicas": len(self.healthy_indices()),
            "routed_total": list(self.routed_total),
            "affinity_routed_total": self.affinity_routed_total,
            "reroutes_total": self.reroutes_total,
            "migrations_total": self.migrations_total,
            "migrated_requests_total": self.migrated_requests_total,
            "lost_total": self.lost_total,
        }

    def _refresh_ops(self) -> None:
        if self._ops_cache is None:
            return
        self._ops_cache.update(
            metrics_text=self.metrics_text(),
            healthz=json.dumps(self.health()),
            statez=json.dumps({"events": self.recorder.tail()}))

    def close(self) -> None:
        """Shut the ops listener down (tests / clean teardown)."""
        if self.ops is not None:
            self.ops.close()
        for replica in self.replicas:
            replica.supervisor.close_ops()
