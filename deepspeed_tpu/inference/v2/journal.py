"""Durable request journal — the serving WAL behind crash recovery.

A serving-process crash (OOM, preempted VM, wedged device) used to silently
destroy every queued and in-flight request: PR 4's resilience is all
in-process.  This module makes the v2 engine's request state crash-durable
with an append-only, CRC-framed write-ahead log (frame layout and
torn-tail-truncation semantics shared with the checkpoint layer via
``utils/wal.py`` — PR-2's "the tail that wasn't durably written never
happened" applied to a log file):

- ``admit`` — one record per admitted request: uid, prompt, priority,
  effective TTL + a WALL-clock admit stamp (the engine's monotonic clock is
  meaningless across a process restart, so cross-generation deadline math
  runs on wall time: recovered requests keep their ORIGINAL TTL clock),
  ``max_new_tokens``/``eos_token_id``/``greedy``, and the request's sampling
  key ``(engine seed, uid)``.  Determinism scope, honestly: GREEDY recovered
  decodes are byte-identical to an uninterrupted run (deterministic from the
  token prefix alone — the smoke proves it end-to-end).  SAMPLED decode
  continues from the journaled prefix but is NOT guaranteed to reproduce the
  uninterrupted stream: the engine rng is engine-wide and advances with
  batch history, which a restart cannot replay; the key is recorded as
  forensic provenance and as the seam a future per-request rng would need.
  Re-admissions after a recovery append a fresh ``admit`` carrying
  ``prefix_len`` — the emitted-prefix provenance (admission.py); replay
  keeps the emitted stream exactly up to that prefix, and an admit with
  ``prefix_len=0`` starts the uid clean (uids are reused across serve
  calls, so every admit is authoritative for the request's identity).
- ``tok`` — batched emitted-token deltas, appended at wave-boundary flushes
  where the host ALREADY holds the materialized ints (zero extra device
  syncs; ``fsync_every`` amortizes the disk barrier).  Tokens emitted after
  the last flush die with the process — and are regenerated identically on
  recovery, because the journaled prefix pins the decode continuation.
- ``end`` — one terminal record mirroring the request's ``RequestResult``
  status, so replay can tell finished work from work to re-admit.

Replay (:func:`replay_journal`) tolerates a torn tail by truncating at the
first bad frame and folds the record stream into per-uid
:class:`JournalEntry` state; :meth:`JournalState.incomplete` is the set a
supervised restart re-admits *with their already-emitted token prefix* so
recovered decodes continue from where they died instead of restarting from
scratch.

All host-side; tokens arriving here are python ints the serve loop already
materialized.  Wall-clock reads go through the injectable ``wall_clock``
seam (bound to ``time.time`` as a default — the dslint ``raw-clock-in-
serving`` contract).
"""

import dataclasses
import json
import os
import struct
import time
from array import array
from typing import Any, Dict, Iterable, List, Optional, Tuple, Union

from ...utils.logging import logger
from ...utils.wal import encode_frame, scan_frames, truncate_torn_tail

JOURNAL_FORMAT_VERSION = 1

# token-delta frames are the journal's volume (one per wave, every emitted
# token rides one) and dominate its host cost — they use a compact binary
# payload (~1µs/token to encode) instead of JSON (~10µs/token), keeping the
# durability tax well under the serve loop's own python cost.  Metadata
# records (open/admit/end — a handful per request) stay JSON for
# debuggability.  A binary payload is tagged by its first byte; JSON
# payloads always start with '{'.
TOK_BINARY_TAG = b"\x01"
_TOK_GROUP = struct.Struct("<qI")  # uid (i64), token count (u32)


def _encode_tok_payload(delta: Dict[int, List[int]]) -> bytes:
    parts = [TOK_BINARY_TAG]
    for uid, toks in delta.items():
        parts.append(_TOK_GROUP.pack(int(uid), len(toks)))
        parts.append(array("i", toks).tobytes())
    return b"".join(parts)


def _decode_tok_payload(payload: bytes) -> Dict[int, List[int]]:
    delta: Dict[int, List[int]] = {}
    off = 1
    n = len(payload)
    while off + _TOK_GROUP.size <= n:
        uid, count = _TOK_GROUP.unpack_from(payload, off)
        off += _TOK_GROUP.size
        end = off + 4 * count
        if end > n:
            break  # CRC said the frame is whole; defend against skew anyway
        toks = array("i")
        toks.frombytes(payload[off:end])
        delta.setdefault(uid, []).extend(toks.tolist())
        off = end
    return delta


@dataclasses.dataclass
class JournalEntry:
    """Folded per-uid journal state after replay."""
    uid: int
    prompt: List[int]
    priority: int = 0
    # TTL budget as of the LATEST admit (a re-admission journals the
    # remaining budget), paired with that admit's wall stamp — the two
    # compose so the ORIGINAL deadline survives any number of restarts
    ttl_s: Optional[float] = None
    admit_wall: float = 0.0
    max_new_tokens: int = 0
    eos_token_id: Optional[int] = None
    greedy: bool = True
    sampling_key: Tuple[int, int] = (0, 0)
    emitted: List[int] = dataclasses.field(default_factory=list)
    prefix_len: int = 0                    # provenance of the latest admit
    admits: int = 0                        # admit records seen (1 + recoveries)
    terminal: Optional[Dict[str, Any]] = None
    # QoS identity (ISSUE 19): journaled at admit so recovery re-admits a
    # request under its ORIGINAL tenant and service class — a restart can
    # never launder best-effort traffic into interactive or detach a
    # request from its tenant's quota accounting.  Defaults match the
    # pre-QoS engine, so journals written before this field replay cleanly.
    tenant: str = "default"
    service_class: str = "interactive"

    @property
    def done(self) -> bool:
        return self.terminal is not None

    def ttl_remaining(self, now_wall: float) -> Optional[float]:
        """Seconds of the ORIGINAL TTL budget left at ``now_wall`` (None =
        no deadline): the latest-admit budget minus the wall time elapsed
        since that admit.  Recovery passes this as the re-admission TTL so
        a restart never refreshes — and never double-shrinks — a request's
        deadline."""
        if self.ttl_s is None:
            return None
        return self.ttl_s - max(0.0, now_wall - self.admit_wall)


@dataclasses.dataclass
class JournalState:
    """Everything a replay learned: per-uid entries + file forensics."""
    entries: Dict[int, JournalEntry] = dataclasses.field(default_factory=dict)
    records: int = 0
    generations: int = 0                   # open records seen (journal lifetimes)
    truncated_tail: Optional[str] = None   # torn-tail description, if any

    def incomplete(self) -> List[JournalEntry]:
        """Admitted-but-not-terminal entries, in first-admit order — the
        recovery set a supervised restart re-admits with prefix."""
        return [e for e in self.entries.values() if not e.done]


class RequestJournal:
    """Append-only CRC-framed request WAL for one serving engine.

    The engine drives four hooks: :meth:`record_admit` when a request clears
    admission, :meth:`note_tokens` as sampled tokens become host-visible
    (buffered — no IO), :meth:`flush` at wave boundaries (ONE ``tok`` frame
    for everything buffered; fsync every ``fsync_every`` flushes, 0 = only
    at close), and :meth:`record_terminal` when a ``RequestResult`` is
    constructed (strict mode writes + fsyncs it eagerly — a lost terminal
    means replay re-serves finished work; throughput mode batches it into
    the next wave flush, a one-iteration window whose loss recovery absorbs
    by re-serving from the journaled prefix).

    ``watched`` is the uid filter: only requests this journal admitted are
    journaled, so foreign ``put()`` traffic sharing the engine can't bloat
    another caller's WAL.
    """

    def __init__(self, path: str, *, fsync_every: int = 1,
                 wall_clock=time.time, seed: int = 0):
        self.path = path
        self.fsync_every = max(int(fsync_every), 0)
        self._wall = wall_clock
        self.seed = int(seed)
        self.watched: set = set()
        self._fh = None
        self._pending: Dict[int, List[int]] = {}
        # throughput mode (fsync_every=0): records buffer here and land in
        # ONE file write per wave boundary — the journal's python cost per
        # serve iteration is one join+write instead of a write per record.
        # Strict mode (fsync_every>=1) writes each record immediately, with
        # admits/terminals fsynced eagerly.
        self._record_buffer: List[Union[Dict[str, Any], bytes]] = []
        self._flushes_since_fsync = 0
        self.bytes_written = 0
        self.records_written = 0
        self.enabled = True
        parent = os.path.dirname(path)
        if parent:
            try:
                os.makedirs(parent, exist_ok=True)
            except OSError as exc:
                # a broken journal dir must degrade durability, never serving
                logger.warning(f"request journal: cannot create {parent!r} "
                               f"({exc}); journaling disabled")
                self.enabled = False

    @property
    def strict(self) -> bool:
        """Per-record durability (fsync_every >= 1) vs buffered throughput
        mode (0): the operator's stated crash-window tradeoff."""
        return self.fsync_every > 0

    # ------------------------------------------------------------------ frames
    def _write_records(self, records: List[Union[Dict[str, Any], bytes]], *,
                       fsync: bool) -> None:
        """Append frames — dict records as JSON, pre-encoded binary payloads
        (token deltas) as-is — in ONE file write."""
        if not records or not self.enabled:
            return
        try:
            if self._fh is None:
                # extend a CLEAN prefix: a torn tail left by a crashed writer
                # would make every frame appended after it unreachable
                tail = truncate_torn_tail(self.path)
                if tail:
                    logger.warning(f"request journal {self.path}: {tail}")
                self._fh = open(self.path, "ab")
            data = b"".join(
                encode_frame(r if isinstance(r, bytes)
                             else json.dumps(r, separators=(",", ":")).encode())
                for r in records)
            self._fh.write(data)
            # always push to the OS: a hard-killed PROCESS then loses
            # nothing (kernel pages survive it) — fsync_every only governs
            # the stronger power-loss barrier.  One syscall per wave-batched
            # write, not per record.
            self._fh.flush()
            self.bytes_written += len(data)
            self.records_written += len(records)
            if fsync:
                os.fsync(self._fh.fileno())
                self._flushes_since_fsync = 0
        except OSError as exc:
            logger.warning(f"request journal {self.path}: append failed ({exc}); "
                           f"journaling disabled — recovery will see state up to "
                           f"the last durable frame")
            self.enabled = False

    def _emit(self, record: Union[Dict[str, Any], bytes], *, durable: bool) -> None:
        """One record: written now (strict mode; ``durable`` also fsyncs) or
        buffered until the next wave-boundary flush (throughput mode)."""
        if self.strict:
            self._write_records([record], fsync=durable)
        else:
            self._record_buffer.append(record)

    def _drain_tokens(self) -> Optional[bytes]:
        if not self._pending:
            return None
        payload = _encode_tok_payload(self._pending)
        self._pending = {}
        return payload

    # ------------------------------------------------------------------- hooks
    def open_generation(self, generation: int = 0) -> None:
        """Stamp a journal lifetime (engine construction / supervised
        restart) — replay counts these, and the wall stamp dates the file."""
        self._emit({"t": "open", "v": JOURNAL_FORMAT_VERSION,
                    "gen": int(generation), "seed": self.seed,
                    "wall": self._wall()}, durable=False)

    def record_admit(self, uid: int, prompt: Iterable[int], *, priority: int = 0,
                     ttl_s: Optional[float] = None, max_new_tokens: int = 0,
                     eos_token_id: Optional[int] = None, greedy: bool = True,
                     prefix_len: int = 0,
                     admit_wall: Optional[float] = None,
                     tenant: str = "default",
                     service_class: str = "interactive") -> None:
        uid = int(uid)
        self.watched.add(uid)
        # ``admit_wall`` transplants an entry between journals (fleet failover
        # migration): the ORIGINAL wall stamp rides along with the original
        # ttl_s so the deadline keeps ticking on the request's own clock —
        # the ttl/wall pairing contract replay documents.  Fresh admits stamp
        # their own wall.
        wall = self._wall() if admit_wall is None else float(admit_wall)
        # strict mode fsyncs admits eagerly: losing one loses the request
        rec = {"t": "admit", "uid": uid, "prompt": [int(t) for t in prompt],
               "priority": int(priority), "ttl_s": ttl_s,
               "wall": wall, "max_new_tokens": int(max_new_tokens),
               "eos": eos_token_id, "greedy": bool(greedy),
               "key": [self.seed, uid], "prefix_len": int(prefix_len)}
        # QoS identity rides the admit record only when it differs from the
        # defaults — a QoS-off engine's journal stays byte-identical to PR-8
        if tenant and tenant != "default":
            rec["tenant"] = str(tenant)
        if service_class and service_class != "interactive":
            rec["cls"] = str(service_class)
        self._emit(rec, durable=True)

    def note_tokens(self, uid: int, tokens) -> None:
        """Buffer emitted tokens (one int or a list) — no IO until flush().
        Values are python ints by the engine's own contract (they come off
        ``materialize()``); the binary encoder's ``array('i', ...)`` is the
        type check, so no per-token coercion burns the hot path."""
        if not self.enabled or uid not in self.watched:
            return
        bucket = self._pending.setdefault(int(uid), [])
        if isinstance(tokens, int):
            bucket.append(tokens)
        else:
            bucket.extend(tokens)

    def note_token_map(self, out: Dict[int, Any]) -> None:
        """Buffer a whole absorb/burst result map ({uid: tok-or-list})."""
        if not self.enabled or not out:
            return
        for uid, toks in out.items():
            self.note_tokens(uid, toks)

    def flush(self) -> bool:
        """Wave boundary: emit buffered token deltas as one ``tok`` frame —
        and in throughput mode land every buffered record in ONE file write.
        Returns True when bytes were actually appended."""
        if not self.enabled:
            return False
        tok = self._drain_tokens()
        if self.strict:
            if tok is None:
                return False
            self._flushes_since_fsync += 1
            self._write_records(
                [tok], fsync=self._flushes_since_fsync >= self.fsync_every)
            return True
        if tok is not None:
            self._record_buffer.append(tok)
        if not self._record_buffer:
            return False
        records, self._record_buffer = self._record_buffer, []
        self._write_records(records, fsync=False)
        return True

    def record_terminal(self, uid: int, status: str, *,
                        finish_reason: Optional[str] = None,
                        reason: Optional[str] = None, retryable: bool = False,
                        n_tokens: int = 0,
                        shed_code: Optional[str] = None) -> None:
        """No uid filtering here — the ENGINE's hooks filter on ``watched``;
        the supervisor writes terminals directly (drain-mode sheds,
        budget-exhaustion finalization) for uids it owns by contract.

        The terminal never outruns its own tokens: pending deltas emit
        first, in order.  Durability: strict mode writes + fsyncs the
        terminal eagerly (losing one means replay re-serves completed
        work).  Throughput mode batches it into the next wave flush like
        everything else — the serve loop flushes every iteration and the
        serve call's ``finally`` always flushes, so the in-memory window is
        ONE loop iteration, and a crash inside it merely re-serves the
        finished request from its journaled prefix (deterministic for
        greedy decode)."""
        tok = self._drain_tokens()
        end = {"t": "end", "uid": int(uid), "status": str(status),
               "finish_reason": finish_reason, "reason": reason,
               "retryable": bool(retryable), "n_tokens": int(n_tokens)}
        if shed_code is not None:
            # machine-readable shed code (ISSUE 19), written only when the
            # caller has one: a quota shed adopted from this journal after a
            # crash must still read as quota_exceeded to the fleet router
            # (reroute-to-sibling cannot help) — and records without codes
            # stay byte-identical to the pre-QoS format
            end["code"] = str(shed_code)
        if self.strict:
            self._write_records(([tok] if tok else []) + [end], fsync=True)
        else:
            if tok is not None:
                self._record_buffer.append(tok)
            self._record_buffer.append(end)

    def close(self) -> None:
        """Flush everything buffered and durably close the file handle."""
        self.flush()
        if self._fh is not None:
            try:
                self._fh.flush()
                os.fsync(self._fh.fileno())
                self._fh.close()
            except OSError as exc:
                logger.warning(f"request journal {self.path}: close failed ({exc})")
            self._fh = None


# ============================================================== replay side
def replay_journal(path: str, *, truncate: bool = True) -> JournalState:
    """Fold a journal file into :class:`JournalState`.

    ``truncate=True`` (the writer-side default) physically truncates a torn
    tail first, so a subsequent append-mode writer extends a clean prefix;
    readers that must not mutate (a live engine's health probe) pass False
    and simply ignore the tail.  Unparseable-but-CRC-valid payloads (foreign
    writer, version skew) are skipped with a warning, never fatal — replay
    exists to save what CAN be saved.
    """
    state = JournalState()
    if truncate:
        state.truncated_tail = truncate_torn_tail(path)
        payloads, _, _ = scan_frames(path)
    else:
        payloads, _, state.truncated_tail = scan_frames(path)
    for payload in payloads:
        if payload[:1] == TOK_BINARY_TAG:
            state.records += 1
            for uid, toks in _decode_tok_payload(payload).items():
                entry = state.entries.get(uid)
                if entry is not None:
                    entry.emitted.extend(toks)
            continue
        try:
            rec = json.loads(payload)
            kind = rec["t"]
        except (ValueError, KeyError, TypeError):
            logger.warning(f"request journal {path}: skipping undecodable "
                           f"(but CRC-valid) record")
            continue
        state.records += 1
        if kind == "open":
            state.generations += 1
        elif kind == "admit":
            uid = int(rec["uid"])
            prefix_len = int(rec.get("prefix_len", 0))
            entry = state.entries.get(uid)
            if entry is None:
                entry = JournalEntry(uid=uid, prompt=[],
                                     admit_wall=float(rec.get("wall", 0.0)))
                state.entries[uid] = entry
            # every admit is authoritative for the request's identity: uids
            # are REUSED across serve calls (generate/serve derive them from
            # batch position), so a fresh admit of a recycled uid must not
            # inherit the previous request's prompt or emitted stream.  The
            # emitted list survives exactly up to the admit's own
            # ``prefix_len`` — a recovery re-admission declares the prefix
            # it continues from (== everything journaled so far), while a
            # fresh admit declares 0 and starts clean.
            entry.prompt = [int(t) for t in rec["prompt"]]
            entry.emitted = entry.emitted[:prefix_len]
            entry.priority = int(rec.get("priority", 0))
            # ttl_s and admit_wall move TOGETHER: a re-admission journals the
            # REMAINING budget as of ITS OWN wall stamp, so pairing the new
            # ttl with the old stamp would double-count the elapsed time on
            # every crash after the first (shrinking the deadline each
            # restart — the opposite of the keep-the-original-clock contract)
            entry.ttl_s = rec.get("ttl_s")
            entry.admit_wall = float(rec.get("wall", entry.admit_wall))
            entry.max_new_tokens = int(rec.get("max_new_tokens", 0))
            entry.eos_token_id = rec.get("eos")
            entry.greedy = bool(rec.get("greedy", True))
            key = rec.get("key") or [0, uid]
            entry.sampling_key = (int(key[0]), int(key[1]))
            entry.prefix_len = prefix_len
            # QoS identity (ISSUE 19): absent keys fold to the pre-QoS
            # defaults, so old journals — and QoS-off journals, which omit
            # default values — replay unchanged
            entry.tenant = str(rec.get("tenant", "default"))
            entry.service_class = str(rec.get("cls", "interactive"))
            entry.admits += 1
            # a re-admission reopens a request a previous generation may have
            # finalized (results adopted then re-served is a logic error the
            # supervisor never commits; stale terminals from a lost race are
            # superseded by the newest admit)
            entry.terminal = None
        elif kind == "tok":
            for uid_s, toks in rec.get("d", {}).items():
                entry = state.entries.get(int(uid_s))
                if entry is not None:
                    entry.emitted.extend(int(t) for t in toks)
        elif kind == "end":
            uid = int(rec["uid"])
            entry = state.entries.get(uid)
            if entry is None:
                # a terminal without an admit: the supervisor finalized a
                # request the engine never admitted (drain-mode shed) — a
                # stub entry keeps the status visible to replay consumers
                entry = JournalEntry(uid=uid, prompt=[])
                state.entries[uid] = entry
            entry.terminal = {"status": rec.get("status"),
                              "finish_reason": rec.get("finish_reason"),
                              "reason": rec.get("reason"),
                              "retryable": bool(rec.get("retryable", False)),
                              "n_tokens": int(rec.get("n_tokens", 0)),
                              "code": rec.get("code")}
        else:
            logger.warning(f"request journal {path}: unknown record type "
                           f"{kind!r} skipped (version skew?)")
    return state


def journal_bytes(path: Optional[str]) -> int:
    """On-disk journal size for health gauges (0 when absent/unset)."""
    if not path:
        return 0
    try:
        return os.path.getsize(path)
    except OSError:
        return 0
