"""Blocked KV allocator — host-side free list over the paged KV pool.

Analog of the reference BlockedAllocator (inference/v2/ragged/blocked_allocator.py):
fixed number of KV blocks, O(1) allocate/free via a free list.  The last block
id is reserved as the trash target for padded writes (models.llama.forward_paged).

Failures raise :class:`KVAllocationError` (a RuntimeError) so callers can tell
"the pool is tight, retry later" apart from programming errors — the SplitFuse
scheduler treats it as a failed reservation and retries the chunk on a later
step, which is also the seam the serving fault-injection harness drives
(tests/unit/fault_injection_serving.py FaultyBlockedAllocator).
"""

from typing import List


class KVAllocationError(RuntimeError):
    """The KV pool could not satisfy an allocation (exhausted, or an injected
    transient fault).  Retryable: freed blocks make the same request succeed."""


class BlockedAllocator:

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (1 usable + trash)")
        self.num_blocks = num_blocks
        self.trash_block = num_blocks - 1
        self._free: List[int] = list(range(num_blocks - 1))  # trash never allocated
        # every outstanding block id; a free() of a block not in here is a
        # double free (the bug class that silently aliases two sequences' KV)
        self._in_use: set = set()

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def free_block_set(self) -> frozenset:
        """The free list as a set — the block census checks its owned set
        partitions exactly against this (kv_metrics.BlockCensus.check_against,
        the PR-4 double-free guard as a continuously-checked pool invariant)."""
        return frozenset(self._free)

    def allocate(self, n: int) -> List[int]:
        if n > len(self._free):
            raise KVAllocationError(f"KV pool exhausted: requested {n}, free {len(self._free)}")
        out = self._free[:n]
        self._free = self._free[n:]
        self._in_use.update(out)
        return out

    def free(self, blocks: List[int]) -> None:
        seen = set()
        for b in blocks:
            if b == self.trash_block or b < 0 or b >= self.num_blocks:
                raise ValueError(f"bad block id {b}")
            if b not in self._in_use or b in seen:
                raise ValueError(f"double free of block {b}: not currently allocated "
                                 f"(would alias two sequences onto one KV block)")
            seen.add(b)
        for b in blocks:
            self._in_use.discard(b)
        self._free.extend(blocks)
