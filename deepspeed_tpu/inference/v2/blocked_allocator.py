"""Blocked KV allocator — host-side free list over the paged KV pool.

Analog of the reference BlockedAllocator (inference/v2/ragged/blocked_allocator.py):
fixed number of KV blocks, O(1) allocate/free via a free list.  The last block
id is reserved as the trash target for padded writes (models.llama.forward_paged).
"""

from typing import List


class BlockedAllocator:

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (1 usable + trash)")
        self.num_blocks = num_blocks
        self.trash_block = num_blocks - 1
        self._free: List[int] = list(range(num_blocks - 1))  # trash never allocated

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def allocate(self, n: int) -> List[int]:
        if n > len(self._free):
            raise RuntimeError(f"KV pool exhausted: requested {n}, free {len(self._free)}")
        out = self._free[:n]
        self._free = self._free[n:]
        return out

    def free(self, blocks: List[int]) -> None:
        for b in blocks:
            if b == self.trash_block or b < 0 or b >= self.num_blocks:
                raise ValueError(f"bad block id {b}")
        self._free.extend(blocks)
