"""Blocked KV allocator — host-side free list over the paged KV pool.

Analog of the reference BlockedAllocator (inference/v2/ragged/blocked_allocator.py):
fixed number of KV blocks, O(1) allocate/free via a free list.  The last block
id is reserved as the trash target for padded writes (models.llama.forward_paged).

Block-level ref-counting (ISSUE 13): a block can be mapped read-only by more
than one sequence at a time (copy-on-write prefix sharing —
ragged_manager.PrefixCache).  ``allocate`` hands out blocks at refcount 1,
``incref`` adds a mapping, and ``free`` RELEASES ONE MAPPING: the block
returns to the free list only when its refcount reaches zero.  The PR-4
double-free guard is thereby extended into a refcount invariant — evicting
one request can never free a block another request still maps, and releasing
a block more times than it was mapped is still the loud ``ValueError`` it
always was (the bug class that silently aliases two sequences' KV).

Failures raise :class:`KVAllocationError` (a RuntimeError) so callers can tell
"the pool is tight, retry later" apart from programming errors — the SplitFuse
scheduler treats it as a failed reservation and retries the chunk on a later
step, which is also the seam the serving fault-injection harness drives
(tests/unit/fault_injection_serving.py FaultyBlockedAllocator).
"""

from typing import Dict, List


class KVAllocationError(RuntimeError):
    """The KV pool could not satisfy an allocation (exhausted, or an injected
    transient fault).  Retryable: freed blocks make the same request succeed."""


class BlockedAllocator:

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (1 usable + trash)")
        self.num_blocks = num_blocks
        self.trash_block = num_blocks - 1
        self._free: List[int] = list(range(num_blocks - 1))  # trash never allocated
        # every outstanding block id; a free() of a block not in here is a
        # double free (the bug class that silently aliases two sequences' KV)
        self._in_use: set = set()
        # mappings per outstanding block: 1 at allocation, +1 per incref
        # (copy-on-write prefix sharing), -1 per free; the free list gets the
        # block back only at zero
        self._refs: Dict[int, int] = {}

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def free_block_set(self) -> frozenset:
        """The free list as a set — the block census checks its owned set
        partitions exactly against this (kv_metrics.BlockCensus.check_against,
        the PR-4 double-free guard as a continuously-checked pool invariant)."""
        return frozenset(self._free)

    def refcount(self, block: int) -> int:
        """Outstanding mappings of ``block`` (0 for a free/unknown block) —
        the census's refcount-agreement invariant reads this."""
        return self._refs.get(block, 0)

    def allocate(self, n: int) -> List[int]:
        if n > len(self._free):
            raise KVAllocationError(f"KV pool exhausted: requested {n}, free {len(self._free)}")
        out = self._free[:n]
        self._free = self._free[n:]
        self._in_use.update(out)
        for b in out:
            self._refs[b] = 1
        return out

    def incref(self, block: int) -> None:
        """Add one read-only mapping to an OUTSTANDING block (prefix-cache
        sharing).  Incref of a free/unknown block is a programming error —
        the mapped KV would be rewritten by the block's next owner."""
        if block not in self._in_use:
            raise ValueError(f"incref of block {block}: not currently allocated "
                             f"(a free block's KV has no owner to share)")
        self._refs[block] += 1

    def free(self, blocks: List[int]) -> List[int]:
        """Release one mapping per listed block.  Returns the blocks whose
        refcount reached zero and actually went back to the free list —
        callers invalidating caches (the prefix tree) key on that list, not
        on the request's own block table."""
        seen = set()
        for b in blocks:
            if b == self.trash_block or b < 0 or b >= self.num_blocks:
                raise ValueError(f"bad block id {b}")
            if b not in self._in_use or b in seen:
                raise ValueError(f"double free of block {b}: not currently allocated "
                                 f"(would alias two sequences onto one KV block)")
            seen.add(b)
        released: List[int] = []
        for b in blocks:
            self._refs[b] -= 1
            if self._refs[b] <= 0:
                del self._refs[b]
                self._in_use.discard(b)
                released.append(b)
        self._free.extend(released)
        return released
