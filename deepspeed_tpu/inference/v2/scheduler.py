"""Dynamic SplitFuse scheduler.

Analog of InferenceEngineV2.can_schedule / the FastGen token-budget policy
(inference/v2/engine_v2.py:184, blogs/deepspeed-fastgen): every engine step
runs a fixed token budget; decoding sequences contribute 1 token each, the
remaining budget is filled with prompt CHUNKS (long prompts are split across
steps — "split"), and prompts co-run with decodes in one ragged batch
("fuse").  Fixed-size steps keep forward latency flat and the MXU saturated.
"""

import dataclasses
from typing import Dict, List, Optional, Tuple

from .ragged_manager import RaggedStateManager, SequenceDescriptor


@dataclasses.dataclass(frozen=True)
class ScheduledChunk:
    uid: int
    n_tokens: int  # tokens of this sequence to run this step


class SplitFuseScheduler:

    def __init__(self, token_budget: int = 512, max_seqs_per_step: int = 64,
                 telemetry=None):
        self.token_budget = token_budget
        self.max_seqs = max_seqs_per_step
        # TelemetryCollector (monitor/telemetry.py); every schedule() emits
        # the scheduler gauges through it when attached
        self.telemetry = telemetry
        self.steps = 0
        self.last_gauges: Dict[str, float] = {}

    def schedule(self, manager: RaggedStateManager) -> List[ScheduledChunk]:
        """Pick this step's ragged batch. Decodes first (latency), then prompt
        chunks to fill the budget; respects KV-pool availability."""
        budget = self.token_budget
        chunks: List[ScheduledChunk] = []
        decoding, prefilling = [], []
        for uid in manager.live_uids():
            seq = manager.seqs[uid]
            if seq.pending_tokens <= 0:
                continue
            (prefilling if seq.pending_tokens > 1 else decoding).append(seq)

        for seq in decoding:
            if budget <= 0 or len(chunks) >= self.max_seqs:
                break
            if not self._reserve(manager, seq, 1):
                continue
            chunks.append(ScheduledChunk(seq.uid, 1))
            budget -= 1

        for seq in prefilling:
            if budget <= 0 or len(chunks) >= self.max_seqs:
                break
            take = min(seq.pending_tokens, budget)
            while take > 0 and not seq.done and not self._reserve(manager, seq, take):
                take //= 2  # shrink the chunk if the KV pool is tight
            if take <= 0 or seq.done:
                continue
            chunks.append(ScheduledChunk(seq.uid, take))
            budget -= take
        self._emit_gauges(manager, chunks, len(decoding), len(prefilling))
        return chunks

    def _emit_gauges(self, manager: RaggedStateManager, chunks: List[ScheduledChunk],
                     n_decoding: int, n_prefilling: int) -> None:
        """Scheduler observability: queue depth, batch token occupancy, and
        KV-block utilization per step, flowing through the shared telemetry
        collector (the scheduler was a black box before — ISSUE 1)."""
        scheduled_tokens = sum(c.n_tokens for c in chunks)
        self.last_gauges = {
            "queue_depth": float(n_decoding + n_prefilling),
            "decode_seqs": float(n_decoding),
            "prefill_seqs": float(n_prefilling),
            "scheduled_seqs": float(len(chunks)),
            "scheduled_tokens": float(scheduled_tokens),
            "token_occupancy": scheduled_tokens / max(self.token_budget, 1),
            "kv_block_utilization": manager.kv_utilization(),
        }
        self.steps += 1
        if self.telemetry is not None:
            self.telemetry.record_gauges(self.last_gauges, step=self.steps,
                                         prefix="Inference/Scheduler")

    @staticmethod
    def _reserve(manager: RaggedStateManager, seq: SequenceDescriptor, n: int) -> bool:
        upto = seq.seen_tokens + n
        if manager.over_cap(upto):
            # fail just this sequence (reference: request rejection), not the step
            manager.fail(seq.uid, f"needs {upto} tokens > "
                         f"{manager.max_blocks_per_seq * manager.block_size} cap")
            return False
        need = manager.blocks_needed(seq, upto)
        if need and not manager.can_allocate(need):
            return False
        manager.ensure_blocks(seq, upto)
        return True
