"""Dynamic SplitFuse scheduler.

Analog of InferenceEngineV2.can_schedule / the FastGen token-budget policy
(inference/v2/engine_v2.py:184, blogs/deepspeed-fastgen): every engine step
runs a fixed token budget; decoding sequences contribute 1 token each, the
remaining budget is filled with prompt CHUNKS (long prompts are split across
steps — "split"), and prompts co-run with decodes in one ragged batch
("fuse").  Fixed-size steps keep forward latency flat and the MXU saturated.

Resilience (ISSUE 4): a decode-starvation guard with KV-pressure preemption —
a decode that cannot reserve its one block reclaims capacity from the NEWEST
prefilling sequence, which is rolled back to a block boundary (prefix KV kept)
and requeued; a victim preempted past ``max_preemptions`` is evicted with
finish reason ``preempt_requeued_exhausted``.  A decoding sequence that hits
``max_blocks_per_seq`` now completes gracefully (``length_capped`` — every
generated token is valid) instead of being hard-failed mid-generation, and
injected/transient :class:`KVAllocationError`s degrade to "chunk skipped this
step" instead of detonating the whole step.
"""

import dataclasses
from typing import Dict, List, Optional

from ...runtime.config import ServingResilienceConfig
from .blocked_allocator import KVAllocationError
from .ragged_manager import RaggedStateManager, SequenceDescriptor


@dataclasses.dataclass(frozen=True)
class ScheduledChunk:
    uid: int
    n_tokens: int  # tokens of this sequence to run this step


class SplitFuseScheduler:

    def __init__(self, token_budget: int = 512, max_seqs_per_step: int = 64,
                 telemetry=None, resilience: Optional[ServingResilienceConfig] = None,
                 tracer=None, gauge_timestamp=None):
        self.token_budget = token_budget
        self.max_seqs = max_seqs_per_step
        # TelemetryCollector (monitor/telemetry.py); every schedule() emits
        # the scheduler gauges through it when attached
        self.telemetry = telemetry
        # RequestTracer (monitor/tracing.py): preempt/requeue land in the
        # victim's span chain and the always-on flight recorder (ISSUE 6)
        self.tracer = tracer
        # engine-provided deterministic gauge timestamp (None -> wall clock):
        # the engine returns its injected clock's last read under FakeClock
        # tests so scheduler gauge records stamp deterministically too
        self.gauge_timestamp = gauge_timestamp
        self.resilience = resilience if resilience is not None else ServingResilienceConfig()
        # QosPolicy (inference/v2/qos.py), installed by the engine when
        # serving_qos is armed: steers preemption-victim choice toward
        # over-quota tenants and lower service classes.  None → the PR-4
        # newest-prefill heuristic, byte-identical
        self.qos = None
        self.steps = 0
        self.preempted_total = 0
        # fused-decode work accounting (ISSUE 20): `steps` NEVER advances
        # inside a fused burst or a speculative verify (that contract keeps
        # step-keyed seams — watchdog signatures, trace step stamps —
        # identical across decode paths), so fairness/preemption math that
        # wants decode work in step units reads these instead: a k-step burst
        # notes k fused steps, and a speculative verify notes the deepest
        # per-sequence accepted run (its sequential-step equivalent) plus
        # every emitted token
        self.fused_steps = 0
        self.fused_tokens = 0
        self.last_gauges: Dict[str, float] = {}
        self._requeued: set = set()  # victims preempted THIS step (skip their prefill)
        self._reserve_faulted = False  # last _reserve failed on an injected/transient
        # allocator fault (pool may have room) rather than genuine exhaustion

    def note_fused_work(self, steps: int, tokens: int) -> None:
        """Record one fused decode round's work in step units (ISSUE 20):
        ``steps`` is the round's sequential-step equivalent (burst length k,
        or a speculative round's deepest accepted run) and ``tokens`` the
        tokens it emitted across the batch — so a verify that emits between 1
        and k+1 tokens per sequence is charged as k-token decode work for
        fairness accounting without ever advancing :attr:`steps` mid-burst."""
        self.fused_steps += int(steps)
        self.fused_tokens += int(tokens)

    def live_split(self, manager: RaggedStateManager
                   ) -> "tuple[List[SequenceDescriptor], List[SequenceDescriptor]]":
        """Split the live, schedulable set into (decoding, prefilling) —
        shared by schedule() and the engine's decode-fusion applicability
        check (a pure-decode stable live set is what the fused burst needs)."""
        decoding: List[SequenceDescriptor] = []
        prefilling: List[SequenceDescriptor] = []
        for uid in manager.live_uids():
            seq = manager.seqs[uid]
            if seq.pending_tokens <= 0:
                continue
            (prefilling if seq.pending_tokens > 1 else decoding).append(seq)
        return decoding, prefilling

    def schedule(self, manager: RaggedStateManager) -> List[ScheduledChunk]:
        """Pick this step's ragged batch. Decodes first (latency), then prompt
        chunks to fill the budget; respects KV-pool availability.

        Prefix caching (ISSUE 13): each prefill candidate first maps whatever
        shared prompt blocks the tree can serve (late binding — blocks
        computed since the request was admitted still count), and a candidate
        whose NEXT needed block is being computed by a sequence already
        scheduled THIS step is deferred one step instead of duplicating the
        prefill — next step the block maps as a hit."""
        budget = self.token_budget
        chunks: List[ScheduledChunk] = []
        self._requeued = set()
        decoding, prefilling = self.live_split(manager)
        cache = manager.prefix_cache
        # hashes of prompt blocks that sequences scheduled THIS step will
        # complete — a later candidate needing one of these defers
        pending_hashes: set = set()

        def note_pending(seq: SequenceDescriptor, take: int) -> None:
            if cache is None or not seq.prefix_hashes:
                return
            end = min(seq.seen_tokens + take, seq.prompt_len)
            for i in range(seq.seen_tokens // manager.block_size,
                           end // manager.block_size):
                # only blocks this chunk will actually OFFER to the tree:
                # a CoW copy's final block sits below the registration
                # watermark and is never offered — advertising its hash
                # would defer a peer onto a registration that never comes
                if seq.prefix_registered <= i < len(seq.prefix_hashes):
                    pending_hashes.add(seq.prefix_hashes[i])

        starved: List[SequenceDescriptor] = []
        for seq in decoding:
            if budget <= 0 or len(chunks) >= self.max_seqs:
                break
            if not self._reserve(manager, seq, 1):
                # pool-tight (not capped/failed) decodes are preemption-
                # rescuable; a transient allocator FAULT is not exhaustion —
                # retry next step instead of punishing an innocent prefill
                if not seq.done and not self._reserve_faulted:
                    starved.append(seq)
                continue
            chunks.append(ScheduledChunk(seq.uid, 1))
            note_pending(seq, 1)  # a CoW-mapped prompt's final position
            budget -= 1

        if starved and self.resilience.preemption:
            budget = self._rescue_starved_decodes(manager, starved, prefilling,
                                                  chunks, budget)

        for seq in prefilling:
            if budget <= 0 or len(chunks) >= self.max_seqs:
                break
            if seq.done or seq.uid in self._requeued:
                continue  # evicted, or preempted-and-requeued this very step
            if cache is not None:
                manager.map_prefix(seq)  # late-binding shared-prefix lookup
                if seq.pending_tokens <= 0:
                    continue  # fully served from the tree
                nxt = manager.next_prefix_hash(seq)
                if (nxt is not None and cache.defer_shared_prefill
                        and nxt in pending_hashes):
                    # an already-scheduled sequence computes this exact block
                    # this step: wait one step and map it instead of
                    # prefilling the duplicate
                    cache.deferrals_total += 1
                    continue
            take = min(seq.pending_tokens, budget)
            while take > 0 and not seq.done and not self._reserve(manager, seq, take):
                if self._reserve_faulted:
                    take = 0  # transient fault: retry next step at full size
                    break
                take //= 2  # shrink the chunk if the KV pool is tight
            if take <= 0 or seq.done:
                continue
            chunks.append(ScheduledChunk(seq.uid, take))
            note_pending(seq, take)
            budget -= take
        self._emit_gauges(manager, chunks, len(decoding), len(prefilling))
        return chunks

    # ---------------------------------------------- decode-starvation guard
    def _rescue_starved_decodes(self, manager: RaggedStateManager,
                                starved: List[SequenceDescriptor],
                                prefilling: List[SequenceDescriptor],
                                chunks: List[ScheduledChunk], budget: int) -> int:
        """KV-pressure preemption: a decode that could not reserve its single
        block reclaims capacity from the newest prefilling victim.  Victims
        lose their trailing half of blocks per preemption (rolled back to the
        kept-block boundary, requeued for later steps); a victim already at
        ``max_preemptions`` is instead evicted outright so decodes — which
        hold completed prefill work — never starve behind fresh prompts."""
        scheduled = {c.uid for c in chunks}
        max_preempt = self.resilience.max_preemptions
        # victim preference (ISSUE 19): with a QoS policy armed, over-quota
        # tenants are preempted first, then lower classes, and only then the
        # newest-prefill heuristic breaks ties; without one the rank prefix
        # is constant and max() degenerates to the legacy arrival order
        if self.qos is not None:
            victim_key = lambda s: self.qos.victim_rank(s) + (s.arrival,)
        else:
            victim_key = lambda s: s.arrival
        for seq in starved:
            if budget <= 0 or len(chunks) >= self.max_seqs:
                break
            rescued = False
            while not rescued:
                if self._reserve(manager, seq, 1):
                    rescued = True
                    break
                if self._reserve_faulted:
                    break  # fault, not pressure: no victim deserves preemption
                # only victims whose droppable tail RELEASES real capacity
                # qualify: under prefix sharing a tail of shared mappings
                # only decrements refcounts, so preempting (or evicting) such
                # a victim would burn its budget while the decode stays
                # starved — the capacity lives with the other mapper
                victims = [p for p in prefilling
                           if p.blocks and not p.done and p.uid not in scheduled
                           and manager.releasable_blocks(p, 0) > 0]
                fresh = [p for p in victims if p.preemptions < max_preempt
                         and manager.releasable_blocks(p, len(p.blocks) // 2) > 0]
                if fresh:
                    victim = max(fresh, key=victim_key)
                    keep = len(victim.blocks) // 2
                    freed = manager.preempt(victim, keep_blocks=keep)
                    victim.preemptions += 1
                    self.preempted_total += 1
                    self._requeued.add(victim.uid)
                    self._record("serving_preempt", uid=victim.uid, freed_blocks=freed,
                                 rolled_back_to=victim.seen_tokens,
                                 preemptions=victim.preemptions)
                    if self.tracer is not None:
                        self.tracer.event("preempt", step=self.steps, uid=victim.uid,
                                          freed_blocks=freed)
                        self.tracer.on_preempt(victim.uid, freed_blocks=freed,
                                               rolled_back_to=victim.seen_tokens,
                                               preemptions=victim.preemptions)
                elif victims:
                    # every candidate exhausted its requeue budget: evict the
                    # newest one for good rather than deadlock the decodes
                    victim = max(victims, key=victim_key)
                    freed = manager.evict(victim, "preempt_requeued_exhausted")
                    self.preempted_total += 1
                    self._record("serving_preempt_exhausted", uid=victim.uid,
                                 freed_blocks=freed, preemptions=victim.preemptions)
                    if self.tracer is not None:
                        self.tracer.event("preempt_exhausted", step=self.steps,
                                          uid=victim.uid, freed_blocks=freed)
                else:
                    break  # nothing left to reclaim; the stall watchdog owns this
            if rescued:
                chunks.append(ScheduledChunk(seq.uid, 1))
                budget -= 1
        return budget

    def _record(self, event: str, **fields) -> None:
        if self.telemetry is not None:
            self.telemetry.record_resilience(event, step=self.steps, **fields)

    def _emit_gauges(self, manager: RaggedStateManager, chunks: List[ScheduledChunk],
                     n_decoding: int, n_prefilling: int) -> None:
        """Scheduler observability: queue depth, batch token occupancy, and
        KV-block utilization per step, flowing through the shared telemetry
        collector (the scheduler was a black box before — ISSUE 1)."""
        scheduled_tokens = sum(c.n_tokens for c in chunks)
        self.last_gauges = {
            "queue_depth": float(n_decoding + n_prefilling),
            "decode_seqs": float(n_decoding),
            "prefill_seqs": float(n_prefilling),
            "scheduled_seqs": float(len(chunks)),
            "scheduled_tokens": float(scheduled_tokens),
            "token_occupancy": scheduled_tokens / max(self.token_budget, 1),
            "kv_block_utilization": manager.kv_utilization(),
            "preempted_total": float(self.preempted_total),
        }
        self.steps += 1
        if self.telemetry is not None:
            self.telemetry.record_gauges(
                self.last_gauges, step=self.steps, prefix="Inference/Scheduler",
                timestamp=self.gauge_timestamp() if self.gauge_timestamp else None)

    def _reserve(self, manager: RaggedStateManager, seq: SequenceDescriptor, n: int) -> bool:
        self._reserve_faulted = False
        upto = seq.seen_tokens + n
        if manager.over_cap(upto):
            if seq.generated_tokens > 0:
                # mid-generation cap: every token generated so far is valid
                # (sampled from real logits), so complete gracefully instead
                # of hard-failing the request (reference: max-length finish)
                seq.done = True
                seq.finish_reason = "length_capped"
            else:
                # the PROMPT itself cannot fit — a genuine rejection
                manager.fail(seq.uid, f"needs {upto} tokens > "
                             f"{manager.max_blocks_per_seq * manager.block_size} cap")
            return False
        need = manager.blocks_needed(seq, upto)
        if need and not manager.can_allocate(need):
            return False
        try:
            manager.ensure_blocks(seq, upto)
        except KVAllocationError:
            self._reserve_faulted = True
            return False  # transient/injected pool failure: retry a later step
        return True
