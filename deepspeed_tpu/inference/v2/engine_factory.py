"""v2 engine factory — build a ragged serving engine from a HF checkpoint.

Reference ``build_hf_engine`` (inference/v2/engine_factory.py:66): resolves the
model's policy by HF ``model_type`` and assembles InferenceEngineV2.  Supported:
llama, mistral (sliding window), mixtral (MoE), opt, falcon, phi, qwen2, gptj.
(BLOOM serves through the v1 engine — ALiBi needs the biased dense attention,
models/bloom.py.)
"""

from typing import Any, Dict, Optional

from ...utils.logging import log_dist
from .engine_v2 import InferenceEngineV2


def _registry():
    from ...models import falcon, gptj, llama, mistral, mixtral, opt, phi, qwen
    return {
        "llama": (llama, llama.config_from_hf),
        "mistral": (mistral, mistral.config_from_hf),
        "mixtral": (mixtral, None),  # config built field-by-field below
        "opt": (opt, opt.config_from_hf),
        "falcon": (falcon, falcon.config_from_hf),
        "phi": (phi, phi.config_from_hf),
        "qwen2": (qwen, qwen.config_from_hf),
        "gptj": (gptj, gptj.config_from_hf),
    }


def _mixtral_config(hf_config):
    from ...models.mixtral import MixtralConfig
    return MixtralConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        intermediate_size=hf_config.intermediate_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        num_kv_heads=getattr(hf_config, "num_key_value_heads", hf_config.num_attention_heads),
        num_experts=getattr(hf_config, "num_local_experts", 8),
        top_k=getattr(hf_config, "num_experts_per_tok", 2),
        max_seq_len=getattr(hf_config, "max_position_embeddings", 4096),
        rope_theta=getattr(hf_config, "rope_theta", 1e6),
        rms_eps=getattr(hf_config, "rms_norm_eps", 1e-5),
    )


def build_engine(model_type: str, model_config, params, config: Optional[Dict] = None,
                 **engine_kwargs) -> InferenceEngineV2:
    """Assemble a v2 engine for a known model family with ready params."""
    reg = _registry()
    if model_type not in reg:
        raise ValueError(f"v2 serving supports {sorted(reg)}; got {model_type!r}")
    module, _ = reg[model_type]
    return InferenceEngineV2(module, model_config, params, config=config, **engine_kwargs)


def build_hf_engine(hf_model_or_path: Any, config: Optional[Dict] = None,
                    **engine_kwargs) -> InferenceEngineV2:
    """Reference build_hf_engine analog: accepts a transformers model instance
    (or a local path loadable by transformers) and converts its weights."""
    if isinstance(hf_model_or_path, str):
        import transformers
        hf_model = transformers.AutoModelForCausalLM.from_pretrained(hf_model_or_path)
    else:
        hf_model = hf_model_or_path
    hf_config = hf_model.config
    model_type = hf_config.model_type
    reg = _registry()
    if model_type not in reg:
        raise ValueError(f"v2 serving supports {sorted(reg)}; got {model_type!r}")
    module, conv = reg[model_type]
    model_config = conv(hf_config) if conv is not None else _mixtral_config(hf_config)
    params = module.from_hf_state_dict(model_config, hf_model.state_dict())
    log_dist(f"build_hf_engine: {model_type} params ready", ranks=[0])
    return InferenceEngineV2(module, model_config, params, config=config, **engine_kwargs)
