"""Block-level observability over the paged KV pool (ISSUE 12 tentpole).

The blocked allocator knows a free list; the ops plane knew one utilization
gauge.  Neither can answer the questions the next serving-scale items
(copy-on-write prefix caching, int8 quantized KV) will be decided by: which
blocks are shared candidates, which are cold, how fragmented the pool is, and
how many steps of headroom remain before shed/preempt pressure starts.  This
module is the measurement layer for those decisions, built entirely from
host-side state the allocator and ragged manager already own:

- :class:`BlockCensus` — per-block bookkeeping (owner uid, allocated-at step,
  last-touched step, tokens resident) fed by the ragged manager's
  alloc/free/preempt/retire seams, with pool-level rollups: utilization,
  fragmentation (allocated-but-unfilled token slots), a block-age histogram
  on :class:`~...monitor.tracing.StreamingHistogram`, and a blocks-per-request
  distribution sampled at each sequence's terminal.  The census's owned-block
  set must exactly partition against the allocator's free list at all times —
  :meth:`BlockCensus.check_against` turns the PR-4 double-free guard into a
  continuously-checked pool invariant (:class:`CensusInvariantError` names the
  offending uid/block).
- :class:`PrefixObservatory` — hashes full prompt token-blocks with the exact
  chained token-block hash a future prefix tree will key on
  (:func:`block_hashes`), and reports per serve pass the COUNTERFACTUAL
  prefix-cache win across live + admitted requests: duplicate-block count,
  prefill tokens sharing would have saved, and a would-be hit-rate.
- :class:`CapacityForecaster` — EWMA of block alloc/free rates per serve
  iteration yielding a steps-to-exhaustion gauge, so overload becomes
  predictable (surfaced next to the PR-4 shed/preempt counters) instead of
  observed after the fact.

Timing discipline (the PR-6/PR-10 contract): every input is a python int the
host already owns — census hooks fire at manager bookkeeping points, the
refresh walks ``seen_tokens``/block tables, the observatory hashes prompt
lists.  ZERO device syncs, enforced by dslint's host-sync whole-file scan
(this module is scanned like ``runtime/heartbeat.py`` and the ops plane), and
proven by the kv-obs smoke's byte-identical ``ServeCounters`` with
observability on vs off.  Nothing here imports jax or numpy.
"""

import dataclasses
import hashlib
from typing import Any, Dict, Iterable, List, Optional, Tuple

from ...monitor.tracing import StreamingHistogram


class CensusInvariantError(RuntimeError):
    """The census's owned-block set stopped partitioning the allocator's free
    list — either a block is owned by a sequence AND on the free list (the
    aliasing bug class the PR-4 double-free guard exists for) or a block
    vanished from both sides (a leak) — or, with copy-on-write prefix sharing
    (ISSUE 13), a shared block's bookkeeping went inconsistent: census owners
    disagree with the allocator refcount, or two mappers' token ids for the
    block differ (one request would observe another's KV).  Carries the
    offending block id and, when known, the owning uid(s)."""

    def __init__(self, message: str, *, block: Optional[int] = None,
                 uid: Optional[int] = None, uid2: Optional[int] = None):
        super().__init__(message)
        self.block = block
        self.uid = uid
        self.uid2 = uid2


@dataclasses.dataclass
class BlockRecord:
    """One allocated block's census entry (all host ints).  ``owners`` lists
    every sequence mapping the block — one entry for a private block, more
    under copy-on-write prefix sharing; the record lives until the last
    mapping is released (mirroring the allocator refcount)."""
    owners: List[int]         # mapping sequences (first = the allocating writer)
    allocated_step: int       # scheduler step at allocation
    last_touched_step: int    # scheduler step of the last resident-token change
    tokens_resident: int = 0  # KV positions actually written into this block

    @property
    def uid(self) -> int:
        """The allocating (writer) uid — the single-owner view pre-sharing
        callers read."""
        return self.owners[0]

    def as_dict(self) -> Dict[str, Any]:
        return {"uid": self.uid, "owners": list(self.owners),
                "allocated_step": self.allocated_step,
                "last_touched_step": self.last_touched_step,
                "tokens_resident": self.tokens_resident}


class BlockCensus:
    """Per-block bookkeeping over the paged KV pool.

    Hooks (:meth:`on_alloc` / :meth:`on_free`) fire from the ragged manager's
    single reclaim seam, so every path that moves a block — prefill growth,
    burst pre-allocation and rollback, preemption, eviction, failure,
    retirement — keeps the census exact.  :meth:`refresh` runs at wave
    boundaries on the engine's step counter and updates resident-token counts
    and last-touched stamps from ``seen_tokens`` (pure host arithmetic).

    Ages are measured in SCHEDULER STEPS, not wall time: deterministic under
    any clock, so FakeClock tests assert exact quantiles.
    """

    def __init__(self, block_size: int, num_blocks: int, trash_block: int, *,
                 age_buckets_per_decade: int = 6):
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.trash_block = int(trash_block)
        self.step = 0
        self.blocks: Dict[int, BlockRecord] = {}
        # lifetime flow counters (the forecaster's inputs; registry counters)
        self.blocks_allocated_total = 0
        self.blocks_freed_total = 0
        # peak blocks each live uid has held; sampled into the
        # blocks-per-request distribution at the sequence's retirement — the
        # per-request KV footprint the prefix-cache sizing will read
        self._peak_blocks: Dict[int, int] = {}
        self._held_blocks: Dict[int, int] = {}
        # running resident-token total, maintained incrementally by
        # refresh/on_free so fragmentation_tokens() is O(1) — it is read on
        # every decode step (serving gauges, peak tracking, counter track)
        # and a per-step full-pool walk would tax large pools for nothing
        self._resident_total = 0
        # callbacks run when a sequence's pool life ends (retire): the
        # engine's KVObservability subscribes the prefix observatory's cache
        # invalidation here, so a reused uid is charged as the NEW request
        # it is instead of riding the dead request's cached hashes
        self.terminal_listeners: List[Any] = []
        # seen_tokens at each uid's previous refresh: residency is a pure
        # function of (seen_tokens, block index), so a refresh only needs to
        # touch the blocks inside [prev_seen, seen) — unchanged sequences
        # cost one dict lookup instead of a full block-table walk per wave
        self._last_seen: Dict[int, int] = {}
        self._age_bpd = int(age_buckets_per_decade)
        self.blocks_per_request = StreamingHistogram(self._age_bpd, 1.0)
        # high-water marks, sampled at each refresh: a completed scenario
        # always ends with an empty pool, so POINT-IN-TIME fragmentation at
        # the end carries no signal — the peaks are what sizing reads
        self.peak_fragmentation_tokens = 0
        self.peak_allocated_blocks = 0

    # -------------------------------------------------------------- hooks
    def on_alloc(self, uid: int, blocks: Iterable[int]) -> None:
        uid = int(uid)
        n = 0
        for b in blocks:
            self.blocks[int(b)] = BlockRecord(owners=[uid],
                                              allocated_step=self.step,
                                              last_touched_step=self.step)
            n += 1
        self.blocks_allocated_total += n
        held = self._held_blocks.get(uid, 0) + n
        self._held_blocks[uid] = held
        if held > self._peak_blocks.get(uid, 0):
            self._peak_blocks[uid] = held

    def on_share(self, uid: int, block: int) -> None:
        """A sequence mapped an existing block read-only (copy-on-write
        prefix sharing, ISSUE 13): the block gains an owner — NOT an
        allocation; the flow counters and the forecaster see only real
        pool movement."""
        uid = int(uid)
        rec = self.blocks.get(int(block))
        if rec is not None:
            rec.owners.append(uid)
        held = self._held_blocks.get(uid, 0) + 1
        self._held_blocks[uid] = held
        if held > self._peak_blocks.get(uid, 0):
            self._peak_blocks[uid] = held

    def on_free(self, uid: int, blocks: Iterable[int]) -> None:
        """Release ``uid``'s mapping of each block; the record (and the
        freed-flow counter) goes only when the LAST owner lets go —
        mirroring the allocator's refcount-zero release."""
        uid = int(uid)
        n = 0
        fully = 0
        for b in blocks:
            b = int(b)
            rec = self.blocks.get(b)
            if rec is None:
                continue
            n += 1
            if uid in rec.owners:
                rec.owners.remove(uid)
            if not rec.owners:
                del self.blocks[b]
                self._resident_total -= rec.tokens_resident
                fully += 1
        self.blocks_freed_total += fully
        if uid in self._held_blocks:
            self._held_blocks[uid] = max(self._held_blocks[uid] - n, 0)

    def on_terminal(self, uid: int) -> None:
        """A sequence's pool life ended (manager ``retire``): sample its PEAK
        held blocks into the blocks-per-request distribution (evictions and
        failures free their blocks before retirement, so sampling current
        holdings there would undercount; zero-peak requests still sample —
        they are the shed-adjacent tail the distribution should show)."""
        uid = int(uid)
        self.blocks_per_request.add(float(self._peak_blocks.pop(uid, 0)))
        self._held_blocks.pop(uid, None)
        self._last_seen.pop(uid, None)
        for listener in self.terminal_listeners:
            listener(uid)

    def refresh(self, seqs: Dict[int, Any], step: int) -> None:
        """Wave-boundary update: advance the census step and refresh resident
        tokens / last-touched stamps from each live sequence's ``seen_tokens``
        (block ``i`` of a sequence holds positions ``[i*bs, (i+1)*bs)``).

        Incremental: residency is a pure function of ``(seen_tokens, block
        index)``, so only the blocks whose index range the seen-pointer
        crossed since the previous refresh are touched — an unchanged
        sequence costs one dict lookup, not a block-table walk."""
        self.step = int(step)
        bs = self.block_size
        for uid, seq in seqs.items():
            seen = seq.seen_tokens
            prev = self._last_seen.get(uid, 0)
            if seen == prev:
                continue  # new blocks (burst pre-alloc) start resident 0
            self._last_seen[uid] = seen
            lo = min(prev, seen) // bs
            hi = min(-(-max(prev, seen) // bs), len(seq.blocks))
            for i in range(lo, hi):
                rec = self.blocks.get(int(seq.blocks[i]))
                if rec is None:
                    continue  # the invariant check reports this, not refresh
                resident = min(max(seen - i * bs, 0), bs)
                if len(rec.owners) > 1:
                    # a shared block is full by construction (only completed
                    # prompt blocks are mappable); one owner's rollback must
                    # not mark KV absent that the other owners still read
                    resident = max(resident, rec.tokens_resident)
                if resident != rec.tokens_resident:
                    self._resident_total += resident - rec.tokens_resident
                    rec.tokens_resident = resident
                    rec.last_touched_step = self.step
        frag = self.fragmentation_tokens()
        if frag > self.peak_fragmentation_tokens:
            self.peak_fragmentation_tokens = frag
        if self.allocated_blocks > self.peak_allocated_blocks:
            self.peak_allocated_blocks = self.allocated_blocks

    # ------------------------------------------------------------ rollups
    @property
    def allocated_blocks(self) -> int:
        return len(self.blocks)

    def shared_blocks(self) -> int:
        """Blocks currently mapped by more than one sequence (copy-on-write
        prefix sharing) — the one home for this definition; the rollup and
        the Prometheus gauge both read it.  Iterates a GIL-atomic list copy:
        health() threads call this while the serve thread allocates/frees."""
        return sum(1 for rec in list(self.blocks.values())
                   if len(rec.owners) > 1)

    def tokens_resident(self) -> int:
        return self._resident_total

    def fragmentation_tokens(self) -> int:
        """Allocated-but-unfilled token slots: pool bytes paid for but not yet
        holding KV (prefill in flight, burst pre-allocation, block-granularity
        waste).  The int8-KV and prefix-cache items both feed on this.  O(1):
        the resident total is maintained incrementally, never re-walked."""
        return self.allocated_blocks * self.block_size - self._resident_total

    def age_histogram(self) -> StreamingHistogram:
        """Block ages (census step - allocated step) as a log histogram —
        rebuilt on demand so it always describes the CURRENT pool, not an
        accumulation over dead blocks.  Age 0 lands in the underflow bucket
        (representative 0.0); quantiles are deterministic."""
        hist = StreamingHistogram(self._age_bpd, 1.0)
        # list copy: built on demand from health()/scrape threads while the
        # serve thread mutates the census — iterating the live dict crashes
        for rec in list(self.blocks.values()):
            hist.add(float(self.step - rec.allocated_step))
        return hist

    def idle_histogram(self) -> StreamingHistogram:
        """Steps since each block was last touched — the cold-block signal an
        age-aware quantization policy would key on."""
        hist = StreamingHistogram(self._age_bpd, 1.0)
        for rec in list(self.blocks.values()):  # list copy: see age_histogram
            hist.add(float(self.step - rec.last_touched_step))
        return hist

    def rollup(self, free_blocks: int) -> Dict[str, Any]:
        usable = max(self.num_blocks - 1, 1)  # trash never allocated
        return {
            "step": self.step,
            "allocated_blocks": self.allocated_blocks,
            "shared_blocks": self.shared_blocks(),
            "free_blocks": int(free_blocks),
            "usable_blocks": usable,
            "utilization": self.allocated_blocks / usable,
            "tokens_resident": self.tokens_resident(),
            "fragmentation_tokens": self.fragmentation_tokens(),
            "peak_fragmentation_tokens": self.peak_fragmentation_tokens,
            "peak_allocated_blocks": self.peak_allocated_blocks,
            "blocks_allocated_total": self.blocks_allocated_total,
            "blocks_freed_total": self.blocks_freed_total,
            "block_age_steps": self.age_histogram().snapshot(),
            "block_idle_steps": self.idle_histogram().snapshot(),
            "blocks_per_request": self.blocks_per_request.snapshot(),
        }

    def table(self) -> Dict[int, Dict[str, int]]:
        """The full per-block census (state_snapshot diagnostics; bounded by
        the pool size).  Sorts a GIL-atomic list copy — diagnostics threads
        read this while the serve thread allocates/frees."""
        return {b: rec.as_dict()
                for b, rec in sorted(list(self.blocks.items()))}

    # ---------------------------------------------------------- invariant
    def check_against(self, allocator, seqs: Optional[Dict[int, Any]] = None) -> None:
        """The census's owned set and the allocator's free list must exactly
        partition the usable pool; with copy-on-write sharing the refcount
        invariant rides along — every census owner list must agree with the
        allocator refcount, and (when ``seqs`` is provided) every mapper of a
        shared block must hold IDENTICAL token ids for the block's positions,
        or one request would be reading another's KV.  Raises
        :class:`CensusInvariantError` naming the first offending uid/block
        (and both uids for a shared-content violation); returns None when the
        invariant holds."""
        free = allocator.free_block_set()
        owned = set(self.blocks)
        both = owned & free
        if both:
            b = min(both)
            uid = self.blocks[b].uid
            raise CensusInvariantError(
                f"block {b} is owned by uid {uid} (census) AND on the "
                f"allocator free list — the double-free/aliasing bug class; "
                f"{len(both)} block(s) affected", block=b, uid=uid)
        usable = set(range(self.num_blocks)) - {self.trash_block}
        missing = usable - owned - free
        if missing:
            b = min(missing)
            raise CensusInvariantError(
                f"block {b} is neither census-owned nor on the allocator "
                f"free list — {len(missing)} block(s) leaked", block=b)
        extra = (owned | free) - usable
        if extra:
            b = min(extra)
            uid = self.blocks[b].uid if b in self.blocks else None
            raise CensusInvariantError(
                f"block {b} is outside the usable pool (trash block "
                f"{self.trash_block} excluded from [0, {self.num_blocks})) "
                f"yet tracked"
                + (f" by uid {uid}" if uid is not None else " as free"),
                block=b, uid=uid)
        # refcount agreement: owners-per-block must equal the allocator's
        # outstanding mappings (a drifted count frees too early or leaks)
        if hasattr(allocator, "refcount"):
            for b, rec in self.blocks.items():
                refs = allocator.refcount(b)
                if refs != len(rec.owners):
                    raise CensusInvariantError(
                        f"block {b}: census lists {len(rec.owners)} owner(s) "
                        f"{rec.owners} but the allocator refcount is {refs} — "
                        f"a mapping was gained or released without the other "
                        f"side noticing", block=b, uid=rec.owners[0])
        if seqs is not None:
            self._check_shared_content(seqs)

    def _check_shared_content(self, seqs: Dict[int, Any]) -> None:
        """Every mapper of a shared block must hold the SAME token ids for
        the block's position range — the no-request-observes-another's-KV
        invariant the prefix tree's token verification exists to uphold —
        AND belong to the same tenant: the tenant-seeded hash chain makes
        cross-tenant sharing impossible by keying, and this audit proves it
        stayed impossible through CoW maps, rollbacks and reclaims."""
        bs = self.block_size
        for b, rec in self.blocks.items():
            if len(rec.owners) < 2:
                continue
            tenants = {getattr(seqs[uid], "tenant", "default")
                       for uid in rec.owners if uid in seqs}
            if len(tenants) > 1:
                raise CensusInvariantError(
                    f"block {b} is shared ACROSS tenants {sorted(tenants)} — "
                    f"the per-tenant hash namespace was bypassed; one "
                    f"tenant can time another's cache", block=b,
                    uid=rec.owners[0])
            reference: Optional[List[int]] = None
            ref_uid: Optional[int] = None
            for uid in rec.owners:
                seq = seqs.get(uid)
                if seq is None:
                    raise CensusInvariantError(
                        f"block {b} is mapped by uid {uid} which the manager "
                        f"no longer tracks — its mapping was never released",
                        block=b, uid=uid)
                if b not in seq.blocks:
                    raise CensusInvariantError(
                        f"block {b} lists uid {uid} as an owner but is absent "
                        f"from that sequence's block table", block=b, uid=uid)
                i = seq.blocks.index(b)
                slice_ = [int(t) for t in seq.tokens[i * bs:(i + 1) * bs]]
                if reference is None:
                    reference, ref_uid = slice_, uid
                elif slice_ != reference:
                    raise CensusInvariantError(
                        f"shared block {b} maps DIFFERENT content for uid "
                        f"{ref_uid} and uid {uid} — one request is observing "
                        f"another's KV", block=b, uid=ref_uid, uid2=uid)


# ==========================================================================
# Prefix-sharing opportunity analysis
# ==========================================================================

def tenant_namespace(tenant: Optional[str]) -> bytes:
    """Hash-chain seed for a tenant's prefix keying.  The default tenant
    keeps the legacy empty seed (single-tenant hashes — and therefore
    sharing, affinity homing and journal replay — are byte-identical with
    QoS on or off); any other tenant seeds the chain with its id, so two
    tenants' byte-identical prompts hash to DISJOINT chains and can never
    share a block (the cross-tenant cache-timing side-channel is closed
    structurally, not by a lookup-time filter)."""
    if not tenant or tenant == "default":
        return b""
    return b"tenant:" + tenant.encode("utf-8", "surrogatepass")


def block_hashes(tokens: List[int], block_size: int,
                 namespace: bytes = b"") -> List[bytes]:
    """Chained token-block hashes over the FULL blocks of ``tokens`` — the
    exact keying a copy-on-write prefix tree will use: block ``i``'s hash
    covers its own tokens AND its ancestry (hash chaining), so two sequences
    share hash ``i`` iff their first ``(i+1) * block_size`` tokens are
    identical.  Partial trailing blocks are excluded (they can never be
    shared read-only).  ``namespace`` seeds the chain root (see
    :func:`tenant_namespace`); the default empty seed preserves the legacy
    keying."""
    out: List[bytes] = []
    parent = namespace
    for i in range(len(tokens) // block_size):
        chunk = tokens[i * block_size:(i + 1) * block_size]
        h = hashlib.blake2b(digest_size=16)
        h.update(parent)
        h.update(",".join(str(int(t)) for t in chunk).encode())
        parent = h.digest()
        out.append(parent)
    return out


class PrefixObservatory:
    """Counterfactual prefix-cache win, measured per serve pass.

    :meth:`observe` takes the prompt token histories of every live + admitted
    request in a pass and reports what a block-granular prefix cache WOULD
    have saved: for each chained block hash seen ``n`` times, ``n - 1``
    prefills were duplicates.  ``hit_rate`` is duplicate blocks over total
    full prompt blocks — exactly the cache hit-rate a prefix tree keyed on
    these hashes would report, so the ROADMAP prefix-cache item lands with
    its validation metric already in place.
    """

    def __init__(self, block_size: int):
        self.block_size = int(block_size)
        self.passes_total = 0
        self.prompt_blocks_total = 0
        self.duplicate_blocks_total = 0
        self.prefill_tokens_saved_total = 0
        self.last_report: Dict[str, Any] = self._empty_report()
        # per-uid hash cache: a live sequence's prompt is immutable for its
        # whole life (add_sequence refuses duplicate live uids), so its
        # chained block hashes are computed exactly once; :meth:`forget` —
        # wired to the census's retirement listener — invalidates on uid
        # reuse, and entries for uids absent from a pass are pruned as a
        # backstop, so long-lived servers stay bounded by the live set
        self._hash_cache: Dict[int, List[bytes]] = {}

    @staticmethod
    def _empty_report() -> Dict[str, Any]:
        return {"requests": 0, "prompt_blocks": 0, "unique_blocks": 0,
                "duplicate_blocks": 0, "prefill_tokens_saved": 0,
                "hit_rate": 0.0}

    def has(self, uid: int) -> bool:
        """True when ``uid``'s prompt hashes are cached — callers may then
        pass ``None`` as its observe() entry and skip building the token
        list entirely (the per-intake fast path)."""
        return int(uid) in self._hash_cache

    def observe(self, prompts: Dict[int, Optional[List[int]]]) -> Dict[str, Any]:
        """``prompts``: uid -> prompt token history (live requests contribute
        their prompt portion, admitted requests their full prompt), or
        ``None`` for a uid whose hashes are cached (:meth:`has`) — the
        caller then skips materializing the token list.  Returns (and caches
        as ``last_report``) this pass's counterfactual report.

        Two accountings with different lifetimes:

        - ``last_report`` is the INSTANTANEOUS view: duplicates across
          everything live right now (the gauge a dashboard watches).
        - The lifetime ``*_total`` counters charge each request ONCE, at its
          first observation: the blocks of its prompt that already existed in
          the then-live set (or in an earlier request of the same intake) are
          the prefills a cache would actually have skipped — re-observing a
          still-live request on a later pass adds nothing, so the totals are
          a realizable A/B target, not an overcount.
        """
        counts: Dict[bytes, int] = {}
        total_blocks = 0
        cache = self._hash_cache
        new_uids: List[int] = []
        per_uid: Dict[int, List[bytes]] = {}
        for uid, tokens in prompts.items():
            hashes = cache.get(uid)
            if hashes is None:
                if tokens is None:
                    continue  # caller promised a cache hit that isn't there
                hashes = block_hashes(tokens, self.block_size)
                cache[uid] = hashes
                new_uids.append(uid)
            per_uid[uid] = hashes
            for h in hashes:
                counts[h] = counts.get(h, 0) + 1
                total_blocks += 1
        for uid in list(cache):
            if uid not in prompts:
                del cache[uid]
        # lifetime accounting: walk the NEW requests in intake order, counting
        # each one's blocks already present in the prior live set or an
        # earlier new request — exactly the prefills sharing would have saved
        new_set = set(new_uids)
        seen: set = set()
        for uid, hashes in per_uid.items():
            if uid not in new_set:
                seen.update(hashes)
        new_dup = 0
        new_blocks = 0
        for uid in new_uids:
            for h in per_uid[uid]:
                new_blocks += 1
                if h in seen:
                    new_dup += 1
                else:
                    seen.add(h)
        duplicates = total_blocks - len(counts)
        self.passes_total += 1
        self.prompt_blocks_total += new_blocks
        self.duplicate_blocks_total += new_dup
        self.prefill_tokens_saved_total += new_dup * self.block_size
        self.last_report = {
            "requests": len(prompts),
            "prompt_blocks": total_blocks,
            "unique_blocks": len(counts),
            "duplicate_blocks": duplicates,
            "prefill_tokens_saved": duplicates * self.block_size,
            "hit_rate": duplicates / total_blocks if total_blocks else 0.0,
        }
        return self.last_report

    def forget(self, uid: int) -> None:
        """Drop a uid's cached hashes (its request ended): the next prompt
        under this uid is a NEW request and must be charged to the lifetime
        counters even when its tokens are identical."""
        self._hash_cache.pop(int(uid), None)

    def snapshot(self) -> Dict[str, Any]:
        return {
            "passes_total": self.passes_total,
            "prompt_blocks_total": self.prompt_blocks_total,
            "duplicate_blocks_total": self.duplicate_blocks_total,
            "prefill_tokens_saved_total": self.prefill_tokens_saved_total,
            "last_pass": dict(self.last_report),
        }


# ==========================================================================
# Capacity forecasting
# ==========================================================================

class CapacityForecaster:
    """EWMA of block alloc/free rates per SERVE STEP, yielding a
    steps-to-exhaustion gauge.

    Each :meth:`update` consumes the census's lifetime alloc/free totals (the
    deltas since the previous update are this interval's flows) and the
    current free-block count.  ``step`` is the engine's serve-step clock —
    a stepwise dispatch advances it by 1, a fused decode burst of k by k —
    so the deltas are normalized to per-step rates and
    ``steps_to_exhaustion`` means the same thing on a burst-heavy serve as
    on a stepwise one (omitting ``step`` treats each update as one step).
    ``steps_to_exhaustion`` is free blocks over the smoothed NET consumption
    rate — ``None`` (Prometheus family absent) while the pool is not
    trending toward exhaustion, so dashboards alarm on "finite and small",
    the predictable-overload signal this forecaster exists for.
    """

    def __init__(self, alpha: float = 0.2):
        self.alpha = float(alpha)
        self.alloc_rate = 0.0
        self.free_rate = 0.0
        self.updates = 0
        self._last_allocs = 0
        self._last_frees = 0
        self._last_step: Optional[int] = None
        self.free_blocks = 0

    def update(self, allocs_total: int, frees_total: int,
               free_blocks: int, step: Optional[int] = None) -> None:
        d_alloc = max(int(allocs_total) - self._last_allocs, 0)
        d_free = max(int(frees_total) - self._last_frees, 0)
        d_steps = 1
        if step is not None:
            if self._last_step is not None:
                d_steps = max(int(step) - self._last_step, 1)
            self._last_step = int(step)
        self._last_allocs = int(allocs_total)
        self._last_frees = int(frees_total)
        self.free_blocks = int(free_blocks)
        alloc_sample = d_alloc / d_steps
        free_sample = d_free / d_steps
        if self.updates == 0:
            self.alloc_rate = alloc_sample
            self.free_rate = free_sample
        else:
            a = self.alpha
            self.alloc_rate += a * (alloc_sample - self.alloc_rate)
            self.free_rate += a * (free_sample - self.free_rate)
        self.updates += 1

    @property
    def net_rate(self) -> float:
        """Smoothed net blocks consumed per serve step (negative = draining)."""
        return self.alloc_rate - self.free_rate

    def steps_to_exhaustion(self) -> Optional[float]:
        net = self.net_rate
        if net <= 1e-9:
            return None  # not trending toward exhaustion
        return self.free_blocks / net

    def snapshot(self) -> Dict[str, Any]:
        return {
            "alloc_rate_blocks_per_step": self.alloc_rate,
            "free_rate_blocks_per_step": self.free_rate,
            "net_rate_blocks_per_step": self.net_rate,
            "free_blocks": self.free_blocks,
            "steps_to_exhaustion": self.steps_to_exhaustion(),
            "updates": self.updates,
        }


# ==========================================================================
# The engine-facing facade
# ==========================================================================

class KVObservability:
    """What the engine owns: one census + one observatory + one forecaster,
    plus the pressure-event edge detector the flight recorder consumes.

    ``pressure_steps`` is the steps-to-exhaustion threshold below which the
    pool counts as under pressure; :meth:`pressure_crossing` reports only the
    CROSSINGS (entered/cleared), so a long pressure episode is two flight-
    recorder events, not one per iteration."""

    def __init__(self, block_size: int, num_blocks: int, trash_block: int, *,
                 ewma_alpha: float = 0.2, pressure_steps: float = 64.0,
                 age_buckets_per_decade: int = 6):
        self.census = BlockCensus(block_size, num_blocks, trash_block,
                                  age_buckets_per_decade=age_buckets_per_decade)
        self.prefix = PrefixObservatory(block_size)
        # retirement invalidates the prefix hash cache: a reused uid (the
        # generate() API numbers requests 0..n-1 every call) must be charged
        # to the lifetime counterfactual as the new request it is
        self.census.terminal_listeners.append(self.prefix.forget)
        self.forecaster = CapacityForecaster(ewma_alpha)
        self.pressure_steps = float(pressure_steps)
        self.under_pressure = False
        self.pressure_events_total = 0
        self.invariant_checks_total = 0

    def refresh(self, seqs: Dict[int, Any], step: int,
                free_blocks: int) -> None:
        """Wave-boundary refresh: census resident/touch update + forecaster
        rate sample, all from host ints the serve loop already holds.
        ``step`` is the SERVE-STEP clock (a fused burst of k advances it by
        k), so ages and rates mean the same thing on every decode path."""
        self.census.refresh(seqs, step)
        self.forecaster.update(self.census.blocks_allocated_total,
                               self.census.blocks_freed_total, free_blocks,
                               step=step)

    def pressure_crossing(self) -> Optional[Tuple[str, float]]:
        """('entered'|'cleared', steps_to_exhaustion) when the pressure state
        just flipped; None otherwise."""
        ste = self.forecaster.steps_to_exhaustion()
        pressured = ste is not None and ste < self.pressure_steps
        if pressured == self.under_pressure:
            return None
        self.under_pressure = pressured
        if pressured:
            self.pressure_events_total += 1
            return ("entered", float(ste))
        return ("cleared", float("inf") if ste is None else float(ste))

    def check_invariant(self, allocator, seqs: Optional[Dict[int, Any]] = None) -> None:
        self.invariant_checks_total += 1
        self.census.check_against(allocator, seqs)

    def snapshot(self, free_blocks: int) -> Dict[str, Any]:
        """The ``health()["kv"]`` payload (JSON-safe: no inf/nan)."""
        return {
            "enabled": True,
            "census": self.census.rollup(free_blocks),
            "prefix": self.prefix.snapshot(),
            "forecast": self.forecaster.snapshot(),
            "under_pressure": self.under_pressure,
            "pressure_events_total": self.pressure_events_total,
            "invariant_checks_total": self.invariant_checks_total,
        }
