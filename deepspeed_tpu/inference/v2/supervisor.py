"""Supervised serving restart + crash recovery with decode continuation.

``ServingSupervisor`` closes the gap PR 4 left open: all of the v2 engine's
resilience is in-process, so a serving-process crash (OOM, preempted VM,
wedged device) silently destroyed every queued and in-flight request.  The
supervisor composes the pieces the stack already owns — PR 2's fsync+CRC
write protocol (the request journal, inference/v2/journal.py), PR 6's flight
recorder, and PR 7's heartbeat liveness + supervised-restart machinery
(runtime/heartbeat.py) — into the serving analog of the elastic training
agent:

- **Liveness.**  The engine stamps a phase-``serving`` heartbeat each serve
  iteration (zero device syncs — the writer only touches host ints).  In
  subprocess mode (:meth:`ServingSupervisor.supervise_command`) a stale
  stamp (``hang_timeout_s``, after ``startup_grace_s``) or a dead process
  both count as ONE failure: kill, reap, restart.  In-process mode
  (:meth:`ServingSupervisor.serve`) an engine exception is the failure
  signal; a wedged-but-live loop is already bounded by the engine's own
  stall watchdog (PR 4), so in-process hang detection is intentionally not
  duplicated here.
- **Recovery.**  Each restart replays the journal (torn tail truncated,
  PR-2 style), adopts already-terminal results, finalizes requests whose
  journaled prefix already satisfies their budget/eos/TTL, and re-admits the
  rest *with their emitted token prefix* (``engine.serve_recovered``) so
  recovered decodes continue from where they died instead of restarting from
  scratch.  Recovered requests keep their ORIGINAL TTL clock: remaining
  budget is computed against the journal's wall-clock admit stamp.
- **Budget.**  ``max_restarts`` within ``restart_window_s``; past it the
  supervisor degrades to drain-only mode — new (never-journaled) admissions
  are shed with a structured retryable reason, recoverable journal work gets
  ONE final attempt, and whatever still isn't terminal is finalized as
  ``failed`` directly in the journal.  Every request reaches exactly one
  terminal :class:`RequestResult`; the supervisor never hangs.

Clock discipline: monotonic reads flow through the injectable ``clock`` seam
and wall-clock reads through ``wall_clock`` (both bound to the ``time``
functions as DEFAULTS — the dslint ``raw-clock-in-serving`` contract), so
fault tests drive fake time deterministically.
"""

import dataclasses
import json
import os
import shutil
import subprocess
import tempfile
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ...monitor.tracing import FlightRecorder
from ...runtime.config import OpsServerConfig, ServingFaultToleranceConfig
from ...runtime.heartbeat import (HEARTBEAT_DIR_ENV, HEARTBEAT_INTERVAL_ENV,
                                  OPS_DIR_ENV, SERVING_DRAIN_ENV,
                                  SERVING_FSYNC_ENV, SERVING_GENERATION_ENV,
                                  SERVING_JOURNAL_ENV, heartbeat_age,
                                  read_heartbeats)
from ...utils.logging import logger
from .admission import (DEADLINE_EXPIRED, FAILED, OK, SHED, RecoveredRequest,
                        RequestResult)
from .journal import JournalEntry, JournalState, RequestJournal, replay_journal

DRAIN_SHED_REASON = ("drain mode: serving restart budget exhausted — new "
                     "admissions are shed; resubmit once the service recovers")
FINALIZE_REASON = ("restart budget exhausted and the drain-only recovery "
                   "attempt also failed — request finalized by the supervisor")


@dataclasses.dataclass
class ServeSpec:
    """One request as the CALLER describes it (the workload side of
    recovery planning; the journal side is :class:`JournalEntry`)."""
    uid: int
    prompt: List[int]
    priority: int = 0
    ttl_s: Optional[float] = None
    # QoS identity (ISSUE 19): the caller's tenant + service class, carried
    # through admission, the journal and recovery unchanged
    tenant: str = "default"
    service_class: str = "interactive"


@dataclasses.dataclass
class RecoveryPlan:
    """What a journal replay means for one serve attempt."""
    adopted: Dict[int, RequestResult] = dataclasses.field(default_factory=dict)
    # terminals to append for requests PLANNING resolved (prefix already
    # complete, TTL spent in a dead generation, drain-mode shed): the journal
    # must reach terminal-everywhere without another serve touching them
    finalize: List[Tuple[int, str, Dict[str, Any]]] = dataclasses.field(default_factory=list)
    entries: List[RecoveredRequest] = dataclasses.field(default_factory=list)
    recovered: int = 0  # entries carrying a non-empty emitted prefix


def result_from_entry(entry: JournalEntry) -> RequestResult:
    """Rebuild the ``RequestResult`` a journaled terminal mirrors."""
    term = entry.terminal or {}
    status = term.get("status", FAILED)
    tokens = entry.prompt + entry.emitted if status != SHED else []
    return RequestResult(uid=entry.uid, status=status, tokens=tokens,
                         finish_reason=term.get("finish_reason"),
                         reason=term.get("reason"),
                         retryable=bool(term.get("retryable", False)),
                         shed_code=term.get("code"))


def plan_recovery(state: JournalState, specs: Sequence[ServeSpec], *,
                  max_new_tokens: int, eos_token_id: Optional[int] = None,
                  token_cap: Optional[int] = None, drain: bool = False,
                  now_wall: float = 0.0) -> RecoveryPlan:
    """Partition a workload against the replayed journal.

    Per spec uid: adopt a journaled terminal as-is; finalize incomplete
    entries whose prefix already satisfies the budget / eos / per-sequence
    cap (finish as ``ok`` without re-serving) or whose ORIGINAL TTL has run
    out (``deadline_expired`` — the deadline clock never resets across
    restarts); re-admit the rest with their emitted prefix and remaining
    TTL; and in drain mode shed anything the journal has never seen.
    """
    plan = RecoveryPlan()
    for spec in specs:
        uid = int(spec.uid)
        entry = state.entries.get(uid)
        if entry is None:
            if drain:
                plan.adopted[uid] = RequestResult(uid=uid, status=SHED,
                                                  reason=DRAIN_SHED_REASON,
                                                  retryable=True)
                plan.finalize.append((uid, SHED,
                                      {"reason": DRAIN_SHED_REASON,
                                       "retryable": True}))
            else:
                # an explicit caller TTL pins (serve_recovered only forwards
                # pinned TTLs); ttl_s=None stays unpinned so the engine's
                # default_ttl_s applies exactly like generate()
                plan.entries.append(RecoveredRequest(
                    uid=uid, prompt=list(spec.prompt), prefix=[],
                    priority=spec.priority, ttl_s=spec.ttl_s,
                    pin_ttl=spec.ttl_s is not None,
                    tenant=spec.tenant, service_class=spec.service_class))
            continue
        if entry.done:
            plan.adopted[uid] = result_from_entry(entry)
            continue
        prompt, emitted = entry.prompt, entry.emitted
        # the CALLER's budget/eos are authoritative — they are what
        # serve_recovered will enforce on the re-admitted sequence, so the
        # plan must judge completion by the same contract (judging by the
        # journaled values while the engine enforces the caller's would
        # silently truncate or over-run recovered decodes whenever the two
        # disagree; the journaled values remain for forensics)
        budget = max_new_tokens
        eos = eos_token_id
        remaining = entry.ttl_remaining(now_wall)
        if remaining is not None and remaining <= 0:
            reason = "original TTL exhausted across restart"
            plan.adopted[uid] = RequestResult(uid=uid, status=DEADLINE_EXPIRED,
                                              tokens=prompt + emitted,
                                              reason=reason, retryable=True)
            plan.finalize.append((uid, DEADLINE_EXPIRED,
                                  {"reason": reason, "retryable": True,
                                   "n_tokens": len(emitted)}))
            continue
        finish = None
        if emitted and eos is not None and emitted[-1] == eos:
            finish = "eos"
        elif len(emitted) >= budget:
            finish = "max_new_tokens"
        elif emitted and token_cap is not None \
                and len(prompt) + len(emitted) + 1 > token_cap:
            finish = "length_capped"
        if finish is not None:
            # the journaled prefix IS the complete answer: only the terminal
            # record died with the old process — finalize without re-serving
            plan.adopted[uid] = RequestResult(uid=uid, status=OK,
                                              tokens=prompt + emitted,
                                              finish_reason=finish)
            plan.finalize.append((uid, OK, {"finish_reason": finish,
                                            "n_tokens": len(emitted)}))
            continue
        # identity comes from the JOURNAL, not the spec: the journaled
        # tenant/class is what admission actually accepted — recovery must
        # not let a resubmitted spec launder a best-effort request into
        # interactive (or reassign its tenant) across a crash
        plan.entries.append(RecoveredRequest(
            uid=uid, prompt=list(prompt), prefix=list(emitted),
            priority=entry.priority, ttl_s=remaining, pin_ttl=True,
            tenant=entry.tenant, service_class=entry.service_class))
        if emitted:
            plan.recovered += 1
    return plan


def recover_and_serve(engine, specs: Sequence[ServeSpec], *,
                      max_new_tokens: int, eos_token_id: Optional[int] = None,
                      greedy: bool = True, drain: Optional[bool] = None,
                      wall_clock: Callable[[], float] = time.time
                      ) -> Dict[int, RequestResult]:
    """One generation's worth of work on a journal-armed engine: replay,
    plan, journal the planning's terminals, serve the rest.  The seam both
    the in-process supervisor and supervised worker processes call — a
    worker's whole body is ``recover_and_serve(engine, specs, ...)``.

    ``drain=None`` reads the supervisor-exported ``DSTPU_SERVING_DRAIN``
    env, so drain-only degradation needs no worker-side plumbing."""
    journal = engine.journal
    if journal is None:
        raise ValueError("recover_and_serve needs a journal-armed engine "
                         "(serving_fault_tolerance.journal_path, the "
                         "DSTPU_SERVING_JOURNAL env, or engine journal=)")
    if drain is None:
        drain = bool(os.environ.get(SERVING_DRAIN_ENV))
    state = replay_journal(journal.path, truncate=False)
    engine.tracer.event("replay", records=state.records,
                        requests=len(state.entries),
                        incomplete=len(state.incomplete()),
                        **({"truncated_tail": state.truncated_tail}
                           if state.truncated_tail else {}))
    token_cap = engine.manager.max_blocks_per_seq * engine.manager.block_size
    plan = plan_recovery(state, specs, max_new_tokens=max_new_tokens,
                         eos_token_id=eos_token_id, token_cap=token_cap,
                         drain=drain, now_wall=wall_clock())
    for uid, status, kw in plan.finalize:
        journal.record_terminal(uid, status, **kw)
        engine.tracer.event("finalized", uid=uid, status=status)
    results = dict(plan.adopted)
    if plan.entries:
        results.update(engine.serve_recovered(plan.entries,
                                              max_new_tokens=max_new_tokens,
                                              eos_token_id=eos_token_id,
                                              greedy=greedy, strict=False))
    return results


class ServingSupervisor:
    """Runs the v2 serving engine under liveness supervision with a
    crash-durable request journal (module docstring for the full story).

    ``engine_factory`` (in-process mode) builds a FRESH engine per
    generation — restart semantics are a clean device state; the supervisor
    attaches the journal and recovery counters.  Subprocess mode
    (:meth:`supervise_command`) needs no factory: the worker process builds
    its own engine from the supervisor-exported env.

    One journal per WORKLOAD: the journal is the workload's durable state,
    keyed by uid.  Serving a NEW workload against a journal that already
    holds terminals for the same uids adopts those results instead of
    serving (that is the recovery contract working as designed) — give a
    fresh workload a fresh ``journal_path``.
    """

    def __init__(self, engine_factory: Optional[Callable[[], Any]] = None, *,
                 journal_path: Optional[str] = None, config=None,
                 telemetry=None, clock: Callable[[], float] = time.monotonic,
                 wall_clock: Callable[[], float] = time.time,
                 sleep: Callable[[float], None] = time.sleep,
                 ops_server=None):
        if config is None:
            config = ServingFaultToleranceConfig(enabled=False)
        elif isinstance(config, dict):
            config = ServingFaultToleranceConfig(**{"enabled": False, **config})
        self.cfg = config
        self.engine_factory = engine_factory
        self.journal_path = journal_path or self.cfg.journal_path
        if not self.journal_path:
            raise ValueError("ServingSupervisor needs journal_path (argument "
                             "or serving_fault_tolerance.journal_path)")
        self.telemetry = telemetry
        self._clock = clock
        self._wall = wall_clock
        self._sleep = sleep
        self.restarts_total = 0
        self.recovered_requests_total = 0
        self.degraded = False
        self.generations = 0
        self._failure_times: deque = deque()
        # the supervisor's own postmortem ring, mirroring the elastic agent's
        self.recorder = FlightRecorder(256)
        # fleet-level ops endpoint (ISSUE 11): workers publish per-generation
        # registry snapshots (env-armed via DSTPU_OPS_DIR in subprocess mode;
        # absorbed directly from the engine in-process), and the aggregator
        # merges them — histograms via StreamingHistogram.merge, counters
        # carried across generations so a restart never makes a fleet
        # counter jump backwards.  `ops_server` is an OpsServerConfig/dict;
        # None leaves the plane off.
        self.ops_cfg: Optional[OpsServerConfig] = None
        self.ops = None
        self._ops_cache = None
        self._ops_agg = None
        self._ops_dir: Optional[str] = None
        self._ops_own_dir = False
        if ops_server is not None:
            cfg = ops_server if isinstance(ops_server, OpsServerConfig) \
                else OpsServerConfig(**dict(ops_server))
            if cfg.enabled or cfg.textfile_dir:
                from ...monitor.metrics import FleetAggregator
                from ...monitor.ops_server import OpsCache, try_start_ops_server
                self.ops_cfg = cfg
                self._ops_agg = FleetAggregator()
                self._ops_cache = OpsCache()
                self._ops_dir = cfg.textfile_dir
                if self._ops_dir is None:
                    self._ops_dir = tempfile.mkdtemp(prefix="dstpu_serving_ops_")
                    self._ops_own_dir = True
                if cfg.enabled:
                    self.ops = try_start_ops_server(self._ops_cache,
                                                    host=cfg.host, port=cfg.port,
                                                    owner="serving supervisor")
                self._ops_last_refresh = -float("inf")
                self._refresh_ops(force=True)

    # ----------------------------------------------------------- ops endpoint
    def ops_health(self) -> Dict[str, Any]:
        """The supervisor's /healthz: restart budget, degradation, and which
        worker ranks have published metrics — the router's admit signal."""
        return {
            "restarts_total": self.restarts_total,
            "generations": self.generations,
            "degraded": self.degraded,
            "recovered_requests_total": self.recovered_requests_total,
            "failures_in_window": len(self._failure_times),
            "max_restarts": self.cfg.max_restarts,
            "ranks": self._ops_agg.ranks() if self._ops_agg is not None else [],
        }

    def _refresh_ops(self, force: bool = False) -> None:
        """Absorb fresh worker snapshots and re-render the merged fleet
        registry + supervisor health into the scrape cache (owning-thread
        only; host values only).  The whole pass — dir scan, snapshot
        parses, render — sits behind one throttle of ``refresh_interval_s``:
        the watch loop polls every ``poll_interval_s`` (20x/s by default)
        and must not pay it on every tick."""
        if self._ops_agg is None:
            return
        now = self._clock()
        if not force and now - self._ops_last_refresh < self.ops_cfg.refresh_interval_s:
            return
        self._ops_last_refresh = now
        self._ops_absorb_dir()
        from ...monitor.exposition import render
        from ...monitor.metrics import populate_from_supervisor
        merged = self._ops_agg.registry(namespace=self.ops_cfg.namespace)
        populate_from_supervisor(merged, self)
        self._ops_cache.update(
            metrics_text=render(merged, collect=False),
            healthz=json.dumps(self.ops_health()),
            statez=json.dumps({"events": self.recorder.tail(),
                               "ranks": self._ops_agg.ranks()}))

    def _ops_absorb_dir(self) -> None:
        """Fold every readable worker snapshot under the ops dir into the
        aggregator (subprocess mode; generation bumps roll counter carry)."""
        if self._ops_agg is None or self._ops_dir is None:
            return
        from ...monitor.ops_server import read_rank_snapshots
        from ...utils.logging import warning_once
        for rank, snap in read_rank_snapshots(self._ops_dir).items():
            try:
                self._ops_agg.absorb(rank, snap)
            except (ValueError, KeyError, TypeError) as exc:
                # a malformed-but-parseable snapshot degrades that rank's
                # freshness; it must never unwind the watch loop that every
                # worker's kill-and-reap lifecycle hangs off
                warning_once(f"ops: rank {rank} snapshot rejected ({exc!r}); "
                             f"keeping its last merged state")

    def _ops_absorb_engine(self, engine, generation: int) -> None:
        """Fold an in-process engine's final state into the aggregator (the
        in-process analog of a worker's published snapshot)."""
        if self._ops_agg is None or engine is None:
            return
        from ...monitor.metrics import MetricsRegistry, populate_from_engine
        reg = MetricsRegistry(namespace=self.ops_cfg.namespace,
                              generation=generation)
        populate_from_engine(reg, engine)
        self._ops_agg.absorb(0, reg.snapshot())

    def close_ops(self) -> None:
        """Shut the ops listener down (tests / clean teardown)."""
        if self.ops is not None:
            self.ops.close()

    # ------------------------------------------------------------- accounting
    def _event(self, event: str, **fields) -> None:
        self.recorder.record(event, t=self._wall(), **fields)
        if self.telemetry is not None:
            self.telemetry.record_resilience(f"serving_{event}", **fields)

    def _note_failure(self, detail: str) -> None:
        now = self._clock()
        self._failure_times.append(now)
        window = self.cfg.restart_window_s
        while self._failure_times and now - self._failure_times[0] > window:
            self._failure_times.popleft()
        self._event("worker_failed", detail=detail,
                    failures_in_window=len(self._failure_times))
        logger.warning(f"serving supervisor: worker failed ({detail}); "
                       f"{len(self._failure_times)} failure(s) in the last "
                       f"{window:.0f}s")

    def _budget_exhausted(self) -> bool:
        return len(self._failure_times) > self.cfg.max_restarts

    # --------------------------------------------------------- in-process mode
    def _build_engine(self, generation: int):
        engine = self.engine_factory()
        if engine.journal is not None \
                and os.path.abspath(engine.journal.path) != os.path.abspath(self.journal_path):
            # fail fast: recovery would replay one file while finalization
            # replays the other — every unresolved request would be
            # finalized FAILED while its real prefixes sit unread
            raise ValueError(
                f"engine_factory armed its own journal at "
                f"{engine.journal.path!r} but this supervisor owns "
                f"{self.journal_path!r} — point serving_fault_tolerance."
                f"journal_path at the supervisor's path (or leave the "
                f"engine journal-less and let the supervisor attach one)")
        if engine.journal is None:
            engine.journal = RequestJournal(self.journal_path,
                                            fsync_every=self.cfg.fsync_every,
                                            seed=engine.config.seed,
                                            wall_clock=self._wall)
            engine.journal.open_generation(generation)
        engine.ft_stats["restarts_total"] = self.restarts_total
        engine.ft_stats["degraded"] = self.degraded
        if generation > 0:
            engine.tracer.event("restart", generation=generation)
            self._event("restart", generation=generation)
        return engine

    def serve(self, prompts: Sequence[Sequence[int]], *, uids=None,
              max_new_tokens: int = 32, eos_token_id: Optional[int] = None,
              greedy: bool = True, priorities: Optional[Sequence[int]] = None,
              ttl_s: Optional[float] = None) -> List[RequestResult]:
        """Serve a batch to completion across engine crashes.

        Same surface as ``generate(strict=False)`` plus durability: any
        exception out of the engine counts one restart (fresh engine, journal
        replay, prefix re-admission); past the budget the final attempt runs
        drain-only, and if that fails too every unresolved request is
        finalized as ``failed``.  Always returns one terminal
        :class:`RequestResult` per request, in input order."""
        if self.engine_factory is None:
            raise ValueError("in-process serve() needs an engine_factory")
        uid_list = list(range(len(prompts))) if uids is None else [int(u) for u in uids]
        specs = [ServeSpec(uid=uid, prompt=[int(t) for t in prompt],
                           priority=int(priorities[i]) if priorities is not None else 0,
                           ttl_s=ttl_s)
                 for i, (uid, prompt) in enumerate(zip(uid_list, prompts))]
        results: Dict[int, RequestResult] = {}
        drain = False
        final_attempt = False
        generation = 0
        while any(s.uid not in results for s in specs):
            engine = None
            todo = [s for s in specs if s.uid not in results]
            try:
                engine = self._build_engine(generation)
                got = recover_and_serve(engine, todo,
                                        max_new_tokens=max_new_tokens,
                                        eos_token_id=eos_token_id,
                                        greedy=greedy, drain=drain,
                                        wall_clock=self._wall)
                self.recovered_requests_total += \
                    engine.ft_stats["recovered_requests_total"]
                results.update({u: r for u, r in got.items()
                                if u in {s.uid for s in todo}})
                self._event("run_complete", generation=generation,
                            served=len(got))
                break
            except Exception as exc:  # the crash seam: ANY engine failure
                self.restarts_total += 1
                self._note_failure(f"{type(exc).__name__}: {exc}")
                if final_attempt:
                    self._finalize_failed(results, todo)
                    break
                if self._budget_exhausted():
                    self.degraded = True
                    drain = True
                    final_attempt = True
                    self._event("degraded", reason="restart budget exhausted",
                                restarts=self.restarts_total)
                    logger.warning("serving supervisor: restart budget "
                                   "exhausted — degrading to drain-only mode")
            finally:
                self.generations = generation + 1
                if engine is not None and engine.journal is not None:
                    engine.journal.close()
                # ops aggregation (ISSUE 11): this generation's final engine
                # state joins the fleet view; a crash resets the NEXT
                # generation's counters to zero, which the aggregator's
                # generation carry absorbs — the merged endpoint stays
                # monotone across the restart
                self._ops_absorb_engine(engine, generation)
                self._refresh_ops(force=True)
            generation += 1
        return [results[u] for u in uid_list]

    def serve_specs(self, specs: Sequence[ServeSpec], *,
                    max_new_tokens: int = 32, eos_token_id: Optional[int] = None,
                    greedy: bool = True,
                    on_generation: Optional[Callable[[Any, int], None]] = None
                    ) -> Tuple[Dict[int, RequestResult], bool]:
        """In-process crash-restart serve that STOPS at budget exhaustion
        instead of degrading to drain-only: returns ``(results, exhausted)``.

        This is the fleet router's failover seam (ISSUE 17).  ``serve()``
        owns the single-replica endgame — drain-only final attempt, then
        finalize-as-failed — because a lone engine has nowhere else to send
        work.  A router DOES: on ``exhausted=True`` the journal still holds
        every unresolved request's emitted prefix and original wall-clock
        admit stamp, ready to migrate to a healthy replica's journal for
        byte-identical ``serve_recovered`` continuation.  ``on_generation``
        (called with each generation's engine, crashed or clean, before its
        journal closes) is the router's metrics-absorption hook — per-
        generation registry snapshots keep the fleet endpoint's counter
        carry exact across restarts."""
        if self.engine_factory is None:
            raise ValueError("serve_specs needs an engine_factory")
        results: Dict[int, RequestResult] = {}
        exhausted = False
        generation = self.generations  # resume numbering across serve calls
        while any(s.uid not in results for s in specs):
            engine = None
            todo = [s for s in specs if s.uid not in results]
            try:
                engine = self._build_engine(generation)
                got = recover_and_serve(engine, todo,
                                        max_new_tokens=max_new_tokens,
                                        eos_token_id=eos_token_id,
                                        greedy=greedy, drain=False,
                                        wall_clock=self._wall)
                self.recovered_requests_total += \
                    engine.ft_stats["recovered_requests_total"]
                results.update({u: r for u, r in got.items()
                                if u in {s.uid for s in todo}})
                self._event("run_complete", generation=generation,
                            served=len(got))
                break
            except Exception as exc:  # the crash seam: ANY engine failure
                self.restarts_total += 1
                self._note_failure(f"{type(exc).__name__}: {exc}")
                if self._budget_exhausted():
                    self.degraded = True
                    exhausted = True
                    self._event("budget_exhausted",
                                restarts=self.restarts_total,
                                unresolved=len([s for s in specs
                                                if s.uid not in results]))
                    logger.warning("serving supervisor: restart budget "
                                   "exhausted — handing journaled work back "
                                   "for migration")
            finally:
                self.generations = generation + 1
                if engine is not None and on_generation is not None:
                    # metrics hook BEFORE the journal closes: the router
                    # absorbs this generation's final engine state (health +
                    # registry) under this generation's stamp
                    on_generation(engine, generation)
                if engine is not None and engine.journal is not None:
                    engine.journal.close()
                self._ops_absorb_engine(engine, generation)
                self._refresh_ops(force=True)
            if exhausted:
                break
            generation += 1
        return results, exhausted

    def _finalize_failed(self, results: Dict[int, RequestResult],
                         todo: Sequence[ServeSpec]) -> None:
        """Drain failed too: every unresolved request becomes a structured
        ``failed`` result, durably terminal in the journal.  Never a hang."""
        journal = RequestJournal(self.journal_path, fsync_every=1,
                                 wall_clock=self._wall)
        state = replay_journal(self.journal_path, truncate=True)
        for spec in todo:
            if spec.uid in results:
                continue
            entry = state.entries.get(spec.uid)
            if entry is not None and entry.done:
                results[spec.uid] = result_from_entry(entry)
                continue
            tokens = (entry.prompt + entry.emitted) if entry is not None else []
            results[spec.uid] = RequestResult(uid=spec.uid, status=FAILED,
                                              tokens=tokens, retryable=True,
                                              reason=FINALIZE_REASON)
            journal.record_terminal(spec.uid, FAILED, reason=FINALIZE_REASON,
                                    retryable=True,
                                    n_tokens=len(entry.emitted) if entry else 0)
        journal.close()
        self._event("finalized", requests=len(todo))

    # --------------------------------------------------------- subprocess mode
    def supervise_command(self, argv: Sequence[str], *,
                          env: Optional[Dict[str, str]] = None,
                          cwd: Optional[str] = None,
                          heartbeat_base: Optional[str] = None) -> Dict[str, Any]:
        """Spawn + supervise a serving worker process (the elastic-agent
        pattern applied to serving): per-generation heartbeat dirs, exit-code
        AND heartbeat-staleness failure detection, kill-and-reap on every
        path (zero orphans), restart budget with drain-only degradation, and
        journal finalization when even the drain generation fails.

        The worker contract is environment-only: ``DSTPU_SERVING_JOURNAL``
        (arm the engine's journal), ``DSTPU_HEARTBEAT_DIR`` +
        ``DSTPU_HEARTBEAT_INTERVAL_S`` (arm serve-iteration stamps),
        ``DSTPU_SERVING_GENERATION``, and ``DSTPU_SERVING_DRAIN`` once
        degraded.  Exit 0 = all work terminal; any other exit or a stale
        heartbeat = one failure.

        Returns a report: generations, restarts, degraded, the final
        :class:`JournalState`, and per-uid ``results`` rebuilt from journaled
        terminals."""
        cfg = self.cfg
        hb_base = heartbeat_base or cfg.heartbeat_dir
        own_hb_base = hb_base is None
        if own_hb_base:
            hb_base = tempfile.mkdtemp(prefix="dstpu_serving_hb_")
        drain = False
        final_attempt = False
        clean_exit = False
        generation = 0
        while True:
            hb_dir = os.path.join(hb_base, f"gen{generation}")
            worker_env = dict(os.environ)
            worker_env.update(env or {})
            worker_env[SERVING_JOURNAL_ENV] = self.journal_path
            worker_env[SERVING_FSYNC_ENV] = str(cfg.fsync_every)
            worker_env[HEARTBEAT_DIR_ENV] = hb_dir
            worker_env[HEARTBEAT_INTERVAL_ENV] = str(cfg.heartbeat_interval_s)
            worker_env[SERVING_GENERATION_ENV] = str(generation)
            if self._ops_dir is not None:
                # workers publish per-rank registry snapshots the aggregator
                # merges into the fleet endpoint (generation-stamped, so the
                # counter carry engages across restarts)
                worker_env[OPS_DIR_ENV] = self._ops_dir
            else:
                # scrub an inherited dir: a foreign supervisor's aggregator
                # must not absorb THIS worker's snapshots as one of its ranks
                worker_env.pop(OPS_DIR_ENV, None)
            if drain:
                worker_env[SERVING_DRAIN_ENV] = "1"
            else:
                worker_env.pop(SERVING_DRAIN_ENV, None)
            self._event("generation_spawned", generation=generation,
                        drain=drain)
            proc = subprocess.Popen(list(argv), env=worker_env, cwd=cwd)
            failure = self._watch(proc, hb_dir)
            self.generations = generation + 1
            if failure is None:
                self._event("run_complete", generation=generation)
                clean_exit = True
                break
            self.restarts_total += 1
            self._note_failure(failure)
            if final_attempt:
                n = self._finalize_journal()
                self._event("finalized", requests=n)
                break
            if self._budget_exhausted():
                self.degraded = True
                drain = True
                final_attempt = True
                self._event("degraded", reason="restart budget exhausted",
                            restarts=self.restarts_total)
            generation += 1
        # final aggregation pass BEFORE any cleanup, and AFTER the recovery
        # accounting below lands — the endpoint's restarts/recovered counters
        # must describe the finished run, not the pre-run state
        state = replay_journal(self.journal_path, truncate=True)
        self.recovered_requests_total = sum(
            1 for e in state.entries.values() if e.admits > 1)
        self._refresh_ops(force=True)
        if own_hb_base and clean_exit:
            # launcher convention (run_elastic): sweep OUR tempdir stamps on
            # a clean run, keep them for postmortem on any failure path;
            # caller-provided dirs are never touched
            shutil.rmtree(hb_base, ignore_errors=True)
        if self._ops_own_dir and clean_exit:
            shutil.rmtree(self._ops_dir, ignore_errors=True)
        return {"generations": self.generations,
                "restarts": self.restarts_total,
                "degraded": self.degraded,
                "state": state,
                "results": {uid: result_from_entry(e)
                            for uid, e in state.entries.items() if e.done}}

    def _watch(self, proc, hb_dir: str) -> Optional[str]:
        """Poll one worker generation to its end.  Returns None on a clean
        exit, else the failure description.  The process is ALWAYS reaped
        before returning — a hung worker is killed, never abandoned."""
        cfg = self.cfg
        start = self._clock()
        failure = None
        while True:
            rc = proc.poll()
            if rc is not None:
                if rc == 0:
                    return None
                failure = f"worker exited rc={rc}"
                break
            # fold any fresh worker metrics into the fleet endpoint (the
            # dir scan + render ride _refresh_ops' throttle, not the poll
            # rate) — scrapes mid-generation see live cached numbers
            self._refresh_ops()
            record = read_heartbeats(hb_dir).get(0)
            if record is None:
                if self._clock() - start > cfg.startup_grace_s:
                    failure = (f"no heartbeat within startup_grace_s="
                               f"{cfg.startup_grace_s:.0f}s — worker wedged "
                               f"before its first serve iteration")
                    break
            else:
                age = heartbeat_age(record, self._wall())
                if age > cfg.hang_timeout_s:
                    failure = (f"heartbeat stale for {age:.1f}s "
                               f"(> hang_timeout_s={cfg.hang_timeout_s:.0f}s) "
                               f"at step {record.get('step', '?')} — serving "
                               f"loop hung")
                    self._event("hang_detected", age_s=round(age, 2),
                                step=record.get("step"))
                    break
            self._sleep(cfg.poll_interval_s)
        # reap on EVERY failure path: SIGKILL (a hung worker ignores less),
        # then wait() so no zombie/orphan survives the supervisor
        try:
            proc.kill()
        except OSError as exc:
            logger.warning(f"serving supervisor: kill failed ({exc}); "
                           f"worker may already be gone")
        proc.wait()
        return failure

    def _finalize_journal(self) -> int:
        """Terminal-ize every journal entry the drain generation left
        incomplete, so replay-side consumers see a fully-resolved log."""
        state = replay_journal(self.journal_path, truncate=True)
        incomplete = state.incomplete()
        if not incomplete:
            return 0
        journal = RequestJournal(self.journal_path, fsync_every=1,
                                 wall_clock=self._wall)
        for entry in incomplete:
            journal.record_terminal(entry.uid, FAILED, reason=FINALIZE_REASON,
                                    retryable=True,
                                    n_tokens=len(entry.emitted))
        journal.close()
        return len(incomplete)
