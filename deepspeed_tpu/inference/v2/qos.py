"""Multi-tenant QoS policy for the v2 serving plane (ISSUE 19).

The admission/scheduling stack has every isolation *mechanism* — bounded
priority queue, TTL deadlines, structured retryable sheds, KV-pressure
preemption, per-request spans — but treats all traffic as one anonymous
tenant.  This module is the *policy* layer on those mechanisms:

- :class:`QosPolicy` — per-tenant front-door quotas.  A token bucket
  rate-limits each tenant's admitted token volume and a resident-block cap
  bounds its KV footprint; both produce a structured, retryable
  ``quota_exceeded`` :class:`~.admission.ShedReason` whose ``retry_after_s``
  is the EXACT bucket refill time (rate sheds) or a pressure-scaled hint
  (KV sheds), riding the FleetRouter's existing backoff path.
- :class:`DeficitRoundRobin` — weighted-fair dequeue across the three
  service classes (``interactive`` / ``batch`` / ``best_effort``) on TOKEN
  cost, the classic DRR discipline: each round grants a class
  ``quantum * weight`` deficit, a class serves while its head ticket's
  token cost fits its deficit, and an emptied class forfeits its deficit.
  Pure arrival-sequence arithmetic — zero clock reads — so dequeue order
  is FakeClock-deterministic and rerun-identical, and no class can starve
  (every round strictly grows every backlogged class's deficit).
- victim steering for KV-pressure preemption: over-quota tenants first,
  then lower classes, then the PR-4 newest-prefill heuristic as tie-break.

Everything here is host-side policy; nothing touches jax.  With
``serving_qos.enabled=false`` the engine never constructs a policy and all
behavior is byte-identical to the policy-free stack.
"""

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .admission import ShedReason

# ------------------------------------------------------------ service classes
INTERACTIVE = "interactive"
BATCH = "batch"
BEST_EFFORT = "best_effort"
SERVICE_CLASSES = (INTERACTIVE, BATCH, BEST_EFFORT)

# preemption preference: HIGHER rank = preferred victim (a best-effort
# prefill dies before a batch one, batch before interactive)
CLASS_RANK = {INTERACTIVE: 0, BATCH: 1, BEST_EFFORT: 2}

DEFAULT_TENANT = "default"

QUOTA_EXCEEDED = "quota_exceeded"


def normalize_tenant(tenant: Optional[str]) -> str:
    return DEFAULT_TENANT if not tenant else str(tenant)


@dataclasses.dataclass(frozen=True)
class TenantQuota:
    """Effective quota for one tenant (section defaults + per-tenant
    overrides, resolved once per lookup).  Zeros disable a dimension."""
    tokens_per_s: float = 0.0
    token_burst: float = 0.0
    max_kv_blocks: int = 0


class TokenBucket:
    """Deterministic token bucket on an injected clock.

    ``try_take(cost, now)`` refills by elapsed time, then either charges
    ``cost`` (returning ``(True, 0.0)``) or reports the EXACT time until
    the bucket holds ``cost`` tokens (``(False, retry_after_s)``) — the
    quota-derived backoff hint the shed carries."""

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = max(float(burst), 1.0)
        self.level = self.burst  # a fresh tenant starts with full burst
        self.last = None  # type: Optional[float]

    def _refill(self, now: float) -> None:
        if self.last is None:
            self.last = now
            return
        if now > self.last:
            self.level = min(self.burst, self.level + (now - self.last) * self.rate)
        self.last = now

    def try_take(self, cost: float, now: float) -> Tuple[bool, float]:
        self._refill(now)
        if cost <= self.level:
            self.level -= cost
            return True, 0.0
        deficit = cost - self.level
        # a cost above the burst capacity can never fit: report the time to
        # a FULL bucket (the best the tenant will ever have) — still finite
        if cost > self.burst:
            deficit = self.burst - self.level
        return False, deficit / self.rate if self.rate > 0 else float("inf")


class DeficitRoundRobin:
    """Token-cost deficit-round-robin over the fixed class order.

    State is (cursor, per-class deficit); :meth:`select` is a pure function
    of the call sequence — no clocks, no randomness — so two identical
    arrival traces dequeue in identical order."""

    def __init__(self, weights: Dict[str, float], quantum: int):
        self.order: Tuple[str, ...] = tuple(c for c in SERVICE_CLASSES)
        self.weights = {c: max(1.0, float(weights.get(c, 1.0))) for c in self.order}
        self.quantum = max(1, int(quantum))
        self.deficit: Dict[str, float] = {c: 0.0 for c in self.order}
        self._cursor = 0
        self._granted = False  # cursor class already got this visit's quantum

    def select(self, head_costs: Dict[str, int]) -> Optional[str]:
        """Pick the class whose head ticket is served next; charges its
        deficit.  ``head_costs`` maps each NON-EMPTY class to the token
        cost of the ticket that would pop from it.

        Textbook DRR visit semantics: the cursor class is granted
        ``quantum * weight`` ONCE per visit, serves heads while the deficit
        covers them, and the visit ends — deficit retained — the moment it
        cannot.  Serving must not re-grant (or interactive's big weight
        would cover every head forever and starve the other classes);
        a backlogged class's deficit therefore grows every full cycle,
        which is the starvation-freedom argument."""
        active = [c for c in self.order if c in head_costs]
        if not active:
            return None
        # an emptied class forfeits its deficit (standard DRR: idle queues
        # must not bank credit and later burst past their weight)
        for c in self.order:
            if c not in head_costs:
                self.deficit[c] = 0.0
        while True:
            c = self.order[self._cursor % len(self.order)]
            if c not in head_costs:
                self._cursor += 1
                self._granted = False
                continue
            if not self._granted:
                self.deficit[c] += self.quantum * self.weights[c]
                self._granted = True
            if self.deficit[c] >= head_costs[c]:
                self.deficit[c] -= head_costs[c]
                return c  # visit continues: no re-grant on the next call
            self._cursor += 1
            self._granted = False


class QosPolicy:
    """Per-tenant quota enforcement + class policy, owned by the engine.

    ``clock`` is the engine's injectable clock (fault tests drive a fake);
    the policy NEVER reads any other time source.  ``kv_blocks_of`` is
    installed by the engine (``manager.tenant_blocks``) so the KV quota
    check sees live resident usage without this module importing the
    manager.
    """

    def __init__(self, config=None, *, clock: Callable[[], float] = time.monotonic):
        from ...runtime.config import ServingQosConfig
        self.config = config if config is not None else ServingQosConfig()
        self.enabled = bool(self.config.enabled)
        self.clock = clock
        self.weights = {INTERACTIVE: float(self.config.interactive_weight),
                        BATCH: float(self.config.batch_weight),
                        BEST_EFFORT: float(self.config.best_effort_weight)}
        self._buckets: Dict[str, TokenBucket] = {}
        self.kv_blocks_of: Optional[Callable[[str], int]] = None
        # per-tenant lifetime accounting (exported as serving_tenant_*)
        self.admitted_by_tenant: Dict[Tuple[str, str], int] = {}
        self.tokens_by_tenant: Dict[str, int] = {}
        self.shed_by_tenant: Dict[Tuple[str, str], int] = {}
        self.last_retry_after_by_tenant: Dict[str, float] = {}

    # ------------------------------------------------------------- identity
    def service_class(self, cls: Optional[str]) -> str:
        """Normalize a caller-supplied class (None → section default)."""
        if cls is None:
            return str(self.config.default_class)
        if cls not in SERVICE_CLASSES:
            raise ValueError(f"unknown service class {cls!r} — expected one "
                             f"of {SERVICE_CLASSES}")
        return cls

    def make_drr(self) -> DeficitRoundRobin:
        return DeficitRoundRobin(self.weights, self.config.drr_quantum_tokens)

    # --------------------------------------------------------------- quotas
    def quota_for(self, tenant: str) -> TenantQuota:
        cfg = self.config
        over = cfg.tenants.get(tenant) if isinstance(cfg.tenants, dict) else None
        over = over if isinstance(over, dict) else {}
        rate = float(over.get("tokens_per_s", cfg.tenant_tokens_per_s))
        burst = float(over.get("token_burst", cfg.tenant_token_burst))
        if burst <= 0.0:
            burst = rate  # default burst: one second of rate
        return TenantQuota(tokens_per_s=rate, token_burst=burst,
                           max_kv_blocks=int(over.get("max_kv_blocks",
                                                      cfg.tenant_max_kv_blocks)))

    def _bucket(self, tenant: str, quota: TenantQuota) -> TokenBucket:
        b = self._buckets.get(tenant)
        if b is None or b.rate != quota.tokens_per_s:
            b = TokenBucket(quota.tokens_per_s, quota.token_burst)
            self._buckets[tenant] = b
        return b

    def admission_check(self, tenant: str, cls: str,
                        token_cost: int) -> Optional[ShedReason]:
        """Front-door quota verdict; None = admit (bucket already charged).

        Runs AFTER the structural/pressure checks in ``shed_reason`` (an
        over-cap prompt is fatal regardless of whose it is) and BEFORE any
        KV allocation, like every other shed."""
        if not self.enabled:
            return None
        quota = self.quota_for(tenant)
        if quota.max_kv_blocks > 0 and self.kv_blocks_of is not None:
            used = int(self.kv_blocks_of(tenant))
            if used >= quota.max_kv_blocks:
                # resident-cap shed: blocks free as this tenant's own
                # requests retire — hint scales with the overshoot, same
                # clamped band as the kv_pressure hint
                return ShedReason(
                    QUOTA_EXCEEDED,
                    f"tenant {tenant!r} holds {used} KV blocks >= its quota "
                    f"of {quota.max_kv_blocks} (class {cls})", retryable=True,
                    retry_after_s=min(2.0, 0.1 + 0.05 * (used - quota.max_kv_blocks + 1)))
        if quota.tokens_per_s > 0.0:
            ok, wait = self._bucket(tenant, quota).try_take(
                float(token_cost), self.clock())
            if not ok:
                return ShedReason(
                    QUOTA_EXCEEDED,
                    f"tenant {tenant!r} over its token-rate quota of "
                    f"{quota.tokens_per_s:g} tok/s (cost {token_cost}, "
                    f"class {cls})", retryable=True,
                    retry_after_s=max(0.001, min(60.0, wait)))
        return None

    # ----------------------------------------------------------- accounting
    def note_admit(self, tenant: str, cls: str, token_cost: int) -> None:
        key = (tenant, cls)
        self.admitted_by_tenant[key] = self.admitted_by_tenant.get(key, 0) + 1
        self.tokens_by_tenant[tenant] = (self.tokens_by_tenant.get(tenant, 0)
                                         + int(token_cost))

    def note_shed(self, tenant: str, code: str,
                  retry_after_s: Optional[float]) -> None:
        key = (tenant, code)
        self.shed_by_tenant[key] = self.shed_by_tenant.get(key, 0) + 1
        if retry_after_s is not None:
            self.last_retry_after_by_tenant[tenant] = float(retry_after_s)

    def tenants_seen(self) -> List[str]:
        seen = set(self.tokens_by_tenant)
        seen.update(t for t, _ in self.admitted_by_tenant)
        seen.update(t for t, _ in self.shed_by_tenant)
        return sorted(seen)

    # ------------------------------------------------- preemption steering
    def over_kv_quota(self, tenant: str) -> bool:
        quota = self.quota_for(tenant)
        if quota.max_kv_blocks <= 0 or self.kv_blocks_of is None:
            return False
        return int(self.kv_blocks_of(tenant)) > quota.max_kv_blocks

    def victim_rank(self, seq) -> Tuple[int, int]:
        """Preemption preference prefix for a candidate victim: over-quota
        tenants outrank everything, then lower classes; the scheduler
        appends arrival as the final tie-break (the PR-4 heuristic).  With
        steering disabled the rank is constant and the legacy newest-first
        choice is byte-identical."""
        if not self.enabled or not self.config.preempt_over_quota:
            return (0, 0)
        tenant = getattr(seq, "tenant", DEFAULT_TENANT)
        cls = getattr(seq, "service_class", INTERACTIVE)
        return (1 if self.over_kv_quota(tenant) else 0,
                CLASS_RANK.get(cls, 0))

    # ------------------------------------------------------------ snapshot
    def snapshot(self) -> Dict[str, Any]:
        """Host-side state for ``engine.health()`` and the ops plane."""
        return {
            "enabled": self.enabled,
            "tenants": self.tenants_seen(),
            "admitted_by_tenant": {f"{t}/{c}": n for (t, c), n
                                   in sorted(self.admitted_by_tenant.items())},
            "tokens_by_tenant": dict(sorted(self.tokens_by_tenant.items())),
            "shed_by_tenant": {f"{t}/{c}": n for (t, c), n
                               in sorted(self.shed_by_tenant.items())},
        }
