"""Tensor-parallel sharding for v2 (ragged/paged) serving.

Analog of the reference's v2 sharding-helper tree
(inference/v2/model_implementations/sharding/{qkv,mlp,attn,embedding,unembed}.py
+ the TP group the engine builds on, inference/v2/engine_v2.py:81-92): the
reference hand-slices each weight per TP rank at load time; here the model's
``tp_rules`` (or AutoTP path inference) produce a PartitionSpec tree, params and
the paged KV pool are placed with NamedShardings, and the ragged forward runs
under ``shard_map`` with ``tp_axis`` threading psums through the row-parallel
matmuls (models/llama.py forward_paged).

Layout (matching the reference helpers):
  qkv (wq/wk/wv)      column-parallel — heads split over 'tensor'  (sharding/qkv.py)
  attn out (wo)       row-parallel    — psum                       (sharding/attn.py)
  mlp up/gate         column-parallel                              (sharding/mlp.py)
  mlp down            row-parallel    — psum
  embedding           replicated                                   (sharding/embedding.py)
  lm head             vocab-parallel  — all_gather of logit shards (sharding/unembed.py)
  paged KV pool       head-sharded    — dim 2 of [L, NB, KV, bs, Dh]
"""

from typing import Any, Callable, Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ...parallel.mesh import TENSOR_AXIS, MeshTopology
from ...runtime.zero.sharding import _normalize_rule, _path_str
from ..auto_tp import auto_tp_rules


def resolve_rules(model_module, model_config=None) -> Callable:
    """Config-aware rules first (make_tp_rules(config) — models whose layout
    depends on head counts, e.g. falcon's MQA KV replication), then the static
    tp_rules, then AutoTP pattern inference."""
    maker = getattr(model_module, "make_tp_rules", None)
    if maker is not None and model_config is not None:
        return maker(model_config)
    return getattr(model_module, "tp_rules", None) or auto_tp_rules


def param_specs(model_module, params, tp: int, model_config=None):
    """PartitionSpec tree for v2 params over the 'tensor' axis.

    Raises when a rule names a dim not divisible by tp — silent replication
    there would serve wrong math under shard_map (local head counts are derived
    from the shard shapes)."""
    rules = resolve_rules(model_module, model_config)

    def spec_for(path, leaf):
        shape = np.shape(leaf)
        path_s = _path_str(path)
        dims = [None] * len(shape)
        for dim, axis in _normalize_rule(rules(path_s, tuple(shape))):
            if axis != TENSOR_AXIS:
                continue  # v2 serving shards over 'tensor' only
            if shape[dim] % tp != 0:
                raise ValueError(
                    f"v2 TP: param {path_s} dim {dim} ({shape[dim]}) not divisible by "
                    f"tp={tp}; pick a tp that divides heads/ffn/vocab")
            dims[dim] = TENSOR_AXIS
        return PartitionSpec(*dims)

    return jax.tree_util.tree_map_with_path(spec_for, params)


def kv_pool_spec(kv_pool, tp: int = 0) -> Any:
    """Pool sharding: leaves are [L, NB, KV, bs, Dh] — head-shard dim 2 when it
    divides tp, else REPLICATE (MQA: every shard holds the single KV head and
    computes it identically; the reference's KV-replication fallback,
    sharding/qkv.py)."""
    def spec(leaf):
        kv_heads = np.shape(leaf)[2]
        if tp and kv_heads % tp != 0:
            return PartitionSpec()
        return PartitionSpec(None, None, TENSOR_AXIS)

    return jax.tree_util.tree_map(spec, kv_pool)


def validate_model(model_config, tp: int, model_module=None) -> None:
    """Head/GQA divisibility — the same constraint the reference asserts in its
    sharding helpers (sharding/attn.py head-distribution logic).  MQA (1 KV
    head) is allowed ONLY for models with config-aware ``make_tp_rules`` that
    keep wk/wv replicated (falcon) — static rule sets that unconditionally
    shard wk/wv would silently split the single head's feature dim."""
    h = getattr(model_config, "num_heads", None)
    kv = getattr(model_config, "num_kv_heads", h)
    if h is not None and h % tp != 0:
        raise ValueError(f"v2 TP: num_heads={h} not divisible by tp={tp}")
    mqa_ok = kv == 1 and model_module is not None and hasattr(model_module, "make_tp_rules")
    if kv is not None and kv % tp != 0 and not mqa_ok:
        raise ValueError(
            f"v2 TP: num_kv_heads={kv} not divisible by tp={tp} — partial KV-head "
            f"replication is not implemented; use tp <= num_kv_heads (MQA kv=1 "
            f"replicates fully for models with config-aware make_tp_rules, e.g. falcon)")


def place(topology: MeshTopology, tree, specs):
    """Place a pytree with NamedShardings from a PartitionSpec tree.

    Multi-controller meshes (TP spanning processes) can't eager-device_put to
    non-addressable devices — build from per-shard callbacks instead, each
    process materializing only its addressable shards (same pattern as
    checkpoint load, runtime/checkpointing.py)."""
    mesh = topology.mesh
    multi = jax.process_count() > 1

    def put(x, s):
        sharding = NamedSharding(mesh, s)
        if multi:
            host = np.asarray(x)  # dslint: disable=host-sync-in-hot-path  # init-time weight placement (multi-controller shard callback), not a serve-loop step-result fetch
            return jax.make_array_from_callback(host.shape, sharding,
                                                lambda idx, a=host: a[idx])
        return jax.device_put(x, sharding)

    return jax.tree_util.tree_map(put, tree, specs)
