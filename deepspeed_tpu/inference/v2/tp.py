"""Tensor-parallel sharding for v2 (ragged/paged) serving.

Analog of the reference's v2 sharding-helper tree
(inference/v2/model_implementations/sharding/{qkv,mlp,attn,embedding,unembed}.py
+ the TP group the engine builds on, inference/v2/engine_v2.py:81-92): the
reference hand-slices each weight per TP rank at load time; here the model's
``tp_rules`` (or AutoTP path inference) produce a PartitionSpec tree, params and
the paged KV pool are placed with NamedShardings, and the ragged forward runs
under ``shard_map`` with ``tp_axis`` threading psums through the row-parallel
matmuls (models/llama.py forward_paged).

Layout (matching the reference helpers):
  qkv (wq/wk/wv)      column-parallel — heads split over 'tensor'  (sharding/qkv.py)
  attn out (wo)       row-parallel    — psum                       (sharding/attn.py)
  mlp up/gate         column-parallel                              (sharding/mlp.py)
  mlp down            row-parallel    — psum
  embedding           replicated                                   (sharding/embedding.py)
  lm head             vocab-parallel  — all_gather of logit shards (sharding/unembed.py)
  paged KV pool       head-sharded    — dim 2 of [L, NB, KV, bs, Dh]
"""

from typing import Any, Callable, Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ...parallel.mesh import TENSOR_AXIS, MeshTopology
from ...runtime.zero.sharding import _normalize_rule, _path_str
from ..auto_tp import auto_tp_rules


def resolve_rules(model_module) -> Callable:
    return getattr(model_module, "tp_rules", None) or auto_tp_rules


def param_specs(model_module, params, tp: int):
    """PartitionSpec tree for v2 params over the 'tensor' axis.

    Raises when a rule names a dim not divisible by tp — silent replication
    there would serve wrong math under shard_map (local head counts are derived
    from the shard shapes)."""
    rules = resolve_rules(model_module)

    def spec_for(path, leaf):
        shape = np.shape(leaf)
        path_s = _path_str(path)
        dims = [None] * len(shape)
        for dim, axis in _normalize_rule(rules(path_s, tuple(shape))):
            if axis != TENSOR_AXIS:
                continue  # v2 serving shards over 'tensor' only
            if shape[dim] % tp != 0:
                raise ValueError(
                    f"v2 TP: param {path_s} dim {dim} ({shape[dim]}) not divisible by "
                    f"tp={tp}; pick a tp that divides heads/ffn/vocab")
            dims[dim] = TENSOR_AXIS
        return PartitionSpec(*dims)

    return jax.tree_util.tree_map_with_path(spec_for, params)


def kv_pool_spec(kv_pool) -> Any:
    """Head-shard the paged pool: leaves are [L, NB, KV, bs, Dh]."""
    return jax.tree_util.tree_map(lambda _: PartitionSpec(None, None, TENSOR_AXIS), kv_pool)


def validate_model(model_config, tp: int) -> None:
    """Head/GQA divisibility — the same constraint the reference asserts in its
    sharding helpers (sharding/attn.py head-distribution logic)."""
    h = getattr(model_config, "num_heads", None)
    kv = getattr(model_config, "num_kv_heads", h)
    if h is not None and h % tp != 0:
        raise ValueError(f"v2 TP: num_heads={h} not divisible by tp={tp}")
    if kv is not None and kv % tp != 0:
        raise ValueError(
            f"v2 TP: num_kv_heads={kv} not divisible by tp={tp} — KV-head replication "
            f"is not implemented; use tp <= num_kv_heads")


def place(topology: MeshTopology, tree, specs):
    """device_put a pytree with NamedShardings from a PartitionSpec tree."""
    mesh = topology.mesh
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, specs)
