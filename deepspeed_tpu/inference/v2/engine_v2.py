"""Continuous-batching inference engine (FastGen analog).

Reference InferenceEngineV2 (inference/v2/engine_v2.py:30): ``put()`` enqueues
requests, each ``step()`` runs ONE ragged forward over a SplitFuse-scheduled
token batch against the paged KV pool, and sampled tokens stream back per uid.

TPU shape discipline: the ragged batch is padded to fixed (max_seqs, chunk)
buckets so jit compiles a small set of programs (one per bucket) instead of
one per ragged shape — the XLA analog of the reference's CUDA-graph-free
ragged kernels.
"""

import functools
import inspect
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

from ...parallel.mesh import TENSOR_AXIS, MeshTopology
from ...utils.logging import log_dist
from ..config import DTYPES as _DTYPES, load_inference_config
from .ragged_manager import RaggedStateManager
from .scheduler import ScheduledChunk, SplitFuseScheduler

def candidate_sample(row, rng, *, temperature, top_k, top_p, axis):
    """Candidate-set sampling over a vocab-sharded logits row (reference
    logits_gather ragged kernels): each shard contributes its local top-k'
    (logit, global index) pairs, k' = max(top_k, 64), and the full sampler
    runs on the gathered [N, k'*tp] candidate row — O(k'*tp) pairs on the
    wire per token instead of an O(V) full-vocab gather.  Exact whenever the
    candidates cover the top-k/nucleus set: always for top-k <= k'; for
    top-p the mass outside 64*tp candidates is negligible for real
    vocabularies (and zero when k'*tp >= V, where this is a permuted full
    row).  ``rng`` must be replicated so every shard samples the identical
    candidate index.  Returns (global token ids [N], rng)."""
    from ..engine import _sample
    vlocal = row.shape[-1]
    kc = min(vlocal, max(int(top_k) if top_k else 0, 64))
    vals, idx = jax.lax.top_k(row, kc)
    offset = jax.lax.axis_index(axis).astype(jnp.int32) * vlocal
    gidx = idx.astype(jnp.int32) + offset
    allv = jax.lax.all_gather(vals, axis, axis=1, tiled=True)
    alli = jax.lax.all_gather(gidx, axis, axis=1, tiled=True)
    cand, rng = _sample(allv, rng, temperature=temperature, top_k=top_k, top_p=top_p)
    tok = jnp.take_along_axis(alli, cand[:, None], axis=1)[:, 0]
    return tok, rng


class InferenceEngineV2:

    def __init__(self, model_module, model_config, params, config: Optional[Dict] = None,
                 num_blocks: int = 512, block_size: int = 16,
                 max_blocks_per_seq: int = 64, token_budget: int = 256,
                 max_seqs_per_step: int = 32,
                 topology: Optional[MeshTopology] = None,
                 telemetry=None):
        self.config = load_inference_config(config)
        self.model = model_module
        self.model_config = model_config
        self.dtype = _DTYPES[self.config.dtype]
        self.block_size = block_size
        self.manager = RaggedStateManager(num_blocks, block_size, max_blocks_per_seq)
        # telemetry: a monitor.TelemetryCollector; the scheduler emits its
        # gauges through it and step() adds serving rates (ISSUE 1 tentpole)
        self.telemetry = telemetry
        self.scheduler = SplitFuseScheduler(token_budget, max_seqs_per_step,
                                            telemetry=telemetry)
        self.topology = topology
        self.tp = topology.axis_size(TENSOR_AXIS) if topology is not None else 1
        self._warn_truncated_nucleus()
        params = jax.tree_util.tree_map(lambda x: jnp.asarray(x, self.dtype), params)
        kv = model_module.init_paged_cache(model_config, num_blocks, block_size, dtype=self.dtype)
        if self.tp > 1:
            # TP-sharded serving (reference engine_v2.py:81 builds on a TP group;
            # sharding helpers inference/v2/model_implementations/sharding/)
            from . import tp as _tp
            if "tp_axis" not in inspect.signature(model_module.forward_paged).parameters:
                raise NotImplementedError(
                    f"{model_module.__name__}.forward_paged has no tp_axis support; "
                    f"all built-in paged families (llama/mistral/mixtral/opt/falcon/"
                    f"phi/qwen) ship it — thread tp_axis through custom models the "
                    f"same way (psum after row-parallel projections)")
            _tp.validate_model(model_config, self.tp, model_module=model_module)
            self._param_specs = _tp.param_specs(model_module, params, self.tp,
                                                model_config=model_config)
            self._kv_specs = _tp.kv_pool_spec(kv, self.tp)
            params = _tp.place(topology, params, self._param_specs)
            kv = _tp.place(topology, kv, self._kv_specs)
        self.params = params
        self.kv = kv
        self._fwd_cache: Dict = {}
        self._rng = jax.random.PRNGKey(self.config.seed)
        self.max_blocks_per_seq = max_blocks_per_seq
        log_dist(f"InferenceEngineV2: blocks={num_blocks}x{block_size} "
                 f"budget={token_budget} dtype={self.config.dtype} tp={self.tp}", ranks=[0])

    def _warn_truncated_nucleus(self):
        """One-time runtime notice when TP candidate-set sampling approximates
        top-p (ADVICE r5): with ``top_p < 1`` each shard contributes k' =
        max(top_k, 64) candidates, so tail mass outside the k'*tp candidate
        set is redistributed unless k'*tp covers the vocabulary."""
        vocab = getattr(self.model_config, "vocab_size", None)
        if self.tp <= 1 or vocab is None or not self.config.top_p < 1.0:
            return
        kc = max(int(self.config.top_k) if self.config.top_k else 0, 64)
        if kc * self.tp < int(vocab):
            from ...utils.logging import warning_once
            warning_once(
                f"InferenceEngineV2: top_p={self.config.top_p} with tp={self.tp} uses the "
                f"truncated-nucleus approximation — sampling sees {kc}*{self.tp}="
                f"{kc * self.tp} candidates of V={int(vocab)}, so nucleus mass outside the "
                f"per-shard top-{kc} sets is redistributed; raise top_k to widen coverage "
                f"if exact top-p sampling matters")

    def _shard_mapped(self, inner, out_specs):
        """Wrap a (params, kv, *replicated) forward for TP: replicated
        activations in, sharded params/KV, psums inside via tp_axis."""
        from jax import shard_map
        n_rep = len(inspect.signature(inner).parameters) - 2
        rep = tuple(PartitionSpec() for _ in range(n_rep))
        return shard_map(inner, mesh=self.topology.mesh,
                         in_specs=(self._param_specs, self._kv_specs) + rep,
                         out_specs=out_specs, check_vma=False)

    # ------------------------------------------------------------------ intake
    def put(self, uids: Sequence[int], prompts: Sequence[Sequence[int]]) -> None:
        """Enqueue requests (reference engine_v2.put:107)."""
        for uid, prompt in zip(uids, prompts):
            self.manager.add_sequence(int(uid), [int(t) for t in prompt])

    def flush(self, uid: int) -> None:
        self.manager.retire(uid)

    # ------------------------------------------------------------------- step
    def _compiled_fwd(self, n: int, t: int, b: int):
        key = (n, t, b)
        if key not in self._fwd_cache:
            model, cfg, bs = self.model, self.model_config, self.block_size
            if self.tp > 1:
                def fwd(params, kv, tokens, n_tokens, start_pos, tables):
                    return model.forward_paged(cfg, params, tokens, n_tokens, start_pos,
                                               tables, kv, block_size=bs,
                                               tp_axis=TENSOR_AXIS)
                fwd = self._shard_mapped(fwd, (PartitionSpec(), self._kv_specs))
            else:
                def fwd(params, kv, tokens, n_tokens, start_pos, tables):
                    return model.forward_paged(cfg, params, tokens, n_tokens, start_pos,
                                               tables, kv, block_size=bs)

            self._fwd_cache[key] = jax.jit(fwd, donate_argnums=(1, ))  # dslint: disable=donation-after-use  # call-site contract: step() reassigns self.kv from the result in the same statement (the KV pool is donated so decode updates alias in place)
        return self._fwd_cache[key]

    @staticmethod
    def _bucket(n: int) -> int:
        b = 1
        while b < n:
            b *= 2
        return b

    def step(self, greedy: bool = True) -> Dict[int, int]:
        """Run one SplitFuse step; returns {uid: sampled_token} for sequences
        that produced a next token (finished prefill or decoded)."""
        chunks = self.scheduler.schedule(self.manager)
        if not chunks:
            return {}
        n = self._bucket(len(chunks))
        t = self._bucket(max(c.n_tokens for c in chunks))
        # bucket the table width to the live maximum: the paged kernel's grid
        # walks every table slot, so dead trailing slots are pure waste
        b = self._bucket(max(len(self.manager.seqs[c.uid].blocks) for c in chunks))
        b = min(b, self.max_blocks_per_seq)
        tokens = np.zeros((n, t), np.int32)
        n_tokens = np.zeros((n, ), np.int32)
        start_pos = np.zeros((n, ), np.int32)
        tables = np.full((n, b), self.manager.trash_block, np.int32)
        for i, c in enumerate(chunks):
            seq = self.manager.seqs[c.uid]
            sl = seq.tokens[seq.seen_tokens:seq.seen_tokens + c.n_tokens]
            tokens[i, :len(sl)] = sl
            n_tokens[i] = c.n_tokens
            start_pos[i] = seq.seen_tokens
            tables[i] = self.manager.block_table_row(seq)[:b]

        fwd = self._compiled_fwd(n, t, b)
        logits, self.kv = fwd(self.params, self.kv, jnp.asarray(tokens), jnp.asarray(n_tokens),
                              jnp.asarray(start_pos), jnp.asarray(tables))
        # token selection runs ON DEVICE (argmax or temperature/top-k/top-p
        # sampling) — only n ints cross the host link, not [n, V] logits
        # (reference: ragged sampling stays device-side, engine_v2.py:107)
        pick = self._compiled_step_pick(n, greedy)
        toks_dev, self._rng = pick(logits, jnp.asarray(np.maximum(n_tokens - 1, 0)), self._rng)
        toks = np.asarray(toks_dev)  # dslint: disable=host-sync-in-hot-path  # by design: only n sampled ints cross the host link per step (never the [n, V] logits)

        out: Dict[int, int] = {}
        for i, c in enumerate(chunks):
            seq = self.manager.seqs[c.uid]
            seq.seen_tokens += c.n_tokens
            if seq.seen_tokens >= len(seq.tokens):
                # produced a next token (end of prompt, or a decode step)
                tok = int(toks[i])
                seq.tokens.append(tok)
                out[c.uid] = tok
        self._emit_serving_gauges(tokens_run=int(n_tokens.sum()))
        return out

    def _emit_serving_gauges(self, tokens_run: int) -> None:
        """Serving rates on top of the scheduler's per-step gauges: requests/s
        (retired-sequence rate) and tokens/s through the ragged forward."""
        if self.telemetry is None:
            return
        gauges = {"live_seqs": float(len(self.manager.live_uids()))}
        rps = self.telemetry.rate("v2_completed_requests",
                                  float(self.manager.completed_requests))
        if rps is not None:
            gauges["requests_per_sec"] = rps
        self._tokens_run_total = getattr(self, "_tokens_run_total", 0) + tokens_run
        tps = self.telemetry.rate("v2_tokens_total", float(self._tokens_run_total))
        if tps is not None:
            gauges["tokens_per_sec"] = tps
        self.telemetry.record_gauges(gauges, step=self.scheduler.steps,
                                     prefix="Inference/Serving")

    def _compiled_step_pick(self, n: int, greedy: bool):
        key = ("pick", n, greedy, self.config.temperature, self.config.top_k,
               self.config.top_p)
        if key not in self._fwd_cache:
            from ..engine import _sample
            temperature, top_k, top_p = (self.config.temperature, self.config.top_k,
                                         self.config.top_p)

            def pick(logits, last, rng):
                row = jnp.take_along_axis(logits, last[:, None, None], axis=1)[:, 0]
                if greedy:
                    return jnp.argmax(row, axis=-1).astype(jnp.int32), rng
                return _sample(row, rng, temperature=temperature, top_k=top_k, top_p=top_p)

            self._fwd_cache[key] = jax.jit(pick)
        return self._fwd_cache[key]

    # ------------------------------------------------------------ decode burst
    def _compiled_burst(self, n: int, k: int, sample_cfg=None, eos: int = -1):
        """``sample_cfg``: None => greedy; (temperature, top_k, top_p) =>
        on-device sampling with the rng carried through the scan.  ``eos`` >= 0
        makes decode eos-aware: a finished row freezes (re-emits its token) and
        its done flag streams out alongside the tokens."""
        key = ("burst", n, k, sample_cfg, eos)
        if key not in self._fwd_cache:
            from ..engine import _sample
            model, cfg, bs = self.model, self.model_config, self.block_size
            ones = jnp.ones((n, ), jnp.int32)
            sampling = sample_cfg is not None
            if self.tp > 1:
                tp_kw = {"tp_axis": TENSOR_AXIS, "gather_logits": False}
                vocab = getattr(cfg, "vocab_size", None)

                if sampling:
                    # sampled TP decode stays in the same wire-cost class as
                    # greedy via candidate-set sampling (VERDICT r4 #4)
                    temperature, top_k, top_p = sample_cfg

                    def pick(row, rng):  # row [N, V_local]
                        if vocab is not None and row.shape[-1] == vocab:
                            return _sample(row, rng, temperature=temperature,
                                           top_k=top_k, top_p=top_p)
                        return candidate_sample(row, rng, temperature=temperature,
                                                top_k=top_k, top_p=top_p,
                                                axis=TENSOR_AXIS)
                else:
                    # vocab-parallel greedy: argmax the LOCAL logit shard and
                    # reduce (max value, then first-occurrence index) with O(1)
                    # scalars per token over ICI instead of O(V) gathers
                    def pick(row, rng):  # row [N, V_local]
                        if vocab is not None and row.shape[-1] == vocab:
                            return jnp.argmax(row, axis=-1).astype(jnp.int32), rng
                        vlocal = row.shape[-1]
                        local_idx = jnp.argmax(row, axis=-1).astype(jnp.int32)
                        local_val = jnp.max(row, axis=-1)
                        best = jax.lax.pmax(local_val, TENSOR_AXIS)
                        offset = jax.lax.axis_index(TENSOR_AXIS).astype(jnp.int32) * vlocal
                        cand = jnp.where(local_val == best, local_idx + offset,
                                         jnp.int32(2**31 - 1))
                        return jax.lax.pmin(cand, TENSOR_AXIS).astype(jnp.int32), rng
            else:
                tp_kw = {}
                if sampling:
                    temperature, top_k, top_p = sample_cfg

                    def pick(row, rng):
                        return _sample(row, rng, temperature=temperature,
                                       top_k=top_k, top_p=top_p)
                else:
                    pick = lambda row, rng: (jnp.argmax(row, axis=-1).astype(jnp.int32), rng)

            def burst(params, kv, tok0, start0, tables, rng0, done0):
                def body(carry, _):
                    kv, tok, start, rng, done = carry
                    logits, kv = model.forward_paged(cfg, params, tok[:, None], ones,
                                                     start, tables, kv, block_size=bs,
                                                     **tp_kw)
                    nxt, rng = pick(logits[:, 0], rng)
                    # finished rows freeze: re-emit the last token (the pool
                    # keeps absorbing writes into pre-allocated slots; the host
                    # truncates at the first done flag)
                    nxt = jnp.where(done, tok, nxt)
                    done = jnp.logical_or(done, nxt == jnp.int32(eos))
                    return (kv, nxt, start + 1, rng, done), (nxt, done)

                (kv, _, _, _, _), (toks, dones) = jax.lax.scan(
                    body, (kv, tok0, start0, rng0, done0), None, length=k)
                return kv, toks, dones  # [K, N] each

            if self.tp > 1:
                burst = self._shard_mapped(
                    burst, (self._kv_specs, PartitionSpec(), PartitionSpec()))
            self._fwd_cache[key] = jax.jit(burst, donate_argnums=(1, ))  # dslint: disable=donation-after-use  # call-site contract: decode_burst() reassigns self.kv from the result in the same statement
        return self._fwd_cache[key]

    def decode_burst(self, k: int, greedy: bool = True,
                     eos_token_id: Optional[int] = None) -> Optional[Dict[int, List[int]]]:
        """Run ``k`` decode steps INSIDE one compiled program — one host
        round-trip per k tokens instead of per token (the latency lever the
        reference gets from CUDA-graph decode loops; on a remote-relay
        transport this is the difference between ~4 and ~100+ tok/s/seq).

        Greedy AND sampled (temperature/top-k/top-p from the engine config)
        decode both run device-side; with ``eos_token_id`` the scan carries a
        done-mask and finished rows freeze, so the returned per-uid lists stop
        at (and include) the first eos.  Applies only when every live sequence
        is in pure decode (one pending token) and the pool can pre-allocate k
        more slots per sequence; returns None when not applicable (caller
        falls back to step()).
        """
        live = [s for s in self.manager.seqs.values()
                if not s.done and s.pending_tokens > 0]
        if not live or any(s.pending_tokens != 1 for s in live):
            return None
        if len(live) > self.scheduler.max_seqs:
            return None
        max_pos = getattr(self.model_config, "max_seq_len", None)
        total_new = 0
        for seq in live:
            upto = seq.seen_tokens + 1 + k
            if self.manager.over_cap(upto):
                return None
            if max_pos is not None and upto > max_pos:
                # positions past the rotary table would silently clamp — the
                # burst pre-commits k future positions, so bound them here
                return None
            total_new += self.manager.blocks_needed(seq, upto)
        if not self.manager.can_allocate(total_new):
            # check BEFORE allocating anything: a partial grab would strand
            # blocks on some sequences and starve the stepwise fallback
            return None
        for seq in live:
            self.manager.ensure_blocks(seq, seq.seen_tokens + 1 + k)

        n = self._bucket(len(live))
        b = min(self._bucket(max(len(s.blocks) for s in live)), self.max_blocks_per_seq)
        tok0 = np.zeros((n, ), np.int32)
        start0 = np.zeros((n, ), np.int32)
        tables = np.full((n, b), self.manager.trash_block, np.int32)
        for i, seq in enumerate(live):
            tok0[i] = seq.tokens[seq.seen_tokens]
            start0[i] = seq.seen_tokens
            tables[i] = self.manager.block_table_row(seq)[:b]
        # padded rows: decode into the trash block at position 0
        sample_cfg = None if greedy else (self.config.temperature, self.config.top_k,
                                          self.config.top_p)
        eos = -1 if eos_token_id is None else int(eos_token_id)
        burst = self._compiled_burst(n, k, sample_cfg=sample_cfg, eos=eos)
        self._rng, sub = jax.random.split(self._rng)
        done0 = jnp.zeros((n, ), jnp.bool_)
        self.kv, toks, dones = burst(self.params, self.kv, jnp.asarray(tok0),
                                     jnp.asarray(start0), jnp.asarray(tables), sub, done0)
        toks = np.asarray(toks)    # [K, N]  # dslint: disable=host-sync-in-hot-path  # by design: the burst's whole point — ONE host round-trip of k*n ints per k decode steps
        dones = np.asarray(dones)  # [K, N]  # dslint: disable=host-sync-in-hot-path  # rides the same single burst fetch as toks
        out: Dict[int, List[int]] = {}
        for i, seq in enumerate(live):
            col = toks[:, i]
            n_real = k
            if eos >= 0 and dones[:, i].any():
                n_real = int(np.argmax(dones[:, i])) + 1  # first done step, inclusive
            produced = [int(t) for t in col[:n_real]]
            seq.tokens.extend(produced)
            seq.seen_tokens += n_real
            out[seq.uid] = produced
        return out

    # ----------------------------------------------------------- convenience
    def generate(self, prompts: Sequence[Sequence[int]], max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None, greedy: bool = True) -> List[List[int]]:
        """Serve a batch to completion through the continuous-batching loop.

        ``greedy=False`` samples with the engine config's temperature/top-k/
        top-p — still through the device-side burst (the scan carries the rng
        and an eos done-mask), so sampled serving runs at burst throughput
        rather than the one-host-roundtrip-per-token relay floor."""
        uids = list(range(len(prompts)))
        self.put(uids, prompts)
        produced = {u: 0 for u in uids}
        done = set()
        while len(done) < len(uids):
            # pure-decode fast path: burst k steps on device (greedy or
            # sampled; eos-aware via the carried done-mask)
            live = [u for u in uids if u not in done]
            k = min((max_new_tokens - produced[u] for u in live), default=0)
            if k >= 2:
                burst = self.decode_burst(k, greedy=greedy, eos_token_id=eos_token_id)
                if burst:
                    for uid, toks in burst.items():
                        produced[uid] += len(toks)
                        hit_eos = eos_token_id is not None and toks and toks[-1] == eos_token_id
                        if hit_eos or produced[uid] >= max_new_tokens:
                            self.manager.seqs[uid].done = True
                            done.add(uid)
                    continue
            stepped = self.step(greedy=greedy)
            for uid, reason in list(self.manager.failures.items()):
                if uid not in done:
                    raise RuntimeError(f"request {uid} failed: {reason}")
            if not stepped and not any(self.manager.seqs[u].pending_tokens > 0
                                       and not self.manager.seqs[u].done
                                       for u in uids if u not in done):
                break
            if not stepped:
                live = [u for u in uids if u not in done]
                raise RuntimeError(
                    f"scheduler made no progress with {len(live)} live sequences — KV pool "
                    f"exhausted ({self.manager.allocator.free_blocks} free blocks); enlarge "
                    f"num_blocks or lower concurrency")
            for uid, tok in stepped.items():
                produced[uid] += 1
                if produced[uid] >= max_new_tokens or (eos_token_id is not None and tok == eos_token_id):
                    self.manager.seqs[uid].done = True
                    done.add(uid)
        outs = [list(self.manager.seqs[u].tokens) for u in uids]
        for u in uids:
            self.flush(u)
        return outs
