"""Continuous-batching inference engine (FastGen analog).

Reference InferenceEngineV2 (inference/v2/engine_v2.py:30): ``put()`` enqueues
requests, each ``step()`` runs ONE ragged forward over a SplitFuse-scheduled
token batch against the paged KV pool, and sampled tokens stream back per uid.

TPU shape discipline: the ragged batch is padded to fixed (max_seqs, chunk)
buckets so jit compiles a small set of programs (one per bucket) instead of
one per ragged shape — the XLA analog of the reference's CUDA-graph-free
ragged kernels.
"""

import contextlib
import inspect
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

from ...compat import shard_map
from ...monitor.perf import CompileLedger, RooflineModel, StepPhaseProfiler
from ...monitor.tracing import RequestTracer
from ...parallel.mesh import TENSOR_AXIS, MeshTopology
from ...runtime.heartbeat import (HEARTBEAT_DIR_ENV, HEARTBEAT_INTERVAL_ENV,
                                  NULL_HEARTBEAT, OPS_DIR_ENV, SERVING_FSYNC_ENV,
                                  SERVING_GENERATION_ENV, SERVING_JOURNAL_ENV,
                                  HeartbeatWriter)
from ...utils.env import env_float, env_int
from ...utils.logging import log_dist
from ..config import DTYPES as _DTYPES, load_inference_config
from .admission import (DEADLINE_EXPIRED, FAILED, OK, PREEMPT_REQUEUED_EXHAUSTED, SHED,
                        AdmissionQueue, RecoveredRequest, RequestResult,
                        ServingStalledError)
from .blocked_allocator import KVAllocationError
from .fastpath import (FED_SENTINEL, PENDING_TOKEN, DeferredRuns, DeferredTokens,
                       DeviceBatchState, ServeCounters, materialize, round_up_pow2)
from .journal import RequestJournal, journal_bytes
from .kv_metrics import KVObservability
from .qos import QosPolicy
from .ragged_manager import PrefixCache, RaggedStateManager
from .scheduler import SplitFuseScheduler
from .spec_decode import (AdaptiveKController, ModelDrafter, NgramDrafter,
                          SpecDecodeStats, rejection_select)

def candidate_sample(row, rng, *, temperature, top_k, top_p, axis):
    """Candidate-set sampling over a vocab-sharded logits row (reference
    logits_gather ragged kernels): each shard contributes its local top-k'
    (logit, global index) pairs, k' = max(top_k, 64), and the full sampler
    runs on the gathered [N, k'*tp] candidate row — O(k'*tp) pairs on the
    wire per token instead of an O(V) full-vocab gather.  Exact whenever the
    candidates cover the top-k/nucleus set: always for top-k <= k'; for
    top-p the mass outside 64*tp candidates is negligible for real
    vocabularies (and zero when k'*tp >= V, where this is a permuted full
    row).  ``rng`` must be replicated so every shard samples the identical
    candidate index.  Returns (global token ids [N], rng)."""
    from ..engine import _sample
    vlocal = row.shape[-1]
    kc = min(vlocal, max(int(top_k) if top_k else 0, 64))
    vals, idx = jax.lax.top_k(row, kc)
    offset = jax.lax.axis_index(axis).astype(jnp.int32) * vlocal
    gidx = idx.astype(jnp.int32) + offset
    allv = jax.lax.all_gather(vals, axis, axis=1, tiled=True)
    alli = jax.lax.all_gather(gidx, axis, axis=1, tiled=True)
    cand, rng = _sample(allv, rng, temperature=temperature, top_k=top_k, top_p=top_p)
    tok = jnp.take_along_axis(alli, cand[:, None], axis=1)[:, 0]
    return tok, rng


class InferenceEngineV2:

    # decode-burst length while any live request carries a deadline OR the
    # admission queue is non-empty: the deadline is only enforceable between
    # host round-trips, so this bounds eviction overshoot (tokens decoded past
    # expiry) and admission latency while keeping ~SLICE x fewer round-trips
    # than stepwise decode
    BURST_DEADLINE_SLICE = 8
    # table-width bucketing (serving fastpath satellite): widths grow in
    # block-table-slot steps of TABLE_STEP with sticky hysteresis — a shrink
    # only happens after TABLE_SHRINK_PATIENCE consecutive steps of slack, so
    # one long sequence entering/leaving the batch doesn't force a recompile
    # cascade across every (n, t) bucket it touches mid-serve
    TABLE_STEP = 4
    TABLE_SHRINK_PATIENCE = 16

    def __init__(self, model_module, model_config, params, config: Optional[Dict] = None,
                 num_blocks: int = 512, block_size: int = 16,
                 max_blocks_per_seq: int = 64, token_budget: int = 256,
                 max_seqs_per_step: int = 32,
                 topology: Optional[MeshTopology] = None,
                 telemetry=None, clock: Optional[Callable[[], float]] = None,
                 journal: Optional[RequestJournal] = None):
        self.config = load_inference_config(config)
        self.model = model_module
        self.model_config = model_config
        self.dtype = _DTYPES[self.config.dtype]
        self.block_size = block_size
        self.manager = RaggedStateManager(num_blocks, block_size, max_blocks_per_seq)
        # copy-on-write prefix caching (ISSUE 13): requests whose leading full
        # prompt blocks match live computed blocks map them read-only
        # (allocator refcount) and prefill only their divergent tail — the
        # realized form of the counterfactual PR 12's PrefixObservatory
        # measures, keyed on the same chained token-block hashes.  The engine
        # contributes the ONE device action: the CoW block copy for prompts
        # cached to their last token.
        self.prefix_cfg = self.config.serving_prefix_cache
        if self.prefix_cfg.enabled:
            self.manager.prefix_cache = PrefixCache(
                block_size, cow=self.prefix_cfg.cow,
                defer_shared_prefill=self.prefix_cfg.defer_shared_prefill)
            self.manager.cow_copy = self._cow_copy_block
        # block-level KV-pool observability (ISSUE 12): census + prefix-
        # sharing opportunity + capacity forecast, all from host state the
        # manager/allocator already own — zero device syncs (the kv-obs smoke
        # proves ServeCounters byte-identical on vs off)
        self.kv_cfg = self.config.serving_kv_observability
        self.kv_obs: Optional[KVObservability] = None
        if self.kv_cfg.enabled:
            self.kv_obs = KVObservability(
                block_size, num_blocks, self.manager.trash_block,
                ewma_alpha=self.kv_cfg.ewma_alpha,
                pressure_steps=self.kv_cfg.pressure_steps,
                age_buckets_per_decade=self.kv_cfg.age_buckets_per_decade)
            self.manager.census = self.kv_obs.census
        # serve-step clock for kv observability: stepwise dispatches count 1,
        # a fused decode burst of k counts k — so block ages and the
        # forecaster's per-step rates mean the same thing on every decode
        # path (the scheduler's step counter never advances inside a burst)
        self._kv_steps = 0
        # telemetry: a monitor.TelemetryCollector; the scheduler emits its
        # gauges through it and step() adds serving rates (ISSUE 1 tentpole)
        self.telemetry = telemetry
        # serving resilience (ISSUE 4): admission control + load shedding in
        # front of the manager, deadlines on an injectable clock (fault tests
        # drive a fake one), preemption policy shared with the scheduler
        self.resilience = self.config.serving_resilience
        self._clock = clock if clock is not None else time.monotonic
        # an injected clock makes gauge timestamps deterministic too (ISSUE 11
        # satellite): record_gauges stamps the engine clock's last donated
        # read instead of wall time, so FakeClock tests assert exact stamps
        self._clock_injected = clock is not None
        # request-lifecycle tracing (ISSUE 6): span chains per uid, SLO
        # latency histograms (TTFT/TBT/e2e/queue-wait), and the always-on
        # flight recorder — consumes ONLY the injectable clock, at points
        # the host already touches, so tracing adds zero device syncs
        self.tracer = RequestTracer(self.config.serving_tracing,
                                    clock=self._clock, telemetry=telemetry)
        # multi-tenant QoS (ISSUE 19): per-tenant quotas + weighted-fair
        # dequeue + victim steering.  Constructed only when the section is
        # armed — self.qos is None otherwise and every downstream seam
        # (admission, scheduler, metrics) keeps its pre-QoS behavior
        self.qos = None
        if self.config.serving_qos.enabled:
            self.qos = QosPolicy(self.config.serving_qos, clock=self._clock)
            self.qos.kv_blocks_of = self.manager.tenant_blocks
        self.admission = AdmissionQueue(self.resilience, clock=self._clock,
                                        tracer=self.tracer, qos=self.qos)
        self._deadline_expired_total = 0
        self._stall_streak = 0
        self.stalls_total = 0  # lifetime watchdog trips (streaks are transient)
        self._queue_wait_s = 0.0
        self.scheduler = SplitFuseScheduler(token_budget, max_seqs_per_step,
                                            telemetry=telemetry,
                                            resilience=self.resilience,
                                            tracer=self.tracer,
                                            gauge_timestamp=self._gauge_timestamp)
        self.scheduler.qos = self.qos
        # serving fault tolerance (ISSUE 8): durable request journal + serve-
        # iteration liveness heartbeat.  Both arm from config OR the
        # ServingSupervisor's env exports (DSTPU_SERVING_JOURNAL +
        # DSTPU_HEARTBEAT_DIR), so a supervised worker needs no config
        # changes — the same contract the elastic training agent uses.  The
        # env heartbeat dir is honored ONLY under a serving supervisor (the
        # journal env marks that); a serving engine inside a supervised
        # TRAINING worker must not clobber the trainer's rank stamps.
        self.ft = self.config.serving_fault_tolerance
        generation = int(os.environ.get(SERVING_GENERATION_ENV, "0") or 0)
        if journal is None:
            jp = os.environ.get(SERVING_JOURNAL_ENV) or \
                (self.ft.journal_path if self.ft.enabled else None)
            if jp:
                # the supervisor exports its fsync policy alongside the
                # journal path — without this, a supervised worker's default
                # config would silently pin strict mode and the operator's
                # fsync_every choice would be dead in subprocess deployments
                journal = RequestJournal(
                    jp, fsync_every=env_int(SERVING_FSYNC_ENV, self.ft.fsync_every),
                    seed=self.config.seed)
        self.journal = journal
        if self.journal is not None:
            self.journal.open_generation(generation)
        self._heartbeat = NULL_HEARTBEAT
        under_supervisor = bool(os.environ.get(SERVING_JOURNAL_ENV))
        hb_dir = (os.environ.get(HEARTBEAT_DIR_ENV) if under_supervisor else None) \
            or (self.ft.heartbeat_dir if self.ft.heartbeat else None)
        if hb_dir:
            self._heartbeat = HeartbeatWriter(
                hb_dir, rank=0,
                interval_s=env_float(HEARTBEAT_INTERVAL_ENV,
                                     self.ft.heartbeat_interval_s),
                generation=generation)
        # recovery counters surfaced by health()/state_snapshot(); the
        # supervisor stamps restarts_total/degraded onto each engine it builds
        self.ft_stats = {"restarts_total": 0, "recovered_requests_total": 0,
                         "degraded": False}
        # pull-based ops plane (ISSUE 11): a /metrics + /healthz + /statez
        # endpoint over host-side CACHED snapshots.  The serve loop refreshes
        # the cache (throttled on the injectable clock) at host-touch points
        # it already pays for; scrape handlers only read the cached strings,
        # so a scrape can never trigger a device sync or race a step.  The
        # supervisor-exported DSTPU_OPS_DIR additionally publishes per-rank
        # snapshot/textfile pairs for fleet-level merging — honored ONLY
        # under a serving supervisor (same gate as the heartbeat dir above:
        # a serving engine inside a supervised TRAINING worker must not
        # clobber the trainer's ops rank files).
        self.ops_cfg = self.config.ops_server
        ops_dir = (os.environ.get(OPS_DIR_ENV) if under_supervisor else None) \
            or self.ops_cfg.textfile_dir
        self._ops = None
        if self.ops_cfg.enabled or ops_dir:
            from ...monitor.ops_server import OpsPublisher
            self._ops = OpsPublisher(self.ops_cfg, generation=generation,
                                     ops_dir=ops_dir,
                                     rank=int(os.environ.get("RANK", "0") or 0),
                                     owner="serving engine")
        self.ops = self._ops.server if self._ops is not None else None
        self.topology = topology
        self.tp = topology.axis_size(TENSOR_AXIS) if topology is not None else 1
        self._warn_truncated_nucleus()
        params = jax.tree_util.tree_map(lambda x: jnp.asarray(x, self.dtype), params)
        kv = model_module.init_paged_cache(model_config, num_blocks, block_size, dtype=self.dtype)
        if self.tp > 1:
            # TP-sharded serving (reference engine_v2.py:81 builds on a TP group;
            # sharding helpers inference/v2/model_implementations/sharding/)
            from . import tp as _tp
            if "tp_axis" not in inspect.signature(model_module.forward_paged).parameters:
                raise NotImplementedError(
                    f"{model_module.__name__}.forward_paged has no tp_axis support; "
                    f"all built-in paged families (llama/mistral/mixtral/opt/falcon/"
                    f"phi/qwen) ship it — thread tp_axis through custom models the "
                    f"same way (psum after row-parallel projections)")
            _tp.validate_model(model_config, self.tp, model_module=model_module)
            self._param_specs = _tp.param_specs(model_module, params, self.tp,
                                                model_config=model_config)
            self._kv_specs = _tp.kv_pool_spec(kv, self.tp)
            params = _tp.place(topology, params, self._param_specs)
            kv = _tp.place(topology, kv, self._kv_specs)
        self.params = params
        self.kv = kv
        self._fwd_cache: Dict = {}
        self._rng = jax.random.PRNGKey(self.config.seed)
        self.max_blocks_per_seq = max_blocks_per_seq
        # serving fast path (ISSUE 5): persistent device-resident batch
        # buffers, deferred pick syncs, and host-link counters that make the
        # orchestration cost observable (fastpath.py).  Under TP the batch
        # state replicates over the engine's mesh (ISSUE 15) so the same
        # ≤1-sync loop drives the shard_mapped forward unchanged.
        self.fastpath = self.config.serving_fastpath
        self.counters = ServeCounters()
        # serving performance observatory (ISSUE 16): the compile ledger and
        # roofline cost capture are always on (no clock reads, no device
        # work) and the ledger is the single source of truth behind
        # counters.compiles; the phase profiler reads the injectable clock at
        # phase boundaries and is gated on serving_perf.enabled so the off
        # path performs zero extra clock reads (byte-identical FakeClock runs)
        self.perf_cfg = self.config.serving_perf
        self.ledger = CompileLedger(self.counters, tracer=self.tracer)
        self.phase_profiler = StepPhaseProfiler(self.perf_cfg, clock=self._clock,
                                                tracer=self.tracer)
        self.roofline = RooflineModel(self.perf_cfg)
        self.batch_state = DeviceBatchState(
            self.counters, mesh=self.topology.mesh if self.tp > 1 else None,
            ledger=self.ledger)
        # speculative decoding (ISSUE 20): drafter + adaptive-k controller +
        # accounting behind the fused draft/verify path (decode_spec).
        # Constructed only when the section is armed — with spec off (the
        # default) every seam below (tokens, counters, journal bytes,
        # Prometheus exposition) is byte-identical to the pre-spec stack.
        self.spec_cfg = self.config.serving_spec_decode
        self.spec_stats: Optional[SpecDecodeStats] = None
        self._spec_controller: Optional[AdaptiveKController] = None
        self._drafter = None
        if self.spec_cfg.enabled:
            self.spec_stats = SpecDecodeStats()
            self._spec_controller = AdaptiveKController(self.spec_cfg)
            if self.spec_cfg.drafter == "ngram":
                self._drafter = NgramDrafter(self.spec_cfg.ngram_max,
                                             self.spec_cfg.ngram_min)
            # drafter == "model": speculation stays dormant (plain burst)
            # until the caller provides weights via attach_draft_model()
        self._inflight: Optional[DeferredTokens] = None
        self._table_width = 0
        self._table_slack = 0
        # health() freshness stamp: advanced at state-change boundaries
        # (wave-boundary / serve-end _refresh_kv), NOT per health() call —
        # the cached /healthz snapshot must mirror health() verbatim
        self._health_generated_at = self._clock()
        log_dist(f"InferenceEngineV2: blocks={num_blocks}x{block_size} "
                 f"budget={token_budget} dtype={self.config.dtype} tp={self.tp} "
                 f"fastpath={'on' if self.fastpath.enabled else 'off'}", ranks=[0])
        # first ops snapshot at attach, so a scrape between construction and
        # the first serve sees real (zeroed) families instead of an empty body
        self.refresh_ops(force=True)

    def _warn_truncated_nucleus(self):
        """One-time runtime notice when TP candidate-set sampling approximates
        top-p (ADVICE r5): with ``top_p < 1`` each shard contributes k' =
        max(top_k, 64) candidates, so tail mass outside the k'*tp candidate
        set is redistributed unless k'*tp covers the vocabulary."""
        vocab = getattr(self.model_config, "vocab_size", None)
        if self.tp <= 1 or vocab is None or not self.config.top_p < 1.0:
            return
        kc = max(int(self.config.top_k) if self.config.top_k else 0, 64)
        if kc * self.tp < int(vocab):
            from ...utils.logging import warning_once
            warning_once(
                f"InferenceEngineV2: top_p={self.config.top_p} with tp={self.tp} uses the "
                f"truncated-nucleus approximation — sampling sees {kc}*{self.tp}="
                f"{kc * self.tp} candidates of V={int(vocab)}, so nucleus mass outside the "
                f"per-shard top-{kc} sets is redistributed; raise top_k to widen coverage "
                f"if exact top-p sampling matters")

    def _shard_mapped(self, inner, out_specs):
        """Wrap a (params, kv, *replicated) forward for TP: replicated
        activations in, sharded params/KV, psums inside via tp_axis."""
        n_rep = len(inspect.signature(inner).parameters) - 2
        rep = tuple(PartitionSpec() for _ in range(n_rep))
        return shard_map(inner, mesh=self.topology.mesh,
                         in_specs=(self._param_specs, self._kv_specs) + rep,
                         out_specs=out_specs, check_vma=False)

    # ------------------------------------------------------------------ intake
    def put(self, uids: Sequence[int], prompts: Sequence[Sequence[int]],
            ttl_s: Optional[float] = None, *, tenant: Optional[str] = None,
            service_class: Optional[str] = None) -> None:
        """Enqueue requests directly into the state manager (reference
        engine_v2.put:107), bypassing the admission queue — the step()-level
        API for callers running their own loop.  ``ttl_s`` stamps a deadline
        that step() enforces between forwards: an expired sequence is evicted
        (done, ``finish_reason: deadline_expired``, blocks reclaimed) before
        the next ragged batch is scheduled.

        ``tenant``/``service_class`` (ISSUE 19) stamp QoS identity on the
        whole batch: the prefix cache keys on the tenant and the per-tenant
        gauges attribute the load.  put() bypasses the admission queue, so
        quota SHEDDING does not apply here — callers running their own loop
        own their own backpressure — but identity and accounting do."""
        ttl = ttl_s if ttl_s is not None else self.resilience.default_ttl_s
        tenant = str(tenant) if tenant else "default"
        if self.qos is not None:
            service_class = self.qos.service_class(service_class)
        elif service_class is None:
            service_class = "interactive"
        now = None
        if ttl is not None or self.tracer.enabled:
            # one clock read covers the whole batch: the deadline stamp, the
            # flight-recorder tick, and the admit marks all share it
            now = self._clock()
            self.tracer.tick(now)
        deadline = now + ttl if ttl is not None else None
        self._reset_table_width_if_idle()
        for uid, prompt in zip(uids, prompts):
            seq = self.manager.add_sequence(int(uid), [int(t) for t in prompt],
                                            deadline=deadline, tenant=tenant,
                                            service_class=service_class)
            self._map_prefix(seq)
            if self.qos is not None:
                self.qos.note_admit(tenant, service_class, len(prompt))
            if self.journal is not None:
                # step()-level requests journal too (max_new_tokens=0: the
                # caller's own loop owns the budget) so a crash loses neither
                # path's requests; recovery re-admission targets the
                # generate()/serve_recovered contract
                self.journal.record_admit(int(uid), [int(t) for t in prompt],
                                          ttl_s=ttl, max_new_tokens=0,
                                          tenant=tenant,
                                          service_class=service_class)
            self.tracer.event("admit", uid=int(uid), direct=True)
            self.tracer.on_admit(int(uid), now, prompt_len=len(prompt),
                                 tenant=(tenant if self.qos is not None
                                         else None))
        # prefix-sharing opportunity over the post-intake live set (the put()
        # analog of _serve's per-pass observation; the new sequences are
        # already live, so no extras needed)
        self._observe_prefix({})

    def flush(self, uid: int) -> None:
        seq = self.manager.seqs.get(uid)
        finish_reason = seq.finish_reason if seq is not None else None
        failure = self.manager.failures.get(uid)
        self.manager.retire(uid)
        # step()-level callers end a request's life here: terminal status
        # mirrors retire()'s completion accounting (failures stay failed —
        # fail() marks the seq done with finish_reason None — evictions keep
        # their own status, everything else flushed-as-completed)
        if failure is not None:
            status = FAILED
        elif finish_reason in (DEADLINE_EXPIRED, PREEMPT_REQUEUED_EXHAUSTED):
            status = finish_reason
        else:
            status = OK
        if self.journal is not None and uid in self.journal.watched:
            self.journal.record_terminal(
                uid, status, finish_reason=finish_reason, reason=failure,
                n_tokens=seq.generated_tokens if seq is not None else 0)
        self.tracer.on_terminal(uid, status, finish_reason=finish_reason,
                                reason=failure, t=self.tracer.last_now)

    def _reset_table_width_if_idle(self) -> None:
        """Fresh serve (no tracked sequences): drop the sticky table width so
        a repeated scenario replays the same width trajectory — and therefore
        hits the same compiled programs — as its first run."""
        if not self.manager.seqs:
            self._table_width = 0
            self._table_slack = 0

    # ------------------------------------------------------------------- step
    def _build_fwd_jit(self):
        model, cfg, bs = self.model, self.model_config, self.block_size
        if self.tp > 1:
            def fwd(params, kv, tokens, n_tokens, start_pos, tables):
                return model.forward_paged(cfg, params, tokens, n_tokens, start_pos,
                                           tables, kv, block_size=bs,
                                           tp_axis=TENSOR_AXIS)
            fwd = self._shard_mapped(fwd, (PartitionSpec(), self._kv_specs))
        else:
            def fwd(params, kv, tokens, n_tokens, start_pos, tables):
                return model.forward_paged(cfg, params, tokens, n_tokens, start_pos,
                                           tables, kv, block_size=bs)
        return jax.jit(fwd, donate_argnums=(1, ))  # dslint: disable=donation-after-use  # call-site contract: step() reassigns self.kv from the result in the same statement (the KV pool is donated so decode updates alias in place)

    def _compiled_fwd(self, n: int, t: int, b: int):
        key = (n, t, b)
        if key not in self._fwd_cache:
            try:
                # compile ahead-of-time even for buckets the prewarm missed:
                # the ledger gets the real compile wall time and the roofline
                # gets cost_analysis coverage for EVERY dispatched bucket,
                # instead of only the prewarmed ones (ISSUE 16)
                self._aot_compile_fwd(n, t, b, prewarmed=False)
            except Exception:
                # AOT lowering can fail where plain jit works (backend
                # quirks); serving must degrade to the lazy wrapper, not die
                self._fwd_cache[key] = self._build_fwd_jit()
                # lazy jit wrapper: XLA compiles at first dispatch, so the
                # wall time shows up in the dispatch phase histogram instead
                self.ledger.record("fwd", key)
        return self._fwd_cache[key]

    def _aot_compile_fwd(self, n: int, t: int, b: int, *,
                         prewarmed: bool = True) -> None:
        """Prewarm one (n_seqs, chunk, table_width) bucket ahead of the serve
        loop: lower + compile the ragged forward against abstract shapes and
        cache the executable, so the first mid-wave step that lands in the
        bucket dispatches instead of stalling p95 on a compile.

        Under TP the avals carry the engine's mesh shardings (params/KV
        sharded per their specs, batch buffers replicated — exactly what
        DeviceBatchState commits at dispatch): an unsharded lowering would
        build an executable the first sharded dispatch could never hit, so
        the "prewarm" would silently recompile mid-wave anyway."""
        key = (n, t, b)
        if key in self._fwd_cache:
            return
        if self.tp > 1:
            rep = self.topology.replicated()
            ints = lambda shape: jax.ShapeDtypeStruct(shape, jnp.int32, sharding=rep)
            abstract = lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                                      sharding=x.sharding)
        else:
            ints = lambda shape: jax.ShapeDtypeStruct(shape, jnp.int32)
            abstract = lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
        # time.perf_counter, not the injectable clock: this is a host-side
        # duration (XLA compiles synchronously here), and reading the engine
        # clock would shift FakeClock-driven deadline semantics with the
        # observatory on — the ledger must never perturb what it measures
        t0 = time.perf_counter()  # dslint: disable=raw-clock-in-serving  # genuinely wall-clock-only: measuring the synchronous XLA compile itself; reading the injectable clock here would shift FakeClock-driven deadline semantics with the observatory on
        compiled = self._build_fwd_jit().lower(
            jax.tree_util.tree_map(abstract, self.params),
            jax.tree_util.tree_map(abstract, self.kv),
            ints((n, t)), ints((n, )), ints((n, )), ints((n, b))).compile()
        self._fwd_cache[key] = compiled
        self.ledger.record("fwd", key, wall_s=time.perf_counter() - t0,  # dslint: disable=raw-clock-in-serving  # same stopwatch as t0 above — host compile duration, never the engine clock
                           prewarmed=prewarmed)
        if self.perf_cfg.capture_cost_analysis:
            # the ONE seam holding a compiled executable: capture the
            # compiler's own per-invocation cost numbers for the roofline
            # (plain floats cross into monitor/perf.py — never a jax object)
            try:
                cost = compiled.cost_analysis()
                if isinstance(cost, list):  # older jax returns [dict]
                    cost = cost[0] if cost else {}
                self.roofline.note_cost(key, float(cost.get("flops", 0.0)),
                                        float(cost.get("bytes accessed", 0.0)))
            except Exception:  # dslint: disable=silent-except  # cost analysis is best-effort: some backends/executables can't report costs, and the roofline must never break prewarm
                pass

    def _cow_copy_block(self, src: int, dst: int) -> None:
        """Copy-on-write block duplication (ISSUE 13): copy one KV block's
        contents device-side so a fully-prefix-cached prompt's single
        recomputed position writes a PRIVATE block, never a shared one.  One
        compiled program serves every copy (src/dst ride as a traced [2]
        array); every paged cache in the model zoo lays blocks on axis 1
        ([L, num_blocks, ...] — models/transformer.py), which this relies on."""
        fn = self._fwd_cache.get("cow_copy")
        if fn is None:
            def copy(kv, pair):
                return jax.tree_util.tree_map(
                    lambda leaf: leaf.at[:, pair[1]].set(leaf[:, pair[0]]), kv)
            if self.tp > 1:
                # the pool's head-sharding must survive the copy: pin
                # out_shardings to the live pool's NamedShardings so the
                # donated sharded pool aliases in place instead of degrading
                # to a gather + single-device copy
                kv_sh = jax.tree_util.tree_map(lambda leaf: leaf.sharding, self.kv)
                fn = jax.jit(copy, donate_argnums=(0, ), out_shardings=kv_sh)
            else:
                fn = jax.jit(copy, donate_argnums=(0, ))
            self._fwd_cache["cow_copy"] = fn
            self.ledger.record("cow_copy", "cow_copy")
        self.counters.dispatches += 1
        self.counters.uploads += 1
        self.counters.upload_ints += 2
        self.kv = fn(self.kv, jnp.asarray([src, dst], jnp.int32))

    # batch-shape bucketing shares the ONE pow2 primitive with the scatter-row
    # padding in fastpath.DeviceBatchState (divergence would multiply shapes)
    _bucket = staticmethod(round_up_pow2)

    def _stepped_width(self, blocks: int) -> int:
        """Block-table width rounded up in TABLE_STEP-slot increments, capped
        at max_blocks_per_seq — shared by the live bucketing (hysteresis) and
        the prewarm's bucket prediction so the two can't drift apart."""
        return min(-(-blocks // self.TABLE_STEP) * self.TABLE_STEP,
                   self.max_blocks_per_seq)

    def _table_width_for(self, need: int) -> int:
        """Bucketed block-table width for this step's batch.

        Fast path: round ``need`` up in TABLE_STEP-slot increments with sticky
        hysteresis — the width never shrinks until TABLE_SHRINK_PATIENCE
        consecutive steps had at least a full step of slack.  The paged
        kernel's grid walks every table slot, so stepped widths waste at most
        TABLE_STEP-1 dead slots (pure doubling wastes up to 2x), and the
        stickiness keeps one long sequence joining/leaving the batch from
        recompiling every (n, t) bucket it touches.  Reference mode
        (``serving_fastpath.enabled=False``) keeps the original pure-doubling
        behavior as the equivalence oracle."""
        need = min(need, self.max_blocks_per_seq)
        if not self.fastpath.enabled:
            return min(self._bucket(need), self.max_blocks_per_seq)
        stepped = self._stepped_width(need)
        w = self._table_width
        if stepped > w:
            w = stepped
            self._table_slack = 0
        elif stepped <= w - self.TABLE_STEP:
            self._table_slack += 1
            if self._table_slack >= self.TABLE_SHRINK_PATIENCE:
                w = stepped
                self._table_slack = 0
        else:
            self._table_slack = 0
        self._table_width = w
        return w

    def step(self, greedy: bool = True) -> Dict[int, int]:
        """Run one SplitFuse step; returns {uid: sampled_token} for sequences
        that produced a next token (finished prefill or decoded).

        With the serving fast path enabled this is dispatch + immediate
        materialize over the persistent device batch buffers; the serve loop
        uses the split halves directly to defer the materialize by one step.
        TP-sharded engines ride the same path (ISSUE 15): DeviceBatchState
        replicates its buffers over the mesh, so the shard_mapped forward
        consumes them with zero resharding."""
        if not self.fastpath.enabled:
            return self._step_reference(greedy)
        deferred = self._dispatch_step(greedy)
        if deferred is None:
            return {}
        return deferred.patch(self.manager)

    def _dispatch_step(self, greedy: bool) -> Optional[DeferredTokens]:
        """Fast-path step dispatch: incrementally scatter this step's deltas
        into the bucket's persistent device buffers, launch forward + pick,
        and return a :class:`DeferredTokens` handle WITHOUT waiting on the
        sampled tokens.  Emitting sequences get a PENDING_TOKEN placeholder
        (count-accurate for scheduling) that ``patch()`` later overwrites; a
        decode row whose input token is still in flight is fed on-device from
        the previous step's sampled tokens and never visits the host."""
        self._expire_live()  # TTL enforcement between forwards, never mid-batch
        chunks = self.scheduler.schedule(self.manager)
        if not chunks:
            return None
        self.tracer.event("dispatch", step=self.scheduler.steps, seqs=len(chunks),
                          tokens=sum(c.n_tokens for c in chunks))
        if self.tracer.enabled:  # don't build the chunk list for an early-return
            self.tracer.on_chunks([(c.uid, c.n_tokens) for c in chunks],
                                  step=self.scheduler.steps)
        n = self._bucket(len(chunks))
        t = self._bucket(max(c.n_tokens for c in chunks))
        # bucket the table width to the live maximum: the paged kernel's grid
        # walks every table slot, so dead trailing slots are pure waste
        b = self._table_width_for(max(len(self.manager.seqs[c.uid].blocks)
                                      for c in chunks))
        key = (n, t, b)
        rows = []
        feeds = []
        tokens_run = 0
        for i, c in enumerate(chunks):
            seq = self.manager.seqs[c.uid]
            sl = seq.tokens[seq.seen_tokens:seq.seen_tokens + c.n_tokens]
            packed = np.zeros(3 + t + b, np.int32)
            packed[0] = i
            if c.n_tokens == 1 and sl[0] == PENDING_TOKEN:
                # the input token is the previous step's sample, still on
                # device: feed it device-side instead of waiting for it
                if self._inflight is None or c.uid not in self._inflight.row_of:
                    raise RuntimeError(f"uid {c.uid}: pending token scheduled with no "
                                       f"in-flight step to feed it from")
                feeds.append((i, self._inflight.row_of[c.uid]))
                packed[1] = FED_SENTINEL
            else:
                packed[1:1 + len(sl)] = sl
            packed[1 + t] = c.n_tokens
            packed[2 + t] = seq.seen_tokens
            packed[3 + t:] = self.manager.block_table_row(seq, width=b)
            rows.append((i, packed))
            tokens_run += c.n_tokens
        slot = self.batch_state.update(key, rows, n_active=len(chunks),
                                       trash_block=self.manager.trash_block)
        if feeds:
            self.batch_state.feed(key, self._inflight.toks_dev, feeds)
        self.phase_profiler.mark("scatter_upload")
        fwd = self._compiled_fwd(n, t, b)
        self.counters.dispatches += 1
        logits, self.kv = fwd(self.params, self.kv, slot.tokens, slot.n_tokens,
                              slot.start_pos, slot.tables)
        # token selection runs ON DEVICE (argmax or temperature/top-k/top-p
        # sampling) — only n ints cross the host link, not [n, V] logits
        # (reference: ragged sampling stays device-side, engine_v2.py:107)
        pick = self._compiled_step_pick(n, greedy)
        self.counters.dispatches += 1
        toks_dev, self._rng = pick(logits, slot.n_tokens, self._rng)
        self.phase_profiler.mark("dispatch")
        self.roofline.note_dispatch(key, tokens_run)
        emits = []
        row_of: Dict[int, int] = {}
        for i, c in enumerate(chunks):
            seq = self.manager.seqs[c.uid]
            seq.seen_tokens += c.n_tokens
            # prompt blocks this chunk just completed become shareable
            self.manager.register_prefix_blocks(seq)
            if seq.seen_tokens >= len(seq.tokens):
                # produced a next token (end of prompt, or a decode step)
                seq.tokens.append(PENDING_TOKEN)
                emits.append((c.uid, len(seq.tokens) - 1, i))
                row_of[c.uid] = i
        self.counters.step_tokens += len(emits)
        self._kv_steps += 1
        self._refresh_kv()
        self._emit_serving_gauges(tokens_run=tokens_run)
        return DeferredTokens(toks_dev=toks_dev, emits=emits, row_of=row_of,
                              counters=self.counters, tracer=self.tracer,
                              journal=self.journal)

    def _step_reference(self, greedy: bool) -> Dict[int, int]:
        """The pre-fastpath step: full host-side batch rebuild + four uploads
        + synchronous fetch per step.  Kept verbatim as the equivalence oracle
        (``serving_fastpath.enabled=False``) the fastpath tests diff against."""
        self._expire_live()
        chunks = self.scheduler.schedule(self.manager)
        if not chunks:
            return {}
        self.tracer.event("dispatch", step=self.scheduler.steps, seqs=len(chunks),
                          tokens=sum(c.n_tokens for c in chunks))
        if self.tracer.enabled:  # don't build the chunk list for an early-return
            self.tracer.on_chunks([(c.uid, c.n_tokens) for c in chunks],
                                  step=self.scheduler.steps)
        n = self._bucket(len(chunks))
        t = self._bucket(max(c.n_tokens for c in chunks))
        b = self._table_width_for(max(len(self.manager.seqs[c.uid].blocks)
                                      for c in chunks))
        tokens = np.zeros((n, t), np.int32)
        n_tokens = np.zeros((n, ), np.int32)
        start_pos = np.zeros((n, ), np.int32)
        tables = np.full((n, b), self.manager.trash_block, np.int32)
        for i, c in enumerate(chunks):
            seq = self.manager.seqs[c.uid]
            sl = seq.tokens[seq.seen_tokens:seq.seen_tokens + c.n_tokens]
            tokens[i, :len(sl)] = sl
            n_tokens[i] = c.n_tokens
            start_pos[i] = seq.seen_tokens
            tables[i] = self.manager.block_table_row(seq, width=b)

        fwd = self._compiled_fwd(n, t, b)
        self.counters.dispatches += 2
        # five uploads: four batch arrays into the forward + n_tokens again
        # into the pick (the fast path derives the pick's input on device)
        self.counters.uploads += 5
        self.counters.upload_ints += int(tokens.size + 2 * n_tokens.size
                                         + start_pos.size + tables.size)
        logits, self.kv = fwd(self.params, self.kv, jnp.asarray(tokens), jnp.asarray(n_tokens),
                              jnp.asarray(start_pos), jnp.asarray(tables))
        pick = self._compiled_step_pick(n, greedy)
        toks_dev, self._rng = pick(logits, jnp.asarray(n_tokens), self._rng)
        toks = materialize(toks_dev, self.counters)  # one sync: n sampled ints

        out: Dict[int, int] = {}
        for i, c in enumerate(chunks):
            seq = self.manager.seqs[c.uid]
            seq.seen_tokens += c.n_tokens
            # prompt blocks this chunk just completed become shareable
            self.manager.register_prefix_blocks(seq)
            if seq.seen_tokens >= len(seq.tokens):
                tok = int(toks[i])
                seq.tokens.append(tok)
                out[c.uid] = tok
        self.counters.step_tokens += len(out)
        self.tracer.event("absorb", step=self.scheduler.steps, tokens=len(out))
        self.tracer.on_tokens_map(out)
        if self.journal is not None:
            self.journal.note_token_map(out)
        self._kv_steps += 1
        self._refresh_kv()
        self._emit_serving_gauges(tokens_run=int(n_tokens.sum()))
        return out

    def _gauge_timestamp(self) -> Optional[float]:
        """Deterministic gauge timestamp when the engine runs on an injected
        clock (FakeClock tests): the clock's last donated read.  None keeps
        record_gauges' wall-clock default — unchanged production behavior."""
        return self.tracer.last_now if self._clock_injected else None

    # ------------------------------------------------------ kv observability
    def _refresh_kv(self) -> None:
        """Wave-boundary census/forecast refresh (ISSUE 12): update per-block
        residency + last-touched stamps from ``seen_tokens``, sample the
        alloc/free rates into the capacity forecaster, land pressure-edge
        events in the flight recorder, and append a Chrome-trace counter-track
        sample when a trace export is configured.  Pure host arithmetic over
        ints the engine already owns — zero device syncs, and no effect on
        ``ServeCounters`` (the kv-obs smoke pins byte-identity on vs off)."""
        self._health_generated_at = self._clock()
        if self.kv_obs is None:
            return
        free = self.manager.allocator.free_blocks
        self.kv_obs.refresh(self.manager.seqs, self._kv_steps, free)
        crossing = self.kv_obs.pressure_crossing()
        if crossing is not None:
            edge, ste = crossing
            self.tracer.event(
                "kv_pressure", step=self.scheduler.steps, edge=edge,
                steps_to_exhaustion=None if ste == float("inf") else round(ste, 1),
                free_blocks=free)
        if self.tracer.config.chrome_trace_path:
            # only assemble the counter-track payload when an export will
            # actually consume it — fragmentation_tokens() walks the census
            census = self.kv_obs.census
            ste = self.kv_obs.forecaster.steps_to_exhaustion()
            self.tracer.counter_track("kv_pool", {
                "allocated_blocks": census.allocated_blocks,
                "free_blocks": free,
                "fragmentation_tokens": census.fragmentation_tokens(),
                **({} if ste is None else {"steps_to_exhaustion": round(ste, 1)}),
            })

    def _observe_prefix(self, extra_prompts: Dict[int, List[int]]) -> None:
        """One PrefixObservatory pass over live + admitted requests: every
        live sequence contributes its PROMPT portion (generated tokens are
        never shareable read-only), ``extra_prompts`` the not-yet-admitted
        prompts of the current intake (queued tickets / a put() batch)."""
        if self.kv_obs is None:
            return
        obs = self.kv_obs.prefix
        # cache-aware: a live uid whose hashes are already cached passes None
        # (no token-list slice built) — an intake over a large live set costs
        # dict lookups, not prompt copies
        prompts: Dict[int, Optional[List[int]]] = {
            uid: (None if obs.has(uid) else seq.tokens[:seq.prompt_len])
            for uid, seq in self.manager.seqs.items() if not seq.done}
        prompts.update(extra_prompts)
        obs.observe(prompts)

    def _map_prefix(self, seq) -> int:
        """Admit-time shared-prefix mapping with the hit landed in the flight
        recorder (the scheduler's per-chunk late-binding remap shares the
        manager seam but skips the event — per-step noise)."""
        mapped = self.manager.map_prefix(seq)
        if mapped:
            self.tracer.event("prefix_hit", uid=seq.uid, tokens=mapped,
                              blocks=len(seq.blocks))
        return mapped

    def _forget_prefix(self, uid: int) -> None:
        """Invalidate a uid's PrefixObservatory hash cache for a request that
        dies WITHOUT ever becoming a live sequence (queue expiry, stall
        drain, strict-abort drain) — live sequences invalidate through the
        census's retirement listener, but a queued-only ticket never reaches
        ``retire()``, and a stale entry would credit the uid's NEXT life with
        the dead prompt's hashes (phantom sharing)."""
        if self.kv_obs is not None:
            self.kv_obs.prefix.forget(uid)

    def check_kv_invariant(self) -> None:
        """Census-vs-allocator invariant: the census's owned-block set must
        exactly partition against the allocator free list (no block owned
        while free, none leaked).  Raises ``CensusInvariantError`` naming the
        offending uid/block.  Run automatically after every serve pass
        (``serving_kv_observability.invariant_check``); public so smokes and
        fault-injection tests can assert it at arbitrary points.  With prefix
        sharing the live sequences ride along, so the refcount-agreement and
        shared-content (no-request-observes-another's-KV) checks run too."""
        if self.kv_obs is not None:
            self.kv_obs.check_invariant(self.manager.allocator, self.manager.seqs)

    # ---------------------------------------------------------- ops endpoints
    def refresh_ops(self, force: bool = False) -> None:
        """Refresh the host-side ops snapshots the scrape handlers serve:
        re-populate the metrics registry from engine state (all python ints/
        floats the host already owns — zero device syncs, dslint-enforced on
        the whole ops plane), re-render the Prometheus text, re-dump
        ``health()``/``state_snapshot()`` JSON, and republish the per-rank
        exchange files when a supervisor exported ``DSTPU_OPS_DIR``.

        Called from the serve loop (throttled on the injectable clock to one
        refresh per ``ops_server.refresh_interval_s``) and force-called at
        attach and serve end.  A no-op when the ops plane is off — the
        byte-identical ServeCounters guarantee of the ops-smoke."""
        if self._ops is None:
            return
        from ...monitor.metrics import populate_from_engine
        self._ops.refresh(lambda reg: populate_from_engine(reg, self),
                          now=self.tracer.last_now, force=force,
                          healthz=lambda: json.dumps(self.health()),
                          statez=lambda: json.dumps(self.state_snapshot()))

    def close_ops(self) -> None:
        """Shut the ops HTTP listener down (tests / clean teardown)."""
        if self._ops is not None:
            self._ops.close()

    def _emit_serving_gauges(self, tokens_run: int) -> None:
        """Serving rates on top of the scheduler's per-step gauges: requests/s
        (retired-sequence rate) and tokens/s through the ragged forward."""
        if self.telemetry is None:
            return
        c = self.counters
        gauges = {"live_seqs": float(len(self.manager.live_uids())),
                  # resilience gauges (ISSUE 4): shed/preempt/deadline lifetime
                  # counters + last admission wait, next to the serving rates
                  "admission_queue_depth": float(len(self.admission)),
                  "shed_total": float(self.admission.shed_total),
                  "preempted_total": float(self.scheduler.preempted_total),
                  "deadline_expired_total": float(self._deadline_expired_total),
                  "queue_wait": float(self._queue_wait_s),
                  # fastpath gauges (ISSUE 5): the host-link cost of serving —
                  # device->host syncs, program dispatches, compiled buckets,
                  # ints uploaded, and the fraction of tokens emitted fused
                  "fastpath_host_syncs": float(c.host_syncs),
                  "fastpath_dispatches": float(c.dispatches),
                  "fastpath_compiled_programs": float(c.compiles),
                  "fastpath_upload_ints": float(c.upload_ints),
                  "fastpath_burst_fraction":
                      c.burst_tokens / max(c.burst_tokens + c.step_tokens, 1)}
        if self.kv_obs is not None:
            # KV-pool gauges (ISSUE 12) under the unified serving_kv_*
            # spelling — the same names the metrics registry exports, so the
            # telemetry stream and /metrics can't drift apart again
            census, fc = self.kv_obs.census, self.kv_obs.forecaster
            ste = fc.steps_to_exhaustion()
            gauges.update({
                "kv_free_blocks": float(self.manager.allocator.free_blocks),
                "kv_utilization": self.manager.kv_utilization(),
                "kv_fragmentation_tokens": float(census.fragmentation_tokens()),
                "kv_alloc_rate": fc.alloc_rate,
                "kv_free_rate": fc.free_rate,
                **({} if ste is None else {"kv_steps_to_exhaustion": float(ste)}),
            })
        pc = self.manager.prefix_cache
        if pc is not None:
            # realized prefix-cache savings (ISSUE 13) next to the
            # counterfactual the observatory reports — same spelling the
            # metrics registry exports
            gauges.update({
                "kv_prefix_hits": float(pc.hit_blocks_total),
                "kv_prefill_tokens_saved": float(pc.tokens_saved_total),
                "kv_prefix_realized_hit_rate": pc.realized_hit_rate(),
            })
        # SLO percentile gauges (ISSUE 6): ttft/tbt/e2e/queue_wait p50/p95/p99
        # from the tracer's streaming histograms ({} while tracing is off)
        gauges.update(self.tracer.gauge_fields())
        # live roofline gauges (ISSUE 16): HBM bytes/token and achieved
        # fractions of the HBM/FLOPs specs — meaningful rates need measured
        # wall time, which only the enabled phase profiler accumulates
        if self.perf_cfg.enabled:
            gauges.update(self.roofline.gauges(self.phase_profiler.wall_s))
            gauges["serving_warm_recompiles"] = float(self.ledger.warm_total)
        rps = self.telemetry.rate("v2_completed_requests",
                                  float(self.manager.completed_requests))
        if rps is not None:
            gauges["requests_per_sec"] = rps
        self._tokens_run_total = getattr(self, "_tokens_run_total", 0) + tokens_run
        tps = self.telemetry.rate("v2_tokens_total", float(self._tokens_run_total))
        if tps is not None:
            gauges["tokens_per_sec"] = tps
        self.telemetry.record_gauges(gauges, step=self.scheduler.steps,
                                     prefix="Inference/Serving",
                                     timestamp=self._gauge_timestamp())

    def _phase_annotation(self, name: str):
        """jax.profiler TraceAnnotation for one serve phase while a capture
        window is open (ISSUE 16 satellite) — a nullcontext otherwise, so the
        un-profiled serve loop pays one attribute check per phase."""
        t = self.telemetry
        if t is not None and t.tracing:
            return t.annotation(name)
        return contextlib.nullcontext()

    def _perf_snapshot(self) -> Dict[str, Any]:
        """Host-side perf observatory snapshot (ISSUE 16): phase attribution,
        compile ledger, roofline — everything health()/statez surface."""
        snap = self.phase_profiler.snapshot()  # enabled/iterations/wall_s/phases
        snap["compile_ledger"] = self.ledger.snapshot()
        snap["roofline"] = self.roofline.snapshot(self.phase_profiler.wall_s)
        return snap

    def _compiled_step_pick(self, n: int, greedy: bool):
        key = ("pick", n, greedy, self.config.temperature, self.config.top_k,
               self.config.top_p)
        if key not in self._fwd_cache:
            from ..engine import _sample
            temperature, top_k, top_p = (self.config.temperature, self.config.top_k,
                                         self.config.top_p)

            def pick(logits, n_tokens, rng):
                # last valid position per row, derived on device so the host
                # uploads nothing pick-specific
                last = jnp.maximum(n_tokens - 1, 0)
                row = jnp.take_along_axis(logits, last[:, None, None], axis=1)[:, 0]
                if greedy:
                    return jnp.argmax(row, axis=-1).astype(jnp.int32), rng
                return _sample(row, rng, temperature=temperature, top_k=top_k, top_p=top_p)

            self._fwd_cache[key] = jax.jit(pick)
            self.ledger.record("pick", key)
        return self._fwd_cache[key]

    # ------------------------------------------------------------ decode burst
    def _compiled_burst(self, n: int, k: int, sample_cfg=None, eos: int = -1):
        """``sample_cfg``: None => greedy; (temperature, top_k, top_p) =>
        on-device sampling with the rng carried through the scan.  ``eos`` >= 0
        makes decode eos-aware: a finished row freezes (re-emits its token) and
        its done flag streams out alongside the tokens."""
        key = ("burst", n, k, sample_cfg, eos)
        if key not in self._fwd_cache:
            from ..engine import _sample
            model, cfg, bs = self.model, self.model_config, self.block_size
            ones = jnp.ones((n, ), jnp.int32)
            sampling = sample_cfg is not None
            if self.tp > 1:
                tp_kw = {"tp_axis": TENSOR_AXIS, "gather_logits": False}
                vocab = getattr(cfg, "vocab_size", None)

                if sampling:
                    # sampled TP decode stays in the same wire-cost class as
                    # greedy via candidate-set sampling (VERDICT r4 #4)
                    temperature, top_k, top_p = sample_cfg

                    def pick(row, rng):  # row [N, V_local]
                        if vocab is not None and row.shape[-1] == vocab:
                            return _sample(row, rng, temperature=temperature,
                                           top_k=top_k, top_p=top_p)
                        return candidate_sample(row, rng, temperature=temperature,
                                                top_k=top_k, top_p=top_p,
                                                axis=TENSOR_AXIS)
                else:
                    # vocab-parallel greedy: argmax the LOCAL logit shard and
                    # reduce (max value, then first-occurrence index) with O(1)
                    # scalars per token over ICI instead of O(V) gathers
                    def pick(row, rng):  # row [N, V_local]
                        if vocab is not None and row.shape[-1] == vocab:
                            return jnp.argmax(row, axis=-1).astype(jnp.int32), rng
                        vlocal = row.shape[-1]
                        local_idx = jnp.argmax(row, axis=-1).astype(jnp.int32)
                        local_val = jnp.max(row, axis=-1)
                        best = jax.lax.pmax(local_val, TENSOR_AXIS)
                        offset = jax.lax.axis_index(TENSOR_AXIS).astype(jnp.int32) * vlocal
                        cand = jnp.where(local_val == best, local_idx + offset,
                                         jnp.int32(2**31 - 1))
                        return jax.lax.pmin(cand, TENSOR_AXIS).astype(jnp.int32), rng
            else:
                tp_kw = {}
                if sampling:
                    temperature, top_k, top_p = sample_cfg

                    def pick(row, rng):
                        return _sample(row, rng, temperature=temperature,
                                       top_k=top_k, top_p=top_p)
                else:
                    pick = lambda row, rng: (jnp.argmax(row, axis=-1).astype(jnp.int32), rng)

            def burst(params, kv, tok0, start0, tables, rng0, done0):
                def body(carry, _):
                    kv, tok, start, rng, done = carry
                    logits, kv = model.forward_paged(cfg, params, tok[:, None], ones,
                                                     start, tables, kv, block_size=bs,
                                                     **tp_kw)
                    # one split key per fused step: the rng carried through the
                    # scan is the ENGINE rng, advanced by _sample exactly as the
                    # stepwise pick advances it — burst and per-step decode
                    # sample identical tokens for the same seed
                    nxt, rng = pick(logits[:, 0], rng)
                    # finished rows freeze: re-emit the last token (the pool
                    # keeps absorbing writes into pre-allocated slots; the host
                    # truncates at the first done flag)
                    nxt = jnp.where(done, tok, nxt)
                    done = jnp.logical_or(done, nxt == jnp.int32(eos))
                    return (kv, nxt, start + 1, rng, done), (nxt, done)

                (kv, _, _, rng, _), (toks, dones) = jax.lax.scan(
                    body, (kv, tok0, start0, rng0, done0), None, length=k)
                # toks/dones ride ONE fetch: pack [K, N] tokens over [K, N]
                # done flags into a single [2K, N] int32 array
                packed = jnp.concatenate([toks, dones.astype(jnp.int32)], axis=0)
                return kv, packed, rng

            if self.tp > 1:
                burst = self._shard_mapped(
                    burst, (self._kv_specs, PartitionSpec(), PartitionSpec()))
            self._fwd_cache[key] = jax.jit(burst, donate_argnums=(1, ))  # dslint: disable=donation-after-use  # call-site contract: decode_burst() reassigns self.kv from the result in the same statement
            self.ledger.record("burst", key)
        return self._fwd_cache[key]

    def decode_burst(self, k: int, greedy: bool = True,
                     eos_token_id: Optional[int] = None) -> Optional[Dict[int, List[int]]]:
        """Run ``k`` decode steps INSIDE one compiled program — one host
        round-trip per k tokens instead of per token (the latency lever the
        reference gets from CUDA-graph decode loops; on a remote-relay
        transport this is the difference between ~4 and ~100+ tok/s/seq).

        Greedy AND sampled (temperature/top-k/top-p from the engine config)
        decode both run device-side; with ``eos_token_id`` the scan carries a
        done-mask and finished rows freeze, so the returned per-uid lists stop
        at (and include) the first eos.  Applies only when every live sequence
        is in pure decode (one pending token) and the pool can pre-allocate k
        more slots per sequence; returns None when not applicable (caller
        falls back to step()).
        """
        live, prefilling = self.scheduler.live_split(self.manager)
        if not live or prefilling:
            return None  # fuse only a pure-decode live set
        if len(live) > self.scheduler.max_seqs:
            return None
        if self._inflight is not None:
            # a deferred pick is still in flight: its placeholder would be
            # this burst's input token — patch it in first (idempotent; the
            # serve loop still absorbs the same handle afterwards)
            self._inflight.patch(self.manager)
        max_pos = getattr(self.model_config, "max_seq_len", None)
        total_new = 0
        for seq in live:
            upto = seq.seen_tokens + 1 + k
            if self.manager.over_cap(upto):
                return None
            if max_pos is not None and upto > max_pos:
                # positions past the rotary table would silently clamp — the
                # burst pre-commits k future positions, so bound them here
                return None
            total_new += self.manager.blocks_needed(seq, upto)
        if not self.manager.can_allocate(total_new):
            # check BEFORE allocating anything: a partial grab would strand
            # blocks on some sequences and starve the stepwise fallback
            return None
        grown: List = []
        try:
            for seq in live:
                prior = len(seq.blocks)
                self.manager.ensure_blocks(seq, seq.seen_tokens + 1 + k)
                grown.append((seq, prior))
        except KVAllocationError:
            # an injected/transient allocator failure mid-grab: roll every
            # sequence back to its prior table so nothing is stranded, and
            # decline — the stepwise fallback retries at finer grain.  The
            # rollback rides the manager's reclaim seam so the block census
            # stays exact through the fault path too.
            for seq, prior in grown:
                self.manager.rollback_blocks(seq, prior)
            return None

        n = self._bucket(len(live))
        b = self._table_width_for(max(len(s.blocks) for s in live))
        tok0 = np.zeros((n, ), np.int32)
        start0 = np.zeros((n, ), np.int32)
        tables = np.full((n, b), self.manager.trash_block, np.int32)
        for i, seq in enumerate(live):
            tok0[i] = seq.tokens[seq.seen_tokens]
            start0[i] = seq.seen_tokens
            tables[i] = self.manager.block_table_row(seq, width=b)
        # padded rows: decode into the trash block at position 0
        sample_cfg = None if greedy else (self.config.temperature, self.config.top_k,
                                          self.config.top_p)
        eos = -1 if eos_token_id is None else int(eos_token_id)
        burst = self._compiled_burst(n, k, sample_cfg=sample_cfg, eos=eos)
        done0 = jnp.zeros((n, ), jnp.bool_)
        self.counters.dispatches += 1
        self.counters.uploads += 3
        self.counters.upload_ints += int(tok0.size + start0.size + tables.size)
        # the scan carries the ENGINE rng itself (no pre-split): each fused
        # step consumes exactly the key the stepwise pick would, so burst and
        # per-step decode are sample-for-sample identical
        self.kv, packed, self._rng = burst(self.params, self.kv, jnp.asarray(tok0),
                                           jnp.asarray(start0), jnp.asarray(tables),
                                           self._rng, done0)
        fetched = materialize(packed, self.counters)  # ONE sync per k steps
        toks, dones = fetched[:k], fetched[k:]        # [K, N] each
        out: Dict[int, List[int]] = {}
        for i, seq in enumerate(live):
            col = toks[:, i]
            n_real = k
            if eos >= 0 and dones[:, i].any():
                n_real = int(np.argmax(dones[:, i])) + 1  # first done step, inclusive
            produced = [int(t) for t in col[:n_real]]
            seq.tokens.extend(produced)
            seq.seen_tokens += n_real
            # a burst's first position can complete the FINAL prompt block
            # (a budget split at prompt_len - 1, or the CoW copy's recompute)
            self.manager.register_prefix_blocks(seq)
            self.counters.burst_tokens += n_real
            out[seq.uid] = produced
        # fused work accounting (ISSUE 20): a k-step burst is k sequential
        # steps' worth of decode work, without ever advancing scheduler.steps
        self.scheduler.note_fused_work(k, sum(len(v) for v in out.values()))
        self.tracer.event("burst", step=self.scheduler.steps, k=k, seqs=len(live))
        self.tracer.on_burst_tokens({uid: len(toks_) for uid, toks_ in out.items()})
        if self.journal is not None:
            # a burst IS a wave boundary: the host just materialized k tokens
            # per sequence in one sync, so the WAL appends one delta frame
            # here at zero extra device cost
            self.journal.note_token_map(out)
            self.journal.flush()
        # the burst is the dominant emission path: emit the serving gauges
        # here too, so burst-heavy serves surface fresh SLO percentiles and
        # burst-fraction instead of only dispatch-time snapshots
        self._kv_steps += k
        self._refresh_kv()
        self._emit_serving_gauges(tokens_run=sum(len(v) for v in out.values()))
        return out

    # ----------------------------------------------------- speculative decode
    def attach_draft_model(self, model_module, model_config, params, *,
                           num_blocks: Optional[int] = None,
                           block_size: Optional[int] = None) -> None:
        """Arm ``drafter: "model"`` spec decode with a small draft model from
        the model zoo (ISSUE 20): the drafter proposes greedily against its
        own private paged pool (catch-up + k-token scan in one compiled
        program per bucket) and its proposals feed the verify program without
        ever visiting the host.  Under TP the draft model runs fully
        replicated over the engine's mesh.  ``num_blocks``/``block_size``
        size the private pool (defaults: mirror the target pool)."""
        if not self.spec_cfg.enabled:
            raise ValueError("serving_spec_decode.enabled is off — arm the "
                             "section before attaching a draft model")
        if self.spec_cfg.drafter != "model":
            raise ValueError(f"serving_spec_decode.drafter is "
                             f"'{self.spec_cfg.drafter}', not 'model'")
        self._drafter = ModelDrafter(
            model_module, model_config, params,
            num_blocks=(num_blocks if num_blocks is not None
                        else self.manager.allocator.num_blocks),
            block_size=(block_size if block_size is not None
                        else self.block_size),
            max_blocks_per_seq=self.max_blocks_per_seq, dtype=self.dtype,
            mesh=self.topology.mesh if self.tp > 1 else None,
            ledger=self.ledger)

    def _build_spec_verify_jit(self, n: int, k: int, sample_cfg=None):
        """The fused verify program: ONE batched target forward over the
        paged pool scoring (input token + k draft tokens) per sequence, then
        the on-device rejection sampler — accept count and emitted run packed
        into one [n, k+2] int32 array so the whole round rides one fetch."""
        model, cfg, bs = self.model, self.model_config, self.block_size
        width = jnp.full((n, ), k + 1, jnp.int32)
        if self.tp > 1:
            def verify(params, kv, tok0, draft, start0, tables, rng):
                tokens = jnp.concatenate([tok0[:, None], draft], axis=1)
                logits, kv = model.forward_paged(cfg, params, tokens, width,
                                                 start0, tables, kv,
                                                 block_size=bs,
                                                 tp_axis=TENSOR_AXIS)
                packed, rng = rejection_select(logits, draft, rng,
                                               sample_cfg=sample_cfg)
                return kv, packed, rng
            verify = self._shard_mapped(
                verify, (self._kv_specs, PartitionSpec(), PartitionSpec()))
        else:
            def verify(params, kv, tok0, draft, start0, tables, rng):
                tokens = jnp.concatenate([tok0[:, None], draft], axis=1)
                logits, kv = model.forward_paged(cfg, params, tokens, width,
                                                 start0, tables, kv,
                                                 block_size=bs)
                packed, rng = rejection_select(logits, draft, rng,
                                               sample_cfg=sample_cfg)
                return kv, packed, rng
        return jax.jit(verify, donate_argnums=(1, ))  # dslint: disable=donation-after-use  # call-site contract: decode_spec() reassigns self.kv from the result in the same statement

    def _compiled_spec_verify(self, n: int, k: int, b: int, sample_cfg=None):
        key = ("spec_verify", n, k, b, sample_cfg)
        if key not in self._fwd_cache:
            try:
                self._aot_compile_spec_verify(n, k, b, sample_cfg,
                                              prewarmed=False)
            except Exception:
                # same degrade as _compiled_fwd: lazy jit when AOT lowering
                # fails — serving must not die on a backend quirk
                self._fwd_cache[key] = self._build_spec_verify_jit(n, k,
                                                                   sample_cfg)
                self.ledger.record("spec_verify", key)
        return self._fwd_cache[key]

    def _aot_compile_spec_verify(self, n: int, k: int, b: int, sample_cfg=None,
                                 *, prewarmed: bool = True) -> None:
        """Prewarm one (n_seqs, draft_k, table_width) verify bucket: the AOT
        bucket key includes the VERIFY WIDTH (k), so every rung of the
        adaptive-k ladder is a compiled executable before the serve loop can
        dispatch it — a mid-serve k drift re-uses a prewarmed program instead
        of stalling p95 on a compile (the fwd-bucket contract extended to
        spec mode).  Sharded avals under TP, same as _aot_compile_fwd."""
        key = ("spec_verify", n, k, b, sample_cfg)
        if key in self._fwd_cache:
            return
        if self.tp > 1:
            rep = self.topology.replicated()
            ints = lambda shape: jax.ShapeDtypeStruct(shape, jnp.int32, sharding=rep)
            rng_aval = jax.ShapeDtypeStruct(self._rng.shape, self._rng.dtype,
                                            sharding=rep)
            abstract = lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                                      sharding=x.sharding)
        else:
            ints = lambda shape: jax.ShapeDtypeStruct(shape, jnp.int32)
            rng_aval = jax.ShapeDtypeStruct(self._rng.shape, self._rng.dtype)
            abstract = lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
        t0 = time.perf_counter()  # dslint: disable=raw-clock-in-serving  # same contract as _aot_compile_fwd: measuring the synchronous XLA compile itself, never the engine clock
        compiled = self._build_spec_verify_jit(n, k, sample_cfg).lower(
            jax.tree_util.tree_map(abstract, self.params),
            jax.tree_util.tree_map(abstract, self.kv),
            ints((n, )), ints((n, k)), ints((n, )), ints((n, b)),
            rng_aval).compile()
        self._fwd_cache[key] = compiled
        self.ledger.record("spec_verify", key, wall_s=time.perf_counter() - t0,  # dslint: disable=raw-clock-in-serving  # same stopwatch as t0 above — host compile duration, never the engine clock
                           prewarmed=prewarmed)
        if self.perf_cfg.capture_cost_analysis:
            try:
                cost = compiled.cost_analysis()
                if isinstance(cost, list):
                    cost = cost[0] if cost else {}
                self.roofline.note_cost(key, float(cost.get("flops", 0.0)),
                                        float(cost.get("bytes accessed", 0.0)))
            except Exception:  # dslint: disable=silent-except  # cost analysis is best-effort, exactly as in _aot_compile_fwd
                pass

    def decode_spec(self, k: int, greedy: bool = True,
                    eos_token_id: Optional[int] = None
                    ) -> Optional[Dict[int, List[int]]]:
        """One speculative draft/verify round over the pure-decode live set
        (ISSUE 20): the drafter proposes ``k`` tokens per sequence, ONE
        batched target forward scores all of them against the paged pool, and
        the on-device rejection sampler emits the accepted prefix plus one
        corrected/bonus token — 1..k+1 tokens per sequence for a single
        target-weight HBM stream, distribution-exact vs plain decode (token-
        identical under greedy).

        Bookkeeping mirrors decode_burst: all-or-nothing block grab up front
        (rolled back on an injected allocator fault), ONE host sync for the
        packed accept runs, per-sequence seen-token advance by the ACCEPTED
        length with trailing draft-overshoot blocks rolled back before they
        can pollute shared prefix-cache state, WAL frames of verified tokens
        only.  Returns None when not applicable (caller falls back to the
        plain burst / stepwise paths)."""
        drafter = self._drafter
        if drafter is None:
            return None
        live, prefilling = self.scheduler.live_split(self.manager)
        if not live or prefilling:
            return None  # speculate only over a pure-decode live set
        if len(live) > self.scheduler.max_seqs:
            return None
        if any(seq.deadline is not None for seq in live):
            # deadline-armed sequences take the conservative path (the same
            # disengage rule the async pipeline follows): a spec round emits
            # a variable-length run per loop iteration, which would shift
            # eviction timing relative to the plain engine — TTL partials
            # must stay byte-identical to the spec-off stack
            return None
        if self._inflight is not None:
            # the drafter reads token HISTORY: a deferred pick still in
            # flight would leave PENDING_TOKEN placeholders in it
            self._inflight.patch(self.manager)
        max_pos = getattr(self.model_config, "max_seq_len", None)
        total_new = 0
        for seq in live:
            upto = seq.seen_tokens + 1 + k
            if self.manager.over_cap(upto):
                return None
            if max_pos is not None and upto > max_pos:
                return None
            total_new += self.manager.blocks_needed(seq, upto)
        if not self.manager.can_allocate(total_new):
            return None
        grown: List = []
        try:
            for seq in live:
                prior = len(seq.blocks)
                self.manager.ensure_blocks(seq, seq.seen_tokens + 1 + k)
                grown.append((seq, prior))
        except KVAllocationError:
            # injected/transient allocator fault mid-grab: full rollback so
            # nothing is stranded, then decline — the burst/stepwise
            # fallbacks retry at coarser/finer grain (census stays exact)
            for seq, prior in grown:
                self.manager.rollback_blocks(seq, prior)
            return None

        n = self._bucket(len(live))
        b = self._table_width_for(max(len(s.blocks) for s in live))
        tok0 = np.zeros((n, ), np.int32)
        start0 = np.zeros((n, ), np.int32)
        tables = np.full((n, b), self.manager.trash_block, np.int32)
        for i, seq in enumerate(live):
            tok0[i] = seq.tokens[seq.seen_tokens]
            start0[i] = seq.seen_tokens
            tables[i] = self.manager.block_table_row(seq, width=b)
        draft = drafter.propose_batch(live, k, n, counters=self.counters)
        if draft is None:
            # the drafter's private pool couldn't cover the round: undo the
            # target-pool grab and let the plain burst run instead
            for seq, prior in grown:
                self.manager.rollback_blocks(seq, prior)
            return None
        sample_cfg = None if greedy else (self.config.temperature,
                                          self.config.top_k, self.config.top_p)
        verify = self._compiled_spec_verify(n, k, b, sample_cfg=sample_cfg)
        self.counters.dispatches += 1
        if isinstance(draft, np.ndarray):
            self.counters.uploads += 4
            self.counters.upload_ints += int(tok0.size + start0.size
                                             + tables.size + draft.size)
            draft_dev = jnp.asarray(draft)
        else:
            # ModelDrafter proposals are already device-resident
            self.counters.uploads += 3
            self.counters.upload_ints += int(tok0.size + start0.size
                                             + tables.size)
            draft_dev = draft
        self.kv, packed, self._rng = verify(self.params, self.kv,
                                            jnp.asarray(tok0), draft_dev,
                                            jnp.asarray(start0),
                                            jnp.asarray(tables), self._rng)
        handle = DeferredRuns(packed_dev=packed, uids=[s.uid for s in live],
                              counters=self.counters)
        raw = handle.runs()  # ONE sync absorbs the whole ragged round
        bs = self.manager.block_size
        out: Dict[int, List[int]] = {}
        accepted_total = 0
        max_run = 1
        for seq in live:
            run = raw[seq.uid]
            if eos_token_id is not None:
                for j, tok in enumerate(run):
                    if tok == int(eos_token_id):
                        run = run[:j + 1]
                        break
            accepted_total += max(0, len(run) - 1)
            seq.tokens.extend(run)
            seq.seen_tokens += len(run)
            # the verify wrote KV for every draft position; positions past
            # the accepted run are stale and their trailing blocks must not
            # outlive the round — roll the table back to exactly the blocks
            # covering the kept tokens (the census and prefix registration
            # watermarks follow), before the allocator could hand a
            # drafted-into block to another sequence as "free" later
            keep = -(-len(seq.tokens) // bs)
            if len(seq.blocks) > keep:
                self.manager.rollback_blocks(seq, keep)
            # a round's first position can complete the FINAL prompt block
            # (same seam as the burst path)
            self.manager.register_prefix_blocks(seq)
            self.counters.burst_tokens += len(run)
            max_run = max(max_run, len(run))
            out[seq.uid] = run
        self.counters.spec_rounds += 1
        self.counters.spec_proposed += len(live) * k
        self.counters.spec_accepted += accepted_total
        self.spec_stats.note_round(len(live) * k, accepted_total,
                                   [len(r) for r in out.values()])
        self._spec_controller.note_round(len(live) * k, accepted_total)
        # the deepest accepted run is the round's sequential-step equivalent
        self.scheduler.note_fused_work(max_run,
                                       sum(len(r) for r in out.values()))
        self.tracer.event("spec_verify", step=self.scheduler.steps, k=k,
                          seqs=len(live), accepted=accepted_total)
        self.tracer.on_burst_tokens({uid: len(r) for uid, r in out.items()})
        if self.journal is not None:
            # VERIFIED tokens only ever reach the WAL: the accepted prefix +
            # corrected token just materialized is the frame — an unverified
            # draft token can never be journaled, so replay of a crash
            # mid-verify regenerates byte-identical streams
            self.journal.note_token_map(out)
            self.journal.flush()
        self._kv_steps += max_run
        self._refresh_kv()
        self._emit_serving_gauges(tokens_run=sum(len(r) for r in out.values()))
        return out

    def _fused_decode(self, window: int, *, greedy: bool,
                      eos_token_id: Optional[int]
                      ) -> Optional[Dict[int, List[int]]]:
        """Dispatch one fused decode round: speculative draft/verify when the
        section is armed and the adaptive-k controller is off its floor,
        plain burst otherwise.  The draft length is snapped DOWN to the
        largest ladder rung fitting both the controller's pick and the
        remaining-budget window (emitting at most window tokens per
        sequence), so every dispatched verify width is a prewarmable bucket
        — never an off-ladder shape that would compile mid-serve."""
        if self._drafter is not None and self._spec_controller is not None:
            nk = self._spec_controller.next_k()
            if nk > 1:
                cap = min(nk, window - 1)
                k_d = max((r for r in self._spec_controller.ladder if r <= cap),
                          default=0)
                if k_d >= 1:
                    out = self.decode_spec(k_d, greedy=greedy,
                                           eos_token_id=eos_token_id)
                    if out is not None:
                        return out
                    if self.spec_stats is not None:
                        self.spec_stats.fallback_rounds_total += 1
        return self.decode_burst(window, greedy=greedy,
                                 eos_token_id=eos_token_id)

    def _spec_snapshot(self) -> Dict[str, Any]:
        """``health()["spec_decode"]``: {"enabled": False} with the section
        off (one shape for probes, same contract as qos), else controller
        state (live k, acceptance EWMA, ladder) + lifetime counters + the
        tokens-per-verify histogram."""
        if self.spec_stats is None or self._spec_controller is None:
            return {"enabled": False}
        return {"enabled": True,
                "drafter": (self.spec_cfg.drafter if self._drafter is not None
                            else "none"),
                **self._spec_controller.snapshot(),
                **self.spec_stats.snapshot()}

    # ----------------------------------------------------------- convenience
    def generate(self, prompts: Sequence[Sequence[int]], max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None, greedy: bool = True, *,
                 strict: bool = True, priorities: Optional[Sequence[int]] = None,
                 ttl_s: Optional[float] = None,
                 tenants: Optional[Sequence[str]] = None,
                 service_classes: Optional[Sequence[str]] = None
                 ) -> Union[List[List[int]], List[RequestResult]]:
        """Serve a batch to completion through the continuous-batching loop.

        Requests flow through the admission queue (bounded, priority-aware,
        load-shed under pressure — admission.py), are evicted between steps
        once past their deadline (``ttl_s`` or the config default), and a
        progress watchdog bounds live-but-unschedulable loops.

        ``strict=True`` (default, the pre-resilience contract): returns
        ``List[List[int]]`` of prompt+generated tokens and raises on the first
        shed/failure/stall (:class:`ServingStalledError` carries a full state
        snapshot).  ``strict=False``: every request runs to a terminal status
        and the call returns per-request :class:`RequestResult` objects
        (status in {ok, shed, deadline_expired, preempt_requeued_exhausted,
        failed}) — one bad request no longer costs the rest of the batch.

        ``greedy=False`` samples with the engine config's temperature/top-k/
        top-p — still through the device-side burst (the scan carries the rng
        and an eos done-mask), so sampled serving runs at burst throughput
        rather than the one-host-roundtrip-per-token relay floor."""
        uids = list(range(len(prompts)))
        results = self._serve(uids, prompts, max_new_tokens=max_new_tokens,
                              eos_token_id=eos_token_id, greedy=greedy, strict=strict,
                              priorities=priorities, ttl_s=ttl_s,
                              tenants=tenants, service_classes=service_classes)
        if strict:
            return [results[u].tokens for u in uids]
        return [results[u] for u in uids]

    def serve_recovered(self, requests: Sequence[RecoveredRequest], *,
                        max_new_tokens: int, eos_token_id: Optional[int] = None,
                        greedy: bool = True, strict: bool = False
                        ) -> Dict[int, RequestResult]:
        """Serve a batch where some requests resume a previous engine life
        (ISSUE 8): each :class:`RecoveredRequest` carries the token prefix it
        already emitted (replayed from the durable journal) and its REMAINING
        TTL.  Re-admitted sequences prefill ``prompt + prefix`` in one pass —
        the KV rebuild — and then continue decoding from where they died; the
        prefix counts against ``max_new_tokens`` so a recovered request never
        overruns its original budget.  Entries with an empty prefix are
        ordinary admissions riding the same call (the supervisor routes new
        work through here too, so one serve covers a mixed recovery)."""
        uids = [int(r.uid) for r in requests]
        prompts = [list(r.prompt) for r in requests]
        prefixes = {int(r.uid): [int(t) for t in r.prefix]
                    for r in requests if r.prefix}
        ttls = {int(r.uid): r.ttl_s for r in requests if r.pin_ttl}
        priorities = [int(r.priority) for r in requests]
        # QoS identity rides recovery AS JOURNALED (ISSUE 19): the planner
        # copied tenant/class from the journal entry, so a crash can never
        # launder a best-effort request into interactive
        tenants = [r.tenant for r in requests]
        service_classes = [r.service_class for r in requests]
        self.ft_stats["recovered_requests_total"] += len(prefixes)
        for r in requests:
            if r.prefix:
                self.tracer.event("recovered", uid=int(r.uid),
                                  prefix=len(r.prefix))
                self._record_resilience("serving_recovered", uid=int(r.uid),
                                        prefix_tokens=len(r.prefix))
        return self._serve(uids, prompts, max_new_tokens=max_new_tokens,
                           eos_token_id=eos_token_id, greedy=greedy,
                           strict=strict, priorities=priorities, ttl_s=None,
                           prefixes=prefixes, ttls=ttls, tenants=tenants,
                           service_classes=service_classes)

    def _serve(self, uids: List[int], prompts: Sequence[Sequence[int]], *,
               max_new_tokens: int, eos_token_id: Optional[int], greedy: bool,
               strict: bool, priorities: Optional[Sequence[int]],
               ttl_s: Optional[float],
               prefixes: Optional[Dict[int, List[int]]] = None,
               ttls: Optional[Dict[int, Optional[float]]] = None,
               tenants: Optional[Sequence[str]] = None,
               service_classes: Optional[Sequence[str]] = None
               ) -> Dict[int, RequestResult]:
        my = set(uids)
        self._reset_table_width_if_idle()
        conflict = sorted(my & set(self.manager.seqs))
        if conflict:
            # fail fast BEFORE any queue/manager mutation: finalization and
            # cleanup key on uid, so a collision with a put()-registered
            # sequence would otherwise let this call evict foreign work
            raise ValueError(f"generate() uids {conflict} are already tracked (direct "
                             f"put() requests coexist with generate() only with "
                             f"disjoint uids); flush them first")
        for uid in uids:
            # reusing a retired/flushed uid is legitimate; a failure entry left
            # over from its previous life must not poison the fresh request
            self.manager.failures.pop(uid, None)
        results: Dict[int, RequestResult] = {}
        # a recovered prefix pre-spends its share of the max_new_tokens
        # budget: the request finishes after (budget - prefix) NEW tokens
        produced = {u: len(prefixes[u]) if prefixes and u in prefixes else 0
                    for u in uids}
        token_cap = self.manager.max_blocks_per_seq * self.manager.block_size
        try:
            # ---- admission: shed-or-queue BEFORE any KV allocation
            for i, (uid, prompt) in enumerate(zip(uids, prompts)):
                prefix = prefixes.get(uid, []) if prefixes else []
                if ttls is not None and uid in ttls:
                    t, apply_default = ttls[uid], False  # recovery pins the TTL
                else:
                    t, apply_default = ttl_s, True
                tenant = tenants[i] if tenants is not None else None
                service_class = service_classes[i] if service_classes is not None else None
                if self.qos is not None:
                    # normalize HERE (not just inside submit) so the journal
                    # admit record carries the class the policy resolved —
                    # replay must reconstruct identity, not re-default it
                    tenant = str(tenant) if tenant else "default"
                    service_class = self.qos.service_class(service_class)
                shed = self.admission.submit(
                    uid, [int(tok) for tok in prompt],
                    priority=priorities[i] if priorities is not None else 0,
                    ttl_s=t, apply_default_ttl=apply_default,
                    kv_utilization=self.manager.kv_utilization(),
                    token_cap=token_cap, prefix=prefix or None,
                    recovered=bool(prefix), tenant=tenant,
                    service_class=service_class)
                if shed is not None:
                    self._record_resilience("serving_shed", uid=uid, code=shed.code,
                                            retryable=shed.retryable, detail=shed.detail)
                    if self.journal is not None:
                        # direct write, NOT _journal_terminal: a shed request
                        # was never admitted so it isn't in `watched` (and a
                        # recovered request re-shed at re-admission is only in
                        # a PREVIOUS generation's watched set) — but its
                        # terminal must still be durable, or replay re-serves
                        # it forever / reports it unresolved
                        self.journal.record_terminal(
                            uid, SHED, reason=str(shed),
                            retryable=shed.retryable,
                            # gate on qos: a QoS-off journal stays byte-
                            # identical to the pre-QoS record format
                            shed_code=(shed.code if self.qos is not None
                                       else None))
                    if strict:
                        raise RuntimeError(f"request {uid} shed: {shed}")
                    results[uid] = RequestResult(uid=uid, status=SHED, reason=str(shed),
                                                 retryable=shed.retryable,
                                                 retry_after_s=shed.retry_after_s,
                                                 shed_code=shed.code)
                elif self.journal is not None:
                    # the effective TTL (what admission just stamped) rides
                    # the admit record, with a wall-clock stamp so recovery
                    # can keep the ORIGINAL deadline clock across processes
                    effective = t if t is not None else \
                        (self.resilience.default_ttl_s if apply_default else None)
                    self.journal.record_admit(
                        uid, [int(tok) for tok in prompt],
                        priority=priorities[i] if priorities is not None else 0,
                        ttl_s=effective, max_new_tokens=max_new_tokens,
                        eos_token_id=eos_token_id, greedy=greedy,
                        prefix_len=len(prefix),
                        tenant=(tenant if tenant is not None else "default"),
                        service_class=(service_class if service_class is not None
                                       else "interactive"))
            # counterfactual prefix-cache report for THIS pass: the queued
            # (non-shed) prompts joining whatever is already live
            self._observe_prefix({uid: [int(t) for t in prompt]
                                  for uid, prompt in zip(uids, prompts)
                                  if uid not in results})
            self._prewarm(max_new_tokens, greedy=greedy)
            if self.telemetry is not None:
                # re-arm the serve-loop jax.profiler window for THIS
                # generate() (ISSUE 16 satellite — one window per call)
                self.telemetry.serve_profile_begin()
            self._serve_loop(uids, my, results, produced, max_new_tokens=max_new_tokens,
                             eos_token_id=eos_token_id, greedy=greedy, strict=strict)
            # post-pass pool state: final census/forecast refresh, then the
            # census-vs-allocator partition invariant (the PR-4 double-free
            # guard, continuously checked)
            self._refresh_kv()
            if self.kv_cfg.invariant_check:
                self.check_kv_invariant()
        except Exception:
            # a strict-mode raise must not leak this call's queued tickets or
            # live sequences into the next call (they would decode unbounded
            # with nobody tracking their budget)
            self._abandon(my, results)
            raise
        finally:
            if self.telemetry is not None:
                # a serve capture window must never leak across generate()
                # calls — close it even on a strict raise
                self.telemetry.serve_profile_end()
            # flush the Chrome-trace export (if configured) even on a strict
            # raise — the partial trace is exactly what the postmortem wants
            self.tracer.write_chrome_trace()
            if self.journal is not None:
                # buffered token deltas must not outlive the call that
                # materialized them (a strict raise included)
                self.journal.flush()
            # final ops snapshot: a post-serve scrape must see the completed
            # state (lifetime counters, emptied queue), not a mid-wave cache
            self.refresh_ops(force=True)
        return results

    def _serve_loop(self, uids: List[int], my: set, results: Dict[int, RequestResult],
                    produced: Dict[int, int], *, max_new_tokens: int,
                    eos_token_id: Optional[int], greedy: bool, strict: bool) -> None:
        cfg = self.resilience
        fp = self.fastpath
        fusion_min = max(2, fp.fusion_min_steps) if fp.enabled else 2
        # an externally wrapped step() (fault injectors, tracing shims) must
        # keep intercepting every step, so the split dispatch/materialize
        # pipeline only engages on an unwrapped engine
        can_pipeline = (fp.enabled and fp.pipeline_depth > 0
                        and "step" not in self.__dict__)
        stall_streak = 0
        last_sig = None
        prof = self.phase_profiler
        serve_iter = 0  # per-generate index driving the serve profiler window

        def absorb(stepped):
            self._absorb_step(stepped, my, results, produced,
                              max_new_tokens=max_new_tokens,
                              eos_token_id=eos_token_id, strict=strict)

        while any(u not in results for u in uids):
            self.counters.loop_iterations += 1
            if self.telemetry is not None:
                # serve-loop jax.profiler capture window (ISSUE 16 satellite):
                # [start, stop) in per-generate iterations, one window per
                # generate() — a no-op unless the window knobs are set
                self.telemetry.profile_serve_boundary(serve_iter)
            serve_iter += 1
            prof.begin_iteration()
            # serve-iteration liveness stamp (ISSUE 8): phase "serving" on
            # host-owned ints only — the supervisor reads staleness as a hang.
            # Throttled inside the writer; NULL writer when supervision is off
            self._heartbeat.stamp(self.counters.loop_iterations, phase="serving")
            # ops-plane cache refresh (ISSUE 11): host-only snapshot rebuild,
            # throttled on the injectable clock; a no-op with the plane off
            self.refresh_ops()
            prof.mark("other")  # liveness/ops bookkeeping, not a serve phase
            if self._inflight is not None and (len(self.admission)
                                               or self._any_live_deadline()):
                # wave boundary: admission/deadline handling below may evict
                # or finalize sequences — catch host state up to the device
                # first so PR-4 semantics match the synchronous loop exactly
                self.counters.flushes += 1
                self.tracer.event("flush", step=self.scheduler.steps, cause="wave")
                absorb(self._settle_inflight())
                prof.mark("flush")
            self._expire_live()
            with self._phase_annotation("admission_pump"):
                self._pump_admissions(my, results, strict)
            prof.mark("admission_pump")

            # pure-decode fast path: burst k steps on device (greedy or
            # sampled; eos-aware via the carried done-mask).  The pump just
            # ran, so anything still queued could NOT be admitted this
            # iteration — bursting doesn't delay fusion, provided the burst
            # is SLICED so admission latency (and deadline-eviction
            # overshoot) stays bounded to a few tokens instead of paying the
            # per-token host round-trip for a whole backpressure window.
            k = self._fusion_window(uids, results, produced, max_new_tokens)
            fusible = False
            if k >= fusion_min:
                # cheap host-side applicability check BEFORE paying a pipeline
                # flush: the burst needs a pure-decode live set that fits one
                # ragged batch (decode_burst re-verifies pool capacity itself)
                decoding, prefilling = self.scheduler.live_split(self.manager)
                fusible = (bool(decoding) and not prefilling
                           and len(decoding) <= self.scheduler.max_seqs)
            if fusible and self._inflight is not None:
                # the burst's bookkeeping finalizes sequences host-side:
                # absorb the in-flight step first, then re-measure the window
                self.counters.flushes += 1
                self.tracer.event("flush", step=self.scheduler.steps, cause="fuse")
                absorb(self._settle_inflight())
                prof.mark("flush")
                k = self._fusion_window(uids, results, produced, max_new_tokens)
            if fusible and k >= fusion_min:
                with self._phase_annotation("burst"):
                    burst = self._fused_decode(k, greedy=greedy,
                                               eos_token_id=eos_token_id)
                if burst:
                    for uid, toks in burst.items():
                        if uid not in my or uid in results:
                            continue
                        produced[uid] += len(toks)
                        hit_eos = (eos_token_id is not None and toks
                                   and toks[-1] == eos_token_id)
                        if hit_eos or produced[uid] >= max_new_tokens:
                            self._finish_ok(uid, results,
                                            "eos" if hit_eos else "max_new_tokens")
                    prof.mark("burst")
                    prof.end_iteration()
                    continue
                prof.mark("burst")  # a declined burst attempt still costs time

            if can_pipeline and not (len(self.admission) or self._any_live_deadline()):
                # async step pipelining: dispatch step N, then absorb step
                # N-1's tokens while the device executes N — host scheduling
                # of step N+1 overlaps device execution of N
                if (self._inflight is not None
                        and all(produced[u] + (1 if u in self._inflight.row_of else 0)
                                >= max_new_tokens
                                for u in uids if u not in results)):
                    # every unresolved request finishes the moment the
                    # in-flight step lands — absorb it instead of dispatching
                    # a guaranteed-overshoot step
                    absorb(self._settle_inflight())
                    prof.mark("absorb_patch")
                else:
                    with self._phase_annotation("dispatch"):
                        deferred = self._dispatch_step(greedy)
                    prev, self._inflight = self._inflight, deferred
                    with self._phase_annotation("absorb_patch"):
                        absorb(prev.patch(self.manager) if prev is not None else {})
                    prof.mark("absorb_patch")
            else:
                if self._inflight is not None:
                    self.counters.flushes += 1
                    self.tracer.event("flush", step=self.scheduler.steps,
                                      cause="sync")
                    absorb(self._settle_inflight())
                    prof.mark("flush")
                with self._phase_annotation("dispatch"):
                    absorb(self.step(greedy=greedy))
                prof.mark("absorb_patch")

            # ---- progress watchdog: a live-but-unschedulable engine must trip,
            # not spin.  The signature covers every observable scheduling input;
            # identical signatures for the watchdog window = stall.
            sig = self._progress_signature()
            stall_streak = stall_streak + 1 if sig == last_sig else 0
            last_sig = sig
            self._stall_streak = stall_streak
            if stall_streak >= cfg.stall_watchdog_steps:
                if self._inflight is not None:
                    absorb(self._settle_inflight())
                self._handle_stall(my, results, strict)
                stall_streak, last_sig = 0, None
                self._stall_streak = 0

            if self.journal is not None:
                # wave-boundary WAL flush: every token this iteration
                # materialized is already host-side, so the delta frame costs
                # one buffered file append (fsync amortized per fsync_every)
                self.journal.flush()
            prof.end_iteration()  # residual (watchdog, WAL) lands in "other"

        if self._inflight is not None:
            # the final absorb resolved every request with a step still in
            # flight (e.g. a coexisting put() sequence rode it): patch its
            # placeholders so no PENDING_TOKEN ever escapes the loop
            self._inflight.patch(self.manager)
            self._inflight = None

    def _fusion_window(self, uids: List[int], results: Dict[int, RequestResult],
                       produced: Dict[int, int], max_new_tokens: int) -> int:
        """Tokens worth fusing into one decode burst right now: the smallest
        remaining budget across this call's live requests, sliced to
        BURST_DEADLINE_SLICE while anything is queued or deadlined (ALL live
        sequences, not just this call's — a coexisting direct put(ttl_s=...)
        sequence rides the burst too and its deadline deserves the same
        bounded overshoot)."""
        live = [u for u in uids if u not in results]
        k = min((max_new_tokens - produced[u] for u in live), default=0)
        if len(self.admission) or self._any_live_deadline():
            k = min(k, self.BURST_DEADLINE_SLICE)
        return k

    def _absorb_step(self, stepped: Dict[int, int], my: set,
                     results: Dict[int, RequestResult], produced: Dict[int, int], *,
                     max_new_tokens: int, eos_token_id: Optional[int],
                     strict: bool) -> None:
        """Fold one step's outcomes into per-request results: sampled-token
        finishes (eos / max_new_tokens), failures, and evictions — exactly the
        bookkeeping the synchronous loop ran inline after step().  The
        pipelined loop feeds it the PREVIOUS step's materialized tokens."""
        for uid, tok in stepped.items():
            if uid not in my or uid in results:
                continue
            produced[uid] += 1
            hit_eos = eos_token_id is not None and tok == eos_token_id
            if produced[uid] >= max_new_tokens or hit_eos:
                self._truncate_overshoot(uid)
                self._finish_ok(uid, results, "eos" if hit_eos else "max_new_tokens")

        for uid, reason in list(self.manager.failures.items()):
            if uid in my and uid not in results:
                if strict:
                    raise RuntimeError(f"request {uid} failed: {reason}")
                self._record_resilience("serving_request_failed", uid=uid,
                                        reason=reason)
                self._journal_terminal(uid, FAILED, reason=reason)
                self.tracer.event("failed", step=self.scheduler.steps, uid=uid)
                self.tracer.on_terminal(uid, FAILED, reason=reason)
                seq = self.manager.seqs.get(uid)
                results[uid] = RequestResult(
                    uid=uid, status=FAILED, reason=reason,
                    tokens=list(seq.tokens) if seq is not None else [])
                if seq is not None:
                    self.manager.retire(uid, completed=False)
                # consume the entry: uids are reused across generate()
                # calls and a stale failure must not taint a fresh request
                self.manager.failures.pop(uid, None)

        # sequences finished WITHOUT emitting this step: a decode capped at
        # max_blocks_per_seq completes gracefully (length_capped — all its
        # generated tokens are valid), an expired request was evicted by
        # _expire_live, an exhausted preemption victim ends
        for uid in list(self.manager.seqs):
            if uid not in my or uid in results:
                continue
            seq = self.manager.seqs[uid]
            if not (seq.done and seq.finish_reason):
                continue
            if seq.finish_reason == DEADLINE_EXPIRED:
                if strict:
                    raise RuntimeError(f"request {uid} deadline_expired after "
                                       f"producing {seq.generated_tokens} tokens")
                results[uid] = RequestResult(uid=uid, status=DEADLINE_EXPIRED,
                                             tokens=list(seq.tokens), retryable=True,
                                             reason="deadline expired while running",
                                             queue_wait_s=seq.queue_wait_s,
                                             preemptions=seq.preemptions)
                self._journal_terminal(uid, DEADLINE_EXPIRED, retryable=True,
                                       reason="deadline expired while running")
                self.tracer.on_terminal(uid, DEADLINE_EXPIRED,
                                        reason="deadline expired while running")
                self.manager.retire(uid, completed=False)
            elif seq.finish_reason == PREEMPT_REQUEUED_EXHAUSTED:
                self._record_resilience("serving_preempt_requeued_exhausted",
                                        uid=uid, preemptions=seq.preemptions)
                if strict:
                    raise RuntimeError(
                        f"request {uid} preempted {seq.preemptions}x and evicted "
                        f"(KV pool pressure); enlarge num_blocks or lower concurrency")
                results[uid] = RequestResult(
                    uid=uid, status=PREEMPT_REQUEUED_EXHAUSTED,
                    tokens=list(seq.tokens), retryable=True,
                    reason=f"preempted {seq.preemptions}x under KV pressure",
                    preemptions=seq.preemptions, queue_wait_s=seq.queue_wait_s)
                self._journal_terminal(
                    uid, PREEMPT_REQUEUED_EXHAUSTED, retryable=True,
                    reason=f"preempted {seq.preemptions}x under KV pressure")
                self.tracer.on_terminal(
                    uid, PREEMPT_REQUEUED_EXHAUSTED,
                    reason=f"preempted {seq.preemptions}x under KV pressure")
                self.manager.retire(uid, completed=False)
            else:  # length_capped: a graceful completion
                self._finish_ok(uid, results, seq.finish_reason)

    def _truncate_overshoot(self, uid: int) -> None:
        """A request finishing on its step-N token may already have step N+1
        in flight (pipelined dispatch): drop the in-flight placeholder so the
        finished token list is exactly the synchronous loop's.  The stray
        device-side KV write lands in blocks this retirement frees; any later
        owner's prefill rewrites them before its lengths let them be read."""
        d = self._inflight
        if d is None or uid not in d.row_of:
            return
        seq = self.manager.seqs.get(uid)
        if seq is not None and seq.tokens and seq.tokens[-1] == PENDING_TOKEN:
            seq.tokens.pop()
            seq.seen_tokens = min(seq.seen_tokens, len(seq.tokens))
        d.drop_emit(uid)

    def _settle_inflight(self) -> Dict[int, int]:
        """Materialize and clear the in-flight step (no-op when none)."""
        d, self._inflight = self._inflight, None
        return d.patch(self.manager) if d is not None else {}

    def _any_live_deadline(self) -> bool:
        return any(s.deadline is not None and not s.done
                   for s in self.manager.seqs.values())

    def _abandon(self, my: set, results: Dict[int, RequestResult]) -> None:
        """Strict-mode raise cleanup: reclaim every trace of this call so the
        engine is immediately reusable (blocks freed, queue drained, stale
        failure entries consumed)."""
        if self._inflight is not None:
            try:
                # foreign (direct put()) sequences may hold placeholders from
                # the aborted step — patch them before this call's teardown
                self._inflight.patch(self.manager)
            finally:
                self._inflight = None
        for uid in list(self.manager.seqs):
            if uid in my:
                self.manager.retire(uid, completed=False)
        for uid in my:
            self.manager.failures.pop(uid, None)
        for ticket in self.admission.drain():
            self._forget_prefix(ticket.uid)  # died queued: retire never fires
        # close any still-open traces of this call so the live-trace map and
        # the strict caller's postmortem both see a terminal event
        self.tracer.abort_all(my, reason="strict-mode abort")
        self._stall_streak = 0  # the wedge was evicted with everything else

    # ------------------------------------------------- serving-loop internals
    def _prewarm(self, max_new_tokens: int, greedy: bool = True) -> None:
        """Serve-time compile-cache prewarm: AOT-compile the forward buckets
        this call's queued + live requests are about to hit (bounded by
        ``serving_fastpath.prewarm_buckets``) so the first wave doesn't pay
        mid-serve compile stalls.  With spec decode armed, ALSO prewarm the
        verify bucket for every adaptive-k ladder rung — the AOT key includes
        the verify width, so a k drift mid-serve lands on a compiled
        executable (zero warm recompiles in spec mode).  Best-effort — any
        lowering failure falls back to compile-on-first-step."""
        fp = self.fastpath
        if not fp.enabled or fp.prewarm_buckets <= 0:
            return
        depth, max_prompt = self.admission.queued_stats()
        live = self.manager.live_uids()
        for uid in live:
            max_prompt = max(max_prompt, len(self.manager.seqs[uid].tokens))
        n_total = min(depth + len(live), self.scheduler.max_seqs)
        if n_total <= 0 or max_prompt <= 0:
            return
        bs = self.manager.block_size
        w_prefill = self._stepped_width(-(-(max_prompt + 1) // bs))
        w_decode = self._stepped_width(-(-(max_prompt + 1 + max_new_tokens) // bs))
        n_b = self._bucket(n_total)
        t_pf = self._bucket(max(1, min(self.scheduler.token_budget, max_prompt)))
        candidates = [(n_b, 1, w_prefill), (n_b, 1, w_decode),
                      (n_b, t_pf, w_prefill), (n_b, t_pf, w_decode)]
        warmed = 0
        for n, t, b in candidates:
            if warmed >= fp.prewarm_buckets:
                break
            if (n, t, b) in self._fwd_cache:
                continue
            try:
                self._aot_compile_fwd(n, t, b)
            except Exception as e:
                from ...utils.logging import warning_once
                warning_once(f"serving fastpath: prewarm of bucket {(n, t, b)} "
                             f"failed ({e}); falling back to on-demand compile")
                return
            warmed += 1
        if self._drafter is None or self._spec_controller is None:
            return
        sample_cfg = None if greedy else (self.config.temperature,
                                          self.config.top_k, self.config.top_p)
        ladder = self._spec_controller.ladder
        # deepest verify reach: prompt + per-round input token + run budget +
        # the largest rung of draft overshoot that the rollback then trims
        w_verify = self._stepped_width(
            -(-(max_prompt + 1 + max_new_tokens + max(ladder)) // bs))
        warmed_spec = 0
        for rung in ladder:
            for w in sorted({w_decode, w_verify}):
                if warmed_spec >= fp.prewarm_buckets:
                    return
                if ("spec_verify", n_b, rung, w, sample_cfg) in self._fwd_cache:
                    continue
                try:
                    self._aot_compile_spec_verify(n_b, rung, w, sample_cfg)
                except Exception as e:
                    from ...utils.logging import warning_once
                    warning_once(f"spec decode: prewarm of verify bucket "
                                 f"{(n_b, rung, w)} failed ({e}); falling "
                                 f"back to on-demand compile")
                    return
                warmed_spec += 1

    def _finish_ok(self, uid: int, results: Dict[int, RequestResult],
                   finish_reason: str) -> None:
        seq = self.manager.seqs[uid]
        seq.done = True
        seq.finish_reason = finish_reason
        results[uid] = RequestResult(uid=uid, status=OK, tokens=list(seq.tokens),
                                     finish_reason=finish_reason,
                                     queue_wait_s=seq.queue_wait_s,
                                     preemptions=seq.preemptions)
        self._journal_terminal(uid, OK, finish_reason=finish_reason)
        self.tracer.event("finish", step=self.scheduler.steps, uid=uid,
                          reason=finish_reason)
        self.tracer.on_terminal(uid, OK, finish_reason=finish_reason)
        self.manager.retire(uid)  # reclaim KV blocks immediately, not at batch end

    def _expire_live(self) -> None:
        """Engine-wide deadline enforcement between forwards: any live
        sequence past its deadline — however it was admitted (generate's
        admission pump or a direct put(ttl_s=...)) — is evicted in place:
        done, ``finish_reason: deadline_expired``, KV blocks reclaimed.  The
        serve loop converts evicted sequences into results; step()-level
        callers observe ``done`` + the finish reason."""
        now = self._clock()
        self.tracer.tick(now)  # donate the sweep's clock read to the recorder
        for seq in list(self.manager.seqs.values()):
            if seq.done or seq.deadline is None or now < seq.deadline:
                continue
            self.manager.evict(seq, DEADLINE_EXPIRED)
            self._deadline_expired_total += 1
            self.tracer.event("expire", step=self.scheduler.steps, uid=seq.uid,
                              produced=seq.generated_tokens)
            self._record_resilience("serving_deadline_expired", uid=seq.uid,
                                    produced=seq.generated_tokens,
                                    seen_tokens=seq.seen_tokens)
        # phase attribution (ISSUE 16): a no-op (and no clock read) unless
        # the profiler is enabled AND inside a serve-loop iteration
        self.phase_profiler.mark("expire")

    def _pump_admissions(self, my: set, results: Dict[int, RequestResult],
                         strict: bool) -> bool:
        """Move queued tickets into the state manager while the pool has
        headroom; tickets that expired waiting become deadline_expired results
        without ever owning a block.  Returns True when tickets remain queued
        because the pump has no headroom (live cap / pool pressure) — the
        serve loop may then burst, since nothing could fuse anyway."""
        cfg = self.resilience
        while len(self.admission):
            live = self.manager.live_uids()
            if cfg.max_live_seqs and len(live) >= cfg.max_live_seqs:
                return True
            if live and self.manager.kv_utilization() >= cfg.shed_kv_utilization:
                return True  # pool pressure: hold the queue (progress guaranteed
                # — something is live, and retiring it reopens the pump)
            ticket, expired = self.admission.pop_ready()
            for t in expired:
                self.tracer.event("queue_expired", step=self.scheduler.steps,
                                  uid=t.uid)
                self._forget_prefix(t.uid)  # died queued: retire never fires
                if t.uid in my and t.uid not in results:
                    self._deadline_expired_total += 1
                    self._record_resilience("serving_deadline_expired", uid=t.uid,
                                            produced=0, queued=True)
                    if strict:
                        raise RuntimeError(f"request {t.uid} deadline_expired while queued")
                    results[t.uid] = RequestResult(
                        uid=t.uid, status=DEADLINE_EXPIRED, retryable=True,
                        reason="deadline expired in the admission queue")
                    self._journal_terminal(
                        t.uid, DEADLINE_EXPIRED, retryable=True,
                        reason="deadline expired in the admission queue")
                    self.tracer.on_terminal(
                        t.uid, DEADLINE_EXPIRED, t=self.tracer.last_now,
                        reason="deadline expired in the admission queue")
            if ticket is None:
                break
            now = self._clock()
            self.tracer.tick(now)
            wait = max(0.0, now - ticket.enqueue_t)
            self._queue_wait_s = wait
            # queue-wait histogram feeds health() percentiles even with span
            # tracing off: the wait is already computed, pure host arithmetic
            self.tracer.observe_queue_wait(wait)
            # crash recovery: a re-admitted ticket's token history is
            # prompt + already-emitted prefix (prefilled in one pass — the KV
            # rebuild), with prompt_len pinned so the prefix keeps counting
            # as generated output, not prompt
            seq = self.manager.add_sequence(ticket.uid, ticket.prompt + ticket.prefix,
                                            priority=ticket.priority,
                                            deadline=ticket.deadline, queue_wait_s=wait,
                                            prompt_len=len(ticket.prompt),
                                            tenant=ticket.tenant,
                                            service_class=ticket.service_class)
            # admit-time prefix lookup (ISSUE 13): map whatever shared prompt
            # blocks are already computed — a journal-replayed request lands
            # back on the shared blocks its previous life rode — and the
            # scheduler re-checks per prefill chunk for late-arriving hits
            self._map_prefix(seq)
            self.tracer.event("admit", step=self.scheduler.steps, uid=ticket.uid,
                              **({"recovered": True} if ticket.recovered else {}))
            self.tracer.on_admit(ticket.uid, now, queue_wait_s=wait,
                                 prompt_len=len(ticket.prompt) + len(ticket.prefix),
                                 tenant=(ticket.tenant if self.qos is not None
                                         else None))
        return False

    def _handle_stall(self, my: set, results: Dict[int, RequestResult],
                      strict: bool) -> None:
        cfg = self.resilience
        self.stalls_total += 1
        self.tracer.event("stall", step=self.scheduler.steps,
                          live_seqs=len(self.manager.seqs),
                          free_blocks=self.manager.allocator.free_blocks)
        # snapshot AFTER the stall event so the dump's flight-recorder tail
        # includes the trip itself at the end of the history that led to it
        snapshot = self.state_snapshot()
        self._record_resilience("serving_stall",
                                live_seqs=len(snapshot["live_uids"]),
                                free_blocks=snapshot["free_blocks"],
                                queue_depth=snapshot["queue_depth"])
        if strict:
            raise ServingStalledError(
                f"serving made no progress for {cfg.stall_watchdog_steps} consecutive "
                f"steps with {len(snapshot['live_uids'])} live sequences and "
                f"{snapshot['free_blocks']} free KV blocks — see .snapshot for the "
                f"full engine state", snapshot)
        # non-strict: fail the stuck requests (live AND still-queued) with the
        # snapshot attached, reclaim their blocks, and keep serving the rest
        reason = (f"stalled: no scheduling progress for "
                  f"{cfg.stall_watchdog_steps} steps")
        for uid in list(self.manager.seqs):
            if uid in my and uid not in results:
                seq = self.manager.seqs[uid]
                results[uid] = RequestResult(uid=uid, status=FAILED, reason=reason,
                                             tokens=list(seq.tokens), retryable=True,
                                             preemptions=seq.preemptions,
                                             queue_wait_s=seq.queue_wait_s)
                self._journal_terminal(uid, FAILED, reason=reason, retryable=True)
                self.tracer.on_terminal(uid, FAILED, reason=reason,
                                        t=self.tracer.last_now)
                self.manager.retire(uid, completed=False)
        for ticket in self.admission.drain():
            self._forget_prefix(ticket.uid)  # died queued: retire never fires
            if ticket.uid in my and ticket.uid not in results:
                results[ticket.uid] = RequestResult(uid=ticket.uid, status=FAILED,
                                                    reason=reason + " (still queued)",
                                                    retryable=True)
                self._journal_terminal(ticket.uid, FAILED, retryable=True,
                                       reason=reason + " (still queued)")
                self.tracer.on_terminal(ticket.uid, FAILED, t=self.tracer.last_now,
                                        reason=reason + " (still queued)")

    def _progress_signature(self):
        return (tuple(sorted((uid, s.seen_tokens, len(s.tokens), s.done)
                             for uid, s in self.manager.seqs.items())),
                len(self.admission), self.manager.allocator.free_blocks)

    def _record_resilience(self, event: str, **fields) -> None:
        if self.telemetry is not None:
            self.telemetry.record_resilience(event, step=self.scheduler.steps, **fields)

    def _journal_terminal(self, uid: int, status: str, *,
                          finish_reason: Optional[str] = None,
                          reason: Optional[str] = None,
                          retryable: bool = False) -> None:
        """Mirror a ``RequestResult`` construction into the durable journal
        (only for uids this journal admitted — foreign put() traffic keeps
        its own lifecycle).  Terminal records order after their buffered
        token deltas; strict mode writes + fsyncs them eagerly, throughput
        mode lands them at the next wave flush (a one-iteration window —
        a crash inside it re-serves the finished request from its
        journaled prefix)."""
        j = self.journal
        if j is None or uid not in j.watched:
            return
        seq = self.manager.seqs.get(uid)
        j.record_terminal(uid, status, finish_reason=finish_reason, reason=reason,
                          retryable=retryable,
                          n_tokens=seq.generated_tokens if seq is not None else 0)

    # ------------------------------------------------------------ introspection
    def state_snapshot(self) -> Dict[str, Any]:
        """Full serving state for stall diagnostics: live uids, per-sequence
        progress and block-table occupancy, allocator free count, queue depth."""
        alloc = self.manager.allocator
        return {
            "live_uids": sorted(self.manager.seqs),
            "sequences": {uid: {"seen_tokens": s.seen_tokens,
                                "pending_tokens": s.pending_tokens,
                                "blocks": list(s.blocks),
                                "done": s.done,
                                "preemptions": s.preemptions,
                                "deadline": s.deadline}
                          for uid, s in self.manager.seqs.items()},
            "free_blocks": alloc.free_blocks,
            "num_blocks": alloc.num_blocks,
            "queue_depth": len(self.admission),
            "scheduler_steps": self.scheduler.steps,
            # block-level pool state (ISSUE 12): the full per-block census
            # table (owner/age/residency — bounded by the pool size) plus the
            # rollups/forecast health() carries, for stall postmortems that
            # need to see WHICH blocks are pinned where
            "kv": self._kv_snapshot(with_table=True),
            # realized prefix-sharing state (ISSUE 13)
            "prefix_cache": (self.manager.prefix_cache.snapshot()
                             if self.manager.prefix_cache is not None
                             else {"enabled": False}),
            # recovery state (ISSUE 8): restart/recovery counters + journal
            # size, so a crash postmortem's snapshot shows the durability side
            "fault_tolerance": self._fault_tolerance_snapshot(),
            # perf observatory (ISSUE 16): phase budget + compile provenance
            # ride the stall dump — a wedge preceded by warm recompiles or a
            # phase blowup is diagnosable from the snapshot alone
            "perf": self._perf_snapshot(),
            # the event history that LED here (ISSUE 6): the always-on flight
            # recorder's tail rides every stall dump for postmortems
            "flight_recorder": self.tracer.recorder.tail(),
        }

    def _kv_snapshot(self, with_table: bool = False) -> Dict[str, Any]:
        """The ``health()["kv"]`` / ``state_snapshot()["kv"]`` payload:
        census rollups, prefix-opportunity report, capacity forecast —
        JSON-safe host values only."""
        if self.kv_obs is None:
            return {"enabled": False}
        snap = self.kv_obs.snapshot(self.manager.allocator.free_blocks)
        if with_table:
            snap["census_table"] = self.kv_obs.census.table()
        return snap

    def _fault_tolerance_snapshot(self) -> Dict[str, Any]:
        return {
            **{k: self.ft_stats[k] for k in ("restarts_total",
                                             "recovered_requests_total",
                                             "degraded")},
            "journal_bytes": journal_bytes(self.journal.path
                                           if self.journal is not None else None),
            "journaling": self.journal is not None and self.journal.enabled,
            "heartbeat": bool(getattr(self._heartbeat, "enabled", False)),
        }

    def health(self) -> Dict[str, Any]:
        """Liveness snapshot for external probes (the serving analog of the
        training engine's telemetry record): pool state, queue depth, and the
        lifetime resilience counters."""
        return {
            # freshness stamp (ISSUE 17) from the INJECTABLE clock, advanced
            # at serve/wave boundaries: a fleet router compares it against its
            # own reading of the same clock and treats a snapshot past its
            # staleness horizon as unhealthy — a frozen replica's last-good
            # gauges must not attract traffic.  Stamped at refresh (not per
            # call) so the cached /healthz snapshot mirrors health() exactly
            "generated_at": self._health_generated_at,
            "live_seqs": len(self.manager.live_uids()),
            "queue_depth": len(self.admission),
            "free_blocks": self.manager.allocator.free_blocks,
            "kv_utilization": self.manager.kv_utilization(),
            # block-level pool observability (ISSUE 12): census rollups
            # (fragmentation, block-age, blocks-per-request), counterfactual
            # prefix-cache opportunity, and the steps-to-exhaustion forecast
            "kv": self._kv_snapshot(),
            # realized copy-on-write prefix sharing (ISSUE 13): hits, tokens
            # saved, CoW copies, realized hit-rate — read next to the
            # counterfactual under kv.prefix
            "prefix_cache": (self.manager.prefix_cache.snapshot()
                             if self.manager.prefix_cache is not None
                             else {"enabled": False}),
            "scheduler_steps": self.scheduler.steps,
            "completed_total": self.manager.completed_requests,
            "failed_total": self.manager.failed_requests,
            "shed_total": self.admission.shed_total,
            "preempted_total": self.scheduler.preempted_total,
            "deadline_expired_total": self._deadline_expired_total,
            # the streak is a live gauge; stalls_total is the observable stall
            # signal (the streak resets the moment the watchdog handles a trip,
            # so a momentary `stalled` boolean could never be caught True)
            "stall_streak": self._stall_streak,
            "stalls_total": self.stalls_total,
            # host-link counters (ISSUE 5): the serve loop's orchestration
            # cost, for probes that watch syncs-per-token drift — plus the
            # parallelism shape (ISSUE 15) so the ops plane can tell a
            # sharded serve apart from a single-chip one at a glance
            "fastpath": {**self.counters.snapshot(), "tp": self.tp,
                         "mesh_shape": ({a: int(s) for a, s in
                                         self.topology.mesh.shape.items()}
                                        if self.topology is not None else {})},
            # SLO latency percentiles (ISSUE 6): queue_wait histogram is fed
            # by the admission pump even with span tracing off; ttft/tbt/e2e
            # fill in once serving_tracing.enabled is set
            "queue_wait": self.tracer.queue_wait.snapshot(),
            "latency": self.tracer.latency_snapshot(),
            "tracing_enabled": self.tracer.enabled,
            # crash-durability counters (ISSUE 8): supervised restarts,
            # requests recovered with an emitted prefix, journal size on
            # disk, and the drain-only degradation flag
            "fault_tolerance": self._fault_tolerance_snapshot(),
            # serving performance observatory (ISSUE 16): per-phase wall-time
            # attribution, compile provenance, live roofline — the ledger and
            # roofline report even with the phase profiler off
            "perf": self._perf_snapshot(),
            # the recent engine-event history (always on, bounded ring)
            "flight_recorder": self.tracer.recorder.tail(32),
            # multi-tenant QoS (ISSUE 19): per-tenant admit/shed/token
            # counters, resident KV blocks, and the last quota retry hint —
            # {"enabled": False} when the policy layer is off so probes can
            # key on one shape
            "qos": (self.qos.snapshot() if self.qos is not None
                    else {"enabled": False}),
            # speculative decoding (ISSUE 20): adaptive-k controller state,
            # lifetime proposal/acceptance counters, tokens-per-verify
            # histogram — {"enabled": False} when the section is off
            "spec_decode": self._spec_snapshot(),
        }
