"""AutoTP — automatic tensor-parallel sharding-rule inference.

Analog of the reference's AutoTP (module_inject/auto_tp.py:188): the reference
walks the module tree matching nn.Linear names to decide row- vs column-
parallel slicing; here we pattern-match param-pytree paths (our model naming
AND common HF naming) and emit the same column/row layout as a TpRuleFn the
sharding plan consumes (runtime/zero/sharding.py).

Column-parallel (shard output dim): q/k/v projections, MLP up/gate, lm head.
Row-parallel (shard input dim): attention output proj, MLP down proj.
"""

import re
from typing import Optional, Tuple

# output-dim-sharded (column-parallel) path suffixes
_COLUMN_PAT = re.compile(
    r"(wq|wk|wv|w_gate|w_up|w_fc1|q_proj|k_proj|v_proj|gate_proj|up_proj|query|key|value|"
    r"c_attn|fc_in|wi|lm_head)$")
# kv-projection subset of the column set: GQA/MQA kv (output narrower than the
# model dim) replicates instead — models.transformer.kv_projection_shardable
_KV_PAT = re.compile(r"(wk|wv|k_proj|v_proj|key|value)$")
# input-dim-sharded (row-parallel)
_ROW_PAT = re.compile(r"(wo|w_down|w_fc2|o_proj|down_proj|dense|c_proj|fc_out|wo_out)$")


def infer_rule(path: str, shape: Tuple[int, ...]) -> Optional[int]:
    """Map a param path to a shard dim over the 'tensor' axis (or None).

    Stacked-layer leaves carry a leading L dim, so 2D [in, out] weights appear
    as 3D [L, in, out]: dims shift by one.
    """
    if len(shape) < 2:
        return None
    leaf = path.split(".")[-1]
    base = len(shape) - 2  # index of the 'in' dim
    if _COLUMN_PAT.search(leaf):
        if _KV_PAT.search(leaf):
            from ..models.transformer import kv_projection_shardable
            return base + 1 if kv_projection_shardable(shape) else None
        return base + 1
    if _ROW_PAT.search(leaf):
        return base
    if leaf == "embed":  # vocab-parallel embedding (reference embedding sharding)
        return None
    return None


def auto_tp_rules(path: str, shape) -> Optional[int]:
    """TpRuleFn entry point: plug into initialize(tp_rules=...) or InferenceEngine."""
    return infer_rule(path, tuple(shape))
