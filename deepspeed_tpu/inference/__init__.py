"""Inference engines (reference deepspeed/inference/)."""
from .auto_tp import auto_tp_rules
from .config import InferenceConfig, load_inference_config
from .engine import InferenceEngine, init_inference
