"""Inference configuration — analog of DeepSpeedInferenceConfig
(deepspeed/inference/config.py: DeepSpeedTPConfig:47, quantization/moe blocks).
"""

from typing import Any, Dict, Optional

import jax.numpy as jnp

from ..runtime.config import (KVObservabilityConfig, OpsServerConfig,
                              ServingFastpathConfig,
                              ServingFaultToleranceConfig,
                              ServingFleetConfig,
                              ServingPerfConfig,
                              ServingPrefixCacheConfig, ServingQosConfig,
                              ServingResilienceConfig,
                              ServingSpecDecodeConfig, ServingTracingConfig)
from ..runtime.config_utils import ConfigModel, Field

DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16, "float16": jnp.float16}


class TPConfig(ConfigModel):
    """Reference DeepSpeedTPConfig (inference/config.py:47)."""
    enabled: bool = True
    tp_size: int = Field(1, ge=1)


class QuantConfig(ConfigModel):
    """Weight-only quantization for serving (reference inference/quantization)."""
    enabled: bool = False
    bits: int = Field(8, choices=(4, 8))
    group_size: int = Field(2048, ge=8)


class InferenceConfig(ConfigModel):
    """Reference DeepSpeedInferenceConfig (inference/config.py)."""
    dtype: str = Field("bfloat16", choices=("float32", "bfloat16", "float16"))
    tensor_parallel: Optional[TPConfig] = None
    max_out_tokens: int = Field(1024, ge=1)
    min_out_tokens: int = Field(1, ge=1)
    max_seq_len: Optional[int] = None
    replace_with_kernel_inject: bool = False  # Pallas flash decode path
    quant: Optional[QuantConfig] = None
    # sampling defaults
    temperature: float = Field(1.0, ge=0.0)
    top_k: int = Field(0, ge=0)
    top_p: float = Field(1.0, gt=0.0, le=1.0)
    seed: int = 0
    # admission control / load shedding / preemption / stall watchdog for the
    # v2 ragged engine (runtime/config.py defines the section so train+serve
    # configs share one spelling)
    serving_resilience: ServingResilienceConfig = Field(ServingResilienceConfig)
    # serving hot-path policy (device-resident batch buffers, async step
    # pipelining, adaptive decode fusion) — inference/v2/fastpath.py
    serving_fastpath: ServingFastpathConfig = Field(ServingFastpathConfig)
    # speculative decoding on the fused decode path: draft/verify with exact
    # rejection sampling — inference/v2/spec_decode.py (section defined in
    # runtime/config.py so train+serve configs share one spelling)
    serving_spec_decode: ServingSpecDecodeConfig = Field(ServingSpecDecodeConfig)
    # request-lifecycle tracing + SLO latency histograms + flight recorder —
    # monitor/tracing.py wired through the v2 serving stack (same section
    # spelling as runtime/config.py so train+serve configs share it)
    serving_tracing: ServingTracingConfig = Field(ServingTracingConfig)
    # durable request journal + supervised restart / crash recovery —
    # inference/v2/journal.py + supervisor.py (same dual-spelling contract)
    serving_fault_tolerance: ServingFaultToleranceConfig = Field(ServingFaultToleranceConfig)
    # pull-based ops endpoints (/metrics + /healthz + /statez) and per-rank
    # metrics textfiles — monitor/ops_server.py (same dual-spelling contract)
    ops_server: OpsServerConfig = Field(OpsServerConfig)
    # block-level KV-pool observability: census + prefix-sharing opportunity
    # + capacity forecast — inference/v2/kv_metrics.py (section defined in
    # runtime/config.py so train+serve configs share one spelling)
    serving_kv_observability: KVObservabilityConfig = Field(KVObservabilityConfig)
    # copy-on-write prefix caching: shared-prefix requests map live computed
    # prompt blocks read-only and skip the duplicate prefill —
    # inference/v2/ragged_manager.py PrefixCache (section defined in
    # runtime/config.py so train+serve configs share one spelling)
    serving_prefix_cache: ServingPrefixCacheConfig = Field(ServingPrefixCacheConfig)
    # serving performance observatory: phase attribution + compile ledger +
    # live roofline gauges — monitor/perf.py wired through the v2 serve loop
    # (section defined in runtime/config.py so train+serve configs share one
    # spelling)
    serving_perf: ServingPerfConfig = Field(ServingPerfConfig)
    # fleet front-end over N supervised replicas: health-gated least-loaded
    # routing, prefix-affinity homing, shed backoff, journaled failover
    # migration — inference/v2/router.py (section defined in
    # runtime/config.py so train+serve configs share one spelling)
    serving_fleet: ServingFleetConfig = Field(ServingFleetConfig)
    # multi-tenant QoS: priority classes, per-tenant token-rate + KV-block
    # quotas, weighted-fair dequeue, tenant-keyed prefix isolation —
    # inference/v2/qos.py (section defined in runtime/config.py so
    # train+serve configs share one spelling)
    serving_qos: ServingQosConfig = Field(ServingQosConfig)

    def model_validate(self):
        if self.tensor_parallel is None:
            object.__setattr__(self, "tensor_parallel", TPConfig())
        if self.quant is None:
            object.__setattr__(self, "quant", QuantConfig())


def load_inference_config(config) -> InferenceConfig:
    if config is None:
        return InferenceConfig()
    if isinstance(config, InferenceConfig):
        return config
    return InferenceConfig(**dict(config))
