"""Weight-only quantization (WOQ) for inference serving.

Analog of deepspeed/inference/quantization/ (quantization.py
``_init_group_wise_weight_quantization``, layers.py QuantizedLinear — int8/int4
weight-only layers dequantizing on the fly, 530 LoC): matched 2D weights are
stored PACKED (int8, or int4 two-per-byte) with per-group scales — a 4x/8x
HBM reduction over fp32 at rest — and dequantized to the compute dtype inside
the jitted forward.  Under the models' scan-over-layers at most one layer's
dequantized weights are live at a time, so peak HBM follows the packed size,
not the dense size (the TPU equivalent of the reference's fused
dequant+gemm CUDA path).

The packed leaf is a registered pytree node (``WOQLeaf``): the int tensors
``q``/``s`` are its children (traced, device-resident) while bits/shape are
static aux data, so the whole tree flows through jit/device_put unchanged and
``dequantize_tree`` restores a dense tree INSIDE the compiled program.
"""

import re
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.quantizer import (dequantize_int4, dequantize_int8, quantize_int4,
                             quantize_int8)
from ..utils.logging import log_dist


@jax.tree_util.register_pytree_node_class
class WOQLeaf:
    """One packed weight: quantized ints + per-group scales, static metadata."""

    def __init__(self, q, s, bits: int, size: int, shape: Tuple[int, ...]):
        self.q = q
        self.s = s
        self.bits = bits
        self.size = size
        self.shape = tuple(shape)

    def tree_flatten(self):
        return (self.q, self.s), (self.bits, self.size, self.shape)

    @classmethod
    def tree_unflatten(cls, aux, children):
        q, s = children
        bits, size, shape = aux
        return cls(q, s, bits, size, shape)

    def __repr__(self):
        return f"WOQLeaf(int{self.bits}, shape={self.shape})"


def is_woq_leaf(x) -> bool:
    return isinstance(x, WOQLeaf)


def quantize_leaf(w, bits: int = 8, group_size: int = 128) -> WOQLeaf:
    """Pack one weight into quantized ints + scales."""
    if bits == 8:
        q, s, n = quantize_int8(w, group_size)
    elif bits == 4:
        q, s, n = quantize_int4(w, group_size)
    else:
        raise ValueError(f"WOQ supports 4/8 bits, got {bits}")
    return WOQLeaf(q, s, bits, int(n), tuple(np.shape(w)))


def dequantize_leaf(leaf: WOQLeaf, dtype=jnp.bfloat16):
    fn = dequantize_int8 if leaf.bits == 8 else dequantize_int4
    return fn(leaf.q, leaf.s, leaf.size, shape=leaf.shape, dtype=dtype)


def quantize_tree(params: Any, bits: int = 8, group_size: int = 128,
                  modules: Optional[Sequence[str]] = None,
                  min_size: int = 4096) -> Any:
    """Pack every matching >=2D leaf (reference
    _init_group_wise_weight_quantization walks matched module names the same
    way).  ``modules``: regexes over dotted leaf paths; None matches all.
    Small leaves (norms, biases) stay dense."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)

    def key_of(path):
        return ".".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)

    n_packed, dense_bytes, packed_bytes = 0, 0, 0
    out = []
    for path, leaf in flat:
        key = key_of(path)
        matchable = (np.ndim(leaf) >= 2 and np.size(leaf) >= min_size
                     and (modules is None or any(re.search(m, key) for m in modules)))
        if matchable:
            packed = quantize_leaf(leaf, bits=bits, group_size=group_size)
            n_packed += 1
            dense_bytes += np.size(leaf) * 2  # vs bf16 serving copy
            packed_bytes += int(np.size(packed.q) + np.size(packed.s) * 4)
            out.append(packed)
        else:
            out.append(leaf)
    log_dist(f"WOQ int{bits}: packed {n_packed} weights "
             f"({dense_bytes / 1e6:.1f} MB bf16 -> {packed_bytes / 1e6:.1f} MB packed)",
             ranks=[0])
    return jax.tree_util.tree_unflatten(treedef, out)


def dequantize_tree(params: Any, dtype=jnp.bfloat16) -> Any:
    """Restore a dense tree — call INSIDE jit so XLA fuses dequantization
    into consumers and frees each layer's dense weights after use."""
    return jax.tree_util.tree_map(
        lambda leaf: dequantize_leaf(leaf, dtype) if is_woq_leaf(leaf) else leaf,
        params, is_leaf=is_woq_leaf)


def packed_nbytes(params: Any) -> int:
    """Serving-resident bytes of a (possibly partially) packed tree."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params, is_leaf=is_woq_leaf):
        if is_woq_leaf(leaf):
            total += int(np.size(leaf.q) + np.size(leaf.s) * 4)
        else:
            total += int(np.size(leaf)) * np.dtype(getattr(leaf, "dtype", np.float32)).itemsize
    return total
