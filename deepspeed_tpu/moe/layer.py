"""MoE facade.

Analog of deepspeed/moe/layer.py (``MoE:16``): bundles a TopKGate + grouped
experts into one layer with an init/apply pair, exposing the reference's
constructor surface (num_experts, k, capacity factors, noisy gating, ep_size).

``ep_size`` maps to the mesh's 'expert' axis: the reference builds
expert-parallel process groups (_create_expert_and_data_parallel, groups.py:113);
here the expert dim of the stacked weights is sharded over that axis and XLA
derives the dispatch all-to-all.
"""

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ..parallel.mesh import EXPERT_AXIS, MeshTopology
from . import experts as experts_lib
from .sharded_moe import TopKGate, moe_layer


class MoE:

    def __init__(self,
                 hidden_size: int,
                 expert_intermediate_size: Optional[int] = None,
                 num_experts: int = 1,
                 ep_size: int = 1,
                 k: int = 1,
                 capacity_factor: float = 1.0,
                 eval_capacity_factor: float = 1.0,
                 min_capacity: int = 4,
                 use_residual: bool = False,
                 noisy_gate_policy: Optional[str] = None,
                 drop_tokens: bool = True,
                 expert_kind: str = "swiglu"):
        if num_experts % ep_size != 0:
            raise ValueError(f"num_experts({num_experts}) must be divisible by ep_size({ep_size}) "
                             "(reference moe/layer.py:16 assertion)")
        self.hidden_size = hidden_size
        self.ffn_dim = expert_intermediate_size or 4 * hidden_size
        self.num_experts = num_experts
        self.ep_size = ep_size
        self.use_residual = use_residual
        self.expert_kind = expert_kind
        self.gate = TopKGate(hidden_size, num_experts, k, capacity_factor, eval_capacity_factor,
                             min_capacity, noisy_gate_policy, drop_tokens)
        if expert_kind == "swiglu":
            self._init_experts = experts_lib.init_swiglu_experts
            self._expert_fn = experts_lib.swiglu_experts
        else:
            self._init_experts = experts_lib.init_gelu_experts
            self._expert_fn = experts_lib.gelu_experts

    def init(self, key, dtype=jnp.float32):
        k_gate, k_exp, k_res, k_coef = jax.random.split(key, 4)
        params = {
            "gate": self.gate.init(k_gate, dtype=dtype),
            "experts": self._init_experts(k_exp, self.num_experts, self.hidden_size, self.ffn_dim, dtype=dtype),
        }
        if self.use_residual:
            # PR-MoE (reference moe/layer.py:77-85, arXiv:2201.05596): a dense
            # expert-shaped MLP on every token + a learned 2-way mixing head
            params["residual_mlp"] = self._init_experts(k_res, 1, self.hidden_size,
                                                        self.ffn_dim, dtype=dtype)
            params["coefficient"] = {
                "w": jax.random.normal(k_coef, (self.hidden_size, 2), dtype) * 0.02,
                "b": jnp.zeros((2, ), dtype),
            }
        return params

    def __call__(self, params, x, train: bool = True, rng=None, topo: Optional[MeshTopology] = None):
        """x [..., hidden] -> (out, l_aux)."""
        out, l_aux = moe_layer(self.gate, params, x, expert_fn=self._expert_fn, train=train,
                               rng=rng, ep_axis=EXPERT_AXIS, topo=topo)
        if self.use_residual:
            # Residual MoE combine (reference moe/layer.py:118-126): softmax'd
            # per-token coefficients weight expert output vs the dense MLP
            flat = x.reshape(-1, self.hidden_size)
            mlp_out = self._expert_fn(params["residual_mlp"], flat[None])[0].reshape(x.shape)
            coef = jax.nn.softmax(
                (x @ params["coefficient"]["w"].astype(x.dtype)
                 + params["coefficient"]["b"].astype(x.dtype)).astype(jnp.float32),
                axis=-1).astype(x.dtype)
            out = out * coef[..., 0:1] + mlp_out * coef[..., 1:]
        return out, l_aux
