"""Mixture-of-Experts gating + dispatch.

Analog of deepspeed/moe/sharded_moe.py (``top1gating:184``, ``top2gating:282``,
``MOELayer:425``, ``_AllToAll:95``).  The reference's einsum-based
dispatch/combine (GShard lineage) is already the TPU-idiomatic formulation, so
the math here matches closely by convergent design; expert parallelism is
expressed as a sharding constraint on the expert dim (XLA lowers the resharding
to the all-to-all the reference issues manually), and the grouped expert FFN is
one batched einsum over the stacked expert weights (megablox-style grouped GEMM
on the MXU instead of a per-expert loop).
"""

import functools
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec

from ..parallel.mesh import EXPERT_AXIS, MeshTopology, get_topology


def _capacity(num_tokens: int, num_experts: int, capacity_factor: float, min_capacity: int, k: int = 1) -> int:
    cap = int(np.ceil(num_tokens * capacity_factor * k / num_experts))
    return max(cap, min_capacity)


def _one_hot(idx, n, dtype=jnp.float32):
    return jax.nn.one_hot(idx, n, dtype=dtype)


class GateOutput(NamedTuple):
    l_aux: jnp.ndarray
    combine_weights: jnp.ndarray  # [S, E, C]
    dispatch_mask: jnp.ndarray  # [S, E, C] bool
    exp_counts: jnp.ndarray  # [E]


def top1gating(logits, capacity_factor: float = 1.0, min_capacity: int = 4,
               noisy_gate_policy: Optional[str] = None, rng=None, used_capacity=None,
               drop_tokens: bool = True) -> GateOutput:
    """Switch-style top-1 gating (reference top1gating, sharded_moe.py:184):
    aux loss = E * sum_e(mean_prob_e * frac_tokens_e); capacity-dropped tokens
    fall through (residual keeps them)."""
    s, e = logits.shape
    capacity = _capacity(s, e, capacity_factor, min_capacity, k=1)
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    if noisy_gate_policy == "RSample" and rng is not None:
        noisy = logits + jax.random.gumbel(rng, logits.shape)
        idx = jnp.argmax(noisy, axis=-1)
    else:
        idx = jnp.argmax(gates, axis=-1)
    mask1 = _one_hot(idx, e)  # [S, E]

    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * e

    # position of each token within its expert queue
    locations = jnp.cumsum(mask1, axis=0) - mask1  # [S, E]
    pos_in_expert = jnp.sum(locations * mask1, axis=-1)  # [S]
    keep = pos_in_expert < capacity if drop_tokens else jnp.ones_like(pos_in_expert, bool)
    mask1 = mask1 * keep[:, None]

    gate_val = jnp.sum(gates * mask1, axis=-1)  # [S]
    cap_onehot = _one_hot(pos_in_expert.astype(jnp.int32), capacity)  # [S, C]
    combine = gate_val[:, None, None] * mask1[:, :, None] * cap_onehot[:, None, :]
    dispatch = combine > 0
    return GateOutput(l_aux, combine, dispatch, jnp.sum(mask1, axis=0).astype(jnp.int32))


def top2gating(logits, capacity_factor: float = 1.0, min_capacity: int = 4,
               drop_tokens: bool = True, rng=None) -> GateOutput:
    """GShard top-2 gating (reference top2gating, sharded_moe.py:282): second
    expert chosen after masking the first; gate values renormalized."""
    s, e = logits.shape
    capacity = _capacity(s, e, capacity_factor, min_capacity, k=2)
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    idx1 = jnp.argmax(gates, axis=-1)
    mask1 = _one_hot(idx1, e)
    gates_wo1 = gates * (1.0 - mask1)
    idx2 = jnp.argmax(gates_wo1, axis=-1)
    mask2 = _one_hot(idx2, e)

    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * e

    loc1 = jnp.cumsum(mask1, axis=0) - mask1
    loc2 = jnp.cumsum(mask2, axis=0) - mask2 + jnp.sum(mask1, axis=0, keepdims=True)
    pos1 = jnp.sum(loc1 * mask1, axis=-1)
    pos2 = jnp.sum(loc2 * mask2, axis=-1)
    if drop_tokens:
        mask1 = mask1 * (pos1 < capacity)[:, None]
        mask2 = mask2 * (pos2 < capacity)[:, None]

    g1 = jnp.sum(gates * mask1, axis=-1)
    g2 = jnp.sum(gates * mask2, axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    cap1 = _one_hot(pos1.astype(jnp.int32), capacity)
    cap2 = _one_hot(pos2.astype(jnp.int32), capacity)
    combine = (g1[:, None, None] * mask1[:, :, None] * cap1[:, None, :] +
               g2[:, None, None] * mask2[:, :, None] * cap2[:, None, :])
    dispatch = combine > 0
    counts = jnp.sum(mask1 + mask2, axis=0).astype(jnp.int32)
    return GateOutput(l_aux, combine, dispatch, counts)


class TopKGate:
    """Gate wrapper (reference TopKGate, sharded_moe.py:348): params = {'wg': [M, E]}."""

    def __init__(self, model_dim: int, num_experts: int, k: int = 1, capacity_factor: float = 1.0,
                 eval_capacity_factor: float = 1.0, min_capacity: int = 4,
                 noisy_gate_policy: Optional[str] = None, drop_tokens: bool = True):
        if k not in (1, 2):
            raise ValueError("TopKGate supports k=1 or k=2 (reference sharded_moe.py:355)")
        self.model_dim = model_dim
        self.num_experts = num_experts
        self.k = k
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = eval_capacity_factor
        self.min_capacity = min_capacity
        self.noisy_gate_policy = noisy_gate_policy
        self.drop_tokens = drop_tokens

    def init(self, key, dtype=jnp.float32):
        return {"wg": jax.random.normal(key, (self.model_dim, self.num_experts), dtype) * 0.02}

    def __call__(self, params, x, train: bool = True, rng=None) -> GateOutput:
        logits = x.astype(jnp.float32) @ params["wg"].astype(jnp.float32)
        cf = self.capacity_factor if train else self.eval_capacity_factor
        if self.k == 1:
            return top1gating(logits, cf, self.min_capacity,
                              self.noisy_gate_policy if train else None, rng, drop_tokens=self.drop_tokens)
        return top2gating(logits, cf, self.min_capacity, drop_tokens=self.drop_tokens, rng=rng)


def moe_layer(gate: TopKGate, params, x, *, expert_fn: Callable, train: bool = True, rng=None,
              ep_axis: str = EXPERT_AXIS, topo: Optional[MeshTopology] = None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Dispatch -> grouped experts -> combine (reference MOELayer.forward,
    sharded_moe.py:425).

    x: [..., M] (leading dims flattened to the token dim).
    params: {'gate': gate params, 'experts': stacked expert params (leading dim E)}.
    expert_fn(expert_params, tokens[E, C, M]) -> [E, C, M] batched over experts.
    Returns (out, l_aux).
    """
    orig_shape = x.shape
    m = orig_shape[-1]
    tokens = x.reshape(-1, m)
    gout = gate(params["gate"], tokens, train=train, rng=rng)

    # dispatch: [S,E,C] x [S,M] -> [E,C,M]
    dispatched = jnp.einsum("sec,sm->ecm", gout.dispatch_mask.astype(x.dtype), tokens)
    t = topo or get_topology()
    ep_world = t.axis_size(ep_axis)
    if ep_world > 1:
        # expert-parallel resharding: XLA lowers this to the all-to-all the
        # reference performs explicitly (_AllToAll, sharded_moe.py:95)
        dispatched = lax.with_sharding_constraint(
            dispatched, NamedSharding(t.mesh, PartitionSpec(ep_axis, None, None)))
    expert_out = expert_fn(params["experts"], dispatched)
    if ep_world > 1:
        expert_out = lax.with_sharding_constraint(
            expert_out, NamedSharding(t.mesh, PartitionSpec(ep_axis, None, None)))
    out = jnp.einsum("sec,ecm->sm", gout.combine_weights.astype(x.dtype), expert_out)
    return out.reshape(orig_shape), gout.l_aux
