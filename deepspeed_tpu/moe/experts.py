"""Grouped expert FFNs.

Analog of deepspeed/moe/experts.py — but instead of a ModuleList of per-expert
FFNs looped over, expert weights are STACKED on a leading E dim and applied as
one batched einsum (grouped GEMM on the MXU; the pattern the reference's v2
inference gets from CUTLASS moe_gemm, inference/v2/kernels/cutlass_ops/moe_gemm).
"""

import jax
import jax.numpy as jnp


def init_linear(key, in_dim, out_dim, dtype=jnp.float32):
    """Fan-in normal init, identical to models.transformer.init_linear
    (duplicated 2 lines instead of imported: models/__init__ pulls in mixtral,
    which imports this module — a cycle when deepspeed_tpu.moe loads first)."""
    return jax.random.normal(key, (in_dim, out_dim), dtype) * (1.0 / jnp.sqrt(jnp.float32(in_dim)))


def init_swiglu_experts(key, num_experts: int, model_dim: int, hidden_dim: int, dtype=jnp.float32):
    ks = jax.random.split(key, 3)

    def stack(k, i, o):
        kk = jax.random.split(k, num_experts)
        return jnp.stack([init_linear(q, i, o, dtype=dtype) for q in kk])

    return {
        "w_gate": stack(ks[0], model_dim, hidden_dim),
        "w_up": stack(ks[1], model_dim, hidden_dim),
        "w_down": stack(ks[2], hidden_dim, model_dim),
    }


def swiglu_experts(params, tokens):
    """tokens [E, C, M] -> [E, C, M], vectorized over experts."""
    gate = jax.nn.silu(jnp.einsum("ecm,emh->ech", tokens, params["w_gate"].astype(tokens.dtype)))
    up = jnp.einsum("ecm,emh->ech", tokens, params["w_up"].astype(tokens.dtype))
    return jnp.einsum("ech,ehm->ecm", gate * up, params["w_down"].astype(tokens.dtype))


def init_gelu_experts(key, num_experts: int, model_dim: int, hidden_dim: int, dtype=jnp.float32):
    ks = jax.random.split(key, 2)

    def stack(k, i, o):
        kk = jax.random.split(k, num_experts)
        return jnp.stack([init_linear(q, i, o, dtype=dtype) for q in kk])

    return {
        "w_fc1": stack(ks[0], model_dim, hidden_dim),
        "b_fc1": jnp.zeros((num_experts, hidden_dim), dtype),
        "w_fc2": stack(ks[1], hidden_dim, model_dim),
        "b_fc2": jnp.zeros((num_experts, model_dim), dtype),
    }


def gelu_experts(params, tokens):
    h = jnp.einsum("ecm,emh->ech", tokens, params["w_fc1"].astype(tokens.dtype)) + \
        params["b_fc1"][:, None, :].astype(tokens.dtype)
    h = jax.nn.gelu(h, approximate=True)
    return jnp.einsum("ech,ehm->ecm", h, params["w_fc2"].astype(tokens.dtype)) + \
        params["b_fc2"][:, None, :].astype(tokens.dtype)
