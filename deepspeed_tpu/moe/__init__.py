from .layer import MoE
from .sharded_moe import GateOutput, TopKGate, moe_layer, top1gating, top2gating
