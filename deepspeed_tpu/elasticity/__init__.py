"""Elastic training (reference deepspeed/elasticity/)."""
from .elastic_agent import DSElasticAgent, WorkerGroup, select_consensus_tag
from .elasticity import (ElasticityConfig, compute_elastic_config, get_best_candidates,
                         get_valid_gpus)
