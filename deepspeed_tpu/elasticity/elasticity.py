"""Elastic training configuration solver.

Analog of the reference elasticity module (elasticity/elasticity.py:233
compute_elastic_config, batch/GPU compatibility solvers :83-146): given a
target batch-size range and micro-batch candidates, compute the largest total
batch size compatible with EVERY admissible chip count, so scaling events
never change the effective batch.

TPU framing: "gpus" become chips; valid worlds are whole TPU slice shapes
(the caller passes candidate chip counts or we enumerate divisors).
"""

import dataclasses
import math
from functools import reduce
from typing import Dict, List, Optional, Tuple

from ..runtime.config_utils import ConfigModel, Field


class ElasticityConfig(ConfigModel):
    """Reference elasticity config block (elasticity/config.py)."""
    enabled: bool = False
    max_train_batch_size: int = Field(2000, ge=1)
    micro_batch_sizes: List[int] = Field(lambda: [2, 4, 6])
    min_gpus: int = Field(1, ge=1)
    max_gpus: int = Field(10000, ge=1)
    min_time: int = Field(0, ge=0)
    version: float = 0.2
    ignore_non_elastic_batch_info: bool = False
    prefer_larger_batch: bool = True


def _lcm(nums: List[int]) -> int:
    return reduce(lambda a, b: a * b // math.gcd(a, b), nums, 1)


def get_valid_gpus(batch_size: int, micro_batches: List[int], min_gpus: int,
                   max_gpus: int) -> List[int]:
    """Chip counts that evenly fit batch = micro * gas * world for some micro
    (reference elasticity.py:60)."""
    valid = set()
    for mb in micro_batches:
        if batch_size % mb != 0:
            continue
        max_world = batch_size // mb
        for world in range(min_gpus, min(max_gpus, max_world) + 1):
            if max_world % world == 0:
                valid.add(world)
    return sorted(valid)


def get_best_candidates(max_batch: int, micro_batches: List[int], min_gpus: int,
                        max_gpus: int, prefer_larger: bool = True) -> Tuple[int, List[int], Optional[int]]:
    """v0.1 solver (reference elasticity.py:83): candidate batches are
    lcm(micro_batches) * k; pick the one admitting the most chip counts."""
    base = _lcm(micro_batches)
    best = (0, [], None)
    for batch in range(base, max_batch + 1, base):
        valid = get_valid_gpus(batch, micro_batches, min_gpus, max_gpus)
        better = len(valid) > len(best[1]) or (len(valid) == len(best[1]) and prefer_larger
                                               and best[2] is not None and batch > best[2])
        if valid and (best[2] is None or better):
            best = (len(valid), valid, batch)
    return best[2], best[1], None if best[2] is None else best[2]


def compute_elastic_config(ds_config: Dict, target_deepspeed_version: str = "",
                           world_size: int = 0, return_microbatch: bool = False):
    """Reference compute_elastic_config (elasticity.py:233): resolve the final
    (train_batch_size, valid_gpus[, micro_batch]) for this world size."""
    ecfg = ElasticityConfig(**ds_config.get("elasticity", {}))
    if not ecfg.enabled:
        raise ValueError("elasticity section missing or disabled")
    batch, valid_gpus, _ = get_best_candidates(ecfg.max_train_batch_size,
                                               list(ecfg.micro_batch_sizes),
                                               ecfg.min_gpus, ecfg.max_gpus,
                                               ecfg.prefer_larger_batch)
    if batch is None:
        raise ValueError("no elastic batch size satisfies the constraints")
    if world_size > 0 and world_size not in valid_gpus:
        raise ValueError(f"world size {world_size} is not in the elastic-compatible set {valid_gpus}")
    if not return_microbatch:
        return batch, valid_gpus
    micro = None
    if world_size > 0:
        per_chip = batch // world_size
        for mb in sorted(ecfg.micro_batch_sizes, reverse=ecfg.prefer_larger_batch):
            if per_chip % mb == 0:
                micro = mb
                break
    return batch, valid_gpus, micro
