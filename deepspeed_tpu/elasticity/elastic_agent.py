"""Elastic agent: worker supervision with restart + world rescaling.

Analog of the reference ``DSElasticAgent`` (deepspeed/elasticity/
elastic_agent.py:28, extending torch-elastic's LocalElasticAgent): spawn the
training workers, monitor them, and on failure re-form the world at a size
the elasticity config permits, then restart from the latest checkpoint.
Without torch-elastic's rendezvous store, membership is what the agent itself
launches (single-host supervisor; multi-host agents coordinate via the
launcher's hostfile + per-host agents), and the "valid world sizes" come from
the same solver the config uses (elasticity.py ``get_valid_gpus``).

Workers see: RANK, WORLD_SIZE, DSTPU_ELASTIC_RESTART (restart ordinal) — a
worker resumes from its checkpoint exactly as after a cold restart, which is
the reference's recovery model too (elastic training = checkpoint + relaunch
at a new valid batch/world configuration).
"""

import os
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence

from ..utils.logging import logger
from .elasticity import get_valid_gpus


class WorkerGroup:
    """One generation of worker processes."""

    def __init__(self, procs: List[subprocess.Popen], world_size: int, restart: int):
        self.procs = procs
        self.world_size = world_size
        self.restart = restart

    def poll_failed(self) -> Optional[int]:
        """Return an exit code if any worker failed, else None."""
        for p in self.procs:
            rc = p.poll()
            if rc is not None and rc != 0:
                return rc
        return None

    def all_done(self) -> bool:
        return all(p.poll() == 0 for p in self.procs)

    def terminate(self):
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + 10
        for p in self.procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()  # reap — the respawn must not race a dying worker


class DSElasticAgent:
    """Supervise `world_size` copies of a worker command.

    ``elastic_config``: the ds-config ``elasticity`` section (max batch,
    micro-batches, min/max gpus) constraining which world sizes are valid.
    On a worker failure the agent assumes capacity loss, drops to the next
    smaller valid world size, and relaunches (up to ``max_restarts``).
    """

    def __init__(self, worker_cmd: Sequence[str], world_size: int,
                 elastic_config: Optional[Dict] = None, max_restarts: int = 3,
                 poll_interval: float = 0.2, env: Optional[Dict[str, str]] = None):
        self.worker_cmd = list(worker_cmd)
        self.initial_world = world_size
        self.elastic_config = elastic_config
        self.max_restarts = max_restarts
        self.poll_interval = poll_interval
        self.base_env = dict(env or os.environ)
        self.restart_count = 0

    # ------------------------------------------------------------- world math
    def valid_world_sizes(self) -> List[int]:
        if not self.elastic_config:
            return list(range(1, self.initial_world + 1))
        cfg = dict(self.elastic_config)
        valid = get_valid_gpus(
            int(cfg["max_train_batch_size"]),
            [int(m) for m in cfg["micro_batch_sizes"]],
            int(cfg.get("min_gpus", 1)),
            int(cfg.get("max_gpus", self.initial_world)))
        return sorted(w for w in valid if w <= self.initial_world)

    def next_world_size(self, current: int) -> Optional[int]:
        smaller = [w for w in self.valid_world_sizes() if w < current]
        return max(smaller) if smaller else None

    # --------------------------------------------------------------- spawning
    def _spawn(self, world_size: int) -> WorkerGroup:
        procs = []
        for rank in range(world_size):
            env = dict(self.base_env,
                       RANK=str(rank), WORLD_SIZE=str(world_size),
                       DSTPU_ELASTIC_RESTART=str(self.restart_count))
            procs.append(subprocess.Popen(self.worker_cmd, env=env))
        logger.info(f"elastic agent: launched {world_size} workers "
                    f"(restart {self.restart_count})")
        return WorkerGroup(procs, world_size, self.restart_count)

    # -------------------------------------------------------------------- run
    def run(self) -> int:
        """Supervise until success (0), unrecoverable failure (worker rc), or
        restart budget exhausted (1)."""
        world = self.initial_world
        valid = self.valid_world_sizes()
        if world not in valid:
            # launching at a size the elastic config forbids breaks the batch
            # math from step 0 — clamp before the first generation
            fitting = [w for w in valid if w <= world]
            if not fitting:
                logger.error(f"elastic agent: no valid world size <= {world} "
                             f"(valid: {valid})")
                return 1
            logger.warning(f"elastic agent: world_size {world} is not elastic-valid "
                           f"{valid}; clamping to {max(fitting)}")
            world = max(fitting)
        group = self._spawn(world)
        while True:
            time.sleep(self.poll_interval)
            rc = group.poll_failed()
            if rc is not None:
                logger.warning(f"elastic agent: worker failed rc={rc} "
                               f"(world={world}, restart {self.restart_count})")
                group.terminate()
                if self.restart_count >= self.max_restarts:
                    logger.error("elastic agent: restart budget exhausted")
                    return 1
                self.restart_count += 1
                shrunk = self.next_world_size(world)
                if shrunk is not None:
                    logger.info(f"elastic agent: rescaling {world} -> {shrunk}")
                    world = shrunk
                # world == min valid size: respawn at the same size
                group = self._spawn(world)
                continue
            if group.all_done():
                logger.info("elastic agent: all workers finished cleanly")
                return 0
