"""Elastic agent: worker supervision with liveness monitoring, hang
diagnosis, coordinated checkpoint-aware restart, and world rescaling.

Analog of the reference ``DSElasticAgent`` (deepspeed/elasticity/
elastic_agent.py:28, extending torch-elastic's LocalElasticAgent): spawn the
training workers, monitor them, and on failure re-form the world at a size
the elasticity config permits, then restart from the latest checkpoint.
Without torch-elastic's rendezvous store, membership is what the agent itself
launches (single-host supervisor; multi-host agents coordinate via the
launcher's hostfile + per-host agents), and the "valid world sizes" come from
the same solver the config uses (elasticity.py ``get_valid_gpus``).

Beyond the reference's exit-code watching, this agent supervises *liveness*
(the reference delegates that to torch-elastic/NCCL timeouts, which the JAX
runtime has no analog of):

- **Heartbeats** — workers stamp ``step + wall-clock + last-entered-
  collective`` to per-rank files (runtime/heartbeat.py; armed via the
  ``DSTPU_HEARTBEAT_DIR`` env this agent exports).  A stale stamp is a
  failure: the dominant distributed failure mode is a rank stuck in a
  collective while its peers wait forever, which no exit-code poll ever sees.
- **Hang diagnosis** — on staleness the agent dumps a cross-rank snapshot
  showing which ranks sat in which collective (``format_hang_report``), then
  restarts; stragglers (step lagging the group median) are flagged, not
  killed.
- **Coordinated checkpoint-aware restart** — before respawning, the agent
  selects the newest checkpoint tag valid across ALL ranks of the NEW world
  size (``select_consensus_tag`` — the same validation walk PR 2's
  ``fallback_to_valid`` uses) and pins it via ``DSTPU_RESUME_TAG`` so every
  rank of the new generation resumes from the same tag.
- **Graceful handoff** — termination is SIGTERM → ``term_grace_secs``
  (letting ``checkpoint.save_on_preemption`` take a final save at the
  failure moment) → SIGKILL, with children reaped on every path.
- **Lifecycle telemetry** — worker_failed / hang_detected / straggler /
  rescale / resume_tag events through ``record_resilience`` JSONL (when a
  TelemetryCollector is attached) plus an always-on supervisor
  flight-recorder ring (monitor/tracing.FlightRecorder) surfaced by
  ``state_snapshot()``.

Workers see: RANK, WORLD_SIZE, DSTPU_ELASTIC_RESTART (restart ordinal),
DSTPU_HEARTBEAT_DIR (+interval), and DSTPU_RESUME_TAG (the pinned consensus
checkpoint tag, when one exists) — a worker resumes from its checkpoint
exactly as after a cold restart, which is the reference's recovery model too
(elastic training = checkpoint + relaunch at a new valid batch/world
configuration).
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..monitor.tracing import FlightRecorder
from ..runtime.checkpointing import is_valid_tag, list_tags
from ..runtime.heartbeat import (COLLECTIVE_TIMEOUT_ENV, HEARTBEAT_DIR_ENV,
                                 HEARTBEAT_INTERVAL_ENV, INIT_RETRIES_ENV,
                                 INIT_RETRY_BACKOFF_ENV, OPS_DIR_ENV,
                                 RESUME_DIR_ENV, RESUME_TAG_ENV,
                                 format_hang_report, heartbeat_age,
                                 read_heartbeats, stale_ranks, straggler_ranks)
from ..utils.logging import logger
from .elasticity import get_valid_gpus


class WorkerGroup:
    """One generation of worker processes."""

    def __init__(self, procs: List[subprocess.Popen], world_size: int, restart: int,
                 heartbeat_dir: Optional[str] = None):
        self.procs = procs
        self.world_size = world_size
        self.restart = restart
        self.heartbeat_dir = heartbeat_dir  # this generation's stamp dir
        self.spawned_at = time.time()

    def poll_failed(self) -> Optional[Tuple[int, int]]:
        """``(rank, exit_code)`` of the first failed worker, else None."""
        for rank, p in enumerate(self.procs):
            rc = p.poll()
            if rc is not None and rc != 0:
                return rank, rc
        return None

    def all_done(self) -> bool:
        return all(p.poll() == 0 for p in self.procs)

    def alive_ranks(self) -> List[int]:
        return [rank for rank, p in enumerate(self.procs) if p.poll() is None]

    def pids(self) -> List[int]:
        return [p.pid for p in self.procs]

    def terminate(self, grace_secs: float = 10.0):
        """Graceful handoff: SIGTERM every live worker, wait up to
        ``grace_secs`` (the ``save_on_preemption`` window — a final save at
        the failure moment beats resuming from the last periodic one), then
        SIGKILL survivors.  Every child is reaped before returning, so a
        respawn never races a dying worker and no zombies outlive the agent."""
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + max(grace_secs, 0.0)
        for p in self.procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()  # reap — the respawn must not race a dying worker


def select_consensus_tag(checkpoint_dirs: Sequence[str],
                         verify_integrity: bool = False) -> Optional[str]:
    """Newest checkpoint tag valid across EVERY directory in
    ``checkpoint_dirs`` — the resume-tag consensus for a new generation.

    Walks the first directory's tag order (checkpoint-index append order,
    newest first — the same walk ``load_checkpoint(fallback_to_valid=True)``
    uses) and returns the first tag that validates (manifest completeness +
    byte sizes; CRC32s too with ``verify_integrity``) in ALL directories.  A
    tag torn on any rank — e.g. the crash that triggered this restart
    interrupted that rank's save — is skipped everywhere, so divergent
    "newest" tags converge on the newest COMMON valid one.  None when no tag
    is valid across the board (fresh start)."""
    dirs = [d for d in checkpoint_dirs if d]
    if not dirs:
        return None
    for tag in reversed(list_tags(dirs[0])):
        if all(is_valid_tag(d, tag, verify_integrity=verify_integrity) for d in dirs):
            return tag
    return None


class DSElasticAgent:
    """Supervise ``world_size`` copies of a worker command.

    ``elastic_config``: the ds-config ``elasticity`` section (max batch,
    micro-batches, min/max gpus) constraining which world sizes are valid.
    On a worker failure or detected hang the agent assumes capacity loss,
    drops to the next smaller valid world size, and relaunches (up to
    ``max_restarts``) — except exit codes in ``non_restartable_exit_codes``
    (config/usage errors: restarting cannot fix a bad flag), which are
    returned to the caller immediately.

    Liveness monitoring engages when ``heartbeat_timeout_s`` is set (with
    ``heartbeat_dir`` — the constructor refuses one without the other):
    workers get a per-generation heartbeat dir via env, and a rank whose
    stamp goes stale (or that never stamps within ``startup_grace_s``) is
    treated as hung — cross-rank snapshot dumped, group restarted.

    ``checkpoint_dir`` (+ ``per_rank_checkpoints`` for node-local layouts
    ``<dir>/rank<R>/``) arms coordinated restart: each new generation is
    pinned to the newest tag valid across all ranks of its world size via
    ``DSTPU_RESUME_TAG``.
    """

    # merged-metrics rebuild throttle (the poll loop ticks much faster; a
    # scrape between rebuilds reads the cached strings)
    OPS_REFRESH_INTERVAL_S = 0.25

    def __init__(self, worker_cmd: Sequence[str], world_size: int,
                 elastic_config: Optional[Dict] = None, max_restarts: int = 3,
                 poll_interval: float = 0.2, env: Optional[Dict[str, str]] = None,
                 checkpoint_dir: Optional[str] = None,
                 per_rank_checkpoints: bool = False,
                 verify_checkpoint_integrity: bool = False,
                 heartbeat_dir: Optional[str] = None,
                 heartbeat_timeout_s: Optional[float] = None,
                 heartbeat_interval_s: float = 0.25,
                 startup_grace_s: Optional[float] = None,
                 straggler_lag_steps: Optional[int] = None,
                 io_grace_factor: float = 10.0,
                 term_grace_secs: float = 10.0,
                 non_restartable_exit_codes: Sequence[int] = (2, ),
                 collective_timeout_s: Optional[float] = None,
                 init_retries: Optional[int] = None,
                 init_retry_backoff_s: Optional[float] = None,
                 telemetry=None, recorder_events: int = 256,
                 ops_port: Optional[int] = None,
                 ops_dir: Optional[str] = None,
                 ops_host: str = "127.0.0.1"):
        self.worker_cmd = list(worker_cmd)
        self.initial_world = world_size
        self.elastic_config = elastic_config
        self.max_restarts = max_restarts
        self.poll_interval = poll_interval
        self.base_env = dict(env or os.environ)
        self.restart_count = 0
        self.checkpoint_dir = checkpoint_dir
        self.per_rank_checkpoints = per_rank_checkpoints
        self.verify_checkpoint_integrity = verify_checkpoint_integrity
        if heartbeat_timeout_s is not None and heartbeat_dir is None:
            # fail fast: without a stamp dir the liveness monitor is silently
            # inert and a wedged rank deadlocks the job — the exact failure
            # this knob exists to catch (the launcher's --heartbeat_timeout
            # derives a tempdir; direct callers must pass heartbeat_dir)
            raise ValueError("heartbeat_timeout_s is set but heartbeat_dir is "
                             "None: hang detection needs a directory for the "
                             "per-rank liveness stamps")
        self.heartbeat_dir = heartbeat_dir
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.heartbeat_interval_s = heartbeat_interval_s
        self.startup_grace_s = (startup_grace_s if startup_grace_s is not None
                                else (5.0 * heartbeat_timeout_s if heartbeat_timeout_s else None))
        self.straggler_lag_steps = straggler_lag_steps
        self.io_grace_factor = max(float(io_grace_factor), 1.0)
        self.term_grace_secs = term_grace_secs
        self.non_restartable_exit_codes = frozenset(int(c) for c in non_restartable_exit_codes)
        self.collective_timeout_s = collective_timeout_s
        self.init_retries = None if init_retries is None else int(init_retries)
        self.init_retry_backoff_s = init_retry_backoff_s
        self.telemetry = telemetry
        self.recorder = FlightRecorder(capacity=recorder_events)
        self.resume_tags: List[Optional[str]] = []  # per generation, for postmortems
        self._flagged_stragglers: set = set()
        self._last_heartbeats: Dict[int, dict] = {}
        self._interrupt_signum: Optional[int] = None
        self._prev_handlers: Dict[int, object] = {}
        # fleet-level ops endpoint (ISSUE 11): workers publish per-rank
        # registry snapshots under DSTPU_OPS_DIR (this agent exports it), the
        # poll loop merges them (generation carry keeps counters monotone
        # across restarts/rescales) and serves /metrics + /healthz + /statez
        # with per-rank liveness gauges on top — the health surface a fleet
        # router admits on.  `ops_port` arms it (0 = ephemeral; read
        # agent.ops.port); `ops_dir` defaults to a tempdir.
        self.ops = None
        self._ops_cache = None
        self._ops_agg = None
        self._ops_dir = ops_dir
        self._ops_own_dir = False
        self._current_world = world_size
        if ops_port is not None or ops_dir is not None:
            from ..monitor.metrics import FleetAggregator
            from ..monitor.ops_server import OpsCache, try_start_ops_server
            self._ops_agg = FleetAggregator()
            self._ops_cache = OpsCache()
            if self._ops_dir is None:
                self._ops_dir = tempfile.mkdtemp(prefix="dstpu_elastic_ops_")
                self._ops_own_dir = True
            if ops_port is not None:
                self.ops = try_start_ops_server(self._ops_cache, host=ops_host,
                                                port=ops_port,
                                                owner="elastic agent")
            self._ops_last_refresh = -float("inf")
            self._refresh_ops(group=None, force=True)

    # ------------------------------------------------------------- world math
    def valid_world_sizes(self) -> List[int]:
        if not self.elastic_config:
            return list(range(1, self.initial_world + 1))
        cfg = dict(self.elastic_config)
        valid = get_valid_gpus(
            int(cfg["max_train_batch_size"]),
            [int(m) for m in cfg["micro_batch_sizes"]],
            int(cfg.get("min_gpus", 1)),
            int(cfg.get("max_gpus", self.initial_world)))
        return sorted(w for w in valid if w <= self.initial_world)

    def next_world_size(self, current: int) -> Optional[int]:
        smaller = [w for w in self.valid_world_sizes() if w < current]
        return max(smaller) if smaller else None

    # ------------------------------------------------------------- lifecycle
    def _record(self, event: str, **fields):
        """One lifecycle event → supervisor flight-recorder ring + (when a
        collector is attached) a ``kind: resilience`` JSONL record, mirroring
        the serving engine's event plumbing.  ``step`` defaults to the restart
        ordinal; events that carry a worker step (straggler) override it."""
        fields.setdefault("step", self.restart_count)
        self.recorder.record(event, t=time.time(), **fields)
        if self.telemetry is not None:
            self.telemetry.record_resilience(f"elastic_{event}", **fields)

    def state_snapshot(self) -> Dict:
        """Supervisor postmortem: restart budget, per-generation resume tags,
        the flight-recorder tail, and the last heartbeat sweep."""
        return {
            "restart_count": self.restart_count,
            "max_restarts": self.max_restarts,
            "resume_tags": list(self.resume_tags),
            "events": self.recorder.tail(),
            "heartbeats": dict(self._last_heartbeats),
        }

    # ----------------------------------------------------------- ops endpoint
    def ops_health(self, group: Optional[WorkerGroup] = None) -> Dict:
        """The agent's /healthz: world/restart state + per-rank liveness —
        host-side values the poll loop already maintains."""
        alive = group.alive_ranks() if group is not None else []
        return {
            "world_size": self._current_world,
            "restart_count": self.restart_count,
            "max_restarts": self.max_restarts,
            "alive_ranks": alive,
            "resume_tags": list(self.resume_tags),
            "ranks_reporting": (self._ops_agg.ranks()
                                if self._ops_agg is not None else []),
        }

    def _refresh_ops(self, group: Optional[WorkerGroup],
                     force: bool = False) -> None:
        """Merge worker snapshots + agent liveness into the scrape cache.
        Runs on the agent's poll loop (host-only file reads + string work),
        throttled to one rebuild per ``ops_server`` refresh interval so a
        fast poll_interval doesn't pay a dir-scan + render every tick."""
        if self._ops_agg is None:
            return
        now_mono = time.monotonic()
        if not force and now_mono - self._ops_last_refresh < self.OPS_REFRESH_INTERVAL_S:
            return
        self._ops_last_refresh = now_mono
        from ..monitor.exposition import render
        from ..monitor.metrics import populate_from_agent
        from ..monitor.ops_server import read_rank_snapshots
        from ..utils.logging import warning_once
        for rank, snap in read_rank_snapshots(self._ops_dir).items():
            try:
                self._ops_agg.absorb(rank, snap)
            except (ValueError, KeyError, TypeError) as exc:
                # a malformed-but-parseable snapshot degrades that rank's
                # freshness; it must never unwind the poll loop that owns
                # every worker's teardown
                warning_once(f"ops: rank {rank} snapshot rejected ({exc!r}); "
                             f"keeping its last merged state")
        merged = self._ops_agg.registry()
        populate_from_agent(merged, self,
                            heartbeats=self._last_heartbeats,
                            alive_ranks=group.alive_ranks() if group else None,
                            now=time.time())
        merged.set_gauge("dstpu_elastic_world_size", self._current_world,
                         help_text="current worker-group world size")
        self._ops_cache.update(metrics_text=render(merged, collect=False),
                               healthz=json.dumps(self.ops_health(group)),
                               statez=json.dumps(self.state_snapshot()))

    def close_ops(self) -> None:
        """Shut the ops listener down (tests / clean teardown)."""
        if self.ops is not None:
            self.ops.close()

    # -------------------------------------------------------- checkpoint pin
    def checkpoint_dirs(self, world_size: int) -> List[str]:
        if not self.checkpoint_dir:
            return []
        if self.per_rank_checkpoints:
            return [os.path.join(self.checkpoint_dir, f"rank{r}")
                    for r in range(world_size)]
        return [self.checkpoint_dir]

    def select_resume_tag(self, world_size: int) -> Optional[str]:
        """The consensus tag the next generation of ``world_size`` ranks must
        resume from (None = fresh start / no checkpointing configured)."""
        tag = select_consensus_tag(self.checkpoint_dirs(world_size),
                                   verify_integrity=self.verify_checkpoint_integrity)
        if tag is not None:
            self._record("resume_tag", tag=tag, world=world_size)
        return tag

    # --------------------------------------------------------------- spawning
    def _generation_heartbeat_dir(self) -> Optional[str]:
        """Per-generation subdir so stale stamps from a killed generation can
        never mask (or falsely indict) the new one."""
        if self.heartbeat_dir is None:
            return None
        d = os.path.join(self.heartbeat_dir, f"gen{self.restart_count}")
        os.makedirs(d, exist_ok=True)
        return d

    def _spawn(self, world_size: int) -> WorkerGroup:
        resume_tag = self.select_resume_tag(world_size)
        self.resume_tags.append(resume_tag)
        hb_dir = self._generation_heartbeat_dir()
        procs = []
        for rank in range(world_size):
            env = dict(self.base_env,
                       RANK=str(rank), WORLD_SIZE=str(world_size),
                       DSTPU_ELASTIC_RESTART=str(self.restart_count))
            if hb_dir is not None:
                env[HEARTBEAT_DIR_ENV] = hb_dir
                env[HEARTBEAT_INTERVAL_ENV] = str(self.heartbeat_interval_s)
            else:
                # same hygiene as the resume-tag scrub below: an inherited
                # heartbeat dir (outer agent, stale operator export) would
                # have these workers stamp into a FOREIGN generation dir,
                # corrupting whoever reads it with colliding rank numbers
                env.pop(HEARTBEAT_DIR_ENV, None)
                env.pop(HEARTBEAT_INTERVAL_ENV, None)
            # bounded-collective / init-retry knobs ride the same env contract
            # so a supervised worker fails fast instead of deadlocking even
            # when its own ds config never sets fault_tolerance.  Same scrub
            # hygiene as the rest of the contract: env wins over worker
            # config, so a value leaked from an operator shell or outer agent
            # would bound THIS job's collectives with a timeout nobody set
            for knob, var in ((self.collective_timeout_s, COLLECTIVE_TIMEOUT_ENV),
                              (self.init_retries, INIT_RETRIES_ENV),
                              (self.init_retry_backoff_s, INIT_RETRY_BACKOFF_ENV)):
                if knob is not None:
                    env[var] = str(knob)
                else:
                    env.pop(var, None)
            # ops-plane exchange dir: workers publish per-rank metrics
            # snapshots here for the agent's merged endpoint.  Same scrub
            # hygiene as every env knob above — an inherited dir would feed
            # this job's metrics into a FOREIGN aggregator as its ranks
            if self._ops_dir is not None:
                env[OPS_DIR_ENV] = self._ops_dir
            else:
                env.pop(OPS_DIR_ENV, None)
            if resume_tag is not None:
                env[RESUME_TAG_ENV] = resume_tag
                # scope the pin: tag names are the generic global_step<N>, so
                # without the dir a warm-start load from an UNRELATED base
                # checkpoint holding an identically-named tag would be
                # hijacked (engine applies the pin only under this dir)
                env[RESUME_DIR_ENV] = self.checkpoint_dir
            else:
                env.pop(RESUME_TAG_ENV, None)  # never leak a stale pin into gen 0
                env.pop(RESUME_DIR_ENV, None)
            procs.append(subprocess.Popen(self.worker_cmd, env=env))
        self._flagged_stragglers = set()
        self._last_heartbeats = {}
        self._record("generation_spawned", world=world_size,
                     generation=self.restart_count,
                     resume_tag=resume_tag, pids=[p.pid for p in procs])
        logger.info(f"elastic agent: launched {world_size} workers "
                    f"(restart {self.restart_count}, resume_tag={resume_tag})")
        return WorkerGroup(procs, world_size, self.restart_count, heartbeat_dir=hb_dir)

    # -------------------------------------------------------------- liveness
    def _check_liveness(self, group: WorkerGroup) -> Optional[List[int]]:
        """Stale ranks of the current generation (hang!), else None.  Also
        flags stragglers as a side effect.  A rank that never stamped counts
        as stale only after ``startup_grace_s`` (workers pay jit compiles +
        imports before their first step); a rank whose last stamp is the
        engine's post-resume marker (``phase=resumed``) gets the same grace —
        it is paying the recompile between load_checkpoint and its first
        step, which no heartbeat can tick through."""
        if self.heartbeat_timeout_s is None or group.heartbeat_dir is None:
            return None
        now = time.time()
        heartbeats = read_heartbeats(group.heartbeat_dir)
        self._last_heartbeats = heartbeats
        alive = group.alive_ranks()
        # ranks that already exited are the exit-code poll's business
        stale = [r for r in stale_ranks(heartbeats, alive, self.heartbeat_timeout_s, now)
                 if r in heartbeats]
        # a rank whose LAST stamp declared a checkpoint phase is in known-slow
        # IO (the engine force-stamps phase=checkpoint_save/load at entry and
        # writes nothing until the IO finishes) — killing it would re-run the
        # same slow save every generation until the budget burns on a healthy
        # job, so those ranks get io_grace_factor x the timeout before
        # indictment
        stale = [r for r in stale
                 if not (str(heartbeats[r].get("phase", "")).startswith("checkpoint")
                         and heartbeat_age(heartbeats[r], now)
                         <= self.heartbeat_timeout_s * self.io_grace_factor)]
        # phase=resumed: the engine finished load_checkpoint and is paying
        # the jit recompile before its first step — stale by the plain
        # timeout, but a healthy restarted generation, so it gets the same
        # grace a never-stamped launcher does
        stale = [r for r in stale
                 if not (heartbeats[r].get("phase") == "resumed"
                         and heartbeat_age(heartbeats[r], now)
                         <= (self.startup_grace_s or 0.0))]
        # a rank still at step 0 stamped (setup barrier, collective entry)
        # but hasn't trained yet — it is inside the same import+compile
        # window the never-stamped grace covers, and one early stamp must
        # not strip that grace from a healthy slow-compiling launch
        stale = [r for r in stale
                 if not (int(heartbeats[r].get("step") or 0) == 0
                         and (now - group.spawned_at)
                         <= (self.startup_grace_s or 0.0))]
        never_stamped = [r for r in alive if r not in heartbeats]
        if never_stamped and (now - group.spawned_at) > (self.startup_grace_s or 0.0):
            stale = sorted(set(stale) | set(never_stamped))
        if stale:
            return stale
        # straggler math over LIVE ranks only: an exited rank's frozen stamp
        # is not a laggard (nothing is waiting on it) and would skew the
        # median the live ranks are measured against
        live_beats = {r: rec for r, rec in heartbeats.items() if r in alive}
        if self.straggler_lag_steps is not None and len(live_beats) >= 2:
            for rank in straggler_ranks(live_beats, self.straggler_lag_steps):
                key = (group.restart, rank)
                if key not in self._flagged_stragglers:
                    self._flagged_stragglers.add(key)
                    record = heartbeats.get(rank, {})
                    self._record("straggler", rank=rank, step=record.get("step"),
                                 generation=group.restart,
                                 lag_threshold=self.straggler_lag_steps)
                    logger.warning(f"elastic agent: rank {rank} is a straggler "
                                   f"(step {record.get('step')}, > "
                                   f"{self.straggler_lag_steps} steps behind the median)")
        return None

    def _dump_hang(self, group: WorkerGroup, stale: List[int]) -> None:
        report = format_hang_report(self._last_heartbeats, list(range(group.world_size)),
                                    self.heartbeat_timeout_s or 0.0)
        logger.error(f"elastic agent: hang detected — stale rank(s) {stale} "
                     f"(no heartbeat for > {self.heartbeat_timeout_s}s)\n{report}")
        collectives = {r: self._last_heartbeats.get(r, {}).get("collective")
                       for r in stale}
        ages = {r: round(heartbeat_age(self._last_heartbeats[r]), 2)
                for r in stale if r in self._last_heartbeats}
        self._record("hang_detected", ranks=stale, collectives=collectives,
                     stamp_ages_s=ages, generation=group.restart, report=report)

    # ---------------------------------------------------------------- signals
    def _install_signal_handlers(self):
        """SIGINT/SIGTERM to the agent must tear the worker group down (with
        the grace window) instead of orphaning it — handlers just set a flag
        the poll loop acts on, so teardown happens in one place."""
        if threading.current_thread() is not threading.main_thread():
            return  # signal.signal is main-thread-only; threaded callers own teardown

        def _on_signal(signum, frame):
            self._interrupt_signum = signum

        for signum in (signal.SIGINT, signal.SIGTERM):
            try:
                self._prev_handlers[signum] = signal.signal(signum, _on_signal)
            except (ValueError, OSError) as exc:
                logger.warning(f"elastic agent: could not install handler for "
                               f"signal {signum} ({exc})")

    def _restore_signal_handlers(self):
        for signum, prev in self._prev_handlers.items():
            try:
                signal.signal(signum, prev)
            except (ValueError, OSError):
                pass  # teardown best-effort: restore can only fail off the main thread, where none was installed
        self._prev_handlers = {}

    # -------------------------------------------------------------------- run
    def run(self) -> int:
        """Supervise until success (0), non-restartable worker failure (that
        worker's rc, immediately — restarting a config/usage error just burns
        the budget), interruption (128+signum after tearing the group down),
        or restart budget exhausted (1).  Restartable failures — nonzero
        exits outside ``non_restartable_exit_codes`` and detected hangs —
        trigger the terminate → rescale → pin-resume-tag → respawn cycle.
        Children are reaped on every exit path."""
        world = self.initial_world
        valid = self.valid_world_sizes()
        if world not in valid:
            # launching at a size the elastic config forbids breaks the batch
            # math from step 0 — clamp before the first generation
            fitting = [w for w in valid if w <= world]
            if not fitting:
                logger.error(f"elastic agent: no valid world size <= {world} "
                             f"(valid: {valid})")
                return 1
            logger.warning(f"elastic agent: world_size {world} is not elastic-valid "
                           f"{valid}; clamping to {max(fitting)}")
            world = max(fitting)
        # a leftover flag from a previous interrupted run() would kill the
        # fresh generation on the first poll — each run starts clean
        self._interrupt_signum = None
        self._install_signal_handlers()
        group: Optional[WorkerGroup] = None
        try:
            self._current_world = world
            group = self._spawn(world)
            while True:
                time.sleep(self.poll_interval)
                # merged fleet metrics + liveness gauges each poll (host-only)
                self._refresh_ops(group)
                if self._interrupt_signum is not None:
                    signum = self._interrupt_signum
                    logger.warning(f"elastic agent: received signal {signum}; "
                                   f"terminating worker group (grace "
                                   f"{self.term_grace_secs}s)")
                    self._record("agent_interrupted", signum=signum, world=world)
                    group.terminate(self.term_grace_secs)
                    return 128 + signum
                failure = group.poll_failed()
                hung: Optional[List[int]] = None
                if failure is not None:
                    rank, rc = failure
                    if rc in self.non_restartable_exit_codes:
                        logger.error(f"elastic agent: rank {rank} exited rc={rc} "
                                     f"(non-restartable class) — returning it "
                                     f"instead of burning {self.max_restarts} restarts")
                        self._record("worker_failed", rank=rank, rc=rc,
                                     restartable=False, world=world)
                        group.terminate(self.term_grace_secs)
                        return rc
                    logger.warning(f"elastic agent: worker rank {rank} failed rc={rc} "
                                   f"(world={world}, restart {self.restart_count})")
                    self._record("worker_failed", rank=rank, rc=rc,
                                 restartable=True, world=world)
                else:
                    hung = self._check_liveness(group)
                    if hung is not None:
                        self._dump_hang(group, hung)
                    elif group.all_done():
                        logger.info("elastic agent: all workers finished cleanly")
                        self._record("run_complete", world=world,
                                     restarts=self.restart_count)
                        self._refresh_ops(group, force=True)  # final merged view
                        if self._ops_own_dir:
                            # launcher convention: sweep OUR tempdir exchange
                            # files on a clean run, keep them for postmortem
                            # on any failure path; caller dirs never touched
                            import shutil
                            shutil.rmtree(self._ops_dir, ignore_errors=True)
                        return 0
                    else:
                        continue
                # restartable failure or hang: graceful handoff, then respawn
                group.terminate(self.term_grace_secs)
                if self.restart_count >= self.max_restarts:
                    logger.error("elastic agent: restart budget exhausted")
                    self._record("budget_exhausted", world=world,
                                 restarts=self.restart_count)
                    return 1
                self.restart_count += 1
                shrunk = self.next_world_size(world)
                if shrunk is not None:
                    logger.info(f"elastic agent: rescaling {world} -> {shrunk}")
                    self._record("rescale", from_world=world, to_world=shrunk,
                                 reason="hang" if hung else "worker_failed")
                    world = shrunk
                # world == min valid size: respawn at the same size
                self._current_world = world
                group = self._spawn(world)
        finally:
            self._restore_signal_handlers()
            if group is not None and group.alive_ranks():
                # exception/interrupt path: never leave orphans behind
                group.terminate(self.term_grace_secs)
