"""Framework configuration.

TPU-native analog of the reference config system (deepspeed/runtime/config.py —
``DeepSpeedConfig`` with ~80 ``get_*`` extractors plus pydantic sub-models).  A single
JSON file or dict configures the whole engine; the batch-size triple
``train_batch_size = micro_batch * gradient_accumulation_steps * dp_world_size``
is reconciled exactly like the reference (runtime/config.py:837 ``_configure_train_batch_size``).

TPU-specific extension: the ``mesh`` section declaring the device-mesh axis sizes
(data/fsdp/tensor/sequence/expert/pipe) instead of the reference's implicit
world-size + mpu plumbing.
"""

import json
from typing import Any, Dict, List, Optional, Union

from .config_utils import ConfigModel, Field
from ..utils.logging import logger

TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"

# Reference-spelled keys read out of sections this schema deliberately models
# as ``Dict[str, Any]`` (curriculum schedules, compression_training): dslint's
# undeclared-config-key rule checks every string key read from a config dict
# against the union of all ConfigModel fields AND this registry, so a typo'd
# key is a lint error instead of a silent fall-through to the default.  Add a
# key here ONLY when it matches the reference DeepSpeed spelling.
DECLARED_EXTRA_KEYS = frozenset({
    # curriculum learning schedule dict (reference runtime/data_pipeline/config.py
    # + legacy get_curriculum_params spellings)
    "curriculum_type", "schedule_type", "schedule_config", "min_difficulty",
    "max_difficulty", "total_curriculum_step", "difficulty_step", "root_degree",
    "difficulty", "max_step",
    # compression_training sections (reference compression/config.py)
    "weight_quantization", "sparse_pruning", "row_pruning", "head_pruning",
    "channel_pruning", "different_groups", "shared_parameters",
    "layer_reduction", "keep_layers", "keep_number_layer", "teacher_layer",
    "module_name_prefix",
})


class FP16Config(ConfigModel):
    """Reference: deepspeed/runtime/fp16 config (runtime/config.py:125-180)."""
    enabled: bool = False
    auto_cast: bool = False
    loss_scale: float = Field(0.0, ge=0.0)  # 0 => dynamic
    initial_scale_power: int = Field(16, ge=0)
    loss_scale_window: int = Field(1000, ge=1)
    hysteresis: int = Field(2, ge=1)
    consecutive_hysteresis: bool = False
    min_loss_scale: float = Field(1.0, ge=0.0)


class BF16Config(ConfigModel):
    """Reference: bf16 section (runtime/config.py:162). TPU default-on happens in
    TrainingConfig.model_validate when neither fp16 nor fp32 is requested."""
    enabled: bool = True


class OffloadDeviceEnum:
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


class OffloadParamConfig(ConfigModel):
    """Reference: DeepSpeedZeroOffloadParamConfig (runtime/zero/offload_config.py:24)."""
    device: str = Field("none", choices=("none", "cpu", "nvme"))
    nvme_path: Optional[str] = None
    buffer_count: int = Field(5, ge=1)
    buffer_size: int = Field(10**8, ge=1)
    max_in_cpu: int = Field(10**9, ge=0)
    pin_memory: bool = False


class OffloadOptimizerConfig(ConfigModel):
    """Reference: DeepSpeedZeroOffloadOptimizerConfig (runtime/zero/offload_config.py:52)."""
    device: str = Field("none", choices=("none", "cpu", "nvme"))
    nvme_path: Optional[str] = None
    buffer_count: int = Field(4, ge=1)
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False
    ratio: float = Field(1.0, ge=0.0, le=1.0)


class ZeroConfig(ConfigModel):
    """Reference: DeepSpeedZeroConfig (runtime/zero/config.py) — stages, buckets,
    ZeRO++ knobs (hpZ/qwZ/qgZ), offload sub-configs."""
    stage: int = Field(0, ge=0, le=3)
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = Field(int(5e8), ge=0)
    allgather_partitions: bool = True
    allgather_bucket_size: int = Field(int(5e8), ge=0)
    overlap_comm: Optional[bool] = None
    round_robin_gradients: bool = False
    offload_param: Optional[OffloadParamConfig] = None
    offload_optimizer: Optional[OffloadOptimizerConfig] = None
    sub_group_size: int = Field(int(1e9), ge=0)
    prefetch_bucket_size: int = Field(int(5e7), ge=0, deprecated_names=("stage3_prefetch_bucket_size", ))
    param_persistence_threshold: int = Field(int(1e5), ge=0, deprecated_names=("stage3_param_persistence_threshold", ))
    model_persistence_threshold: int = Field(int(1e14), ge=0, deprecated_names=("stage3_model_persistence_threshold", ))
    max_live_parameters: int = Field(int(1e9), ge=0, deprecated_names=("stage3_max_live_parameters", ))
    max_reuse_distance: int = Field(int(1e9), ge=0, deprecated_names=("stage3_max_reuse_distance", ))
    gather_16bit_weights_on_model_save: bool = Field(False,
                                                    deprecated_names=("stage3_gather_16bit_weights_on_model_save", ))
    ignore_unused_parameters: bool = True
    # ZeRO++ analogs (reference runtime/zero/config.py:264-280)
    zero_hpz_partition_size: int = Field(1, ge=1)
    zero_quantized_weights: bool = False
    zero_quantized_gradients: bool = False
    mics_shard_size: int = Field(-1, deprecated_names=("mics_shard_size_", ))
    mics_hierarchical_params_gather: bool = False
    elastic_checkpoint: bool = False

    def model_validate(self):
        if self.overlap_comm is None:
            # Reference defaults overlap_comm True for stage 3 (zero/config.py:308)
            object.__setattr__(self, "overlap_comm", self.stage == 3)


class ActivationCheckpointingConfig(ConfigModel):
    """Reference: runtime/activation_checkpointing config (runtime/config.py:440)."""
    partition_activations: bool = False
    contiguous_memory_optimization: bool = False
    cpu_checkpointing: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False
    # TPU-native: jax.checkpoint policy name applied to the layer scan.
    policy: str = Field("nothing_saveable",
                        choices=("everything_saveable", "nothing_saveable", "dots_saveable",
                                 "dots_with_no_batch_dims_saveable", "checkpoint_dots",
                                 "save_anything_except_these_names", "offload_dot",
                                 "offload_residuals"))


class OptimizerConfig(ConfigModel):
    allow_extra = True
    type: str = "adamw"
    params: Dict[str, Any] = Field(dict)


class SchedulerConfig(ConfigModel):
    allow_extra = True
    type: Optional[str] = None
    params: Dict[str, Any] = Field(dict)


class CommsLoggerConfig(ConfigModel):
    """Reference: DeepSpeedCommsConfig (comm/config.py)."""
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: List[str] = Field(list)


class TensorBoardConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTPUJobName"


class WandbConfig(ConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed_tpu"


class CSVConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTPUJobName"


class MonitorConfig(ConfigModel):
    """Reference: DeepSpeedMonitorConfig (monitor/config.py)."""
    tensorboard: TensorBoardConfig = Field(TensorBoardConfig)
    wandb: WandbConfig = Field(WandbConfig)
    csv_monitor: CSVConfig = Field(CSVConfig)


class FlopsProfilerConfig(ConfigModel):
    """Reference: DeepSpeedFlopsProfilerConfig (profiling/config.py)."""
    enabled: bool = False
    profile_step: int = Field(1, ge=0)
    module_depth: int = -1
    top_modules: int = Field(1, ge=1)
    detailed: bool = True
    output_file: Optional[str] = None


class TelemetryConfig(ConfigModel):
    """Unified telemetry (TPU-native; no single reference analog — subsumes the
    reference's wall_clock_breakdown timers + see_memory_usage + monitor event
    wiring into one per-step record stream, monitor/telemetry.py).

    ``enabled`` (or a non-None ``jsonl_path``) turns on per-step structured
    records: loss, grad-norm, lr, step wall-time, samples/sec, tokens/sec, MFU
    and HBM stats, fanned out to MonitorMaster and a rank-0 JSONL sink.

    ``profile_step_start``/``profile_step_stop`` open a ``jax.profiler`` trace
    window over those global steps (TensorBoard-readable files under
    ``profile_dir``), with StepTraceAnnotation on each step and TraceAnnotation
    around batch-prep and checkpoint IO.

    Cost: a per-step record needs the step's loss and wall-time, so enabling
    telemetry adds ONE host value-fetch (device sync) per train step — host
    work stops overlapping device execution, like ``wall_clock_breakdown``.
    Leave it off for maximum-throughput runs and sample with a profiler window
    instead.
    """
    enabled: bool = False
    jsonl_path: Optional[str] = None
    # flush the JSONL sink every N records (1 = after every record, the
    # pre-tracing behavior tests rely on; raise it for high-rate record
    # streams — per-request serving traces — so file flushes stay off the
    # serve loop; close() always flushes whatever is buffered)
    jsonl_flush_every: int = Field(1, ge=1)
    # -1 disables; [start, stop) in global steps, mirroring the reference's
    # flops_profiler profile_step single-shot trigger but as a window
    profile_step_start: int = Field(-1, ge=-1)
    profile_step_stop: int = Field(-1, ge=-1)
    profile_dir: str = "profiler_traces"
    # -1 disables; [start, stop) in SERVE-LOOP iterations (ISSUE 16): opens
    # one jax.profiler trace window per generate() call, bracketing serve
    # iterations the way profile_step_start/stop brackets train steps, with a
    # TraceAnnotation per serve phase while the window is open
    profile_serve_iteration_start: int = Field(-1, ge=-1)
    profile_serve_iteration_stop: int = Field(-1, ge=-1)
    # see_memory_usage(tag) at each steps_per_print boundary (also honors the
    # reference's top-level memory_breakdown key)
    memory_breakdown: bool = False
    # per-chip peak FLOPs override for MFU; None => detect from device_kind
    peak_flops_per_chip: Optional[float] = Field(None, gt=0.0)

    def model_validate(self):
        if self.jsonl_path is not None and not self.enabled:
            object.__setattr__(self, "enabled", True)
        if (self.profile_step_stop >= 0 and self.profile_step_start >= 0
                and self.profile_step_stop <= self.profile_step_start):
            raise ValueError(f"telemetry: profile_step_stop={self.profile_step_stop} must be "
                             f"> profile_step_start={self.profile_step_start}")
        if (self.profile_serve_iteration_stop >= 0
                and self.profile_serve_iteration_start >= 0
                and self.profile_serve_iteration_stop <= self.profile_serve_iteration_start):
            raise ValueError(
                f"telemetry: profile_serve_iteration_stop={self.profile_serve_iteration_stop} "
                f"must be > profile_serve_iteration_start={self.profile_serve_iteration_start}")


class MeshConfig(ConfigModel):
    """TPU-native: explicit device-mesh axis sizes.

    Replaces the reference's world-size + mpu + groups plumbing
    (deepspeed/utils/groups.py).  Any axis set to -1 absorbs the remaining
    devices (at most one axis may be -1; default: data).
    """
    data: int = -1
    fsdp: int = 1
    tensor: int = 1
    sequence: int = 1
    expert: int = 1
    pipe: int = 1
    # Axis order outer→inner; inner axes map to ICI-adjacent devices.
    axis_order: List[str] = Field(lambda: ["pipe", "data", "fsdp", "expert", "sequence", "tensor"])

    def model_validate(self):
        sizes = self.axis_sizes()
        wild = [a for a, s in sizes.items() if s == -1]
        if len(wild) > 1:
            raise ValueError(f"MeshConfig: at most one axis may be -1, got {wild}")
        for a, s in sizes.items():
            if s < 1 and s != -1:
                raise ValueError(f"MeshConfig.{a}={s} must be >=1 or -1")
        known = set(sizes)
        seen = set()
        for a in self.axis_order:
            if a not in known:
                raise ValueError(f"MeshConfig.axis_order: unknown axis {a!r}; valid axes: {sorted(known)}")
            if a in seen:
                raise ValueError(f"MeshConfig.axis_order: duplicate axis {a!r}")
            seen.add(a)

    def axis_sizes(self):
        return {a: getattr(self, a) for a in ("data", "fsdp", "tensor", "sequence", "expert", "pipe")}


class SparseAttentionConfig(ConfigModel):
    """Blocksparse attention section (reference runtime/config.py:286
    ``get_sparse_attention`` — mode + per-mode knobs).  ``build(num_heads)``
    resolves the matching SparsityConfig from ops/sparse_attention."""
    mode: str = Field("fixed", choices=("dense", "fixed", "variable", "bigbird", "bslongformer", "local"))
    block: int = Field(16, ge=8)  # must be a multiple of 8 (TPU sublane); see model_validate
    different_layout_per_head: bool = False
    # fixed / variable
    num_local_blocks: int = Field(4, ge=1)
    num_global_blocks: int = Field(1, ge=1)
    # None -> per-mode default: "unidirectional" for local (the causal Mistral
    # pattern is that class's own default), "bidirectional" elsewhere.
    attention: Optional[str] = Field(None, choices=(None, "unidirectional", "bidirectional"))
    horizontal_global_attention: bool = False
    num_different_global_patterns: int = Field(1, ge=1)
    # variable / bigbird; None -> per-mode default (bigbird: 1, variable: 0),
    # matching each reference class's own constructor default.
    num_random_blocks: Optional[int] = Field(None, ge=0)
    local_window_blocks: Optional[List[int]] = None
    global_block_indices: Optional[List[int]] = None
    global_block_end_indices: Optional[List[int]] = None
    # bigbird / bslongformer / local
    num_sliding_window_blocks: int = Field(3, ge=1)
    # seeds the random-block placement (variable / bigbird) so layouts are
    # reproducible AND rank-identical — every process derives the same layout
    # from config alone instead of the global `random` module state
    seed: int = Field(1234, ge=0)

    def model_validate(self):
        if self.block % 8 != 0:
            raise ValueError(
                f"sparse_attention.block={self.block} must be a multiple of 8 — the "
                f"Pallas kernel tiles on the TPU sublane; non-multiples silently hit "
                f"the O(S^2) dense fallback")

    def build(self, num_heads: int):
        from ..ops.sparse_attention import (BigBirdSparsityConfig, BSLongformerSparsityConfig,
                                            DenseSparsityConfig, FixedSparsityConfig,
                                            LocalSlidingWindowSparsityConfig,
                                            VariableSparsityConfig)
        attention = self.attention or ("unidirectional" if self.mode == "local" else "bidirectional")
        if self.mode == "dense":
            return DenseSparsityConfig(num_heads, self.block, self.different_layout_per_head)
        if self.mode == "fixed":
            return FixedSparsityConfig(
                num_heads, self.block, self.different_layout_per_head,
                self.num_local_blocks, self.num_global_blocks, attention,
                self.horizontal_global_attention, self.num_different_global_patterns)
        if self.mode == "variable":
            return VariableSparsityConfig(
                num_heads, self.block, self.different_layout_per_head,
                self.num_random_blocks or 0, self.local_window_blocks,
                self.global_block_indices, self.global_block_end_indices,
                attention, self.horizontal_global_attention, seed=self.seed)
        if self.mode == "bigbird":
            num_random = self.num_random_blocks if self.num_random_blocks is not None else 1
            return BigBirdSparsityConfig(
                num_heads, self.block, self.different_layout_per_head,
                num_random, self.num_sliding_window_blocks,
                self.num_global_blocks, attention, seed=self.seed)
        if self.mode == "bslongformer":
            return BSLongformerSparsityConfig(
                num_heads, self.block, self.different_layout_per_head,
                self.num_sliding_window_blocks, self.global_block_indices,
                self.global_block_end_indices, attention)
        return LocalSlidingWindowSparsityConfig(
            num_heads, self.block, self.num_sliding_window_blocks, attention)


class GradientCompressionConfig(ConfigModel):
    """1-bit style compressed gradient reduction (reference runtime/comm/nccl.py:51)."""
    enabled: bool = False
    freeze_step: int = Field(100, ge=0)


class CheckpointSectionConfig(ConfigModel):
    """Reference: the "checkpoint" section (runtime/config.py
    ``get_checkpoint_params``) plus engine selection — the reference picks the
    Nebula async engine vs torch from config in ``_configure_checkpointing``
    (runtime/engine.py:921).  ``checkpoint_engine`` here selects the plug-in
    built by runtime/checkpoint_engine.build_checkpoint_engine.

    Resilience knobs (runtime/checkpointing.py durability protocol):
    ``keep_last_n`` GCs tags beyond the newest N after each save (the newest
    VALID tag is never deleted); ``verify_integrity`` re-checks each leaf's
    CRC32 against the manifest at load; ``save_retries``/``retry_backoff_secs``
    bound the exponential-backoff retry loop around transient save OSErrors;
    ``save_on_preemption`` installs a SIGTERM handler that performs one final
    best-effort save (tag ``preempt_step<N>``, ``client_state.preempted``
    true) before the process dies."""
    allow_extra = True
    checkpoint_engine: str = Field("native", choices=("native", "torch", "async", "nebula"))
    async_max_queue: int = Field(64, ge=1)
    tag_validation: Optional[str] = Field(None, choices=(None, "Ignore", "Warn", "Fail",
                                                         "ignore", "warn", "fail"))
    use_node_local_storage: bool = False
    parallel_write: Optional[Dict[str, Any]] = None
    keep_last_n: Optional[int] = Field(None, ge=1)
    verify_integrity: bool = False
    save_retries: int = Field(2, ge=0)
    retry_backoff_secs: float = Field(0.5, ge=0.0)
    save_on_preemption: bool = False


class FaultToleranceConfig(ConfigModel):
    """Elastic training fault tolerance (runtime/heartbeat.py + the elastic
    agent's liveness monitor + comm/comm.py bounded collectives — the
    training-side analog of the reference's elastic agent supervision,
    ``DSElasticAgent`` in deepspeed/elasticity/elastic_agent.py, extended with
    hang detection the reference delegates to torch-elastic/NCCL timeouts).

    ``heartbeat`` arms per-rank liveness stamps: the engine writes
    ``step + wall-clock + last-entered-collective`` to
    ``<heartbeat_dir>/hb.rank<R>.json`` from its existing host-touch points
    (zero extra device syncs — dslint's host-sync rule scans heartbeat.py),
    throttled to one write per ``heartbeat_interval_s``.  The elastic agent
    exports ``DSTPU_HEARTBEAT_DIR`` to its workers, which arms stamping even
    when this section is absent — config here is for standalone runs that
    want the liveness file anyway.

    ``collective_timeout_s`` bounds host-level collectives (``comm.barrier``
    and anything routed through ``comm.bounded_collective``): instead of a
    silent distributed deadlock, a wedged collective raises
    ``CollectiveTimeoutError`` naming the collective, this rank, and the
    elapsed time — a fast, attributable failure the agent restarts from.
    ``init_retries``/``init_retry_backoff_s`` bound the exponential-backoff
    retry loop around transient process-group setup failures in
    ``comm.init_distributed`` (coordinator not yet listening at scale-up);
    ``deepspeed_tpu.initialize()`` applies them before process-group setup,
    and the agent-exported env (``DSTPU_INIT_RETRIES`` /
    ``DSTPU_INIT_RETRY_BACKOFF_S``) wins over both.
    """
    heartbeat: bool = False
    heartbeat_dir: Optional[str] = None
    heartbeat_interval_s: float = Field(1.0, ge=0.0)
    collective_timeout_s: Optional[float] = Field(None, gt=0.0)
    init_retries: int = Field(3, ge=0)
    init_retry_backoff_s: float = Field(0.5, ge=0.0)

    def model_validate(self):
        import os

        from .heartbeat import HEARTBEAT_DIR_ENV
        # the agent-exported env satisfies the requirement (it's the remedy
        # the error names): heartbeat=true under supervision must not turn
        # every worker into a restartable config error the agent respawns
        # until the budget burns
        if self.heartbeat and not self.heartbeat_dir and not os.environ.get(HEARTBEAT_DIR_ENV):
            raise ValueError("fault_tolerance.heartbeat=true needs heartbeat_dir "
                             "(or launch under the elastic agent, which exports "
                             "DSTPU_HEARTBEAT_DIR and overrides this section)")


class ServingResilienceConfig(ConfigModel):
    """Serving-side overload policy for the v2 ragged engine
    (inference/v2/admission.py — the serving analog of the training-side
    checkpoint/watchdog resilience knobs; no single reference section, this
    models FastGen/MII request rejection + flush as explicit policy).

    Admission: requests enter a bounded, priority-aware queue and are load-shed
    with a structured retryable/fatal reason BEFORE any KV allocation when
    ``max_queue_depth`` or ``shed_kv_utilization`` is crossed
    (``shed_kv_utilization=1.0`` disables pressure shedding: requests queue
    until the pool frees instead).  ``default_ttl_s`` gives every request a
    deadline (per-call ``generate(ttl_s=...)`` overrides); expired requests are
    evicted between steps — never mid-forward — with their blocks reclaimed.

    Scheduling: ``preemption`` lets a starved decode step reclaim KV blocks
    from the newest prefilling sequence (rolled back to a block boundary and
    requeued, at most ``max_preemptions`` times; once every candidate victim
    is exhausted the newest is evicted with status
    ``preempt_requeued_exhausted``).  ``stall_watchdog_steps`` bounds
    live-but-unschedulable loops: after that many steps without progress the
    engine raises ``ServingStalledError`` carrying a full state snapshot
    (strict mode) or fails the stuck requests and keeps serving the rest.
    """
    max_queue_depth: int = Field(0, ge=0)  # 0 => unbounded admission queue
    shed_kv_utilization: float = Field(1.0, gt=0.0, le=1.0)
    default_ttl_s: Optional[float] = Field(None, gt=0.0)
    max_live_seqs: int = Field(0, ge=0)  # 0 => bounded only by the scheduler
    preemption: bool = True
    max_preemptions: int = Field(2, ge=0)
    stall_watchdog_steps: int = Field(100, ge=1)


class ServingFastpathConfig(ConfigModel):
    """Serving hot-path policy for the v2 ragged engine
    (inference/v2/fastpath.py — no reference section; this models the
    orchestration-overhead levers FastGen gets from CUDA graphs + pinned
    ragged batch buffers, translated to XLA: persistent device-resident
    batch state, deferred host syncs, and fused decode slices).

    ``enabled`` turns the whole fast path off, falling back to the
    rebuild-and-upload-per-step reference loop (the equivalence oracle the
    fastpath tests diff against).  ``pipeline_depth=1`` defers the sampled-
    token fetch by one step so host-side scheduling of step N+1 overlaps
    device execution of step N (0 = fully synchronous); the pipeline
    disengages automatically whenever admission tickets are queued or any
    live sequence carries a deadline, so PR-4 eviction semantics are
    bit-exact.  ``fusion_min_steps`` is the smallest remaining-token window
    worth fusing into one on-device decode burst.  ``prewarm_buckets``
    bounds how many (batch, chunk, table) bucket programs ``generate()``
    AOT-compiles at intake so mid-wave recompiles stop stalling p95.

    The whole fast path applies unchanged under TP×DP meshes (ISSUE 15):
    the persistent batch buffers replicate over the engine's mesh
    (``NamedSharding(mesh, PartitionSpec())``) while params/KV keep their
    sharded specs, the delta scatter compiles as a sharded donated update,
    and prewarm lowers against sharded avals — no knob selects this; the
    engine's topology does.
    """
    enabled: bool = True
    pipeline_depth: int = Field(1, choices=(0, 1))
    fusion_min_steps: int = Field(2, ge=2)
    prewarm_buckets: int = Field(4, ge=0)


class ServingSpecDecodeConfig(ConfigModel):
    """Speculative decoding on the v2 engine's fused decode path (ISSUE 20 —
    inference/v2/spec_decode.py; the XLA translation of Leviathan et al.'s
    draft/verify with exact rejection sampling, applied per-sequence inside
    the Orca-style ragged batch).

    ``enabled`` arms the spec path: on every pure-decode fused window a
    drafter proposes ``k`` tokens per sequence, the target model verifies all
    of them in ONE batched forward over the paged KV pool, and on-device
    rejection sampling accepts the longest valid prefix plus one resampled
    token — between 1 and k+1 tokens per sequence per round, with the output
    distribution provably the target model's (token-identical to spec-off
    under greedy decode; distribution-identical under temperature/top-k/
    top-p sampling).  Off (the default) the engine is byte-identical to the
    pre-spec stack.

    ``drafter`` picks the proposal source: ``"ngram"`` is the zero-weight
    prompt-lookup drafter (longest-suffix n-gram match over the sequence's
    own token history — no second model, proposals cost pure host python);
    ``"model"`` uses a small draft model from the model zoo attached via
    ``InferenceEngineV2.attach_draft_model(...)`` (greedy-drafted against
    its own paged pool, replicated under the engine's mesh).

    ``k`` caps the draft length; the ADAPTIVE controller moves the live k
    through a small static ladder (1, 3, 7, 15, ... capped at ``k`` —
    verify widths k+1 stay powers of two) on an EWMA of the acceptance rate
    (``ewma_alpha``; raise above ``raise_threshold``, lower below
    ``lower_threshold``), so every verify program is one of a handful of
    prewarmable bucket shapes and a drifting acceptance rate can never
    recompile mid-serve.  At the k=1 floor the engine falls back to the
    plain fused burst (zero spec overhead, zero recompiles) and re-probes
    spec every ``probe_every`` fused rounds.  ``adaptive_k=False`` pins k.

    ``ngram_max``/``ngram_min`` bound the suffix-match length the n-gram
    drafter tries (longest first).
    """
    enabled: bool = False
    drafter: str = Field("ngram", choices=("ngram", "model"))
    k: int = Field(4, ge=1)
    adaptive_k: bool = True
    ewma_alpha: float = Field(0.3, gt=0.0, le=1.0)
    raise_threshold: float = Field(0.7, ge=0.0, le=1.0)
    lower_threshold: float = Field(0.3, ge=0.0, le=1.0)
    probe_every: int = Field(16, ge=1)
    ngram_max: int = Field(3, ge=1)
    ngram_min: int = Field(1, ge=1)


class ServingTracingConfig(ConfigModel):
    """Request-lifecycle tracing + SLO latency histograms for the v2 ragged
    engine (monitor/tracing.py wired through inference/v2 — no reference
    section; this models the per-request observability vLLM/Orca-class
    systems report: TTFT/TBT/e2e percentiles and per-request span chains).

    ``enabled`` turns on per-uid span recording (queue_wait → prefill →
    decode, requeue spans around preemptions, one terminal event matching the
    request's ``RequestResult`` status) and the TTFT/TBT/e2e histograms.
    Tracing consumes ONLY the engine's injectable clock at host-touch points
    (admission, wave boundaries, token materialization) and adds zero device
    syncs — the serving fast path's counter invariants hold with tracing on.
    ``trace_jsonl`` exports each completed trace as a ``kind: trace`` record
    through the attached telemetry collector's JSONL sink;
    ``chrome_trace_path`` additionally buffers Chrome-trace-event JSON
    (load in Perfetto / chrome://tracing) written by
    ``RequestTracer.write_chrome_trace()`` (the engine writes it at the end
    of each ``generate()`` call).

    The flight recorder — a bounded ring of the last
    ``flight_recorder_events`` engine events (dispatch/absorb/flush/burst/
    preempt/shed/admit/expire/stall) dumped into ``ServingStalledError``
    snapshots and ``health()`` — is ALWAYS on; the knob only sizes the ring.

    Histogram buckets are logarithmic: ``histogram_buckets_per_decade``
    buckets per decade starting at ``histogram_min_s`` seconds; quantiles
    return deterministic bucket representatives (relative error bounded by
    one bucket width), and same-shaped histograms merge exactly.
    """
    enabled: bool = False
    trace_jsonl: bool = True
    chrome_trace_path: Optional[str] = None
    flight_recorder_events: int = Field(256, ge=16)
    histogram_buckets_per_decade: int = Field(6, ge=1, le=100)
    histogram_min_s: float = Field(1e-5, gt=0.0)


class ServingPerfConfig(ConfigModel):
    """Serving performance observatory for the v2 ragged engine (ISSUE 16 —
    monitor/perf.py wired through inference/v2; the serving twin of the
    reference's training-only ``wall_clock_breakdown`` + flops profiler).

    ``enabled`` turns on the StepPhaseProfiler: per-iteration phase spans
    (admission_pump / scatter_upload / dispatch / absorb_patch / burst /
    flush / expire / other) charged by reading the engine's injectable clock
    at phase boundaries, accumulated into deterministic-quantile streaming
    histograms, exported as ``serving_phase_*`` metric families, Chrome-trace
    phase tracks and an every-``phase_budget_every``-iterations phase-budget
    flight-recorder line, plus the live roofline gauges
    (``serving_hbm_bytes_per_token`` / ``serving_roofline_fraction`` /
    ``serving_model_flops_utilization``) against ``hbm_gbps_spec`` and
    ``peak_flops_per_chip``.  Off by default: phase marks READ the clock, and
    deadline/TTL semantics under an injected deterministic clock must not
    shift when the observatory is toggled — with it off, the engine performs
    zero additional clock reads, so tokens and ``ServeCounters`` are
    byte-identical either way (the perf-smoke lane proves it).

    The CompileLedger and per-bucket ``cost_analysis()`` capture are ALWAYS
    on regardless of ``enabled`` — they add no clock reads and no device
    work, and the ledger is the single source of truth behind
    ``ServeCounters.compiles`` (``capture_cost_analysis`` gates only the
    AOT-time cost read, for backends whose executables can't report costs).
    """
    enabled: bool = False
    # emit a phase-budget flight-recorder line every N serve iterations
    phase_budget_every: int = Field(50, ge=1)
    # phase-span histogram shape; min_s is two decades below the request
    # histograms' 1e-5 — phase spans are sub-iteration slivers
    histogram_buckets_per_decade: int = Field(6, ge=1, le=100)
    histogram_min_s: float = Field(1e-7, gt=0.0)
    # HBM bandwidth spec for the roofline denominator (GB/s; 819 = v5e, the
    # same constant BENCH's hbm_stream_fraction_of_spec divides by)
    hbm_gbps_spec: float = Field(819.0, gt=0.0)
    # per-chip peak FLOPs for serving MFU; None leaves the MFU gauge at 0
    peak_flops_per_chip: Optional[float] = Field(None, gt=0.0)
    capture_cost_analysis: bool = True


class ServingFaultToleranceConfig(ConfigModel):
    """Serving-side crash durability + supervised restart for the v2 ragged
    engine (inference/v2/journal.py + inference/v2/supervisor.py — the
    serving analog of the elastic training supervision in PR 7; no single
    reference section: the reference pairs its inference runtime with
    elastic checkpoint-backed recovery, but a serving-process crash there
    still loses every queued and in-flight request).

    ``enabled`` arms the durable request journal: one CRC-framed record per
    admitted request (uid, prompt, priority, TTL, budget, sampling key),
    batched emitted-token deltas appended at wave-boundary flushes (the host
    already holds those tokens — zero extra device syncs), and a terminal
    record mirroring each ``RequestResult``.  ``journal_path`` names the WAL
    file (the supervisor-exported ``DSTPU_SERVING_JOURNAL`` env arms it with
    no config changes, the same contract the elastic agent uses for
    heartbeats); ``fsync_every`` fsyncs the journal every N wave-boundary
    flushes (strict mode also writes + fsyncs admits and terminals
    eagerly).  0 is throughput mode: no fsync until close, but every
    record reaches OS pages at the NEXT wave boundary (the serve loop
    flushes each iteration; the serve call's exit always flushes), so a
    process crash loses at most one iteration's records — which recovery
    absorbs by re-serving from the surviving journaled prefix.

    ``heartbeat`` stamps a serve-iteration liveness file (phase ``serving``)
    through ``runtime/heartbeat.py`` — zero device syncs, same writer the
    training engine uses; ``ServingSupervisor`` arms it via env for its
    workers, and a stale stamp (``hang_timeout_s``, after
    ``startup_grace_s``) or a dead process both count as one failure.

    ``max_restarts`` within ``restart_window_s`` bounds the supervisor's
    restart budget; past it the supervisor degrades to drain-only mode —
    new admissions are shed with a structured retryable reason, recoverable
    journal work gets one final attempt, and anything still unfinished is
    finalized as ``failed`` directly in the journal.  Never a hang.
    """
    enabled: bool = False
    journal_path: Optional[str] = None
    fsync_every: int = Field(1, ge=0)
    heartbeat: bool = False
    heartbeat_dir: Optional[str] = None
    heartbeat_interval_s: float = Field(0.2, ge=0.0)
    max_restarts: int = Field(2, ge=0)
    restart_window_s: float = Field(300.0, gt=0.0)
    hang_timeout_s: float = Field(30.0, gt=0.0)
    startup_grace_s: float = Field(120.0, ge=0.0)
    poll_interval_s: float = Field(0.05, gt=0.0)

    def model_validate(self):
        import os

        from .heartbeat import HEARTBEAT_DIR_ENV, SERVING_JOURNAL_ENV
        # same remedy-is-the-env contract as FaultToleranceConfig: a worker
        # under ServingSupervisor gets both paths from the environment, so
        # enabling the section without explicit paths is only an error when
        # nothing supervises the process
        if self.enabled and not self.journal_path \
                and not os.environ.get(SERVING_JOURNAL_ENV):
            raise ValueError("serving_fault_tolerance.enabled=true needs "
                             "journal_path (or launch under ServingSupervisor, "
                             "which exports DSTPU_SERVING_JOURNAL and overrides "
                             "this section)")
        if self.heartbeat and not self.heartbeat_dir \
                and not os.environ.get(HEARTBEAT_DIR_ENV):
            raise ValueError("serving_fault_tolerance.heartbeat=true needs "
                             "heartbeat_dir (or launch under ServingSupervisor, "
                             "which exports DSTPU_HEARTBEAT_DIR)")


class ServingFleetConfig(ConfigModel):
    """Fleet front-end over N supervised serving replicas
    (inference/v2/router.py — the horizontal-scale layer over the
    single-engine stack: Orca/vLLM-class deployments put a health-gated
    router in front of replicated engines; no reference section, the
    reference delegates fleet routing to external serving infra).

    ``replicas`` sizes the fleet the router fronts.  Admission is
    least-loaded-healthy: the router scores each replica from its last
    ``health()`` snapshot (queue depth weighted by ``queue_weight``, KV
    utilization by ``kv_weight``) and steers AWAY from any replica whose
    ``CapacityForecaster`` predicts KV exhaustion within
    ``exhaustion_steer_steps`` serve steps — pressure-avoidance before the
    replica ever sheds.  A snapshot older than ``health_stale_s`` (per its
    ``generated_at`` stamp) marks the replica unhealthy: a frozen replica's
    last-good gauges must not keep attracting traffic (the hang-worker
    failure mode).

    ``affinity_blocks`` > 0 routes shared-header prompts by prefix
    affinity: the chained token-block hash (the PR-13 ``PrefixCache``
    keying) of the prompt's leading full blocks picks a stable home
    replica, so one header's PrefixCache tree stays hot on one replica
    instead of lukewarm on all of them.  0 disables affinity (pure
    least-loaded).

    A retryable per-replica shed is never surfaced to the caller while
    budget remains: the router re-routes it up to ``max_reroutes`` times
    with exponential backoff (``backoff_base_s`` doubling per attempt,
    capped at ``backoff_max_s``), honoring the shed's ``retry_after_s``
    hint when the admission door supplied one.

    Failover: each replica keeps its own journal under its own
    ``ServingSupervisor`` (restart budget per ``serving_fault_tolerance``);
    a replica that exhausts its budget is drained and its journaled
    in-flight work MIGRATES to a healthy replica — emitted prefixes are
    copied into the target's journal with their ORIGINAL wall-clock admit
    stamps, so ``serve_recovered`` continues them byte-identically on
    their original TTL clocks.  Zero lost requests.
    """
    enabled: bool = False
    replicas: int = Field(2, ge=1)
    health_stale_s: float = Field(5.0, gt=0.0)
    affinity_blocks: int = Field(1, ge=0)  # full prompt blocks hashed; 0 = off
    max_reroutes: int = Field(3, ge=0)
    backoff_base_s: float = Field(0.05, ge=0.0)
    backoff_max_s: float = Field(2.0, gt=0.0)
    exhaustion_steer_steps: float = Field(32.0, gt=0.0)
    queue_weight: float = Field(1.0, ge=0.0)
    kv_weight: float = Field(8.0, ge=0.0)
    namespace: str = "dstpu"


class ServingQosConfig(ConfigModel):
    """Multi-tenant QoS policy over the v2 serving plane
    (inference/v2/qos.py — the *policy* layer on the existing admission /
    preemption / prefix-cache *mechanisms*; no reference section, the
    reference's ragged engine is single-tenant and delegates isolation to
    external serving infra).

    Every request carries a ``tenant`` id and a service class
    (``interactive`` / ``batch`` / ``best_effort``).  With
    ``enabled=false`` (the default) the layer is inert: requests get the
    default tenant, dequeue order, prefix-cache keying and preemption
    victims are byte-identical to the policy-free engine.

    Front-door quotas (checked BEFORE any KV allocation, like every other
    shed): ``tenant_tokens_per_s`` rate-limits each tenant's admitted
    token volume through a token bucket of capacity
    ``tenant_token_burst`` (0 disables; burst defaults to one second of
    rate).  ``tenant_max_kv_blocks`` caps a tenant's RESIDENT KV blocks;
    a tenant at its cap is shed rather than allowed to starve its
    neighbors' pool.  Both produce a structured, retryable
    ``quota_exceeded`` shed whose ``retry_after_s`` is the exact bucket
    refill time (rate) or a pressure-scaled hint (KV), riding the
    FleetRouter's existing backoff path.  ``tenants`` maps tenant id to
    per-tenant overrides (``tokens_per_s`` / ``token_burst`` /
    ``max_kv_blocks``).

    Weighted-fair dequeue: the admission queue becomes per-class with
    deficit-round-robin on TOKEN cost — each visit grants a class
    ``drr_quantum_tokens * weight`` deficit, so interactive (weight 8 by
    default) drains ~8x the token volume of best-effort per round while
    best-effort still makes progress (starvation-free by construction).
    Priority ordering within a class is preserved.  The DRR state is pure
    arrival-sequence arithmetic — no clock reads — so dequeue order is
    FakeClock-deterministic and rerun-identical.

    ``preempt_over_quota`` steers KV-pressure preemption: victims are
    preferred over-quota-tenant first, then lower class, then the PR-4
    newest-prefill heuristic as the tie-break.

    Isolation: the tenant id is folded into the chained block-hash key,
    so cross-tenant prompts can NEVER share prefix blocks (closes the
    cross-tenant cache-timing side-channel); the default tenant keeps the
    legacy keying, so single-tenant sharing is unchanged.
    """
    enabled: bool = False
    default_class: str = Field("interactive",
                               choices=("interactive", "batch", "best_effort"))
    interactive_weight: int = Field(8, ge=1)
    batch_weight: int = Field(2, ge=1)
    best_effort_weight: int = Field(1, ge=1)
    drr_quantum_tokens: int = Field(64, ge=1)
    tenant_tokens_per_s: float = Field(0.0, ge=0.0)  # 0 => no rate quota
    tenant_token_burst: float = Field(0.0, ge=0.0)  # 0 => 1s of rate
    tenant_max_kv_blocks: int = Field(0, ge=0)  # 0 => no KV quota
    tenants: Dict[str, Any] = Field(dict)  # per-tenant quota overrides
    preempt_over_quota: bool = True


class KVObservabilityConfig(ConfigModel):
    """Block-level observability over the paged KV pool for the v2 ragged
    engine (inference/v2/kv_metrics.py — no reference section: the CUDA
    reference's monitor reports aggregate throughput and has no block-granular
    pool view; vLLM-class systems treat block bookkeeping as the substrate for
    prefix caching and eviction policy, which is exactly what this measures
    ahead of those ROADMAP items).

    ``enabled`` arms the block census (per-block owner/age/residency with
    utilization, fragmentation and block-age rollups), the
    ``PrefixObservatory`` (counterfactual prefix-cache win per serve pass:
    duplicate token-block hashes across live+admitted requests, prefill
    tokens sharing would have saved, would-be hit-rate), and the capacity
    forecaster (EWMA block alloc/free rates per iteration yielding a
    steps-to-exhaustion gauge next to the shed/preempt counters).  Everything
    reads host-side ints the allocator and ragged manager already own — ZERO
    device syncs (dslint's host-sync rule scans ``kv_metrics.py`` whole-file,
    and the kv-obs smoke proves byte-identical fastpath ``ServeCounters``
    observability on vs off).

    ``invariant_check`` re-verifies after every serve pass that the census's
    owned-block set exactly partitions against the allocator free list — the
    PR-4 double-free guard as a continuously-checked pool invariant
    (``CensusInvariantError`` names the offending uid/block).
    ``pressure_steps`` is the steps-to-exhaustion threshold below which a
    ``kv_pressure`` event lands in the flight recorder (edge-triggered:
    entered/cleared, not once per iteration); ``ewma_alpha`` smooths the
    forecaster's alloc/free rates.
    """
    enabled: bool = True
    invariant_check: bool = True
    ewma_alpha: float = Field(0.2, gt=0.0, le=1.0)
    pressure_steps: float = Field(64.0, gt=0.0)
    age_buckets_per_decade: int = Field(6, ge=1, le=100)


class ServingPrefixCacheConfig(ConfigModel):
    """Copy-on-write prefix caching over the paged KV pool for the v2 ragged
    engine (inference/v2/ragged_manager.py ``PrefixCache`` — the realized
    form of vLLM-style block-granular prefix reuse / SGLang RadixAttention,
    keyed on the same chained token-block hashes PR 12's
    ``PrefixObservatory`` measures the counterfactual with).

    ``enabled`` arms the tree: an admitted request whose leading FULL prompt
    blocks match live, fully-computed blocks maps them read-only (allocator
    refcount +1 per mapping; shared KV capacity counted once) and only
    prefills its divergent tail — cutting TTFT and prefill FLOPs by exactly
    the hit-rate the observatory predicts, at zero device cost when nothing
    shares (the fastpath ServeCounters are byte-identical on a no-sharing
    workload).

    ``cow`` allows the copy-on-write block copy for prompts cached to their
    LAST token: the final block's KV is duplicated into a private block so
    the one recomputed position (needed for first-token logits) never writes
    a shared block.  Off, such prompts simply recompute their final block.

    ``defer_shared_prefill`` lets the scheduler hold a prefill chunk for ONE
    step when a sequence already scheduled this step is computing the exact
    block it needs — same-wave duplicates of one header become a one-step
    delay plus a cache hit instead of duplicate prefill.
    """
    enabled: bool = True
    cow: bool = True
    defer_shared_prefill: bool = True


class OpsServerConfig(ConfigModel):
    """Pull-based ops endpoints (monitor/metrics.py + monitor/ops_server.py —
    the PULL counterpart of the reference's push-only ``monitor/`` backends:
    a Prometheus ``/metrics`` endpoint plus JSON ``/healthz``/``/statez``
    probes over everything PRs 1-8 measure).

    ``enabled`` starts a stdlib ``ThreadingHTTPServer`` on ``host:port``
    (``port=0`` = ephemeral; read it from the attach point's ``.ops.port``)
    serving ONLY host-side cached snapshots — the owning loop refreshes the
    cache at host-touch points it already pays for, throttled to one refresh
    per ``refresh_interval_s``, so a scrape can never trigger a device sync
    or race a mutating step (dslint's host-sync rule scans the whole ops
    plane).  The serving engine refreshes on its injectable clock; training
    refreshes at the telemetry record boundary.

    ``textfile_dir`` additionally publishes this process's registry as
    atomic per-rank files (``ops.rank<R>.json`` exact-merge snapshot +
    ``ops.rank<R>.prom`` rendered textfile).  The elastic agent and the
    ``ServingSupervisor`` export ``DSTPU_OPS_DIR`` to their workers (the
    heartbeat env contract) and merge the snapshots into one fleet-level
    endpoint whose counters stay monotone across worker restarts; the env
    wins over this field, so supervised workers need no config changes.
    """
    enabled: bool = False
    host: str = "127.0.0.1"
    port: int = Field(0, ge=0, le=65535)  # 0 => ephemeral
    refresh_interval_s: float = Field(0.25, ge=0.0)
    textfile_dir: Optional[str] = None
    namespace: str = "dstpu"


class NebulaConfig(ConfigModel):
    """Reference: top-level "nebula" section (nebula/config.py) — enabling it
    selects the async (background-writer) checkpoint engine."""
    allow_extra = True
    enabled: bool = False
    persistent_storage_path: Optional[str] = None
    persistent_time_interval: int = Field(100, ge=1)
    num_of_version_in_retention: int = Field(2, ge=1)
    enable_nebula_load: bool = True


class DataSamplingConfig(ConfigModel):
    """Reference: data_efficiency.data_sampling (runtime/data_pipeline/config.py:37)
    — the curriculum_learning sub-dict feeds CurriculumScheduler; the reference's
    multi-metric ``curriculum_metrics`` form is accepted, with the ``seqlen``
    metric driving batch truncation (the reference's default difficulty proxy)."""
    allow_extra = True
    enabled: bool = True
    num_workers: int = 0
    curriculum_learning: Dict[str, Any] = Field(dict)


class DataRoutingConfig(ConfigModel):
    """Reference: data_efficiency.data_routing (random-LTD; runtime/data_pipeline/
    config.py:77).  The library lives in runtime/data_pipeline/random_ltd.py;
    models opt in by wrapping their layer stack (initialize() warns loudly when
    the section is enabled, since an opaque loss_fn can't be rewritten)."""
    allow_extra = True
    enabled: bool = False
    random_ltd: Dict[str, Any] = Field(dict)


class DataEfficiencyConfig(ConfigModel):
    """Reference: DeepSpeedDataEfficiencyConfig (runtime/data_pipeline/config.py:12),
    activated through the engine's dataloader (engine.deepspeed_io:1686)."""
    allow_extra = True
    enabled: bool = False
    seed: int = Field(1234, ge=0)
    data_sampling: DataSamplingConfig = Field(DataSamplingConfig)
    data_routing: DataRoutingConfig = Field(DataRoutingConfig)

    def curriculum_dict(self) -> Optional[Dict[str, Any]]:
        """The CurriculumScheduler config when curriculum sampling is active,
        else None.  Accepts both the flat schedule form and the reference's
        ``curriculum_metrics: {seqlen: {...}}`` nesting."""
        cl = dict(self.data_sampling.curriculum_learning or {})
        if not (self.enabled and self.data_sampling.enabled and cl.pop("enabled", False)):
            return None
        metrics = cl.pop("curriculum_metrics", None)
        if metrics:
            name = "seqlen" if "seqlen" in metrics else next(iter(metrics))
            if len(metrics) > 1:
                logger.warning(f"data_efficiency curriculum_metrics: multiple metrics "
                               f"configured; using {name!r} for difficulty (seqlen truncation)")
            return dict(metrics[name])
        return cl or None


class TrainingConfig(ConfigModel):
    """Top-level config — analog of ``DeepSpeedConfig`` (runtime/config.py:687).

    Accepts the same key spellings as a DeepSpeed JSON config where the concept
    carries over.  Unknown top-level keys are accepted with a loud warning (so
    reference configs with not-yet-modeled sections still load); sub-models are
    strict and raise, matching the reference's per-section validation.
    """
    allow_extra = "warn"

    train_batch_size: Optional[int] = Field(None, ge=1)
    train_micro_batch_size_per_gpu: Optional[int] = Field(None, ge=1)
    gradient_accumulation_steps: Optional[int] = Field(None, ge=1)
    steps_per_print: int = Field(10, ge=1)
    gradient_clipping: float = Field(0.0, ge=0.0)
    prescale_gradients: bool = False
    gradient_predivide_factor: float = Field(1.0, gt=0.0)
    sparse_gradients: bool = False
    communication_data_type: Optional[str] = None
    seed: int = 1234

    optimizer: Optional[OptimizerConfig] = None
    scheduler: Optional[SchedulerConfig] = None
    fp16: FP16Config = Field(FP16Config)
    bf16: Optional[BF16Config] = None
    zero_optimization: ZeroConfig = Field(ZeroConfig)
    activation_checkpointing: ActivationCheckpointingConfig = Field(ActivationCheckpointingConfig)
    comms_logger: CommsLoggerConfig = Field(CommsLoggerConfig)
    monitor_config: Optional[MonitorConfig] = None
    tensorboard: TensorBoardConfig = Field(TensorBoardConfig)
    wandb: WandbConfig = Field(WandbConfig)
    csv_monitor: CSVConfig = Field(CSVConfig)
    flops_profiler: FlopsProfilerConfig = Field(FlopsProfilerConfig)
    telemetry: TelemetryConfig = Field(TelemetryConfig)
    mesh: MeshConfig = Field(MeshConfig)
    gradient_compression: GradientCompressionConfig = Field(GradientCompressionConfig)
    sparse_attention: Optional[SparseAttentionConfig] = None
    data_efficiency: DataEfficiencyConfig = Field(DataEfficiencyConfig)
    # legacy pre-data_efficiency curriculum section (reference runtime/config.py
    # ``get_curriculum_params`` — curriculum_type/min/max/schedule keys)
    curriculum_learning: Optional[Dict[str, Any]] = None
    checkpoint: CheckpointSectionConfig = Field(CheckpointSectionConfig)
    # training-side liveness + bounded collectives (heartbeat stamps, hang
    # conversion, process-group setup retries); the elastic agent's env
    # exports override/augment this section for supervised workers
    fault_tolerance: FaultToleranceConfig = Field(FaultToleranceConfig)
    nebula: NebulaConfig = Field(NebulaConfig)
    # serving-side resilience thresholds; consumed by inference/v2 (the
    # InferenceConfig carries the same section so a serving-only config and a
    # combined train+serve config spell it identically)
    serving_resilience: ServingResilienceConfig = Field(ServingResilienceConfig)
    # serving hot-path knobs (device-resident batch state, step pipelining,
    # adaptive decode fusion) — same dual-spelling contract as above
    serving_fastpath: ServingFastpathConfig = Field(ServingFastpathConfig)
    # speculative decoding on the fused decode path (draft/verify with exact
    # rejection sampling) — same dual-spelling contract as above
    serving_spec_decode: ServingSpecDecodeConfig = Field(ServingSpecDecodeConfig)
    # request-lifecycle tracing, SLO latency histograms, flight recorder —
    # same dual-spelling contract as above
    serving_tracing: ServingTracingConfig = Field(ServingTracingConfig)
    # serving crash durability (request journal) + supervised restart —
    # same dual-spelling contract as above
    serving_fault_tolerance: ServingFaultToleranceConfig = Field(ServingFaultToleranceConfig)
    # pull-based ops endpoints (/metrics Prometheus exposition + /healthz +
    # /statez) and per-rank metrics textfiles — same dual-spelling contract
    ops_server: OpsServerConfig = Field(OpsServerConfig)
    # block-level KV-pool observability (census + prefix-sharing opportunity
    # + capacity forecast) — same dual-spelling contract as above
    serving_kv_observability: KVObservabilityConfig = Field(KVObservabilityConfig)
    # copy-on-write prefix caching over the paged KV pool — same
    # dual-spelling contract as above
    serving_prefix_cache: ServingPrefixCacheConfig = Field(ServingPrefixCacheConfig)
    # serving performance observatory (phase attribution, compile ledger,
    # live roofline gauges) — same dual-spelling contract as above
    serving_perf: ServingPerfConfig = Field(ServingPerfConfig)
    # fleet front-end over N supervised replicas (health-gated routing,
    # prefix affinity, journaled failover migration) — same dual-spelling
    # contract as above
    serving_fleet: ServingFleetConfig = Field(ServingFleetConfig)
    # multi-tenant QoS (priority classes, per-tenant quotas, weighted-fair
    # dequeue, tenant-keyed prefix isolation) — same dual-spelling contract
    # as above
    serving_qos: ServingQosConfig = Field(ServingQosConfig)

    wall_clock_breakdown: bool = False
    memory_breakdown: bool = False
    # train-loop watchdog: abort after this many CONSECUTIVE bad steps — fp16
    # overflow-skips, or non-finite loss/grad-norm on bf16/fp32 (which have no
    # overflow-skip and would otherwise silently train on NaNs forever).
    # 0 disables; enabling adds one host value-fetch (device sync) per step
    # when telemetry/wall_clock_breakdown haven't already paid it.
    max_consecutive_skips: int = Field(0, ge=0)
    dump_state: bool = False
    checkpoint_tag_validation: str = Field("Warn", choices=("Ignore", "Warn", "Fail", "ignore", "warn", "fail"))
    load_universal_checkpoint: bool = False
    use_node_local_storage: bool = False
    elasticity: Optional[Dict[str, Any]] = None
    autotuning: Optional[Dict[str, Any]] = None  # parsed by autotuning.AutotuningConfig

    def model_validate(self):
        if self.fp16.enabled and self.bf16 is not None and self.bf16.enabled:
            raise ValueError("fp16 and bf16 cannot both be enabled")
        if self.bf16 is None:
            # TPU-first default: bf16 on unless fp16 explicitly requested.
            object.__setattr__(self, "bf16", BF16Config(enabled=not self.fp16.enabled))
        if self.checkpoint.tag_validation is not None:
            object.__setattr__(self, "checkpoint_tag_validation", self.checkpoint.tag_validation)
        if self.memory_breakdown and not self.telemetry.memory_breakdown:
            # the reference's top-level memory_breakdown key routes to the same
            # see_memory_usage cadence the telemetry section controls
            object.__setattr__(self.telemetry, "memory_breakdown", True)

    def checkpoint_engine_kind(self) -> str:
        """Engine plug-in selection (reference _configure_checkpointing,
        engine.py:921): the "nebula" section wins, else checkpoint.checkpoint_engine."""
        if self.nebula.enabled:
            return "async"
        return self.checkpoint.checkpoint_engine

    def effective_curriculum(self) -> Optional[Dict[str, Any]]:
        """Curriculum schedule dict from either the data_efficiency section or
        the legacy top-level curriculum_learning section; None when inactive."""
        cur = self.data_efficiency.curriculum_dict()
        if cur is not None:
            return cur
        legacy = dict(self.curriculum_learning or {})
        if legacy.pop("enabled", False):
            return legacy
        return None

    # --- batch-size triple reconciliation (reference runtime/config.py:837) ---
    def resolve_batch_sizes(self, dp_world_size: int):
        """Return (train_batch, micro_batch, gas), solving for any missing member of
        train_batch = micro_batch * gas * dp_world_size; raises on inconsistency."""
        tb, mb, gas = (self.train_batch_size, self.train_micro_batch_size_per_gpu,
                       self.gradient_accumulation_steps)
        if tb is not None and mb is not None and gas is not None:
            if tb != mb * gas * dp_world_size:
                raise ValueError(
                    f"train_batch_size={tb} != micro_batch({mb}) * gas({gas}) * dp_world({dp_world_size})")
        elif tb is not None and mb is not None:
            if tb % (mb * dp_world_size) != 0:
                raise ValueError(f"train_batch_size={tb} not divisible by micro_batch*dp={mb * dp_world_size}")
            gas = tb // (mb * dp_world_size)
        elif tb is not None and gas is not None:
            if tb % (gas * dp_world_size) != 0:
                raise ValueError(f"train_batch_size={tb} not divisible by gas*dp={gas * dp_world_size}")
            mb = tb // (gas * dp_world_size)
        elif mb is not None:
            gas = gas or 1
            tb = mb * gas * dp_world_size
        elif tb is not None:
            mb = tb // dp_world_size
            if mb == 0 or tb % dp_world_size != 0:
                raise ValueError(f"train_batch_size={tb} not divisible by dp_world_size={dp_world_size}")
            gas = 1
        else:
            raise ValueError("One of train_batch_size or train_micro_batch_size_per_gpu must be set")
        object.__setattr__(self, "train_batch_size", tb)
        object.__setattr__(self, "train_micro_batch_size_per_gpu", mb)
        object.__setattr__(self, "gradient_accumulation_steps", gas)
        return tb, mb, gas

    @property
    def precision_dtype(self):
        import jax.numpy as jnp
        if self.fp16.enabled:
            return jnp.float16
        if self.bf16 is not None and self.bf16.enabled:
            return jnp.bfloat16
        return jnp.float32


def load_config(config: Union[str, dict, TrainingConfig, None]) -> TrainingConfig:
    """Parse a config path / dict / model into a TrainingConfig.

    Analog of DeepSpeedConfig.__init__ (runtime/config.py:699) accepting either a
    JSON file path or an already-parsed dict.
    """
    if config is None:
        return TrainingConfig()
    if isinstance(config, TrainingConfig):
        return config
    if isinstance(config, str):
        with open(config, "r") as fh:
            config = json.load(fh)
    if not isinstance(config, dict):
        raise TypeError(f"config must be a path, dict, or TrainingConfig; got {type(config)}")
    known_zero_aliases = {"zero_allow_untested_optimizer", "zero_force_ds_cpu_optimizer"}
    config = {k: v for k, v in config.items() if k not in known_zero_aliases}
    return TrainingConfig(**config)
