"""Compressed communication backends (reference deepspeed/runtime/comm/)."""
from .compressed import compress_signs, onebit_allreduce, onebit_allreduce_tree
