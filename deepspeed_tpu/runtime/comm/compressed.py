"""1-bit compressed collectives with error feedback.

Analog of the reference's 1-bit backends (runtime/comm/nccl.py:16
NcclBackend.compressed_allreduce:51, mpi.py, and the 1-bit optimizers built on
them, runtime/fp16/onebit/): gradients are compressed to sign + per-chunk
scale with an error-feedback buffer so compression noise is corrected over
steps; wire traffic drops ~32x for the sign payload.

Mapping to mesh collectives: the reference's two-phase allgather becomes a
sign-packed all_to_all reduce-scatter + allgather over the dp axis inside
shard_map (the server/worker error split of the reference maps to the
scatter/gather halves).
"""

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...compat import axis_size


def compress_signs(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """-> (int8 signs, fp32 scale) with scale = mean(|x|) (reference 1-bit Adam)."""
    scale = jnp.mean(jnp.abs(x))
    signs = jnp.where(x >= 0, 1, -1).astype(jnp.int8)
    return signs, scale


def onebit_allreduce(g: jnp.ndarray, error: jnp.ndarray, axis_name: str,
                     server_error: jnp.ndarray = None
                     ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Error-feedback sign-compressed allreduce of one flat gradient.

    Runs INSIDE shard_map.  Returns (reduced estimate, new worker error, new
    server error).
    Phase 1 (worker): compensate g += error; compress; int8 all-to-all reduce —
    each rank becomes the "server" for its 1/world slice.
    Phase 2 (server): the averaged slice is compensated with the rank's
    persistent ``server_error`` slice, re-compressed, and allgathered as int8 —
    the exact two-phase worker/server-error scheme of compressed_allreduce
    (runtime/comm/nccl.py:51).  Wire traffic ~= n*(1B a2a + 1B gather) vs 8B
    for an fp32 ring allreduce.

    ``server_error`` is the rank's [n_padded/world] slice buffer (pass zeros on
    first use).
    """
    world = axis_size(axis_name)
    n = g.shape[0]
    shard = n // world
    comp = g + error
    signs, scale = compress_signs(comp)
    decompressed = signs.astype(jnp.float32) * scale
    new_error = comp - decompressed

    # phase 1: int8 sign payload all-to-all; each rank averages its slice
    signs_mat = signs[:shard * world].reshape(world, shard)
    recv = jax.lax.all_to_all(signs_mat, axis_name, split_axis=0, concat_axis=0)
    scales = jax.lax.all_gather(scale, axis_name)  # [world]
    partial = jnp.sum(recv.astype(jnp.float32) * scales[:, None], axis=0) / world

    # phase 2: server-error compensation + re-compression, int8 allgather
    if server_error is None:
        server_error = jnp.zeros_like(partial)
    comp2 = partial + server_error
    signs2, scale2 = compress_signs(comp2)
    dec2 = signs2.astype(jnp.float32) * scale2
    new_server_error = comp2 - dec2
    signs2_all = jax.lax.all_gather(signs2, axis_name, axis=0)  # int8 wire
    scales2 = jax.lax.all_gather(scale2, axis_name)  # [world]
    full = (signs2_all.reshape(world, shard).astype(jnp.float32)
            * scales2[:, None]).reshape(-1)
    tail = decompressed[shard * world:]  # remainder stays local-averaged
    tail = jax.lax.pmean(tail, axis_name)
    return jnp.concatenate([full, tail]), new_error, new_server_error


def onebit_allreduce_tree(grads, errors, axis_name: str, server_errors=None):
    """Apply onebit_allreduce leaf-wise over matching pytrees.

    ``server_errors`` (optional) holds each leaf's per-rank slice buffer
    ([numel // world] inside shard_map); when omitted, phase 2 starts from a
    zero server error each call (still correct, slightly noisier)."""
    flat_g, treedef = jax.tree_util.tree_flatten(grads)
    flat_e = jax.tree_util.tree_leaves(errors)
    flat_s = (jax.tree_util.tree_leaves(server_errors) if server_errors is not None
              else [None] * len(flat_g))
    out_g, out_e, out_s = [], [], []
    for g, e, s in zip(flat_g, flat_e, flat_s):
        shape = g.shape
        rg, re, rs = onebit_allreduce(g.reshape(-1), e.reshape(-1), axis_name, s)
        out_g.append(rg.reshape(shape))
        out_e.append(re.reshape(shape))
        out_s.append(rs)
    unf = lambda leaves: jax.tree_util.tree_unflatten(treedef, leaves)
    return unf(out_g), unf(out_e), unf(out_s)
