"""1-bit compressed collectives with error feedback.

Analog of the reference's 1-bit backends (runtime/comm/nccl.py:16
NcclBackend.compressed_allreduce:51, mpi.py, and the 1-bit optimizers built on
them, runtime/fp16/onebit/): gradients are compressed to sign + per-chunk
scale with an error-feedback buffer so compression noise is corrected over
steps; wire traffic drops ~32x for the sign payload.

Mapping to mesh collectives: the reference's two-phase allgather becomes a
sign-packed all_to_all reduce-scatter + allgather over the dp axis inside
shard_map (the server/worker error split of the reference maps to the
scatter/gather halves).
"""

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def compress_signs(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """-> (int8 signs, fp32 scale) with scale = mean(|x|) (reference 1-bit Adam)."""
    scale = jnp.mean(jnp.abs(x))
    signs = jnp.where(x >= 0, 1, -1).astype(jnp.int8)
    return signs, scale


def onebit_allreduce(g: jnp.ndarray, error: jnp.ndarray, axis_name: str
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Error-feedback sign-compressed allreduce of one flat gradient.

    Runs INSIDE shard_map.  Returns (reduced gradient estimate, new error).
    Phase 1 (worker): compensate g += error; compress; int8 all-to-all reduce.
    Phase 2 (server): each rank holds the averaged sign-estimates of its slice;
    compress again and allgather — both phases track their own quantization
    error exactly like compressed_allreduce (runtime/comm/nccl.py:51).
    """
    world = jax.lax.axis_size(axis_name)
    n = g.shape[0]
    comp = g + error
    signs, scale = compress_signs(comp)
    decompressed = signs.astype(jnp.float32) * scale
    new_error = comp - decompressed

    # average the sign estimates across ranks: int8 payload on the wire
    shard = n // world
    signs_mat = signs[:shard * world].reshape(world, shard)
    recv = jax.lax.all_to_all(signs_mat, axis_name, split_axis=0, concat_axis=0)
    scales = jax.lax.all_gather(scale, axis_name)  # [world]
    partial = jnp.sum(recv.astype(jnp.float32) * scales[:, None], axis=0) / world
    full = jax.lax.all_gather(partial, axis_name, axis=0).reshape(-1)
    tail = decompressed[shard * world:]  # remainder stays local-averaged
    tail = jax.lax.pmean(tail, axis_name)
    return jnp.concatenate([full, tail]), new_error


def onebit_allreduce_tree(grads, errors, axis_name: str):
    """Apply onebit_allreduce leaf-wise over matching pytrees."""
    flat_g, treedef = jax.tree_util.tree_flatten(grads)
    flat_e = jax.tree_util.tree_leaves(errors)
    out_g, out_e = [], []
    for g, e in zip(flat_g, flat_e):
        shape = g.shape
        rg, re = onebit_allreduce(g.reshape(-1), e.reshape(-1), axis_name)
        out_g.append(rg.reshape(shape))
        out_e.append(re.reshape(shape))
    return (jax.tree_util.tree_unflatten(treedef, out_g),
            jax.tree_util.tree_unflatten(treedef, out_e))
