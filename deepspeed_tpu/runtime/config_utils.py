"""Typed config-model base.

TPU-native analog of the reference's ``DeepSpeedConfigModel``
(deepspeed/runtime/config_utils.py:16), which is built on pydantic v1 and supports
deprecated-field aliasing/migration.  We implement a small dependency-free model:
class annotations declare fields, ``Field(default, deprecated_names=[...])`` adds
aliases, ``validate_<name>`` methods run per-field checks, and unknown keys raise
unless the subclass sets ``allow_extra = True``.
"""

import copy
import dataclasses
import typing
from typing import Any, Dict, List, Optional, Union

from ..utils.logging import logger


class _MISSING:

    def __repr__(self):
        return "<required>"


MISSING = _MISSING()


@dataclasses.dataclass
class Field:
    default: Any = MISSING
    deprecated_names: tuple = ()
    ge: Optional[float] = None
    gt: Optional[float] = None
    le: Optional[float] = None
    choices: Optional[tuple] = None
    # Set when this field itself is deprecated; reads/writes warn.
    deprecated: bool = False

    def resolve_default(self):
        if callable(self.default) and self.default is not MISSING:
            return self.default()
        return copy.deepcopy(self.default) if isinstance(self.default, (list, dict)) else self.default


def _origin(tp):
    return typing.get_origin(tp)


def _args(tp):
    return typing.get_args(tp)


def _coerce(value, tp, path):
    """Best-effort coercion of a JSON value into the annotated type."""
    if tp is Any or value is None:
        return value
    origin = _origin(tp)
    if origin is Union:
        args = [a for a in _args(tp) if a is not type(None)]
        for a in args:
            try:
                return _coerce(value, a, path)
            except (TypeError, ValueError):
                continue
        raise TypeError(f"{path}: cannot coerce {value!r} to {tp}")
    if origin in (list, List):
        (elem_tp, ) = _args(tp) or (Any, )
        if not isinstance(value, (list, tuple)):
            raise TypeError(f"{path}: expected list, got {type(value).__name__}")
        return [_coerce(v, elem_tp, f"{path}[{i}]") for i, v in enumerate(value)]
    if origin in (dict, Dict):
        return dict(value)
    if origin is tuple:
        return tuple(value)
    if isinstance(tp, type) and issubclass(tp, ConfigModel):
        if isinstance(value, tp):
            return value
        if isinstance(value, dict):
            return tp(**value)
        raise TypeError(f"{path}: expected dict for {tp.__name__}, got {type(value).__name__}")
    if tp is bool:
        if isinstance(value, bool):
            return value
        if isinstance(value, str) and value.lower() in ("true", "false"):
            return value.lower() == "true"
        raise TypeError(f"{path}: expected bool, got {value!r}")
    if tp is int:
        if isinstance(value, bool):
            raise TypeError(f"{path}: expected int, got bool")
        if isinstance(value, int):
            return value
        if isinstance(value, float) and value.is_integer():
            return int(value)
        if isinstance(value, str):
            return int(float(value)) if float(value).is_integer() else _fail_int(path, value)
        raise TypeError(f"{path}: expected int, got {value!r}")
    if tp is float:
        if isinstance(value, bool):
            raise TypeError(f"{path}: expected float, got bool")
        if isinstance(value, (int, float)):
            return float(value)
        if isinstance(value, str):
            return float(value)
        raise TypeError(f"{path}: expected float, got {value!r}")
    if tp is str:
        if isinstance(value, str):
            return value
        raise TypeError(f"{path}: expected str, got {value!r}")
    if isinstance(tp, type):
        if isinstance(value, tp):
            return value
        try:
            return tp(value)
        except Exception as e:
            raise TypeError(f"{path}: cannot coerce {value!r} to {tp}: {e}") from e
    return value


def _fail_int(path, value):
    raise TypeError(f"{path}: expected int, got {value!r}")


class ConfigModel:
    """Declarative config base: annotate fields on the subclass body.

    >>> class MyConf(ConfigModel):
    ...     enabled: bool = False
    ...     size: int = Field(8, ge=1, deprecated_names=("sz",))
    """

    allow_extra = False

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        fields = {}
        for klass in reversed(cls.__mro__):
            for name, tp in getattr(klass, "__annotations__", {}).items():
                if name.startswith("_") or name == "allow_extra":
                    continue
                raw = klass.__dict__.get(name, MISSING)
                field = raw if isinstance(raw, Field) else Field(default=raw)
                fields[name] = (tp, field)
        cls._fields = fields
        cls._aliases = {}
        for name, (_tp, field) in fields.items():
            for alias in field.deprecated_names:
                cls._aliases[alias] = name

    def __init__(self, **kwargs):
        cls = type(self)
        data = {}
        extra = {}
        for key, value in kwargs.items():
            if key in cls._aliases:
                new = cls._aliases[key]
                logger.warning(f"Config field '{key}' is deprecated, use '{new}'", extra={"once": True})
                key = new
            if key in cls._fields:
                data[key] = value
            elif cls.allow_extra:
                if cls.allow_extra == "warn":
                    logger.warning(f"{cls.__name__}: ignoring unknown config field '{key}'",
                                   extra={"once": True})
                extra[key] = value
            else:
                raise ValueError(f"{cls.__name__}: unknown config field '{key}'. "
                                 f"Valid fields: {sorted(cls._fields)}")
        for name, (tp, field) in cls._fields.items():
            if name in data:
                value = _coerce(data[name], tp, f"{cls.__name__}.{name}")
            elif field.default is MISSING:
                raise ValueError(f"{cls.__name__}: missing required field '{name}'")
            else:
                value = field.resolve_default()
            self._check_bounds(name, field, value)
            validator = getattr(self, f"validate_{name}", None)
            if validator is not None:
                value = validator(value)
            object.__setattr__(self, name, value)
        object.__setattr__(self, "_extra", extra)
        self.model_validate()

    def _check_bounds(self, name, field, value):
        if value is None or not isinstance(value, (int, float)) or isinstance(value, bool):
            pass
        else:
            label = f"{type(self).__name__}.{name}"
            if field.ge is not None and value < field.ge:
                raise ValueError(f"{label}={value} must be >= {field.ge}")
            if field.gt is not None and value <= field.gt:
                raise ValueError(f"{label}={value} must be > {field.gt}")
            if field.le is not None and value > field.le:
                raise ValueError(f"{label}={value} must be <= {field.le}")
        if field.choices is not None and value not in field.choices:
            raise ValueError(f"{type(self).__name__}.{name}={value!r} not in {field.choices}")

    def model_validate(self):
        """Subclass hook for cross-field validation."""

    def to_dict(self):
        out = {}
        for name in type(self)._fields:
            value = getattr(self, name)
            if isinstance(value, ConfigModel):
                value = value.to_dict()
            elif isinstance(value, list):
                value = [v.to_dict() if isinstance(v, ConfigModel) else v for v in value]
            out[name] = value
        out.update(self._extra)
        return out

    def replace(self, **updates):
        data = self.to_dict()
        data.update(updates)
        return type(self)(**data)

    def __repr__(self):
        inner = ", ".join(f"{k}={getattr(self, k)!r}" for k in type(self)._fields)
        return f"{type(self).__name__}({inner})"

    def __eq__(self, other):
        return type(self) is type(other) and self.to_dict() == other.to_dict()


def get_scalar_param(param_dict, name, default):
    """Reference-parity helper (deepspeed/runtime/config_utils.py:41)."""
    return param_dict.get(name, default)
