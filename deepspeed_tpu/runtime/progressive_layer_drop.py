"""Progressive layer drop (PLD).

Analog of the reference ProgressiveLayerDrop (runtime/progressive_layer_drop.py:10):
theta(t) = (1 - theta_bar) * exp(-gamma * t) + theta_bar gives the GLOBAL keep
probability; layer i of L keeps with prob 1 - (i / L) * (1 - theta) (deeper
layers drop more).  ``pld_scan_layer`` wraps a scan layer body with the
stochastic skip (the module-hook equivalent for functional models).
"""

import math
from typing import Callable

import jax
import jax.numpy as jnp


class ProgressiveLayerDrop:

    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0

    def get_theta(self) -> float:
        return self.current_theta

    def update_state(self, global_step: int) -> float:
        def _prob(x, gamma, p):
            return (1.0 - p) * math.exp(-gamma * x) + p

        self.current_theta = _prob(global_step, self.gamma, self.theta)
        return self.current_theta

    def get_state(self):
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}


def layer_keep_prob(theta: float, layer_idx, num_layers: int):
    """Per-layer keep probability: deeper layers drop more (PLD paper schedule)."""
    frac = (layer_idx + 1) / num_layers
    return 1.0 - frac * (1.0 - theta)


def pld_scan_layer(layer_fn: Callable, num_layers: int):
    """Wrap a scan body f(x, (idx, rng, theta, params)) with stochastic skip.

    Usage inside a model: carry (x); xs include layer index + per-layer rng;
    theta traced so the schedule updates without recompiling.
    """

    def wrapped(x, inp):
        idx, rng, theta, layer_params = inp
        keep_p = layer_keep_prob(theta, idx, num_layers)
        keep = jax.random.bernoulli(rng, keep_p)
        y, aux = layer_fn(x, layer_params)
        # identity-skip with inverse-prob rescaling of the residual delta
        out = jnp.where(keep, x + (y - x) / jnp.maximum(keep_p, 1e-3), x)
        return out.astype(x.dtype), aux

    return wrapped
