"""Pipeline parallelism.

Analog of deepspeed/runtime/pipe/ (``PipelineModule`` module.py:86, 1F1B
``TrainSchedule`` schedule.py:189, interpreter engine.py:1357, p2p.py send/recv).

TPU-native design: instead of a per-rank instruction interpreter with eager p2p,
the pipeline is ONE differentiable program — a ``lax.scan`` over schedule ticks
inside ``shard_map`` over the 'pipe' mesh axis.  Each tick every stage applies
its layer block and passes activations to the next stage with ``ppermute`` (the
p2p.send/recv analog, riding ICI neighbor links).  Bubble slots compute on
garbage that is masked out of the output buffer — the standard circular-pipeline
formulation.  Because ``ppermute``/``scan``/``where`` are differentiable, XLA
derives the reverse (backward) pipeline automatically, replacing the reference's
hand-scheduled BackwardPass/SendGrad/RecvGrad instructions.

Layer placement: stacked layer params carry leading dims [S, L/S, ...]
(``partition_layers`` = the reference's uniform ``_partition_layers`` method,
module.py:370); the 'pipe'-sharded dim 0 puts each stage's block on its devices.
"""

from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec

from ...compat import shard_map

from ...parallel.mesh import DATA_AXIS, PIPE_AXIS, MeshTopology, get_topology


def partition_layers(num_layers: int, num_stages: int):
    """Uniform layer->stage split (reference ``partition_method='uniform'``,
    pipe/module.py:370).  Requires divisibility (parameters-balanced splits can
    be layered on top)."""
    if num_layers % num_stages != 0:
        raise ValueError(f"num_layers({num_layers}) must divide evenly into num_stages({num_stages})")
    return num_layers // num_stages


def partition_balanced(weights: Sequence[float], num_stages: int):
    """Weight-balanced contiguous split (reference ``partition_method=
    'parameters'``, pipe/module.py:385 via ds_utils.partition_balanced):
    returns stage boundaries [b_0=0, ..., b_S=len] minimizing the heaviest
    stage.  Binary-search over the bottleneck + greedy packing.

    The compiled pipeline needs homogeneous stacks, so this feeds LayerSpec
    grouping / cost modeling rather than the scan layout; the 1F1B engine
    (engine.py) accepts arbitrary per-stage functions built from it."""
    w = [float(x) for x in weights]
    n = len(w)
    if num_stages <= 0 or n < num_stages:
        raise ValueError(f"cannot split {n} layers into {num_stages} stages")

    def fits(cap):
        parts, acc = 1, 0.0
        for x in w:
            if x > cap:
                return False
            if acc + x > cap:
                parts += 1
                acc = x
            else:
                acc += x
        return parts <= num_stages

    lo, hi = max(w), sum(w)
    for _ in range(64):
        mid = (lo + hi) / 2
        if fits(mid):
            hi = mid
        else:
            lo = mid
    cap = hi
    bounds, acc = [0], 0.0
    for i, x in enumerate(w):
        opened = len(bounds)              # parts started so far
        still_to_open = num_stages - opened
        nonempty = i > bounds[-1]
        # break when over budget, or when every remaining layer must start a
        # new part to keep all stages nonempty
        if nonempty and still_to_open > 0 and (acc + x > cap or n - i == still_to_open):
            bounds.append(i)
            acc = x
        else:
            acc += x
    bounds.append(n)
    assert len(bounds) == num_stages + 1
    return bounds


class LayerSpec:
    """Deferred layer description (reference pipe/module.py:30 LayerSpec):
    bundles an init function + static kwargs so stage construction can happen
    after placement is known.  ``build(key)`` returns the layer's params."""

    def __init__(self, init_fn: Callable, **kwargs):
        self.init_fn = init_fn
        self.kwargs = kwargs

    def build(self, key):
        return self.init_fn(key, **self.kwargs)


class TiedLayerSpec(LayerSpec):
    """LayerSpec sharing parameters across stages by name (reference
    pipe/module.py:77): all specs with one ``key_name`` resolve to a single
    params tree, materialized once and passed as the pipeline's tied params
    (gradient summing across stages is handled by the engine/shard_map
    transpose — the analog of allreduce_tied_weight_gradients, :423-447)."""

    def __init__(self, key_name: str, init_fn: Callable, **kwargs):
        super().__init__(init_fn, **kwargs)
        self.key_name = key_name


def build_layer_specs(specs: Sequence[LayerSpec], key):
    """Materialize params for a LayerSpec list: returns (per-layer params,
    tied params dict).  Tied specs materialize once per key_name."""
    tied = {}
    layers = []
    keys = jax.random.split(key, len(specs))
    for spec, k in zip(specs, keys):
        if isinstance(spec, TiedLayerSpec):
            if spec.key_name not in tied:
                tied[spec.key_name] = spec.build(k)
            layers.append(("tied", spec.key_name))
        else:
            layers.append(("own", spec.build(k)))
    return layers, tied


def restack_for_pipeline(layer_params, num_stages: int):
    """[L, ...] stacked leaves -> [S, L/S, ...] for 'pipe' dim-0 sharding."""

    def fix(leaf):
        L = leaf.shape[0]
        per = partition_layers(L, num_stages)
        return leaf.reshape(num_stages, per, *leaf.shape[1:])

    return jax.tree_util.tree_map(fix, layer_params)


class PipelineModule:
    """Bundle a per-layer function into a pipelined block.

    layer_fn(layer_params, x) -> x  — one layer's forward (params unstacked).
    ``__call__(stacked_params, x_microbatches)`` runs the full pipeline:
    x_microbatches [M, mb, ...] -> outputs [M, mb, ...].
    """

    def __init__(self, layer_fn: Callable, num_stages: int, remat: bool = True,
                 topo: Optional[MeshTopology] = None):
        self.layer_fn = layer_fn
        self.num_stages = num_stages
        self.remat = remat
        self._topo = topo

    @property
    def topo(self):
        return self._topo or get_topology()

    def _stage_fn(self):
        layer_fn = self.layer_fn

        def stage(stage_params, x):
            # scan this stage's L/S layers
            def body(h, lp):
                return layer_fn(lp, h), None

            if self.remat:
                body = jax.checkpoint(body)
            x, _ = lax.scan(body, x, stage_params)
            return x

        return stage

    def __call__(self, stacked_params, x_microbatches):
        topo = self.topo
        S = topo.axis_size(PIPE_AXIS)
        if S <= 1:
            # no pipe axis: plain scan over all layers (params [S, L/S, ...] -> [L, ...])
            flat = jax.tree_util.tree_map(lambda l: l.reshape(-1, *l.shape[2:]), stacked_params)
            stage = self._stage_fn()
            return jax.vmap(lambda mb: stage(flat, mb))(x_microbatches) if x_microbatches.ndim > 2 else \
                stage(flat, x_microbatches)
        if S != self.num_stages:
            raise ValueError(f"mesh pipe axis ({S}) != num_stages ({self.num_stages})")
        stage_fn = self._stage_fn()
        M = x_microbatches.shape[0]
        if M < S:
            raise ValueError(f"need at least num_stages({S}) micro-batches, got {M} "
                             "(pipeline fill requirement; reference pipe engine asserts the same)")

        dp = topo.axis_size(DATA_AXIS)
        data_in_batch = dp > 1

        def pipelined(params_local, x_local):
            # params_local leaves: [1, L/S, ...] (this stage's block)
            p = jax.tree_util.tree_map(lambda l: l[0], params_local)
            idx = lax.axis_index(PIPE_AXIS)
            T = M + S - 1
            zero_state = jnp.zeros_like(x_local[0])
            zero_out = jnp.zeros_like(x_local)

            def tick(carry, t):
                state, outputs = carry
                feed = x_local[jnp.clip(t, 0, M - 1)]
                inp = jnp.where(idx == 0, feed, state)
                out = stage_fn(p, inp)
                mb_idx = t - (S - 1)
                valid = jnp.logical_and(mb_idx >= 0, idx == S - 1)
                upd = lax.dynamic_update_index_in_dim(outputs, out, jnp.clip(mb_idx, 0, M - 1), 0)
                outputs = jnp.where(valid, upd, outputs)
                state = lax.ppermute(out, PIPE_AXIS, [(i, (i + 1) % S) for i in range(S)])
                return (state, outputs), None

            (_, outputs), _ = lax.scan(tick, (zero_state, zero_out), jnp.arange(T))
            # outputs are only real on the last stage; broadcast via masked psum
            outputs = lax.psum(jnp.where(idx == S - 1, outputs, jnp.zeros_like(outputs)), PIPE_AXIS)
            return outputs

        mesh = topo.mesh
        x_spec = PartitionSpec(None, DATA_AXIS) if data_in_batch else PartitionSpec()
        param_spec = jax.tree_util.tree_map(lambda _: PartitionSpec(PIPE_AXIS), stacked_params)
        fn = shard_map(pipelined, mesh=mesh,
                       in_specs=(param_spec, x_spec),
                       out_specs=x_spec,
                       check_vma=False)
        return fn(stacked_params, x_microbatches)


def pipe_rules(path: str, shape):
    """Sharding rule: pipeline-stacked leaves (path prefix 'pipe_layers') shard
    dim 0 over 'pipe' — used by the plan like tp_rules."""
    if path.startswith("pipe_layers") or ".pipe_layers" in path:
        return (0, PIPE_AXIS)
    return None
