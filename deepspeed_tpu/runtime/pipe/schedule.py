"""Pipeline schedules: instruction streams for the 1F1B interpreter engine.

Analog of deepspeed/runtime/pipe/schedule.py (PipeSchedule:11,
TrainSchedule:189 — synchronous 1F1B, InferenceSchedule:135,
DataParallelSchedule:301, instruction classes :327-489).

The tick algebra here is a closed form rather than the reference's
even/odd-parity case analysis: in synchronous 1F1B over S stages and M
micro-batches,

    forward  of micro-batch m on stage s runs at tick 2m + s
    backward of micro-batch m on stage s runs at tick 2m + 2S - 1 - s

which yields the same streams (last stage alternates F,B back-to-back; stage
s keeps at most S - s forwards in flight awaiting their backward).  Total
ticks = 2(M + S - 1).

The compiled pipeline (module.py) does not interpret these — XLA schedules
the scan — but the 1F1B engine (engine.py PipelineEngine1F1B) executes them
eagerly with bounded live activations, and tests assert the memory bound.
"""

from dataclasses import dataclass
from typing import Iterator, List


# ------------------------------------------------------------- instructions
@dataclass(frozen=True)
class PipeInstruction:
    """Base instruction (reference schedule.py:327).  ``buffer_id`` names the
    activation/grad slot; buffers are recycled modulo num_pipe_buffers."""
    buffer_id: int = 0


class ForwardPass(PipeInstruction):
    pass


class BackwardPass(PipeInstruction):
    pass


class LoadMicroBatch(PipeInstruction):
    pass


class SendActivation(PipeInstruction):
    pass


class RecvActivation(PipeInstruction):
    pass


class SendGrad(PipeInstruction):
    pass


class RecvGrad(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class OptimizerStep(PipeInstruction):
    pass


# ---------------------------------------------------------------- schedules
class PipeSchedule:
    """Generates this stage's per-tick command lists (reference :11)."""

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        if not 0 <= stage_id < stages:
            raise ValueError(f"stage_id {stage_id} out of range for {stages} stages")
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id

    # convenience
    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def _valid_mb(self, m: int) -> bool:
        return 0 <= m < self.micro_batches

    def num_pipe_buffers(self) -> int:
        raise NotImplementedError

    def steps(self) -> Iterator[List[PipeInstruction]]:
        raise NotImplementedError

    def __iter__(self):
        return self.steps()


class TrainSchedule(PipeSchedule):
    """Synchronous 1F1B (reference TrainSchedule:189)."""

    def num_pipe_buffers(self) -> int:
        """Max in-flight forwards on this stage = its distance from the end
        (reference :254): earlier stages hold more awaiting backwards."""
        return max(2, min(self.stages - self.stage_id, self.micro_batches))

    def _fwd_mb(self, tick: int):
        m, rem = divmod(tick - self.stage_id, 2)
        return m if rem == 0 else None

    def _bwd_mb(self, tick: int):
        m, rem = divmod(tick - (2 * self.stages - 1 - self.stage_id), 2)
        return m if rem == 0 else None

    def steps(self):
        s, S, M = self.stage_id, self.stages, self.micro_batches
        nbuf = self.num_pipe_buffers()
        total = 2 * (M + S - 1)
        for tick in range(total):
            cmds: List[PipeInstruction] = []
            fm = self._fwd_mb(tick)
            bm = self._bwd_mb(tick)
            fwd_ok = fm is not None and self._valid_mb(fm)
            bwd_ok = bm is not None and self._valid_mb(bm)

            if fwd_ok:
                buf = fm % nbuf
                if self.is_first_stage or self.is_last_stage:
                    cmds.append(LoadMicroBatch(buf))
                if not self.is_first_stage:
                    cmds.append(RecvActivation(buf))
                cmds.append(ForwardPass(buf))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buf))
            if bwd_ok:
                buf = bm % nbuf
                if not self.is_last_stage:
                    cmds.append(RecvGrad(buf))
                cmds.append(BackwardPass(buf))
                if not self.is_first_stage:
                    cmds.append(SendGrad(buf))

            if tick == total - 1:
                cmds.extend([ReduceTiedGrads(), ReduceGrads(), OptimizerStep()])
            yield cmds


class InferenceSchedule(PipeSchedule):
    """Forward-only fill-and-drain (reference InferenceSchedule:135)."""

    def num_pipe_buffers(self) -> int:
        return 2

    def steps(self):
        s, S, M = self.stage_id, self.stages, self.micro_batches
        for tick in range(M + S - 1):
            cmds: List[PipeInstruction] = []
            m = tick - s
            if self._valid_mb(m):
                buf = m % 2
                if self.is_first_stage or self.is_last_stage:
                    cmds.append(LoadMicroBatch(buf))
                if not self.is_first_stage:
                    cmds.append(RecvActivation(buf))
                cmds.append(ForwardPass(buf))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buf))
            yield cmds


class DataParallelSchedule(PipeSchedule):
    """Degenerate single-stage schedule (reference DataParallelSchedule:301)."""

    def num_pipe_buffers(self) -> int:
        return 1

    def steps(self):
        for m in range(self.micro_batches):
            cmds = [LoadMicroBatch(0), ForwardPass(0), BackwardPass(0)]
            if m == self.micro_batches - 1:
                cmds.extend([ReduceGrads(), OptimizerStep()])
            yield cmds
