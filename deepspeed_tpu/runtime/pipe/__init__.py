from .engine import PipelineEngine1F1B
from .module import (LayerSpec, PipelineModule, TiedLayerSpec, build_layer_specs,
                     partition_balanced, partition_layers, pipe_rules,
                     restack_for_pipeline)
from .schedule import (DataParallelSchedule, InferenceSchedule, PipeSchedule,
                       TrainSchedule)
