from .module import PipelineModule, partition_layers, pipe_rules, restack_for_pipeline
