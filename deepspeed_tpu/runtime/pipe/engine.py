"""1F1B pipeline interpreter engine.

Analog of deepspeed/runtime/pipe/engine.py (PipelineEngine:55 —
``_exec_schedule:1357`` walks the instruction stream through
``_INSTRUCTION_MAP``, exec handlers :651-1204) re-based on functional JAX:

* a "forward pass" is ``jax.vjp`` of the stage function — the returned
  closure IS the activation stash (the reference's pipe buffer), and dropping
  it after the backward IS buffer reuse;
* send/recv are in-process mailbox moves (single-host multi-device: arrays
  already live on the stage's devices; the reference's p2p tensor-meta
  protocol, pipe/p2p.py:50, is unnecessary under one runtime);
* tied-weight gradient reduction (reference pipe/module.py:423-447
  ``allreduce_tied_weight_gradients``) is a pytree-sum over the stages that
  used the tied params.

The engine asserts the 1F1B memory bound — at most ``num_pipe_buffers()``
live vjp closures per stage — which is the entire point of 1F1B over GPipe.
The compiled circular pipeline (module.py) remains the fully-jitted path;
this engine trades one-program compilation for schedule-exact memory
behavior and per-stage program isolation.
"""

from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

from .schedule import (BackwardPass, ForwardPass, LoadMicroBatch, OptimizerStep,
                       RecvActivation, RecvGrad, ReduceGrads, ReduceTiedGrads,
                       SendActivation, SendGrad, TrainSchedule)


def _tree_add(a, b):
    if a is None:
        return b
    return jax.tree_util.tree_map(jnp.add, a, b)


class PipelineEngine1F1B:
    """Executes TrainSchedule streams over per-stage functions.

    stage_fns[s](stage_params, tied_params, x) -> x  (last stage returns the
    model output fed to ``loss_fn(out, label) -> scalar``).  ``tied_params``
    is one pytree visible to every stage (word-embedding tying etc.); stages
    that ignore it get zero contribution to its gradient.
    """

    def __init__(self, stage_fns: Sequence[Callable], loss_fn: Callable,
                 grad_reduce_fn: Optional[Callable] = None,
                 optimizer_step_fn: Optional[Callable] = None):
        self.stage_fns = list(stage_fns)
        self.num_stages = len(self.stage_fns)
        self.loss_fn = loss_fn
        self.grad_reduce_fn = grad_reduce_fn
        self.optimizer_step_fn = optimizer_step_fn
        self.max_live_buffers = [0] * self.num_stages  # observability + tests

    def train_batch(self, stage_params: Sequence[Any], micro_batches: Sequence[Any],
                    labels: Sequence[Any], tied_params: Any = None):
        """Run one 1F1B batch.  Returns (mean_loss, stage_grads, tied_grads).

        ``micro_batches``/``labels``: length-M sequences; loss is averaged
        over micro-batches (gradient-accumulation semantics, reference
        engine.py train_batch:321)."""
        S, M = self.num_stages, len(micro_batches)
        if len(stage_params) != S:
            raise ValueError(f"expected {S} stage param trees, got {len(stage_params)}")
        if len(labels) != M:
            raise ValueError("labels must match micro_batches in length")
        tied = tied_params if tied_params is not None else {}
        scheds = [TrainSchedule(M, S, s) for s in range(S)]
        streams = [list(sch.steps()) for sch in scheds]
        nbufs = [sch.num_pipe_buffers() for sch in scheds]

        # per-stage mutable state, keyed by buffer slot
        act_in = [dict() for _ in range(S)]      # received/loaded inputs
        act_out = [dict() for _ in range(S)]     # produced outputs (to send)
        vjps = [dict() for _ in range(S)]        # live closures = 1F1B memory
        loss_vjps = [dict() for _ in range(S)]
        grad_in = [dict() for _ in range(S)]
        dx_out = [dict() for _ in range(S)]
        # Cross-stage mailboxes are FIFO: buffer ids are stage-local slots
        # (num_pipe_buffers differs per stage), and micro-batches traverse
        # each edge in order, so ordered hand-off is the pairing rule (the
        # reference pairs by p2p rendezvous, pipe/p2p.py:50, same effect).
        from collections import deque
        act_mail = [deque() for _ in range(S)]   # from stage s-1
        grad_mail = [deque() for _ in range(S)]  # from stage s+1
        fwd_count = [0] * S
        bwd_count = [0] * S
        self.max_live_buffers = [0] * S

        stage_grads: List[Any] = [None] * S
        tied_grads: Any = None
        total_loss = jnp.zeros(())
        inv_m = 1.0 / M

        total_ticks = 2 * (M + S - 1)
        for tick in range(total_ticks):
            for s in range(S):
                for cmd in streams[s][tick]:
                    buf = cmd.buffer_id
                    if isinstance(cmd, LoadMicroBatch):
                        if s == 0:
                            act_in[0][buf] = micro_batches[fwd_count[0]]
                    elif isinstance(cmd, RecvActivation):
                        act_in[s][buf] = act_mail[s].popleft()
                    elif isinstance(cmd, ForwardPass):
                        m = fwd_count[s]
                        x = act_in[s].pop(buf)
                        out, vjp = jax.vjp(self.stage_fns[s], stage_params[s], tied, x)
                        vjps[s][buf] = vjp
                        self.max_live_buffers[s] = max(self.max_live_buffers[s], len(vjps[s]))
                        assert len(vjps[s]) <= nbufs[s], (
                            f"1F1B memory bound violated on stage {s}: "
                            f"{len(vjps[s])} live buffers > {nbufs[s]}")
                        if s == S - 1:
                            loss, lvjp = jax.vjp(self.loss_fn, out, labels[m])
                            total_loss = total_loss + loss * inv_m
                            loss_vjps[s][buf] = lvjp
                        else:
                            act_out[s][buf] = out
                        fwd_count[s] += 1
                    elif isinstance(cmd, SendActivation):
                        act_mail[s + 1].append(act_out[s].pop(buf))
                    elif isinstance(cmd, RecvGrad):
                        grad_in[s][buf] = grad_mail[s].popleft()
                    elif isinstance(cmd, BackwardPass):
                        if s == S - 1:
                            dout, _dlabel = loss_vjps[s].pop(buf)(jnp.asarray(inv_m))
                        else:
                            dout = grad_in[s].pop(buf)
                        dparams, dtied, dx = vjps[s].pop(buf)(dout)
                        stage_grads[s] = _tree_add(stage_grads[s], dparams)
                        tied_grads = _tree_add(tied_grads, dtied) if tied_params is not None else None
                        if s > 0:
                            dx_out[s][buf] = dx
                        bwd_count[s] += 1
                    elif isinstance(cmd, SendGrad):
                        grad_mail[s - 1].append(dx_out[s].pop(buf))
                    elif isinstance(cmd, ReduceTiedGrads):
                        pass  # in-process: tied_grads already summed across stages
                    elif isinstance(cmd, ReduceGrads):
                        # every stage's stream carries the epilogue (one process
                        # per rank in the reference); in-process, run it once
                        if s == 0 and self.grad_reduce_fn is not None:
                            stage_grads = [self.grad_reduce_fn(g) for g in stage_grads]
                            if tied_grads is not None:
                                tied_grads = self.grad_reduce_fn(tied_grads)
                    elif isinstance(cmd, OptimizerStep):
                        if s == 0 and self.optimizer_step_fn is not None:
                            self.optimizer_step_fn(stage_grads, tied_grads)

        assert all(c == M for c in fwd_count) and all(c == M for c in bwd_count), \
            "schedule did not complete all forward/backward passes"
        return total_loss, stage_grads, tied_grads

    def eval_batch(self, stage_params: Sequence[Any], micro_batches: Sequence[Any],
                   tied_params: Any = None):
        """Forward-only fill-and-drain (reference eval_batch:405): returns the
        last stage's outputs per micro-batch."""
        tied = tied_params if tied_params is not None else {}
        outs = []
        for mb in micro_batches:
            x = mb
            for s in range(self.num_stages):
                x = self.stage_fns[s](stage_params[s], tied, x)
            outs.append(x)
        return outs
