from .config import TrainingConfig, ZeroConfig, load_config
