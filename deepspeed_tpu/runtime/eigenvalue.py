"""Hessian eigenvalue estimation (MoQ aid).

Analog of the reference Eigenvalue (runtime/eigenvalue.py:12): power iteration
estimating the dominant eigenvalue of the loss Hessian per parameter block —
used to schedule mixed-precision quantization (MoQ).  The reference iterates
on autograd graphs; here the Hessian-vector product is a jax.jvp-of-grad
(forward-over-reverse), jitted once.
"""

import functools
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np


class Eigenvalue:

    def __init__(self, verbose: bool = False, max_iter: int = 100, tol: float = 1e-2,
                 stability: float = 1e-6):
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.verbose = verbose

    def compute_eigenvalue(self, loss_fn: Callable, params: Any, batch: Any,
                           rng=None, seed: int = 0) -> Dict[str, float]:
        """Dominant Hessian eigenvalue per top-level param block."""
        rng = rng if rng is not None else jax.random.PRNGKey(0)

        def scalar_loss(p):
            out = loss_fn(p, batch, rng)
            return (out[0] if isinstance(out, tuple) else out).astype(jnp.float32)

        grad_fn = jax.grad(scalar_loss)

        @jax.jit
        def hvp(p, v):
            return jax.jvp(grad_fn, (p, ), (v, ))[1]

        key = jax.random.PRNGKey(seed)
        v = jax.tree_util.tree_map(
            lambda x: jax.random.normal(jax.random.fold_in(key, hash(str(x.shape)) % (2**31)),
                                        x.shape, jnp.float32), params)
        v = _normalize(v)
        eig = 0.0
        for i in range(self.max_iter):
            hv = hvp(params, v)
            new_eig = float(_dot(v, hv))
            v = _normalize(hv)
            if abs(new_eig) < self.stability:
                eig = new_eig
                break
            if i > 0 and abs(new_eig - eig) / (abs(new_eig) + self.stability) < self.tol:
                eig = new_eig
                break
            eig = new_eig
        return {"eigenvalue": eig}


def _dot(a, b) -> jnp.ndarray:
    parts = [jnp.vdot(x, y) for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b))]
    return jnp.sum(jnp.stack(parts))


def _normalize(v):
    norm = jnp.sqrt(jnp.maximum(_dot(v, v), 1e-12))
    return jax.tree_util.tree_map(lambda x: x / norm, v)
