"""Training engine.

TPU-native analog of ``DeepSpeedEngine`` (runtime/engine.py:179).  The reference
wraps a torch module and intercepts ``forward/backward/step`` with hooks; here the
engine owns a **pure jitted train step** ``(state, batch) -> (state, metrics)``
compiled once over the device mesh, with ZeRO expressed as sharding annotations
(see runtime/zero/sharding.py).  The imperative ``forward/backward/step`` calling
convention is kept as a thin micro-batch-accumulating shim so reference training
loops port over unchanged.

Precision model (reference BF16_Optimizer semantics, runtime/bf16_optimizer.py:30):
state holds ONE fp32 master copy of the params (sharded over dp from ZeRO-1 up);
the bf16/fp16 compute copy is cast inside the step and — at stage<3 — constrained
replicated so XLA gathers the half-size copy (the analog of allgathering updated
bit16 partitions after the sharded step, stage_1_and_2.py:1786).
"""

import json
import os
import time
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..compat import shard_map, supports_partial_manual
from ..monitor.monitor import MonitorMaster
from ..monitor.telemetry import TelemetryCollector
from ..parallel.mesh import MeshTopology, set_topology
from ..utils.logging import log_dist, logger
from ..utils.memory import see_memory_usage
from ..utils.timer import ThroughputTimer
from . import lr_schedules, optimizers
from .checkpointing import (CheckpointError, _is_rank0, find_latest_valid_tag,
                            load_checkpoint_dir, save_checkpoint_with_retries,
                            sweep_retention, validate_checkpoint_tag)
from .heartbeat import OPS_DIR_ENV, build_heartbeat
from .grad_accum import accumulate_micro_grads
from .config import TrainingConfig, load_config
from .optimizers import (LossScaleState, clip_by_global_norm, global_grad_norm, has_overflow, init_loss_scale,
                         update_loss_scale)
from .zero.sharding import ShardingPlan, build_sharding_plan


class NonFiniteLossError(RuntimeError):
    """The train-loop watchdog tripped: ``max_consecutive_skips`` successive
    steps produced a non-finite loss/grad-norm (bf16/fp32) or overflow-skipped
    (fp16) — the run is diverged and further steps only burn accelerator time."""


class TrainState(NamedTuple):
    """The entire training state as one sharded pytree."""
    step: jnp.ndarray  # int32 global step (optimizer steps taken)
    params: Any  # fp32 master params
    opt_state: Any
    loss_scale: Optional[LossScaleState]
    rng: jnp.ndarray


class StepMetrics(NamedTuple):
    loss: jnp.ndarray
    grad_norm: jnp.ndarray
    lr: jnp.ndarray
    skipped: jnp.ndarray  # bool: fp16 overflow skipped the update
    loss_scale: jnp.ndarray


def _mesh_config_for(config: TrainingConfig):
    """Honor zero_hpz_partition_size (reference zero/config.py:264) when the
    user didn't lay out the mesh: at stage 3 with hpZ requested and mesh axes
    left at defaults, factor the devices into data x fsdp with
    fsdp = hpz_partition_size (the secondary/intra-slice shard group)."""
    mesh_cfg = config.mesh
    hpz = config.zero_optimization.zero_hpz_partition_size
    other_axes = int(np.prod([s for a, s in mesh_cfg.axis_sizes().items()
                              if a not in ("data", "fsdp") and s != -1]))
    if (config.zero_optimization.stage >= 3 and hpz > 1
            and mesh_cfg.fsdp == 1 and mesh_cfg.data == -1
            and jax.device_count() % (hpz * other_axes) == 0):
        from .config import MeshConfig
        sizes = mesh_cfg.axis_sizes()
        sizes["fsdp"] = hpz
        mesh_cfg = MeshConfig(**sizes, axis_order=list(mesh_cfg.axis_order))
    return mesh_cfg


class Engine:
    """Wraps a loss function + params with distributed training mechanics.

    loss_fn(params, batch, rng) -> loss  (params arrive in compute dtype)
    """

    def __init__(self,
                 loss_fn: Callable,
                 params: Any,
                 config: TrainingConfig,
                 topology: Optional[MeshTopology] = None,
                 dp_world_size: Optional[int] = None,
                 tp_rules=None,
                 param_init_fn: Optional[Callable] = None,
                 layer_fn: Optional[Callable] = None,
                 head_fn: Optional[Callable] = None,
                 stem_fn: Optional[Callable] = None,
                 ltd_state: Optional[dict] = None):
        self.config = config
        self._stem_fn = stem_fn
        # random-LTD ramp state ({"keep", "scheduler"}) — train_batch re-jits
        # the step when the scheduler moves the kept-token budget
        self._ltd_state = ltd_state
        self.loss_fn = loss_fn
        self.topology = topology or MeshTopology.build(_mesh_config_for(config))
        set_topology(self.topology)
        self.dp_world_size = dp_world_size or self.topology.get_data_parallel_world_size()
        (self.train_batch_size, self.micro_batch_size,
         self.gradient_accumulation_steps) = config.resolve_batch_sizes(self.dp_world_size)

        self.zero_stage = config.zero_optimization.stage
        self.plan: ShardingPlan = build_sharding_plan(config.zero_optimization, self.topology, tp_rules=tp_rules)

        # optimizer
        opt_cfg = config.optimizer
        opt_params = dict(opt_cfg.params) if opt_cfg else {}
        self.base_lr = float(opt_params.pop("lr", 1e-3))
        self.optimizer = optimizers.get_optimizer(opt_cfg.type if opt_cfg else "adamw", **opt_params)

        # 1-bit optimizers: comm-coupled, so the engine owns their shard_map step
        # (reference fp16/onebit/adam.py restricts to non-ZeRO dp; same here)
        self._onebit = getattr(self.optimizer, "onebit", None)
        self._onebit_world = 1
        if self._onebit is not None:
            pure = all(self.topology.axis_size(a) == 1
                       for a in ("tensor", "sequence", "expert", "pipe"))
            if self.zero_stage != 0 or not pure:
                raise ValueError("1-bit optimizers require ZeRO stage 0 and a pure "
                                 "data-parallel mesh (reference onebit/adam.py compat)")
            if config.fp16.enabled:
                raise ValueError("1-bit optimizers require bf16/fp32 compute (sign "
                                 "compression would launder fp16 overflow)")
            self._onebit_world = int(np.prod([self.topology.axis_size(a)
                                              for a in self.plan.shard_axes]))

        # lr schedule
        sched_cfg = config.scheduler
        self.lr_schedule = lr_schedules.build_lr_schedule(sched_cfg.type if sched_cfg else None,
                                                          dict(sched_cfg.params) if sched_cfg else {},
                                                          base_lr=self.base_lr)
        # host-float reads of the schedule (offload/NVMe steps, engine.lr,
        # telemetry) evaluate on the CPU backend — never an accelerator
        # round-trip in the train hot loop
        self._host_lr = lr_schedules.host_lr_fn(self.lr_schedule)
        self.lr_scheduler = lr_schedules.LRScheduler(self.lr_schedule)

        self.compute_dtype = config.precision_dtype
        self.fp16_enabled = config.fp16.enabled
        self.monitor = MonitorMaster(config)
        self.telemetry = TelemetryCollector(config.telemetry, monitor=self.monitor,
                                            batch_size=self.train_batch_size)
        self._last_telemetry_record = None
        # per-rank liveness stamps for the elastic agent (runtime/heartbeat.py):
        # armed by the fault_tolerance config section OR the agent-exported
        # DSTPU_HEARTBEAT_DIR env; the NULL writer otherwise (no-op stamps)
        self.heartbeat = build_heartbeat(config.fault_tolerance)
        # unconditional: this engine's config OWNS the process default, so a
        # timeout from an earlier engine's config can never leak into a later
        # engine (None resets to unbounded, the historical behavior)
        from ..comm import comm as _dist
        _dist.set_default_collective_timeout(config.fault_tolerance.collective_timeout_s)
        # pull-based ops plane (ISSUE 11): rank 0 serves /metrics (Prometheus
        # text over the telemetry collector's cached records) + /healthz +
        # /statez; every rank publishes per-rank snapshot/textfiles when the
        # elastic agent exported DSTPU_OPS_DIR (or ops_server.textfile_dir is
        # set), which the agent merges into one fleet endpoint.  The cache
        # refreshes at the train-step telemetry boundary — host values only
        self._ops = None
        self._ops_cfg = config.ops_server
        self._ops_rank = int(os.environ.get("RANK", "0") or 0)
        ops_dir = os.environ.get(OPS_DIR_ENV) or self._ops_cfg.textfile_dir
        if self._ops_cfg.enabled or ops_dir:
            from ..monitor.ops_server import OpsPublisher
            from .config import OpsServerConfig
            cfg = self._ops_cfg
            if cfg.enabled and not self.telemetry._is_rank0:
                # one endpoint per job: ranks > 0 publish exchange files only
                # (the agent merges them); a per-rank listener would fight
                # over the configured port across processes
                cfg = OpsServerConfig(enabled=False, host=cfg.host,
                                      refresh_interval_s=cfg.refresh_interval_s,
                                      textfile_dir=cfg.textfile_dir,
                                      namespace=cfg.namespace)
            self._ops = OpsPublisher(
                cfg,
                generation=int(os.environ.get("DSTPU_ELASTIC_RESTART", "0") or 0),
                ops_dir=ops_dir, rank=self._ops_rank, owner="training engine")
        self.ops = self._ops.server if self._ops is not None else None
        self.throughput = ThroughputTimer(batch_size=self.train_batch_size)
        self.global_steps = 0
        self.global_samples = 0
        # per-process counter bases for the ops plane: load_checkpoint moves
        # them to the restored position so exported counters stay
        # this-process-only (see _populate_ops_registry)
        self._ops_steps_base = 0
        self._ops_samples_base = 0
        self._micro_batches: list = []
        self._compiled_step = None
        self._compiled_eval = None
        self._ckpt_engine = None  # built lazily from config (checkpoint/nebula)
        self._consecutive_bad_steps = 0  # NaN/overflow watchdog counter
        # preemption (SIGTERM) best-effort final save: armed on the first
        # save_checkpoint() when checkpoint.save_on_preemption is set
        self._preempt_save_dir: Optional[str] = None
        self._preempt_prev_handler = None
        self._preempt_registered = False
        self._in_preempt_save = False

        act_cfg = config.activation_checkpointing
        if act_cfg.cpu_checkpointing or act_cfg.policy != "nothing_saveable":
            # remat is owned by the MODEL under the functional contract (the
            # loss_fn closes over jax.checkpoint) — same loud requested-but-
            # engine-cannot-apply pattern as the hpZ/qwZ knobs
            log_dist(
                f"activation_checkpointing requests policy="
                f"{'cpu_checkpointing (host-offloaded inputs)' if act_cfg.cpu_checkpointing else act_cfg.policy}: "
                f"apply it in the model config (LlamaConfig.remat_policy="
                f"{'offload_inputs' if act_cfg.cpu_checkpointing else act_cfg.policy!r}, "
                f"or runtime.activation_checkpointing.offload_checkpoint for custom "
                f"stacks) — the engine cannot rewrite remat inside an opaque loss_fn. "
                f"NOTE: host-offload remat is a PER-DEVICE lever (single chip or "
                f"inside shard_map); multi-device GSPMD jit rejects the placement "
                f"annotation (activation_checkpointing.py composition status)",
                ranks=[0])
        off = config.zero_optimization.offload_optimizer
        self.offload_device = off.device if (off is not None and off.device != "none") else None
        off_p = config.zero_optimization.offload_param
        self._nvme_trainer = None
        if off_p is not None and off_p.device == "nvme":
            # ZeRO-Infinity param streaming from config alone (reference
            # partition_parameters.py:1479 + swapper wiring): the engine builds
            # the SwappedLayerTrainer when the caller supplies the layer
            # structure an opaque loss_fn hides.
            if layer_fn is None or head_fn is None:
                raise ValueError(
                    "offload_param: nvme streams one layer at a time, which needs the layer "
                    "structure the opaque loss_fn hides — pass layer_fn(params_l, x) -> x and "
                    "head_fn(head_params, x, labels) -> loss to initialize(), with "
                    "model_parameters = {'layers': stacked [L, ...] tree, ...head leaves} "
                    "(ZeRO-Infinity layer streaming, ref partition_parameters.py:1479)")
            if not (isinstance(params, dict) and "layers" in params):
                raise ValueError("offload_param: nvme expects model_parameters to be a dict "
                                 "with a stacked 'layers' subtree ([L, ...] leaves)")
            if self.gradient_accumulation_steps != 1 or self.dp_world_size != 1:
                raise ValueError(
                    f"offload_param: nvme streams layers on ONE process/device "
                    f"(gas={self.gradient_accumulation_steps}, dp={self.dp_world_size} "
                    f"requested) — set gradient_accumulation_steps=1 and a single-device "
                    f"topology; scale-out composes via the launcher, one trainer per host")
            self._init_nvme_trainer(params, off_p, layer_fn, head_fn)
            return
        abstract = any(isinstance(p, jax.ShapeDtypeStruct) for p in jax.tree_util.tree_leaves(params))
        if abstract and param_init_fn is None:
            raise ValueError("model_parameters is abstract (ShapeDtypeStruct leaves); "
                             "pass param_init_fn so the engine can materialize shards "
                             "(zero.Init semantics, ref partition_parameters.py:786)")
        if self.offload_device is not None:
            if abstract:
                # offload wants the master on HOST anyway — materialize on the
                # CPU backend so the full fp32 tree never touches HBM
                cpu = jax.local_devices(backend="cpu")[0]
                with jax.default_device(cpu):
                    params = param_init_fn()
            self._init_offload(params, off)
            self.state = None
        elif abstract:
            self.state = self._init_state_sharded(param_init_fn)
        else:
            self.state = self._init_state(params)
        n_params = sum(int(np.prod(getattr(p, "shape", ()) or ())) for p in jax.tree_util.tree_leaves(params))
        log_dist(
            f"Engine: zero_stage={self.zero_stage} dp_world={self.dp_world_size} "
            f"batch={self.train_batch_size} (micro={self.micro_batch_size} x gas="
            f"{self.gradient_accumulation_steps} x dp={self.dp_world_size}) "
            f"dtype={self.compute_dtype.__name__} params={n_params/1e6:.2f}M", ranks=[0])
        # first ops snapshot at attach: a scrape during the (possibly long)
        # jit-compile window before step 1 must see real zeroed families and
        # a populated /healthz, not the cache's empty defaults — the same
        # contract the serving engine's attach-time refresh keeps
        self._refresh_ops(force=True)

    # ------------------------------------------------------------------ init
    def _init_state(self, params) -> TrainState:
        """Materialize the sharded train state — the analog of zero.Init +
        initialize_optimizer_states (stage_1_and_2.py:653): every leaf lands on
        device already partitioned per the plan, so full replicas never exist."""

        def make_state(p):
            master = jax.tree_util.tree_map(lambda x: x.astype(jnp.float32), p)
            opt_state = self._opt_init(master)
            ls = init_loss_scale(self.config.fp16) if self.fp16_enabled else None
            return TrainState(step=jnp.zeros((), jnp.int32),
                              params=master,
                              opt_state=opt_state,
                              loss_scale=ls,
                              rng=jax.random.PRNGKey(self.config.seed))

        shapes = jax.eval_shape(make_state, params)
        shardings = self._state_shardings(shapes)
        init_fn = jax.jit(make_state, out_shardings=shardings)
        return init_fn(params)

    def _init_state_sharded(self, param_init_fn: Callable) -> TrainState:
        """zero.Init path (ref partition_parameters.py:786): params are built
        INSIDE the jitted state constructor with sharded out_shardings, so every
        leaf is computed/stored already partitioned — no host or single-device
        full copy of a 7B model ever exists."""

        def make_state():
            p = param_init_fn()
            master = jax.tree_util.tree_map(lambda x: x.astype(jnp.float32), p)
            opt_state = self._opt_init(master)
            ls = init_loss_scale(self.config.fp16) if self.fp16_enabled else None
            return TrainState(step=jnp.zeros((), jnp.int32),
                              params=master,
                              opt_state=opt_state,
                              loss_scale=ls,
                              rng=jax.random.PRNGKey(self.config.seed))

        shapes = jax.eval_shape(make_state)
        shardings = self._state_shardings(shapes)
        return jax.jit(make_state, out_shardings=shardings)()

    def _opt_init(self, master):
        if self._onebit is not None:
            return self._onebit.init(master, self._onebit_world)
        return self.optimizer.init(master)

    def _state_shardings(self, state_shapes: TrainState) -> TrainState:
        rep = NamedSharding(self.topology.mesh, PartitionSpec())
        opt = self.plan.opt_state_shardings(state_shapes.opt_state)
        if self._onebit is not None and self._onebit_world > 1:
            # error-feedback buffers are per-rank data: worker [world, npad]
            # sharded on dim 0, server [npad] sharded (each rank its slice)
            from .onebit import error_buffer_spec
            axes = self.plan.shard_axes
            ax = axes if len(axes) > 1 else axes[0]
            mesh = self.topology.mesh

            def fix(path, sharding):
                spec = error_buffer_spec(path, ax)
                return NamedSharding(mesh, spec) if spec is not None else sharding

            opt = jax.tree_util.tree_map_with_path(fix, opt)
        return TrainState(
            step=rep,
            params=self.plan.master_shardings(state_shapes.params),
            opt_state=opt,
            loss_scale=jax.tree_util.tree_map(lambda _: rep, state_shapes.loss_scale),
            rng=rep,
        )

    # ------------------------------------------------- optimizer offload path
    def _init_nvme_trainer(self, params, off_p, layer_fn, head_fn):
        """Config-reachable ZeRO-Infinity param path (reference reaches the
        AsyncPartitionedParameterSwapper from offload_param: nvme alone,
        partition_parameters.py:1479)."""
        import tempfile

        from .swap_tensor.partitioned_param_swapper import (AsyncPartitionedParameterSwapper,
                                                            SwappedLayerTrainer)
        opt_cfg = self.config.optimizer
        opt_type = (opt_cfg.type if opt_cfg else "adamw").lower()
        if opt_type not in ("adam", "adamw", "fusedadam", "fused_adam"):
            raise ValueError(f"offload_param: nvme steps layers with the host CPU-Adam "
                             f"(csrc/cpu_adam analog); optimizer '{opt_type}' is not supported")
        opt_params = dict(opt_cfg.params) if opt_cfg else {}
        path = off_p.nvme_path or tempfile.mkdtemp(prefix="dstpu_nvme_")
        swapper = AsyncPartitionedParameterSwapper(path, buffer_count=off_p.buffer_count)
        stacked = params["layers"]
        num_layers = int(np.shape(jax.tree_util.tree_leaves(stacked)[0])[0])
        # offload_optimizer: cpu + offload_param: nvme => moments pinned in host
        # RAM (one tier up), halving per-step disk traffic — the reference's
        # mixed ZeRO-Infinity placement (offload_config.py device per tier)
        off_o = self.config.zero_optimization.offload_optimizer
        opt_device = "cpu" if (off_o is not None and off_o.device == "cpu") else "nvme"
        stem_fn = getattr(self, "_stem_fn", None)
        trainer = SwappedLayerTrainer(layer_fn, num_layers, head_fn, swapper,
                                      lr=self.base_lr,
                                      betas=tuple(opt_params.get("betas", (0.9, 0.999))),
                                      eps=float(opt_params.get("eps", 1e-8)),
                                      weight_decay=float(opt_params.get("weight_decay", 0.0)),
                                      compute_dtype=self.compute_dtype,
                                      stem_fn=stem_fn,
                                      optimizer_device=opt_device,
                                      offload_activations=self.config.activation_checkpointing.cpu_checkpointing)
        # "stem" is reserved ONLY when a stem_fn claims it; without one it
        # stays in the head params (e.g. head_fn reading params["stem"])
        head_keys = ("layers", "stem") if stem_fn is not None else ("layers", )
        trainer.init_from_stacked(
            stacked,
            {k: v for k, v in params.items() if k not in head_keys},
            stem_params=params.get("stem") if stem_fn is not None else None)
        self._nvme_trainer = trainer
        self.state = None
        log_dist(f"Engine: ZeRO-Infinity NVMe param streaming — {num_layers} layers, "
                 f"buffer_count={off_p.buffer_count}, moments={opt_device}, path={path}", ranks=[0])

    def _init_offload(self, params, off_cfg):
        """ZeRO-Offload/Infinity analog (reference swap_tensor + cpu_adam): fp32
        master + Adam moments live on host (cpu) or disk (nvme); the device
        holds only the bf16 compute copy.  The jitted program computes grads;
        the C++ cpu_adam steps host buffers."""
        from .swap_tensor.optimizer_swapper import OffloadedAdamState
        if self.fp16_enabled:
            raise ValueError("optimizer offload requires bf16/fp32 (fp16 dynamic loss "
                             "scaling is not supported on the host-offload path)")
        opt_cfg = self.config.optimizer
        opt_type = (opt_cfg.type if opt_cfg else "adamw").lower()
        if opt_type not in ("adam", "adamw"):
            raise ValueError(f"optimizer offload supports adam/adamw, got '{opt_type}'")
        opt_params = dict(opt_cfg.params) if opt_cfg else {}
        from .checkpointing import _leaf_key
        flat, self._offload_treedef = jax.tree_util.tree_flatten_with_path(params)
        self._offload_keys = []
        self._offload_shapes = []
        flat_dict = {}
        for path, leaf in flat:
            key = _leaf_key(path)
            self._offload_keys.append(key)
            self._offload_shapes.append(np.shape(leaf))
            flat_dict[key] = np.asarray(leaf, np.float32).ravel()
        betas = tuple(opt_params.get("betas", (0.9, 0.999)))
        self._offload_state = OffloadedAdamState(
            flat_dict, device=self.offload_device,
            nvme_path=getattr(off_cfg, "nvme_path", None),
            lr=self.base_lr, betas=betas,
            eps=float(opt_params.get("eps", 1e-8)),
            weight_decay=float(opt_params.get("weight_decay", 0.0)))
        self._offload_push_fn = None  # built lazily, cached (jit identity + shardings)
        self._push_compute_params()
        self._offload_grad_fn = None
        self._host_rng = jax.random.PRNGKey(self.config.seed)

    def _push_compute_params(self):
        leaves = [jnp.asarray(self._offload_state.params[k].reshape(shape), self.compute_dtype)
                  for k, shape in zip(self._offload_keys, self._offload_shapes)]
        tree = jax.tree_util.tree_unflatten(self._offload_treedef, leaves)
        if self._offload_push_fn is None:
            shardings = self.plan.param_shardings(tree)
            self._offload_push_fn = jax.jit(lambda p: p, out_shardings=shardings)
        self._compute_params = self._offload_push_fn(tree)

    def _offload_train_batch(self, batch):
        gas = self.gradient_accumulation_steps
        if self._offload_grad_fn is None:
            loss_fn = self.loss_fn
            clip_norm = self.config.gradient_clipping

            def grad_step(params16, batch, rngs):
                grads, loss_sum = accumulate_micro_grads(loss_fn, params16, batch, rngs,
                                                         jnp.float32(1.0))
                grads = jax.tree_util.tree_map(lambda g: g / gas, grads)
                norm = global_grad_norm(grads)
                if clip_norm > 0:
                    grads, norm = clip_by_global_norm(grads, clip_norm, precomputed_norm=norm)
                return grads, loss_sum / gas, norm

            self._offload_grad_fn = jax.jit(grad_step)

        self._host_rng, step_rng = jax.random.split(self._host_rng)
        rngs = jax.random.split(step_rng, gas)
        grads, loss, norm = self._offload_grad_fn(self._compute_params, batch, rngs)
        grad_leaves = jax.tree_util.tree_leaves(grads)
        grads_np = {k: np.asarray(g, np.float32).ravel()  # dslint: disable=host-sync-in-hot-path  # ZeRO-Offload by design: grads must land on host for the CPU-Adam step
                    for k, g in zip(self._offload_keys, grad_leaves)}
        lr = self._host_lr(self.global_steps)
        self._offload_state.step(grads_np, lr=lr)
        self._push_compute_params()
        return StepMetrics(loss=loss, grad_norm=norm, lr=jnp.float32(lr),
                           skipped=jnp.zeros((), jnp.bool_), loss_scale=jnp.float32(1.0))

    # ------------------------------------------------------------- train step
    def _build_train_step(self):
        gas = self.gradient_accumulation_steps
        compute_dtype = self.compute_dtype
        plan = self.plan
        optimizer = self.optimizer
        loss_fn = self.loss_fn
        lr_schedule = self.lr_schedule
        fp16 = self.fp16_enabled
        fp16_cfg = self.config.fp16
        clip_norm = self.config.gradient_clipping
        zero_cfg = self.config.zero_optimization
        topo = self.topology
        # ZeRO++ paths need pure dp/fsdp sharding (replicated model axes) and an
        # actual dp world to save traffic on
        pure_dp = all(topo.axis_size(a) == 1 for a in ("tensor", "sequence", "expert", "pipe"))
        dp_world = 1
        for a in self.plan.shard_axes:
            dp_world *= topo.axis_size(a)
        qgz = (bool(zero_cfg.zero_quantized_gradients) and 1 <= self.zero_stage <= 2
               and pure_dp and dp_world > 1 and not fp16)
        qwz = bool(zero_cfg.zero_quantized_weights) and 1 <= self.zero_stage <= 2 and pure_dp and dp_world > 1
        # stage-3 ZeRO++ (hierarchical over data=slow / fsdp=fast; reference
        # partition_parameters.py:1171-1243 + coalesced_collectives.py:31):
        # requires both axes so the quantized hop ('data') is distinct from the
        # GSPMD per-layer gather axis ('fsdp' — the hpZ secondary partition)
        # fp16 is excluded: int4 quantization would launder grad inf/nan into
        # finite values before overflow detection, defeating loss-scale skips
        # ... and a jax whose shard_map supports partial-manual (manual 'data'
        # hop around GSPMD 'fsdp' gathers); without it the quantized stage-3
        # wire format degrades to the plain GSPMD stage-3 path below, loudly
        zpp3_eligible = (self.zero_stage >= 3 and pure_dp and not fp16
                         and self.plan.shard_axes == ("data", "fsdp")
                         and topo.axis_size("data") > 1 and topo.axis_size("fsdp") > 1
                         and bool(zero_cfg.zero_quantized_gradients
                                  or zero_cfg.zero_quantized_weights))
        zpp3 = zpp3_eligible and supports_partial_manual()
        if zpp3_eligible and not zpp3:
            # only when the jax capability was the DECIDING condition — an
            # fp16/mesh exclusion must not be misattributed to the jax version
            log_dist("stage-3 ZeRO++ quantized communication requires a jax whose "
                     "shard_map supports partial-manual meshes (axis_names=); this "
                     "jax does not — falling back to plain (unquantized) stage-3 "
                     "GSPMD communication", ranks=[0])
        hpz = (zero_cfg.zero_hpz_partition_size > 1 and self.zero_stage >= 3
               and topo.axis_size("fsdp") > 1)
        if zero_cfg.zero_quantized_gradients and not (qgz or zpp3):
            log_dist("zero_quantized_gradients requested but inactive (needs bf16/fp32 "
                     "compute — not fp16 — and a pure dp/fsdp mesh with dp world > 1; "
                     "stage 3 additionally needs data>1 AND fsdp>1)", ranks=[0])
        if zero_cfg.zero_quantized_weights and not (qwz or zpp3):
            log_dist("zero_quantized_weights requested but inactive (needs pure dp/fsdp "
                     "mesh with dp world > 1; stage 3 additionally needs data>1 AND fsdp>1)", ranks=[0])
        if zero_cfg.zero_hpz_partition_size > 1 and not hpz:
            log_dist("zero_hpz_partition_size requested but inactive (needs stage 3 and "
                     "an fsdp mesh axis > 1)", ranks=[0])
        if hpz and zero_cfg.zero_hpz_partition_size != topo.axis_size("fsdp"):
            log_dist(f"hpZ secondary partition follows the fsdp mesh axis "
                     f"(size {topo.axis_size('fsdp')}), not zero_hpz_partition_size="
                     f"{zero_cfg.zero_hpz_partition_size}", ranks=[0])
        # Pallas fused optimizer step: single-device only (pallas_call under
        # GSPMD would replicate sharded leaves); multi-device runs the identical
        # delta-form math, which XLA shards per the plan.
        fused_step = optimizer.step_fn if (optimizer.step_fn is not None
                                           and self.topology.mesh.devices.size == 1) else None
        compute_shardings = None
        if self.zero_stage < 3:
            # Replicated over dp (keeping any tensor-parallel dims sharded): the
            # bit16-allgather analog.
            compute_shardings = self.plan.param_shardings(self.state.params)
        elif hpz:
            # hpZ secondary partition: compute copy sharded over fsdp only
            compute_shardings = self.plan.secondary_shardings(self.state.params)
        elif self.plan.persistence_threshold > 0:
            # stage 3: pin the compute copy to the plan's layout — big leaves
            # sharded (per-layer gathers ride the scan), persistent small
            # leaves REPLICATED (param_persistence_threshold semantics,
            # partition_parameters.py:1479).  threshold=0 leaves layout to
            # GSPMD entirely.
            compute_shardings = self.plan.param_shardings(self.state.params)

        def cast_for_compute(master):
            if qwz:
                from .zero.quantized import qwz_cast_gather
                return qwz_cast_gather(master, topo.mesh, plan.shard_axes, compute_dtype, plan=plan)
            p16 = jax.tree_util.tree_map(lambda x: x.astype(compute_dtype), master)
            if compute_shardings is not None:
                p16 = jax.tree_util.tree_map(jax.lax.with_sharding_constraint, p16, compute_shardings)
            return p16

        qgz_grad_fn = None
        if qgz:
            from .zero.quantized import make_qgz_grad_fn
            qgz_grad_fn = make_qgz_grad_fn(loss_fn, topo.mesh, plan.shard_axes, gas)
        zpp3_fn = None
        if zpp3:
            from .zero.quantized import make_zpp3_grad_fn
            zpp3_fn = make_zpp3_grad_fn(loss_fn, topo.mesh, plan, gas,
                                        qwz=bool(zero_cfg.zero_quantized_weights),
                                        qgz=bool(zero_cfg.zero_quantized_gradients),
                                        compute_dtype=compute_dtype)
        onebit_fn = None
        if self._onebit is not None and self._onebit_world > 1:
            onebit_fn = self._make_onebit_step()

        def train_step(state: TrainState, batch) -> Tuple[TrainState, StepMetrics]:
            rng, step_rng = jax.random.split(state.rng)
            scale = state.loss_scale.cur_scale if fp16 else jnp.float32(1.0)
            micro_rngs = jax.random.split(step_rng, gas)

            if onebit_fn is not None:
                # 1-bit optimizer: grads + compressed momentum reduction +
                # update all inside one shard_map (comm is part of the step)
                lr = lr_schedule(state.step)
                new_params, new_opt, loss_sum, norm = onebit_fn(
                    state.params, state.opt_state, batch, micro_rngs, lr)
                new_state = TrainState(step=state.step + 1, params=new_params,
                                       opt_state=new_opt, loss_scale=None, rng=rng)
                return new_state, StepMetrics(loss=loss_sum / gas, grad_norm=norm, lr=lr,
                                              skipped=jnp.zeros((), jnp.bool_),
                                              loss_scale=jnp.float32(1.0))

            if zpp3_fn is not None:
                # stage-3 ZeRO++: int8 gather + int4 hierarchical grad reduction
                # straight from/to the fp32 master layout
                grads, loss_sum = zpp3_fn(state.params, batch, micro_rngs, scale)
            elif qgz_grad_fn is not None:
                # qgZ: explicit int4-quantized dp gradient reduction (shard_map)
                params16 = cast_for_compute(state.params)
                grads, loss_sum = qgz_grad_fn(params16, batch, micro_rngs, scale)
            else:
                params16 = cast_for_compute(state.params)
                grads, loss_sum = accumulate_micro_grads(loss_fn, params16, batch, micro_rngs, scale)

            # average over micro-batches and unscale; dp reduction happens via
            # sharding propagation (data-sharded batch -> psum/reduce-scatter)
            grads = jax.tree_util.tree_map(lambda g: g / (gas * scale), grads)
            grads = plan.constrain_grads(grads)

            norm = global_grad_norm(grads)
            if clip_norm > 0:
                grads, norm = clip_by_global_norm(grads, clip_norm, precomputed_norm=norm)

            lr = lr_schedule(state.step)
            overflow = jnp.logical_or(has_overflow(grads), jnp.logical_not(jnp.isfinite(norm))) if fp16 \
                else jnp.zeros((), jnp.bool_)

            if fused_step is not None:
                new_params, new_opt = fused_step(grads, state.opt_state, state.params, lr)
            else:
                updates, new_opt = optimizer.update(grads, state.opt_state, state.params, lr)
                new_params = jax.tree_util.tree_map(lambda p, u: p + u, state.params, updates)

            # fp16 overflow: skip the update (reference step:1786 overflow path).
            # bf16/fp32 never overflows-skips — eliding the select keeps the old
            # params dead so the fused step's buffer aliasing holds.
            if fp16:
                def pick(new, old):
                    return jax.tree_util.tree_map(lambda a, b: jnp.where(overflow, b, a), new, old)

                new_params = pick(new_params, state.params)
                new_opt = pick(new_opt, state.opt_state)
            new_ls = update_loss_scale(state.loss_scale, overflow, fp16_cfg) if fp16 else None

            new_state = TrainState(step=state.step + jnp.where(overflow, 0, 1),
                                   params=new_params,
                                   opt_state=new_opt,
                                   loss_scale=new_ls,
                                   rng=rng)
            metrics = StepMetrics(loss=loss_sum / gas,
                                  grad_norm=norm,
                                  lr=lr,
                                  skipped=overflow,
                                  loss_scale=scale)
            return new_state, metrics

        shardings = self._state_shardings(jax.eval_shape(lambda s: s, self.state))
        return jax.jit(train_step,  # dslint: disable=donation-after-use  # call-site contract: train_batch reassigns self.state from the result in the same statement; FlopsProfiler only lower()s (never executes) the callable
                       in_shardings=(shardings, None),
                       out_shardings=(shardings, None),
                       donate_argnums=(0, ))

    def _make_onebit_step(self):
        """shard_map step for 1-bit optimizers: local grads -> local momentum
        update -> sign-compressed allreduce of the momentum -> param update
        (reference fp16/onebit/adam.py:14 + runtime/comm/nccl.py:51)."""
        spec = self._onebit
        axes = self.plan.shard_axes
        ax = axes if len(axes) > 1 else axes[0]
        world = self._onebit_world
        mesh = self.topology.mesh
        gas = self.gradient_accumulation_steps
        compute_dtype = self.compute_dtype
        loss_fn = self.loss_fn
        rep = PartitionSpec()

        from .onebit import error_buffer_spec

        def opt_spec(path, _):
            spec = error_buffer_spec(path, ax)
            return spec if spec is not None else rep

        clip_norm = self.config.gradient_clipping

        def body(master, opt_state, batch, micro_rngs, lr):
            params16 = jax.tree_util.tree_map(lambda x: x.astype(compute_dtype), master)
            grads, loss_sum = accumulate_micro_grads(loss_fn, params16, batch, micro_rngs,
                                                     jnp.float32(1.0))
            grads = jax.tree_util.tree_map(lambda g: g / gas, grads)
            # global norm from ONE scalar psum of squared local norms (no full
            # gradient allreduce — that would defeat the 1-bit compression):
            # normalized by world so it equals the exact global norm when rank
            # grads coincide (post-allreduce semantics); identical on every
            # rank, so the clip factor below is consistent
            sq = global_grad_norm(grads) ** 2
            norm = jnp.sqrt(jax.lax.psum(sq, ax) / world)
            if clip_norm > 0:
                # clip BEFORE the momentum update, like the fp16 optimizer path
                grads, norm = clip_by_global_norm(grads, clip_norm, precomputed_norm=norm)
            new_master, new_opt = spec.local_step(grads, opt_state, master, lr, ax, world)
            return new_master, new_opt, jax.lax.pmean(loss_sum, ax), norm

        def step(master, opt_state, batch, micro_rngs, lr):
            rep_tree = lambda t: jax.tree_util.tree_map(lambda _: rep, t)
            opt_specs = jax.tree_util.tree_map_with_path(opt_spec, opt_state)
            batch_specs = jax.tree_util.tree_map(lambda _: PartitionSpec(None, ax), batch)
            in_specs = (rep_tree(master), opt_specs, batch_specs, rep, rep)
            out_specs = (rep_tree(master), opt_specs, rep, rep)
            return shard_map(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                             check_vma=False)(master, opt_state, batch, micro_rngs, lr)

        return step

    @property
    def train_step_fn(self):
        if self._compiled_step is None:
            self._compiled_step = self._build_train_step()
        return self._compiled_step

    # ------------------------------------------------------------ public API
    def _shard_batch(self, batch):
        """Place a [gas, global_micro, ...] host batch with the global_micro dim
        sharded over the dp axes (DistributedSampler analog — each dp shard sees
        its slice; engine.deepspeed_io:1686).  NOT plan.shard_axes: ZeRO state
        may also partition over 'sequence' (seq_data_parallel composition), but
        the batch dim only spans data x fsdp."""
        dp_axes = self.topology.data_parallel_axes()
        axes = dp_axes if len(dp_axes) > 1 else dp_axes[0]
        sharding = NamedSharding(self.topology.mesh, PartitionSpec(None, axes))
        return jax.tree_util.tree_map(lambda x: jax.device_put(jnp.asarray(x), sharding), batch)

    # ------------------------------------------------------------ ops plane
    def ops_health(self) -> Dict[str, Any]:
        """The training engine's /healthz payload: host-owned progress and
        liveness state plus the newest telemetry record's headline numbers
        (all cached — reading this can never touch a device value)."""
        record = self._last_telemetry_record or {}
        return {
            "global_steps": self.global_steps,
            "global_samples": self.global_samples,
            "consecutive_bad_steps": self._consecutive_bad_steps,
            "heartbeat": bool(getattr(self.heartbeat, "enabled", False)),
            "rank": self._ops_rank,
            "loss": record.get("loss"),
            "step_time_ms": record.get("step_time_ms"),
            "samples_per_sec": record.get("samples_per_sec"),
            "tokens_per_sec": record.get("tokens_per_sec"),
            "mfu": record.get("mfu"),
        }

    def _refresh_ops(self, force: bool = False) -> None:
        """Refresh the cached ops snapshots at the train-step boundary
        (throttled to ``ops_server.refresh_interval_s``): registry from the
        engine's host counters + the telemetry caches, /healthz JSON, and the
        per-rank exchange files under the agent-exported ops dir.  A no-op
        when the ops plane is off.  A checkpoint rollback (load_checkpoint
        after the NaN watchdog) legally rewinds global_steps; the publisher
        exposes that as a standard Prometheus counter reset (OpsPublisher
        docstring) instead of raising into train_batch."""
        if self._ops is None:
            return
        self._ops.refresh(
            self._populate_ops_registry, now=time.monotonic(), force=force,
            healthz=lambda: json.dumps(self.ops_health()),
            statez=lambda: json.dumps(self._ops.registry.snapshot()))

    def _populate_ops_registry(self, reg) -> None:
        from ..monitor.metrics import populate_from_telemetry
        ns = reg.namespace
        # telemetry first, engine families second: both spell the
        # global-step/samples gauges, and after a checkpoint rollback the
        # collector's cached record is stale — the engine's live position
        # must win the overwrite
        populate_from_telemetry(reg, self.telemetry)
        # counters are THIS PROCESS's work (steps/samples since the last
        # checkpoint load): a resumed engine restarts them from zero so the
        # fleet aggregator's generation carry — which folds the previous
        # life's totals — never double-counts the resumed prefix.  The
        # absolute training position rides as a gauge.
        reg.set_counter(f"{ns}_train_steps_total",
                        self.global_steps - self._ops_steps_base,
                        help_text="optimizer steps run by this process")
        reg.set_counter(f"{ns}_train_samples_total",
                        self.global_samples - self._ops_samples_base,
                        help_text="samples consumed by this process")
        reg.set_gauge(f"{ns}_train_global_step", self.global_steps,
                      help_text="absolute training step (checkpoint position)")
        reg.set_gauge(f"{ns}_train_global_samples", self.global_samples,
                      help_text="absolute samples consumed (checkpoint position)")
        reg.set_gauge(f"{ns}_train_consecutive_bad_steps",
                      self._consecutive_bad_steps,
                      help_text="current NaN/overflow watchdog streak")

    def close_ops(self) -> None:
        """Shut the ops HTTP listener down (tests / clean teardown)."""
        if self._ops is not None:
            self._ops.close()

    def train_batch(self, batch):
        """Run one full optimizer step on a global macro-batch.

        ``batch``: pytree with leaves shaped [train_batch_size, ...] or
        [gas, micro*dp, ...]; reshaped/sharded automatically.
        """
        if self._nvme_trainer is not None:
            # ZeRO-Infinity layer streaming: one layer (+ its Adam state) on
            # device / in host buffers at a time; batch passes through whole
            self.telemetry.profile_step_boundary(self.global_steps)
            self.throughput.start()
            lr = self._host_lr(self.global_steps)
            t0 = time.perf_counter()
            with self.telemetry.step_annotation(self.global_steps):
                loss = self._nvme_trainer.train_step(batch, lr=lr)
            step_time = time.perf_counter() - t0
            metrics = StepMetrics(loss=jnp.float32(loss), grad_norm=jnp.float32(0.0),
                                  lr=jnp.float32(lr), skipped=jnp.asarray(False),
                                  loss_scale=jnp.float32(1.0))
            self.global_steps += 1
            self.global_samples += self.train_batch_size
            self.lr_scheduler.last_step = self.global_steps
            self.heartbeat.stamp(self.global_steps)
            if self.telemetry.enabled:
                # XLA cost analysis of the streamed layer loop is not one
                # program; MFU stays null on this path
                self.telemetry.set_flops_per_step(None)
                self._last_telemetry_record = self.telemetry.record_train_step(
                    step=self.global_steps, samples=self.global_samples,
                    loss=loss, grad_norm=0.0, lr=lr, step_time_s=step_time,
                    tokens=self._batch_tokens(batch, seq_dim=1))
            self._refresh_ops()
            self._watchdog_check(metrics, loss_val=loss)
            self._maybe_report(metrics)
            return metrics
        if self._ltd_state is not None:
            if self.global_steps == 1 and not self._ltd_state.get("engaged"):
                from ..utils.logging import logger
                logger.warning(
                    "data_routing.random_ltd is configured but the first traced step "
                    "never engaged token dropping — this loss_fn does not read "
                    "configured_ltd() (llama-family forwards with an rng do); "
                    "training proceeds WITHOUT random-LTD")
            new_keep = self._ltd_state["scheduler"].update_seq(self.global_steps)
            if new_keep != self._ltd_state["keep"]:
                # the kept-token count is a static shape in the traced program
                # (reference random-LTD pays the same via its seqlen buckets):
                # bump it and rebuild the jitted step at the new budget
                self._ltd_state["keep"] = new_keep
                self._compiled_step = None
                self._offload_grad_fn = None  # offload path re-traces at the new budget
        telemetry = self.telemetry.enabled
        if telemetry:
            self.telemetry.profile_step_boundary(self.global_steps)
        breakdown = self.config.wall_clock_breakdown
        timed = breakdown or telemetry
        t0 = time.perf_counter() if timed else 0.0
        with self.telemetry.annotation("batch_prep"):
            batch = self._ensure_gas_layout(batch)
            batch = self._shard_batch(batch)
        t1 = time.perf_counter() if timed else 0.0
        self.throughput.start()
        with self.telemetry.step_annotation(self.global_steps):
            if self.offload_device is not None:
                metrics = self._offload_train_batch(batch)
            else:
                self.state, metrics = self.train_step_fn(self.state, batch)
        loss_val = None
        t2 = 0.0
        if timed:
            # a value fetch is the only true sync; keep it off the fast path
            loss_val = float(metrics.loss)  # dslint: disable=host-sync-in-hot-path  # the step's ONE deliberate sync, opt-in via telemetry/wall_clock_breakdown (documented in TelemetryConfig)
            t2 = time.perf_counter()
        if breakdown:
            self._breakdown_acc = getattr(self, "_breakdown_acc", [0.0, 0.0, 0])
            self._breakdown_acc[0] += t1 - t0
            self._breakdown_acc[1] += t2 - t1
            self._breakdown_acc[2] += 1
            if (self.global_steps + 1) % self.config.steps_per_print == 0:
                bd, bs, n = self._breakdown_acc
                # the reference's fwd/bwd/step split is one fused XLA program
                # here — batch-prep vs compiled-step is the meaningful split
                log_dist(f"wall clock breakdown (avg over {n} steps): "
                         f"batch_prep={bd / n * 1e3:.2f}ms "
                         f"train_step={bs / n * 1e3:.2f}ms", ranks=[0])
                self._breakdown_acc = [0.0, 0.0, 0]
        self.global_steps += 1
        self.global_samples += self.train_batch_size
        self.lr_scheduler.last_step = self.global_steps
        # liveness stamp at the step's existing host-touch point: python-int
        # step + wall clock only, throttled inside the writer (zero syncs)
        self.heartbeat.stamp(self.global_steps)
        if telemetry:
            if self.telemetry.wants_flops():
                self.telemetry.set_flops_per_step(self._train_step_flops(batch))
            # the step already synced for loss_val above: fetch the remaining
            # scalars in ONE transfer instead of two more round-trips
            grad_norm_val, lr_val = map(float, jax.device_get((metrics.grad_norm, metrics.lr)))  # dslint: disable=host-sync-in-hot-path  # telemetry opt-in: single batched fetch after the loss sync
            self._last_telemetry_record = self.telemetry.record_train_step(
                step=self.global_steps, samples=self.global_samples,
                loss=loss_val, grad_norm=grad_norm_val,
                lr=lr_val, step_time_s=max(t2 - t1, 0.0) or None,
                tokens=self._batch_tokens(batch))
        if (self.config.telemetry.memory_breakdown
                and self.global_steps % self.config.steps_per_print == 0):
            # memory_breakdown stands alone: the reference's top-level key must
            # snapshot even when per-step telemetry records are off
            see_memory_usage(f"after train step {self.global_steps}")
        # ops-plane cache refresh (ISSUE 11): host-only, after the telemetry
        # record so a scrape sees THIS step; throttled; no-op when off
        self._refresh_ops()
        self._watchdog_check(metrics, loss_val=loss_val)
        self._maybe_report(metrics, loss=loss_val)
        return metrics

    def _train_step_flops(self, sharded_batch) -> Optional[float]:
        """One-time per-step FLOPs from the XLA cost analysis of the compiled
        train step (FlopsProfiler, fed the exact batch the step runs on — no
        re-layout); None on the offload paths (the step is not one jitted
        program there) or when cost analysis is unavailable."""
        if self.offload_device is not None or self._nvme_trainer is not None:
            return None
        try:
            from ..profiling.flops_profiler import FlopsProfiler
            return FlopsProfiler(self).profile_train_step(sharded_batch,
                                                          pre_sharded=True).flops
        except Exception as e:
            logger.warning(f"telemetry: train-step cost analysis failed ({e}); mfu stays null")
            return None

    def _batch_tokens(self, batch, seq_dim: int = 2) -> Optional[int]:
        """Global tokens this step: train_batch_size * seq_len, with seq_len
        read off the first integer-dtype leaf carrying a sequence dim —
        ``seq_dim=2`` for the gas layout ([gas, micro, seq, ...]), ``seq_dim=1``
        for raw [batch, seq, ...] batches (the NVMe streaming path, which never
        gas-reshapes).  None for sequence-free batches (telemetry then counts
        one token per sample)."""
        for leaf in jax.tree_util.tree_leaves(batch):
            shape = getattr(leaf, "shape", ())
            dt = getattr(leaf, "dtype", None)
            if len(shape) > seq_dim and dt is not None and jnp.issubdtype(dt, jnp.integer):
                return self.train_batch_size * int(shape[seq_dim])
        return None

    def _ensure_gas_layout(self, batch):
        gas = self.gradient_accumulation_steps

        def fix(x):
            x = np.asarray(x)
            if x.shape[0] == self.train_batch_size:
                return x.reshape(gas, self.train_batch_size // gas, *x.shape[1:])
            if x.ndim >= 2 and x.shape[0] == gas:
                return x
            raise ValueError(f"batch leading dim {x.shape[0]} matches neither train_batch_size="
                             f"{self.train_batch_size} nor gas={gas}")

        return jax.tree_util.tree_map(fix, batch)

    # torch-style 3-call shim (reference forward:1781 / backward:1922 / step:2120)
    def forward(self, micro_batch):
        self._micro_batches.append(micro_batch)
        return None

    def backward(self, loss=None):
        return None

    def step(self):
        gas = self.gradient_accumulation_steps
        if len(self._micro_batches) != gas:
            raise RuntimeError(f"engine.step() called after {len(self._micro_batches)} forward() calls; "
                               f"gradient_accumulation_steps={gas} micro-batches are required")
        stacked = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *self._micro_batches)
        self._micro_batches = []
        return self.train_batch(stacked)

    def _nvme_guard(self, what: str):
        if self._nvme_trainer is not None:
            raise NotImplementedError(
                f"{what} is not available on the offload_param:nvme streaming path — state "
                f"lives in the swapper's NVMe files (persistent across runs at nvme_path); "
                f"use the trainer's forward() for inference, and point a new engine at the "
                f"same nvme_path to resume")

    def eval_batch(self, batch, rng=None):
        self._nvme_guard("eval_batch")
        if self._compiled_eval is None:
            compute_dtype = self.compute_dtype

            loss_fn = self.loss_fn
            if self._ltd_state is not None:
                # random-LTD is train-only (reference applies it via the
                # training forward rewrite): eval traces with the LTD scope
                # pinned empty so the full model is measured
                from ..models.transformer import scoped_random_ltd
                loss_fn = scoped_random_ltd(loss_fn, None)

            def eval_step(params, b, rng):
                p16 = jax.tree_util.tree_map(lambda x: x.astype(compute_dtype), params)
                out = loss_fn(p16, b, rng)
                return out[0] if isinstance(out, tuple) else out

            self._compiled_eval = jax.jit(eval_step)
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        # batch dim spans the dp axes only — plan.shard_axes may also carry
        # 'sequence' (seq_data ZeRO composition), which never splits samples
        dp_axes = self.topology.data_parallel_axes()
        sharding = NamedSharding(self.topology.mesh,
                                 PartitionSpec(dp_axes if len(dp_axes) > 1 else dp_axes[0]))
        batch = jax.tree_util.tree_map(lambda x: jax.device_put(jnp.asarray(x), sharding), batch)
        params = self._compute_params if self.offload_device is not None else self.state.params
        if not self.telemetry.enabled:
            return self._compiled_eval(params, batch, rng)
        t0 = time.perf_counter()
        with self.telemetry.annotation("eval_batch"):
            loss = self._compiled_eval(params, batch, rng)
            loss_val = float(loss)  # dslint: disable=host-sync-in-hot-path  # telemetry opt-in: sync so the measured time covers execution
        self.telemetry.record_events([
            ("Eval/loss", loss_val, self.global_samples),
            ("Eval/batch_time_ms", (time.perf_counter() - t0) * 1e3, self.global_samples)])
        return loss

    # ----------------------------------------------------------- watchdog
    def _watchdog_check(self, metrics: StepMetrics, loss_val: Optional[float] = None):
        """NaN/Inf sentinel (``max_consecutive_skips`` config): fp16 runs count
        consecutive overflow-SKIPPED steps (the loss scaler absorbs isolated
        spikes, but an unbroken skip streak means the scale can't find footing);
        bf16/fp32 runs — which have no skip path — count consecutive non-finite
        losses/grad-norms.  One good step resets the streak; hitting the limit
        raises :class:`NonFiniteLossError` with a diagnostic instead of letting
        the run silently train on garbage until the job deadline."""
        limit = self.config.max_consecutive_skips
        if limit <= 0:
            return
        if self.fp16_enabled:
            bad = bool(metrics.skipped)
            grad_norm = None
        else:
            if loss_val is None:
                loss_val = float(metrics.loss)
            grad_norm = float(metrics.grad_norm)
            bad = not (np.isfinite(loss_val) and np.isfinite(grad_norm))
        if not bad:
            self._consecutive_bad_steps = 0
            return
        self._consecutive_bad_steps += 1
        self.telemetry.record_resilience(
            "watchdog_nonfinite", step=self.global_steps, samples=self.global_samples,
            consecutive=self._consecutive_bad_steps, limit=limit,
            loss=loss_val, grad_norm=grad_norm)
        if self._consecutive_bad_steps >= limit:
            kind = ("fp16 overflow-skipped" if self.fp16_enabled
                    else "non-finite loss/grad-norm")
            raise NonFiniteLossError(
                f"train-loop watchdog: {self._consecutive_bad_steps} consecutive "
                f"{kind} steps (max_consecutive_skips={limit}) at global step "
                f"{self.global_steps} — last loss={loss_val}, grad_norm={grad_norm}, "
                f"lr={float(metrics.lr):.3e}. The run has diverged: check the data "
                f"pipeline for corrupt batches, lower the lr, or resume from the "
                f"last checkpoint with load_checkpoint(fallback_to_valid=True)")

    # ----------------------------------------------------------- reporting
    def _maybe_report(self, metrics: StepMetrics, loss: Optional[float] = None):
        if self.global_steps % self.config.steps_per_print == 0:
            elapsed = self.throughput.stop()
            loss = float(metrics.loss) if loss is None else loss
            log_dist(
                f"step={self.global_steps} loss={loss:.4f} lr={float(metrics.lr):.3e} "
                f"grad_norm={float(metrics.grad_norm):.3f}"
                + (f" loss_scale={float(metrics.loss_scale):.0f}" if self.fp16_enabled else "")
                + (f" samples/sec={self.throughput.avg_samples_per_sec():.1f}" if elapsed else ""),
                ranks=[0])
            samples = self.global_samples
            events = [("Train/Samples/train_loss", loss, samples),
                      ("Train/Samples/lr", float(metrics.lr), samples),
                      ("Train/Samples/grad_norm", float(metrics.grad_norm), samples)]
            if self.fp16_enabled:
                events.append(("Train/Samples/loss_scale", float(metrics.loss_scale), samples))
            rec = self._last_telemetry_record
            if elapsed and (rec is None or rec.get("samples_per_sec") is None):
                # telemetry's per-step rate supersedes the running average
                events.append(("Train/Samples/samples_per_sec",
                               self.throughput.avg_samples_per_sec(), samples))
            if rec is not None:
                for key in ("step_time_ms", "samples_per_sec", "tokens_per_sec",
                            "tflops_per_sec", "mfu"):
                    if rec.get(key) is not None:
                        events.append((f"Train/Samples/{key}", float(rec[key]), samples))
                for key, value in (rec.get("hbm") or {}).items():
                    if value is not None:
                        events.append((f"Train/HBM/{key}", float(value), samples))
            if self.config.comms_logger.enabled:
                # comms-logger summary rides the same monitor event stream
                from ..utils.comms_logging import get_comms_logger
                events.extend(get_comms_logger().as_events(samples))
            self.monitor.write_events(events)

    @property
    def lr(self):
        return self._host_lr(self.global_steps)

    def get_global_grad_norm(self):
        return None  # populated per-step in metrics

    # --------------------------------------------------------- checkpointing
    def _validate_tag(self, tag: str):
        """Cross-process tag consistency (reference engine.py:3035
        ``_checkpoint_tag_validation``): every process must save under the
        same tag or loads will mix steps.  Single-process: a no-op beyond the
        mode plumbing; multi-process compares a tag hash via a host allreduce."""
        mode = self.config.checkpoint_tag_validation.lower()
        if mode == "ignore" or jax.process_count() <= 1:
            return
        import zlib
        from jax.experimental import multihost_utils
        # one CRC row PER PROCESS — a local reduce would be the identity
        crcs = multihost_utils.process_allgather(
            jnp.asarray([zlib.crc32(tag.encode())], jnp.uint32))
        if len(np.unique(np.asarray(crcs))) > 1:
            msg = f"checkpoint tag {tag!r} differs across processes"
            if mode == "fail":
                raise ValueError(msg)
            logger.warning(msg)

    @property
    def checkpoint_engine(self):
        """Config-selected persistence plug-in (reference _configure_checkpointing,
        engine.py:921: Nebula async vs torch).  Built lazily so engines that
        never checkpoint don't spawn the async writer thread."""
        if self._ckpt_engine is None:
            from .checkpoint_engine.checkpoint_engine import build_checkpoint_engine
            kind = self.config.checkpoint_engine_kind()
            self._ckpt_engine = build_checkpoint_engine(
                kind, max_queue=self.config.checkpoint.async_max_queue)
            if kind not in ("native", "torch"):
                log_dist(f"checkpoint engine: {kind} "
                         f"({type(self._ckpt_engine).__name__} — background writer; "
                         f"commit() at tag boundaries makes saves durable)", ranks=[0])
        return self._ckpt_engine

    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None, client_state: Optional[dict] = None):
        self._nvme_guard("save_checkpoint")
        tag = tag or f"global_step{self.global_steps}"
        self._validate_tag(tag)
        client_state = dict(client_state or {})
        client_state.update({
            "global_steps": self.global_steps,
            "global_samples": self.global_samples,
            "lr_scheduler": self.lr_scheduler.state_dict(),
        })
        state = self.state if self.offload_device is None else self._offload_host_state()
        ck = self.config.checkpoint
        t0 = time.perf_counter()
        # phase-stamped so the agent's hang dump distinguishes "in checkpoint
        # IO" (expected to be slow) from "wedged in a collective"
        self.heartbeat.stamp(self.global_steps, phase="checkpoint_save", force=True)
        with self.telemetry.annotation("checkpoint_save"):
            save_checkpoint_with_retries(
                save_dir, tag, state, client_state, config=self.config,
                engine=self.checkpoint_engine,
                retries=ck.save_retries, backoff_secs=ck.retry_backoff_secs,
                on_retry=lambda attempt, exc: self.telemetry.record_resilience(
                    "save_retry", step=self.global_steps, samples=self.global_samples,
                    tag=tag, attempt=attempt, error=repr(exc)))
        self.telemetry.record_events([("Train/Checkpoint/save_time_ms",
                                       (time.perf_counter() - t0) * 1e3, self.global_samples)])
        if ck.keep_last_n and _is_rank0():
            sweep_retention(save_dir, ck.keep_last_n, verify_integrity=ck.verify_integrity)
        self._register_preemption_handler(save_dir)
        self.heartbeat.stamp(self.global_steps, force=True)
        return tag

    # ----------------------------------------------- preemption (SIGTERM) save
    def _register_preemption_handler(self, save_dir: str):
        """Arm the best-effort final save (``checkpoint.save_on_preemption``):
        on SIGTERM — the TPU-pod preemption notice — save one last checkpoint
        tagged ``preempt_step<N>`` with ``client_state.preempted`` set, then
        chain to whatever handler was installed before (so the default
        die-on-TERM still happens in production)."""
        self._preempt_save_dir = save_dir
        if self._preempt_registered or not self.config.checkpoint.save_on_preemption:
            return
        import signal
        import threading
        if threading.current_thread() is not threading.main_thread():
            return  # signal.signal only works from the main thread
        try:
            self._preempt_prev_handler = signal.signal(signal.SIGTERM, self._on_preemption)
            self._preempt_registered = True
            log_dist("checkpoint: save_on_preemption armed (SIGTERM -> final save)",
                     ranks=[0])
        except (ValueError, OSError) as exc:
            logger.warning(f"save_on_preemption: could not install SIGTERM handler ({exc})")

    def _on_preemption(self, signum=None, frame=None):
        import signal
        # dslint: disable-next-line=handler-holds-engine  # the PR-2 save_on_preemption contract IS "the handler drives the engine": CPython runs signal handlers on the main thread between bytecodes, so this never executes concurrently with a step, and a best-effort final save_checkpoint is the whole point
        if not self._in_preempt_save and self._preempt_save_dir is not None:
            self._in_preempt_save = True
            try:
                tag = f"preempt_step{self.global_steps}"
                logger.warning(f"SIGTERM: best-effort preemption save -> "
                               f"{self._preempt_save_dir}/{tag}")
                self.save_checkpoint(self._preempt_save_dir, tag=tag,
                                     client_state={"preempted": True})
                self.telemetry.record_resilience("preemption_save", step=self.global_steps,
                                                 samples=self.global_samples, tag=tag)
            except BaseException as exc:  # best-effort: never mask the signal
                logger.error(f"preemption save failed: {exc!r}")
            finally:
                self._in_preempt_save = False
        prev = self._preempt_prev_handler
        if callable(prev):
            prev(signum, frame)
        elif prev == signal.SIG_DFL and signum is not None:
            # restore the default disposition and re-deliver so the process
            # still dies the way the supervisor expects
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)

    def _offload_host_state(self):
        """Host-side state pytree with the SAME key layout as the on-device
        TrainState, so checkpoints and the universal converter are identical
        across offload modes."""
        unflatten = lambda arrs: jax.tree_util.tree_unflatten(
            self._offload_treedef,
            [a.reshape(shape) for a, shape in zip(arrs, self._offload_shapes)])
        sd = self._offload_state.state_dict()
        params = unflatten([self._offload_state.params[k] for k in self._offload_keys])
        m = unflatten([sd["m"][k] for k in self._offload_keys])
        v = unflatten([sd["v"][k] for k in self._offload_keys])
        return {"step": np.int32(sd["step"]), "params": params,
                "opt_state": {"step": np.int32(sd["step"]), "exp_avg": m, "exp_avg_sq": v}}

    def load_checkpoint(self, load_dir: str, tag: Optional[str] = None,
                        load_optimizer_states: bool = True, fallback_to_valid: bool = False):
        """Resume from ``load_dir``.  With ``fallback_to_valid`` a missing,
        incomplete, or corrupt target tag (per manifest sizes, plus CRC32s when
        ``checkpoint.verify_integrity`` is on) doesn't raise: the load walks
        prior tags — checkpoint-index order, newest first — to the newest one
        that validates (resume-from-latest-valid).

        When ``tag`` is None and the elastic agent pinned a consensus resume
        tag (``DSTPU_RESUME_TAG`` env), that pin wins over ``latest``: every
        rank of a restarted generation must resume from the SAME tag, not its
        own per-rank newest (which the failure may have left divergent).  The
        pin only applies when the pinned tag exists under ``load_dir`` — a
        load from a directory the consensus wasn't computed over (e.g. a
        pretrained base checkpoint) still gets its own ``latest``."""
        self._nvme_guard("load_checkpoint")
        t0 = time.perf_counter()
        self.heartbeat.stamp(self.global_steps, phase="checkpoint_load", force=True)
        with self.telemetry.annotation("checkpoint_load"):
            if self.config.load_universal_checkpoint:
                out = self._load_universal_checkpoint(load_dir, tag, load_optimizer_states)
            else:
                tag = self._resolve_load_tag(load_dir, tag, fallback_to_valid)
                if self.offload_device is not None:
                    out = self._load_checkpoint_offload(load_dir, tag, load_optimizer_states)
                else:
                    state, client_state = load_checkpoint_dir(
                        load_dir,
                        tag,
                        self.state,
                        self._state_shardings(jax.eval_shape(lambda s: s, self.state)),
                        load_optimizer_states=load_optimizer_states,
                        # _resolve_load_tag just validated this tag (CRCs per
                        # checkpoint.verify_integrity); don't pay it twice
                        validate=False)
                    self.state = state
                    self.global_steps = client_state.get("global_steps", 0)
                    self.global_samples = client_state.get("global_samples", 0)
                    # ops-plane counter base: the restored steps/samples were
                    # executed by a PREVIOUS process life (the fleet
                    # aggregator carries that life's totals), so this
                    # process's exported counters restart from zero here —
                    # without this, every supervised restart that resumes
                    # from a checkpoint double-counts the resumed work in
                    # the merged fleet endpoint
                    self._ops_steps_base = self.global_steps
                    self._ops_samples_base = self.global_samples
                    if "lr_scheduler" in client_state:
                        self.lr_scheduler.load_state_dict(client_state["lr_scheduler"])
                    out = (tag, client_state)
        self.telemetry.record_events([("Train/Checkpoint/load_time_ms",
                                       (time.perf_counter() - t0) * 1e3, self.global_samples)])
        # trailing marker: clears phase=checkpoint_load (whose 10x IO grace
        # would delay post-resume hang detection) but declares phase=resumed,
        # because the jit recompile between here and the first step can
        # outlast the heartbeat timeout — the agent grants 'resumed' stamps
        # the startup grace window instead of indicting a healthy restart
        self.heartbeat.stamp(self.global_steps, phase="resumed", force=True)
        return out

    def _resolve_load_tag(self, load_dir: str, tag: Optional[str],
                          fallback_to_valid: bool) -> str:
        """Pick the tag to load: the requested one (or the agent-pinned
        ``DSTPU_RESUME_TAG``, or ``latest``) when it validates; otherwise —
        only with ``fallback_to_valid`` — the newest prior tag that does."""
        from .checkpointing import get_latest_tag
        from .heartbeat import RESUME_DIR_ENV, RESUME_TAG_ENV
        pinned = None
        if tag is None:
            pinned = os.environ.get(RESUME_TAG_ENV) or None
            # the pin is scoped to the agent-supervised checkpoint dir: a
            # base/warm-start load from an unrelated directory must not have
            # its 'latest' hijacked.  Tag names are the generic
            # global_step<N>, so a tag-existence check alone can false-match
            # a foreign dir — when the agent also exported the dir it
            # computed consensus over, require load_dir to be under it
            if pinned is not None and os.path.isdir(os.path.join(load_dir, pinned)):
                resume_dir = os.environ.get(RESUME_DIR_ENV) or None
                if resume_dir is not None:
                    try:
                        inside = os.path.commonpath(
                            [os.path.realpath(load_dir), os.path.realpath(resume_dir)]
                        ) == os.path.realpath(resume_dir)
                    except ValueError:  # different drives / mixed abs-rel
                        inside = False
                    if not inside:
                        pinned = None
            else:
                pinned = None
            tag = pinned
        verify = self.config.checkpoint.verify_integrity
        requested, failure = tag, None
        try:
            requested = tag or get_latest_tag(load_dir)
            if requested is None:
                raise CheckpointError(
                    f"checkpoint dir {load_dir!r} has no 'latest' file and no tag was "
                    f"given — nothing to resume from")
            validate_checkpoint_tag(load_dir, requested, verify_integrity=verify)
            return requested
        except CheckpointError as exc:
            if pinned is not None:
                # never silently walk away from the agent's consensus pin:
                # falling back would resume this rank from a DIFFERENT tag
                # than its peers — the exact divergence the pin prevents.
                # Fail fast so the agent restarts and re-runs consensus
                # (enable its verify_checkpoint_integrity to also catch what
                # this rank's CRC pass caught).
                raise CheckpointError(
                    f"agent-pinned resume tag {pinned!r} failed validation on this "
                    f"rank ({exc}); refusing to fall back to a per-rank tag — all "
                    f"ranks must resume from the same checkpoint") from exc
            if not fallback_to_valid:
                raise
            failure = exc
        exclude = (requested, ) if requested else ()
        found = find_latest_valid_tag(load_dir, verify_integrity=verify, exclude=exclude)
        if found is None:
            raise CheckpointError(
                f"checkpoint dir {load_dir!r}: no valid checkpoint to fall back to "
                f"(requested tag {requested!r} failed: {failure})")
        logger.warning(f"checkpoint tag {requested!r} is unusable ({failure}); "
                       f"falling back to newest valid tag {found!r}")
        self.telemetry.record_resilience(
            "fallback_load", step=self.global_steps, samples=self.global_samples,
            requested=str(requested), fallback=found, reason=str(failure))
        return found

    def _load_checkpoint_offload(self, load_dir, tag, load_optimizer_states=True):
        from .checkpointing import get_latest_tag, read_metadata
        tag = tag or get_latest_tag(load_dir)
        ckpt_dir = os.path.join(load_dir, tag)
        meta = read_metadata(ckpt_dir)
        sd = {"m": {}, "v": {}, "step": 0}
        for m in meta["manifest"]:
            key = m["key"]
            path = os.path.join(ckpt_dir, key + ".npy")
            if key.startswith("params."):
                self._offload_state.params[key[len("params."):]][...] = np.load(path).ravel()
            elif key.startswith("opt_state.exp_avg_sq.") and load_optimizer_states:
                sd["v"][key[len("opt_state.exp_avg_sq."):]] = np.load(path).ravel()
            elif key.startswith("opt_state.exp_avg.") and load_optimizer_states:
                sd["m"][key[len("opt_state.exp_avg."):]] = np.load(path).ravel()
            elif key in ("step", "opt_state.step"):
                sd["step"] = int(np.load(path))
        if load_optimizer_states and sd["m"]:
            self._offload_state.load_state_dict(sd)
        self._push_compute_params()
        client_state = meta.get("client_state", {})
        self.global_steps = client_state.get("global_steps", 0)
        self.global_samples = client_state.get("global_samples", 0)
        if "lr_scheduler" in client_state:
            self.lr_scheduler.load_state_dict(client_state["lr_scheduler"])
        return tag, client_state

    def _load_universal_checkpoint(self, load_dir, tag, load_optimizer_states=True):
        """Resume from the universal atom format at ANY topology/optimizer —
        the reference's ``engine.load_universal_checkpoint`` (engine.py:813) +
        ``load_hp_checkpoint_state`` (checkpoint/universal_checkpoint.py:12),
        engaged by ``load_universal_checkpoint: true`` in config.

        ``load_dir`` may point directly at a ds_to_universal output (contains
        universal_metadata.json) or at a checkpoint root whose ``<tag>/``
        subdirectory holds one.  Param leaves rebuild from their fp32 atoms;
        optimizer leaves match atoms by the same suffix discovery used at
        conversion, so any optimizer whose state mirrors the param tree (adam,
        lion, lamb, sgd momentum) resumes — including into a DIFFERENT
        optimizer, where unmatched moments warn and keep their init values.
        Atoms saved with vocab padding stripped are zero-re-padded on dim 0
        (reference merge_tp_slices vocab fixups, ds_to_universal.py:156)."""
        from ..checkpoint.universal import PARAM_ATOM, load_universal
        from .checkpointing import _leaf_key, get_latest_tag
        udir = load_dir
        if not os.path.exists(os.path.join(udir, "universal_metadata.json")):
            tag = tag or get_latest_tag(load_dir)
            if tag is not None and os.path.exists(os.path.join(load_dir, tag, "universal_metadata.json")):
                udir = os.path.join(load_dir, tag)
            else:
                raise FileNotFoundError(
                    f"load_universal_checkpoint: no universal_metadata.json under {load_dir}"
                    + (f" or {load_dir}/{tag}" if tag else "") +
                    " — convert a checkpoint first (python -m deepspeed_tpu.checkpoint.universal)")
        data = load_universal(udir)
        atoms, passthrough = data["params"], data["passthrough"]
        stripped_to = data.get("strip_vocab_padding")
        by_len = sorted(atoms, key=len, reverse=True)

        def lookup(key: str):
            if key.startswith("params."):
                p = key[len("params."):]
                return atoms[p][PARAM_ATOM] if p in atoms else None
            if key.startswith("opt_state."):
                if not load_optimizer_states:
                    return None
                rest = key[len("opt_state."):]
                for p in by_len:
                    if rest.endswith("." + p):
                        got = atoms[p].get(rest[:-(len(p) + 1)])
                        if got is not None:
                            return got
                return passthrough.get(key)
            return passthrough.get(key)

        def fit(arr, cur, key):
            want = tuple(np.shape(cur))
            if tuple(arr.shape) != want:
                # re-pad ONLY atoms the converter recorded as vocab-stripped
                # (strip_vocab_padding in universal_metadata.json) — a bare
                # dim-0 mismatch (e.g. different layer count) must stay a hard
                # error, not silently zero-filled "layers"
                if (stripped_to is not None and arr.ndim == len(want) and arr.ndim >= 1
                        and arr.shape[0] == stripped_to and arr.shape[0] < want[0]
                        and tuple(arr.shape[1:]) == tuple(want[1:])):
                    pad = np.zeros((want[0] - arr.shape[0], ) + tuple(arr.shape[1:]), arr.dtype)
                    arr = np.concatenate([arr, pad], axis=0)
                    log_dist(f"universal load: re-padded {key} dim0 "
                             f"{arr.shape[0] - pad.shape[0]} -> {want[0]} (vocab padding)", ranks=[0])
                else:
                    raise ValueError(f"universal atom {key} shape {arr.shape} != model {want}")
            dtype = getattr(cur, "dtype", None)
            return arr.astype(dtype) if dtype is not None and arr.dtype != dtype else arr

        if self.offload_device is not None:
            # host-offloaded Adam: atoms land in the host buffers via the same
            # state_dict path the native offload resume uses.  load_state_dict
            # consumes EVERY key's m AND v, so unmatched moments must be filled
            # from the current state (not omitted — a partial dict KeyErrors)
            template = lambda shape: np.empty(shape, np.float32)
            cur = self._offload_state.state_dict() if load_optimizer_states else None
            any_moment = False
            sd = {"m": {}, "v": {}, "step": int(passthrough.get("opt_state.step", 0))}
            for key, shape in zip(self._offload_keys, self._offload_shapes):
                a = atoms.get(key)
                if a is None:
                    logger.warning(f"universal load: no atom for param {key}; keeping current")
                    a = {}
                else:
                    self._offload_state.params[key][...] = fit(a[PARAM_ATOM], template(shape),
                                                               key).ravel()
                if load_optimizer_states:
                    for atom_name, slot in (("exp_avg", "m"), ("exp_avg_sq", "v")):
                        if atom_name in a:
                            sd[slot][key] = fit(a[atom_name], template(shape), key).ravel()
                            any_moment = True
                        else:
                            sd[slot][key] = cur[slot][key]
                    extra = sorted(set(a) - {PARAM_ATOM, "exp_avg", "exp_avg_sq"})
                    if a and (extra or "exp_avg" not in a):
                        logger.warning(
                            f"universal load (offload): param {key} has atoms {sorted(a)} "
                            f"but the host-offload Adam consumes exp_avg/exp_avg_sq only — "
                            f"unmatched moments keep their current values")
            if load_optimizer_states and any_moment:
                self._offload_state.load_state_dict(sd)
            self._push_compute_params()
        else:
            shardings = self._state_shardings(jax.eval_shape(lambda s: s, self.state))
            leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(self.state)
            shard_leaves = jax.tree_util.tree_leaves(shardings)
            multi = jax.process_count() > 1
            new_leaves = []
            for (path, cur), sharding in zip(leaves_with_path, shard_leaves):
                key = _leaf_key(path)
                arr = lookup(key)
                if arr is None:
                    skip = (not load_optimizer_states) and key.split(".")[0] in ("opt_state", "loss_scale")
                    if not skip:
                        logger.warning(f"universal load: no atom/passthrough for {key}; "
                                       f"keeping current value")
                    new_leaves.append(cur)
                    continue
                arr = fit(np.asarray(arr), cur, key)
                if multi:
                    new_leaves.append(jax.make_array_from_callback(
                        tuple(arr.shape), sharding, lambda idx, a=arr: np.asarray(a[idx])))
                else:
                    new_leaves.append(jax.device_put(arr, sharding))
            self.state = jax.tree_util.tree_unflatten(treedef, new_leaves)
        client_state = data.get("client_state", {})
        self.global_steps = client_state.get("global_steps", 0)
        self.global_samples = client_state.get("global_samples", 0)
        if "lr_scheduler" in client_state:
            self.lr_scheduler.load_state_dict(client_state["lr_scheduler"])
        log_dist(f"loaded universal checkpoint from {udir} "
                 f"({len(atoms)} parameter atoms, step={self.global_steps})", ranks=[0])
        return tag, client_state

    # ------------------------------------------------------------- utilities
    def get_fp32_params(self):
        """Gather the full fp32 master params on host — the analog of
        zero_to_fp32 consolidation (deepspeed/utils/zero_to_fp32.py)."""
        if self.offload_device is not None:
            return self._offload_host_state()["params"]
        rep = NamedSharding(self.topology.mesh, PartitionSpec())
        gathered = jax.jit(lambda p: p, out_shardings=jax.tree_util.tree_map(lambda _: rep, self.state.params))(
            self.state.params)
        return jax.tree_util.tree_map(np.asarray, gathered)

    def save_16bit_model(self, save_dir: str, filename: str = "model.safetensors"):
        """Consolidated 16-bit weights for deployment/HF export — the analog of
        ``_zero3_consolidated_16bit_state_dict`` + ``save_16bit_model``
        (reference engine.py:3479,3548): ZeRO-3 shards gather leaf-by-leaf
        (never the whole tree at once), cast to the compute dtype, and land in
        one safetensors file keyed by pytree path (the HF deployment format;
        bf16-native, unlike .npz)."""
        from safetensors.numpy import save_file
        from .checkpointing import _is_rank0, _leaf_key
        os.makedirs(save_dir, exist_ok=True)
        params = (self._offload_host_state()["params"] if self.offload_device is not None
                  else self.state.params)
        rep = NamedSharding(self.topology.mesh, PartitionSpec())
        ct = self.compute_dtype
        # cast BEFORE replicating: the gather then moves 2 bytes/param, not 4
        # (the reference gathers the bit16 copy for the same reason), which is
        # why this doesn't reuse checkpointing._gather_to_host (fp32 path)
        gather16 = jax.jit(lambda x: x.astype(ct), out_shardings=rep)
        rank0 = _is_rank0()
        out = {}
        for keypath, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
            if isinstance(leaf, jax.Array) and len(leaf.sharding.device_set) > 1:
                leaf = gather16(leaf)  # collective: every rank participates
            if rank0:  # only the writer pays the D2H copy + host RAM
                out[_leaf_key(keypath)] = np.asarray(jnp.asarray(leaf, ct))
        out_path = os.path.join(save_dir, filename)
        if rank0:  # shared storage: exactly one writer
            save_file(out, out_path)
        log_dist(f"saved 16-bit model weights ({len(out)} leaves) -> {out_path}", ranks=[0])
        return out_path
