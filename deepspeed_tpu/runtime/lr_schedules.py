"""Learning-rate schedules.

Analog of deepspeed/runtime/lr_schedules.py (``LRRangeTest:267``, ``OneCycle:370``,
``WarmupLR:634``, ``WarmupDecayLR:723``, ``WarmupCosineLR:774``).  TPU-native
design: each schedule is a pure ``step -> lr`` function (jnp-traceable, usable
inside the jitted train step), wrapped in a small object with the reference's
``get_lr()/step()`` surface for imperative callers.

Config spelling matches the reference scheduler "params" dicts.
"""

import math
from typing import Any, Callable, Dict, Optional

import jax.numpy as jnp

LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
WARMUP_COSINE_LR = "WarmupCosineLR"

VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR, WARMUP_COSINE_LR]


def lr_range_test(lr_range_test_min_lr: float = 1e-3,
                  lr_range_test_step_size: int = 2000,
                  lr_range_test_step_rate: float = 1.0,
                  lr_range_test_staircase: bool = False) -> Callable:
    """Reference LRRangeTest (lr_schedules.py:267): lr = min_lr * (1 + rate*interval)."""

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        interval = jnp.floor(step / lr_range_test_step_size) if lr_range_test_staircase \
            else step / lr_range_test_step_size
        return lr_range_test_min_lr * (1.0 + interval * lr_range_test_step_rate)

    return schedule


def one_cycle(cycle_min_lr: float,
              cycle_max_lr: float,
              decay_lr_rate: float = 0.0,
              cycle_first_step_size: int = 2000,
              cycle_second_step_size: Optional[int] = None,
              cycle_first_stair_count: int = 0,
              cycle_second_stair_count: Optional[int] = None,
              decay_step_size: int = 0,
              **_ignored) -> Callable:
    """Reference OneCycle (lr_schedules.py:370): ramp min→max over the first phase,
    max→min over the second, then decay by decay_lr_rate per decay_step_size."""
    second = cycle_second_step_size if cycle_second_step_size is not None else cycle_first_step_size
    total_cycle = cycle_first_step_size + second

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        in_first = step < cycle_first_step_size
        frac_up = jnp.clip(step / cycle_first_step_size, 0.0, 1.0)
        frac_down = jnp.clip((step - cycle_first_step_size) / max(second, 1), 0.0, 1.0)
        lr_up = cycle_min_lr + (cycle_max_lr - cycle_min_lr) * frac_up
        lr_down = cycle_max_lr - (cycle_max_lr - cycle_min_lr) * frac_down
        lr_cycle = jnp.where(in_first, lr_up, lr_down)
        post = jnp.maximum(step - total_cycle, 0.0)
        if decay_lr_rate > 0.0 and decay_step_size > 0:
            decay = 1.0 / (1.0 + decay_lr_rate * jnp.floor(post / decay_step_size))
            lr_post = cycle_min_lr * decay
        else:
            lr_post = jnp.asarray(cycle_min_lr, jnp.float32)
        return jnp.where(step < total_cycle, lr_cycle, lr_post)

    return schedule


def warmup_lr(warmup_min_lr: float = 0.0,
              warmup_max_lr: float = 0.001,
              warmup_num_steps: int = 1000,
              warmup_type: str = "log",
              **_ignored) -> Callable:
    """Reference WarmupLR (lr_schedules.py:634): log or linear warmup to max, then hold."""

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        frac = jnp.clip((step + 1.0) / warmup_num_steps, 0.0, 1.0)
        if warmup_type == "log":
            gamma = jnp.log(frac * (math.e - 1.0) + 1.0)
        else:
            gamma = frac
        return jnp.where(step < warmup_num_steps,
                         warmup_min_lr + (warmup_max_lr - warmup_min_lr) * gamma,
                         jnp.asarray(warmup_max_lr, jnp.float32))

    return schedule


def warmup_decay_lr(total_num_steps: int,
                    warmup_min_lr: float = 0.0,
                    warmup_max_lr: float = 0.001,
                    warmup_num_steps: int = 1000,
                    warmup_type: str = "log",
                    **_ignored) -> Callable:
    """Reference WarmupDecayLR (lr_schedules.py:723): warmup then linear decay to 0."""
    base = warmup_lr(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        decay_frac = jnp.clip(
            (total_num_steps - step) / jnp.maximum(float(total_num_steps - warmup_num_steps), 1.0), 0.0, 1.0)
        return jnp.where(step < warmup_num_steps, base(step), warmup_max_lr * decay_frac)

    return schedule


def warmup_cosine_lr(total_num_steps: int,
                     warmup_min_ratio: float = 0.01,
                     warmup_num_steps: int = 1000,
                     cos_min_ratio: float = 0.0001,
                     lr: float = 1.0,
                     **_ignored) -> Callable:
    """Reference WarmupCosineLR (lr_schedules.py:774): linear warmup from
    warmup_min_ratio→1, then cosine decay to cos_min_ratio (ratios of base lr)."""

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warm = warmup_min_ratio + (1.0 - warmup_min_ratio) * jnp.clip(step / max(warmup_num_steps, 1), 0.0, 1.0)
        progress = jnp.clip((step - warmup_num_steps) / jnp.maximum(float(total_num_steps - warmup_num_steps), 1.0),
                            0.0, 1.0)
        cos = cos_min_ratio + (1.0 - cos_min_ratio) * 0.5 * (1.0 + jnp.cos(jnp.pi * progress))
        return lr * jnp.where(step < warmup_num_steps, warm, cos)

    return schedule


_SCHEDULE_BUILDERS = {
    LR_RANGE_TEST: lr_range_test,
    ONE_CYCLE: one_cycle,
    WARMUP_LR: warmup_lr,
    WARMUP_DECAY_LR: warmup_decay_lr,
    WARMUP_COSINE_LR: warmup_cosine_lr,
}


def host_lr_fn(schedule_fn: Callable) -> Callable:
    """Host-side ``step -> float`` evaluation of a jnp schedule.

    The schedules above are written with jnp so they trace into the jitted
    train step (where the per-step lr belongs).  The offload and NVMe-streaming
    paths instead need the lr as a HOST float every step; calling the schedule
    eagerly puts that tiny computation on the default (accelerator) backend and
    the ``float()`` read becomes a per-step device round-trip in the train hot
    loop — dslint's host-sync-in-hot-path rule's first real catch.  Pinning the
    evaluation to the CPU backend keeps the accelerator pipeline untouched; a
    one-entry memo dedups the common read-twice-per-step pattern (train step +
    telemetry/`engine.lr`).
    """
    import jax
    try:
        cpu = jax.local_devices(backend="cpu")[0]
    except RuntimeError:  # no CPU backend registered: eager default-device eval
        cpu = None
    memo = {}

    def host_schedule(step) -> float:
        step = int(step)
        if step not in memo:
            if cpu is None:
                value = float(schedule_fn(step))
            else:
                with jax.default_device(cpu):
                    value = float(schedule_fn(step))
            memo.clear()
            memo[step] = value
        return memo[step]

    return host_schedule


class LRScheduler:
    """Imperative wrapper with the torch-style surface the reference exposes
    (``step()``, ``get_lr()``, ``state_dict()``/``load_state_dict()``)."""

    def __init__(self, schedule_fn: Callable, last_step: int = 0):
        self.schedule_fn = schedule_fn
        self.last_step = last_step

    def step(self, increment: int = 1):
        self.last_step += increment

    def get_lr(self):
        return [float(self.schedule_fn(self.last_step))]

    def get_last_lr(self):
        return self.get_lr()

    def state_dict(self):
        return {"last_step": self.last_step}

    def load_state_dict(self, sd):
        self.last_step = sd["last_step"]


def build_lr_schedule(sched_type: Optional[str], params: Dict[str, Any], base_lr: float = 1e-3) -> Callable:
    """Build a pure step->lr function from a scheduler config section.

    Returns a constant schedule at ``base_lr`` when no scheduler is configured
    (reference behavior: client LR untouched).
    """
    if sched_type is None:
        return lambda step: jnp.asarray(base_lr, jnp.float32)
    if sched_type not in _SCHEDULE_BUILDERS:
        raise ValueError(f"unknown scheduler type {sched_type!r}; valid: {VALID_LR_SCHEDULES}")
    builder = _SCHEDULE_BUILDERS[sched_type]
    if sched_type == WARMUP_COSINE_LR:
        params = dict(params)
        params.setdefault("lr", base_lr)
    return builder(**params)
