"""1-bit optimizers: OnebitAdam / OnebitLamb / ZeroOneAdam.

Reference: runtime/fp16/onebit/{adam.py:14, lamb.py, zoadam.py} built on
``compressed_allreduce`` (runtime/comm/nccl.py:51).  The algorithm family:

- **warmup** (``freeze_step`` steps): exact data-parallel Adam/Lamb — gradients
  reduced in full precision, variance (and Lamb trust ratios) learned.
- **compressed**: the variance is FROZEN; each rank updates its momentum with
  the LOCAL gradient, and only the momentum crosses the wire — sign-compressed
  (~1 bit/element) with persistent worker+server error-feedback buffers
  (runtime/comm/compressed.py onebit_allreduce).  The update is
  ``lr * m_reduced / (sqrt(v_frozen) + eps)``.

TPU-native integration: the comm lives INSIDE the optimizer step, so the engine
runs the whole train step under ``compat.shard_map`` over the dp axes with
**replicated params** (the reference likewise restricts 1-bit optimizers to
ZeRO stage 0/1 semantics; here: stage 0).  Error buffers are optimizer state:
worker errors are per-rank full-size (engine shards them over dp on a leading
world dim), server errors are each rank's 1/world slice.

ZeroOneAdam (zoadam.py) differs: no warmup — compression from step 0, with the
variance refreshed at exponentially spaced intervals (``var_freeze_step``,
``var_update_scaler``); learning-rate freezing between variance updates.  The
reference's local-step intervals (communicate every k steps) are collapsed to
every-step communication — interval skipping is a wire-level optimization the
sign payload already dwarfs.
"""

import dataclasses
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .comm.compressed import onebit_allreduce
from .optimizers import Optimizer, _tree_zeros_like


class OnebitState(NamedTuple):
    step: jnp.ndarray
    exp_avg: Any
    exp_avg_sq: Any
    worker_error: Any  # per-leaf flat [n] (sharded over dp: each rank's own)
    server_error: Any  # per-leaf flat [n // world] slice
    lamb_coeff: Any = None  # OnebitLamb: frozen per-leaf trust ratio


@dataclasses.dataclass(frozen=True)
class OnebitSpec:
    """Attached to Optimizer.onebit — tells the engine to build the shard_map
    step and gives it the local-update rule."""
    freeze_step: int
    local_step: Callable  # (grads_local, state, params, lr, axis_name, world) -> (new_params, new_state)
    init: Callable  # (params, world) -> OnebitState
    name: str = "onebit"


def error_buffer_spec(path, ax):
    """PartitionSpec for a 1-bit opt-state leaf by tree path (None = not an
    error buffer).  Single source of truth for the worker/server buffer layout,
    used by both the engine's state shardings and its shard_map step specs."""
    p = ".".join(str(getattr(k, "name", getattr(k, "key", k))) for k in path)
    from jax.sharding import PartitionSpec
    if "worker_error" in p:
        return PartitionSpec(ax, None)  # [world, npad], rank-owned rows
    if "server_error" in p:
        return PartitionSpec(ax)  # [npad], rank-owned slices
    return None


def _flat_sizes(params, world):
    leaves = jax.tree_util.tree_leaves(params)
    ns = [int(np.prod(l.shape)) for l in leaves]
    # pad to a multiple of world so every element takes the compressed path
    ns_pad = [int(np.ceil(n / world)) * world for n in ns]
    return ns_pad


def _onebit_reduce_tree(m_tree, state, axis_name, world):
    """Sign-compress + allreduce each momentum leaf (flat, padded).

    Worker-error leaves may arrive as [1, npad] (the rank's row of the globally
    [world, npad] dp-sharded buffer inside shard_map) or flat [npad] (serial)."""
    flat_m, treedef = jax.tree_util.tree_flatten(m_tree)
    flat_we = jax.tree_util.tree_leaves(state.worker_error)
    flat_se = jax.tree_util.tree_leaves(state.server_error)
    out_m, out_we, out_se = [], [], []
    for m, we, se in zip(flat_m, flat_we, flat_se):
        rowed = we.ndim == 2
        we_l = we[0] if rowed else we
        n = int(np.prod(m.shape))
        npad = we_l.shape[0]
        flat = jnp.pad(m.reshape(-1), (0, npad - n))
        red, nwe, nse = onebit_allreduce(flat, we_l, axis_name, se)
        out_m.append(red[:n].reshape(m.shape))
        out_we.append(nwe[None] if rowed else nwe)
        out_se.append(nse)
    unf = lambda ls: jax.tree_util.tree_unflatten(treedef, ls)
    we_def = jax.tree_util.tree_structure(state.worker_error)
    return (unf(out_m),
            jax.tree_util.tree_unflatten(we_def, out_we),
            jax.tree_util.tree_unflatten(we_def, out_se))


def onebit_adam(betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                freeze_step: int = 100) -> Optimizer:
    """OnebitAdam (reference runtime/fp16/onebit/adam.py:14)."""
    b1, b2 = betas

    def init(params, world: int = 1):
        # global layouts: worker [world, npad] (dp-sharded dim 0 — each rank
        # owns its row), server [npad] (dp-sharded — each rank its slice)
        ns = _flat_sizes(params, world)
        leaves, treedef = jax.tree_util.tree_flatten(params)
        we = jax.tree_util.tree_unflatten(treedef, [jnp.zeros((world, n), jnp.float32) for n in ns])
        se = jax.tree_util.tree_unflatten(treedef, [jnp.zeros((n,), jnp.float32) for n in ns])
        return OnebitState(step=jnp.zeros((), jnp.int32),
                           exp_avg=_tree_zeros_like(params, jnp.float32),
                           exp_avg_sq=_tree_zeros_like(params, jnp.float32),
                           worker_error=we, server_error=se)

    def local_step(grads, state, params, lr, axis_name, world):
        """grads are the rank's LOCAL (unreduced) fp32 gradients."""
        step = state.step + 1
        warm = step <= freeze_step

        def warm_branch(operand):
            """Exact dp Adam: full-precision gradient reduction."""
            grads, state = operand
            g_red = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g, axis_name) if axis_name else g, grads)
            m = jax.tree_util.tree_map(lambda m, g: b1 * m + (1 - b1) * g,
                                       state.exp_avg, g_red)
            v = jax.tree_util.tree_map(lambda v, g: b2 * v + (1 - b2) * g * g,
                                       state.exp_avg_sq, g_red)
            return m, v, state.worker_error, state.server_error

        def comp_branch(operand):
            """Local momentum update, 1-bit reduction; variance frozen."""
            grads, state = operand
            m_local = jax.tree_util.tree_map(lambda m, g: b1 * m + (1 - b1) * g,
                                             state.exp_avg, grads)
            if axis_name:
                m, we, se = _onebit_reduce_tree(m_local, state, axis_name, world)
            else:
                m, we, se = m_local, state.worker_error, state.server_error
            return m, state.exp_avg_sq, we, se

        # lax.cond (not where): only the live branch's collectives execute, so
        # the compressed phase really drops the fp32 allreduce from the wire
        m_new, v_new, new_we, new_se = jax.lax.cond(warm, warm_branch, comp_branch,
                                                    (grads, state))

        def upd(p, m, v):
            # stability deviation from the reference: (a) v==0 elements (params
            # untouched during warmup — dead units, unsampled embedding rows)
            # take no update instead of m/eps; (b) the elementwise ratio is
            # clipped to ±10 so elements whose variance froze at a tiny value
            # cannot run away (the reference relies on very long warmups for
            # the same effect)
            u = -lr * jnp.where(v > 0, jnp.clip(m / (jnp.sqrt(v) + eps), -10.0, 10.0), 0.0)
            if weight_decay != 0.0:
                u = u - lr * weight_decay * p
            return p + u

        new_params = jax.tree_util.tree_map(upd, params, m_new, v_new)
        return new_params, OnebitState(step=step, exp_avg=m_new, exp_avg_sq=v_new,
                                       worker_error=new_we, server_error=new_se)

    spec = OnebitSpec(freeze_step=freeze_step, local_step=local_step, init=init,
                      name="onebit_adam")

    # serial/delta fallback for world=1 contexts (tests, eval): same math, no comm
    def s_init(params):
        return init(params, world=1)

    def update(grads, state, params, lr):
        new_p, new_s = local_step(grads, state, params, lr, None, 1)
        updates = jax.tree_util.tree_map(lambda a, b: a - b, new_p, params)
        return updates, new_s

    return Optimizer(init=s_init, update=update, name="onebit_adam", onebit=spec)


def zero_one_adam(betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                  var_freeze_step: int = 100, var_update_scaler: int = 16,
                  local_step_scaler: int = 32768, local_step_clipper: int = 16) -> Optimizer:
    """0/1 Adam (reference runtime/fp16/onebit/zoadam.py): compressed from step
    0; the variance is refreshed only at exponentially spaced steps until
    ``var_freeze_step`` then frozen.  (local-step comm intervals collapsed to
    every step — see module docstring.)"""
    b1, b2 = betas

    base = onebit_adam(betas=betas, eps=eps, weight_decay=weight_decay, freeze_step=0)

    def local_step(grads, state, params, lr, axis_name, world):
        step = state.step + 1

        m_local = jax.tree_util.tree_map(lambda m, g: b1 * m + (1 - b1) * g,
                                         state.exp_avg, grads)
        if axis_name:
            m_new, new_we, new_se = _onebit_reduce_tree(m_local, state, axis_name, world)
        else:
            m_new, new_we, new_se = m_local, state.worker_error, state.server_error

        # variance refresh: bootstrapped at step 1 (reference zoadam initialize
        # branch), then every var_update_scaler steps until var_freeze_step
        refresh = jnp.logical_or(step == 1,
                                 jnp.logical_and(step <= var_freeze_step,
                                                 (step % max(var_update_scaler, 1)) == 0))
        v_new = jax.tree_util.tree_map(
            lambda v, m: jnp.where(refresh, b2 * v + (1 - b2) * m * m, v),
            state.exp_avg_sq, m_new)

        def upd(p, m, v):
            # stability deviation from the reference: (a) v==0 elements (params
            # untouched during warmup — dead units, unsampled embedding rows)
            # take no update instead of m/eps; (b) the elementwise ratio is
            # clipped to ±10 so elements whose variance froze at a tiny value
            # cannot run away (the reference relies on very long warmups for
            # the same effect)
            u = -lr * jnp.where(v > 0, jnp.clip(m / (jnp.sqrt(v) + eps), -10.0, 10.0), 0.0)
            if weight_decay != 0.0:
                u = u - lr * weight_decay * p
            return p + u

        new_params = jax.tree_util.tree_map(upd, params, m_new, v_new)
        return new_params, OnebitState(step=step, exp_avg=m_new, exp_avg_sq=v_new,
                                       worker_error=new_we, server_error=new_se)

    spec = OnebitSpec(freeze_step=0, local_step=local_step, init=base.onebit.init,
                      name="zero_one_adam")

    def update(grads, state, params, lr):
        new_p, new_s = local_step(grads, state, params, lr, None, 1)
        updates = jax.tree_util.tree_map(lambda a, b: a - b, new_p, params)
        return updates, new_s

    return Optimizer(init=base.init, update=update, name="zero_one_adam", onebit=spec)


def onebit_lamb(betas=(0.9, 0.999), eps=1e-6, weight_decay=0.0,
                freeze_step: int = 100, max_coeff=10.0, min_coeff=0.01) -> Optimizer:
    """OnebitLamb (reference runtime/fp16/onebit/lamb.py): Lamb during warmup;
    after the freeze the per-leaf trust ratio (lamb coefficient) learned at the
    freeze point is reused while only the 1-bit momentum crosses the wire."""
    b1, b2 = betas

    def init(params, world: int = 1):
        base = onebit_adam(betas=betas, eps=eps).onebit.init(params, world)
        ones = jax.tree_util.tree_map(lambda p: jnp.ones((), jnp.float32), params)
        return base._replace(lamb_coeff=ones)

    def trust(p, u):
        p_norm = jnp.linalg.norm(p.astype(jnp.float32).ravel())
        u_norm = jnp.linalg.norm(u.astype(jnp.float32).ravel())
        return jnp.where((p_norm > 0) & (u_norm > 0),
                         jnp.clip(p_norm / u_norm, min_coeff, max_coeff), 1.0)

    def local_step(grads, state, params, lr, axis_name, world):
        step = state.step + 1
        warm = step <= freeze_step

        def warm_branch(operand):
            grads, state = operand
            g_red = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g, axis_name) if axis_name else g, grads)
            m = jax.tree_util.tree_map(lambda m, g: b1 * m + (1 - b1) * g,
                                       state.exp_avg, g_red)
            v = jax.tree_util.tree_map(lambda v, g: b2 * v + (1 - b2) * g * g,
                                       state.exp_avg_sq, g_red)
            return m, v, state.worker_error, state.server_error

        def comp_branch(operand):
            grads, state = operand
            m_local = jax.tree_util.tree_map(lambda m, g: b1 * m + (1 - b1) * g,
                                             state.exp_avg, grads)
            if axis_name:
                m, we, se = _onebit_reduce_tree(m_local, state, axis_name, world)
            else:
                m, we, se = m_local, state.worker_error, state.server_error
            return m, state.exp_avg_sq, we, se

        m_new, v_new, new_we, new_se = jax.lax.cond(warm, warm_branch, comp_branch,
                                                    (grads, state))
        sel = lambda a, b: jax.tree_util.tree_map(lambda x, y: jnp.where(warm, x, y), a, b)

        def raw_update(m, v, p):
            u = jnp.where(v > 0, jnp.clip(m / (jnp.sqrt(v) + eps), -10.0, 10.0), 0.0)  # stability guards (see adam)
            if weight_decay != 0.0:
                u = u + weight_decay * p
            return u

        u_tree = jax.tree_util.tree_map(lambda m, v, p: raw_update(m, v, p), m_new, v_new, params)
        # warmup: live trust ratio (and remember it); frozen: reuse stored coeff
        live = jax.tree_util.tree_map(trust, params, u_tree)
        coeff = sel(live, state.lamb_coeff)
        new_params = jax.tree_util.tree_map(lambda p, u, c: p - lr * c * u,
                                            params, u_tree, coeff)
        return new_params, OnebitState(step=step, exp_avg=m_new, exp_avg_sq=v_new,
                                       worker_error=new_we, server_error=new_se,
                                       lamb_coeff=coeff)

    spec = OnebitSpec(freeze_step=freeze_step, local_step=local_step, init=init,
                      name="onebit_lamb")

    def s_init(params):
        return init(params, world=1)

    def update(grads, state, params, lr):
        new_p, new_s = local_step(grads, state, params, lr, None, 1)
        updates = jax.tree_util.tree_map(lambda a, b: a - b, new_p, params)
        return updates, new_s

    return Optimizer(init=s_init, update=update, name="onebit_lamb", onebit=spec)
