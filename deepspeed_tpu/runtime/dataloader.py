"""Data loaders.

Analog of deepspeed/runtime/dataloader.py (``DeepSpeedDataLoader:41``,
``RepeatingLoader:17``).  The reference wraps a torch DataLoader with a
DistributedSampler; in single-controller JAX every process assembles the GLOBAL
macro-batch [train_batch_size, ...] and the engine shards it over the dp mesh
axes at device_put time — so the loader's job is batching + shuffling + resume,
not rank slicing.
"""

from typing import Any, Callable, Iterator, Optional, Sequence

import numpy as np


class RepeatingLoader:
    """Wraps an iterator to restart on StopIteration (reference dataloader.py:17)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


class DeepSpeedDataLoader:
    """Global-batch loader over an indexable dataset.

    dataset[i] returns a pytree sample (dict/tuple of arrays); batches are
    collated by stacking.  ``state_dict``/``load_state_dict`` support
    curriculum-style resume (reference: curriculum-aware resume in
    runtime/dataloader.py + data_sampler).
    """

    def __init__(self,
                 dataset: Sequence,
                 batch_size: int,
                 shuffle: bool = True,
                 seed: int = 0,
                 drop_last: bool = True,
                 collate_fn: Optional[Callable] = None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.collate_fn = collate_fn or _default_collate
        self.epoch = 0
        self._consumed_in_epoch = 0

    def __len__(self):
        n = len(self.dataset) // self.batch_size
        if not self.drop_last and len(self.dataset) % self.batch_size:
            n += 1
        return n

    def _order(self):
        idx = np.arange(len(self.dataset))
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(idx)
        return idx

    def __iter__(self) -> Iterator:
        order = self._order()
        start = self._consumed_in_epoch * self.batch_size
        for ofs in range(start, len(self.dataset) - (self.batch_size - 1 if self.drop_last else 0), self.batch_size):
            batch_idx = order[ofs:ofs + self.batch_size]
            if len(batch_idx) == 0:
                break
            self._consumed_in_epoch += 1
            yield self.collate_fn([self.dataset[int(i)] for i in batch_idx])
        self.epoch += 1
        self._consumed_in_epoch = 0

    def state_dict(self):
        return {"epoch": self.epoch, "consumed_in_epoch": self._consumed_in_epoch, "seed": self.seed}

    def load_state_dict(self, sd):
        self.epoch = sd["epoch"]
        self._consumed_in_epoch = sd["consumed_in_epoch"]
        self.seed = sd["seed"]


class CurriculumDataLoader:
    """Config-driven curriculum loader — what the reference's engine builds in
    ``deepspeed_io`` when data_efficiency curriculum sampling is on
    (runtime/engine.py:1686 + data_sampling/data_sampler.py:36): batch indices
    come from DeepSpeedDataSampler and every batch's sequence dim is truncated
    to the scheduler's current difficulty (seqlen).

    Single-controller JAX assembles the GLOBAL macro-batch, so the sampler runs
    with dp_size=1 and micro_batch = train_batch / gas; the engine shards the
    batch over the dp mesh axes at device_put time."""

    def __init__(self, dataset, batch_size: int, gradient_accumulation_steps: int,
                 curriculum: dict, seed: int = 0, drop_last: bool = True,
                 collate_fn: Optional[Callable] = None, seq_axis: int = 1):
        from .data_pipeline.data_sampler import DeepSpeedDataSampler
        if batch_size % gradient_accumulation_steps:
            raise ValueError(f"batch_size={batch_size} not divisible by "
                             f"gas={gradient_accumulation_steps}")
        self.dataset = dataset
        self.collate_fn = collate_fn or _default_collate
        self.seq_axis = seq_axis
        self.batch_size = batch_size
        self.data_sampler = DeepSpeedDataSampler(
            total_samples=len(dataset),
            micro_batch_size=batch_size // gradient_accumulation_steps,
            data_parallel_rank=0, data_parallel_size=1,
            gradient_accumulation_steps=gradient_accumulation_steps,
            curriculum=curriculum, seed=seed, drop_last=drop_last)
        self.current_seqlen: Optional[int] = None

    def __len__(self):
        return len(self.dataset) // self.batch_size

    def _truncate(self, batch, seqlen: int):
        ax = self.seq_axis

        def trim(x):
            x = np.asarray(x)
            if x.ndim > ax and x.shape[ax] > seqlen:
                return np.take(x, np.arange(seqlen), axis=ax)
            return x

        import jax
        return jax.tree_util.tree_map(trim, batch)

    def __iter__(self) -> Iterator:
        # one EPOCH per __iter__ (the contract of the DeepSpeedDataLoader this
        # replaces — `for epoch in ...: for batch in loader:` must terminate);
        # the underlying sampler is an infinite stream, so each pass yields
        # len(self) batches and resumes where the previous epoch stopped
        it = iter(self.data_sampler)
        for _ in range(len(self)):
            # difficulty BEFORE consuming the batch, like the reference's
            # sampler (curriculum difficulty for step N applies to batch N)
            self.current_seqlen = self.data_sampler.get_seqlen()
            idx = next(it)
            batch = self.collate_fn([self.dataset[int(i)] for i in idx])
            if self.current_seqlen is not None:
                batch = self._truncate(batch, self.current_seqlen)
            yield batch

    def state_dict(self):
        return self.data_sampler.state_dict()

    def load_state_dict(self, sd):
        self.data_sampler.load_state_dict(sd)


def _default_collate(samples):
    import jax
    return jax.tree_util.tree_map(lambda *xs: np.stack([np.asarray(x) for x in xs]), *samples)
