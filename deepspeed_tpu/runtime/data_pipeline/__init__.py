"""Data-efficiency pipeline (reference runtime/data_pipeline/)."""
from .curriculum_scheduler import CurriculumScheduler
from .data_analyzer import DataAnalyzer
from .data_sampler import DeepSpeedDataSampler
from .indexed_dataset import (MMapIndexedDataset, MMapIndexedDatasetBuilder,
                              best_fitting_dtype, dataset_exists)
from .random_ltd import (RandomLTDScheduler, gather_tokens, random_ltd_layer, sample_token_indices,
                         scatter_tokens)
