"""Data-efficiency pipeline (reference runtime/data_pipeline/)."""
from .curriculum_scheduler import CurriculumScheduler
from .data_sampler import DeepSpeedDataSampler
from .random_ltd import (RandomLTDScheduler, gather_tokens, random_ltd_layer, sample_token_indices,
                         scatter_tokens)
