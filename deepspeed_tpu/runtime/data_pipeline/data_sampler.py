"""Curriculum-aware distributed data sampler.

Analog of DeepSpeedDataSampler (runtime/data_pipeline/data_sampling/
data_sampler.py:36): deterministic shuffled index stream, partitioned per dp
rank, with curriculum truncation (sequence-length difficulty) and exact resume
from a consumed-samples counter.
"""

from typing import Dict, Iterator, List, Optional

import numpy as np

from .curriculum_scheduler import CurriculumScheduler


class DeepSpeedDataSampler:

    def __init__(self, total_samples: int, micro_batch_size: int, data_parallel_rank: int = 0,
                 data_parallel_size: int = 1, gradient_accumulation_steps: int = 1,
                 curriculum: Optional[Dict] = None, seed: int = 0, drop_last: bool = True):
        self.total_samples = total_samples
        self.micro_batch_size = micro_batch_size
        self.dp_rank = data_parallel_rank
        self.dp_size = data_parallel_size
        self.gas = gradient_accumulation_steps
        self.seed = seed
        self.drop_last = drop_last
        self.consumed_samples = 0
        self.global_batch_size = micro_batch_size * data_parallel_size * gradient_accumulation_steps
        self.curriculum = CurriculumScheduler(curriculum) if curriculum else None

    @property
    def global_step(self) -> int:
        return self.consumed_samples // self.global_batch_size

    def get_seqlen(self) -> Optional[int]:
        """Current curriculum difficulty (sequence length) for batch truncation."""
        if self.curriculum is None:
            return None
        return self.curriculum.update_difficulty(self.global_step + 1)

    def _epoch_perm(self, epoch: int) -> np.ndarray:
        rng = np.random.default_rng(self.seed + epoch)
        return rng.permutation(self.total_samples)

    def __iter__(self) -> Iterator[List[int]]:
        while True:
            epoch = self.consumed_samples // self.total_samples
            offset = self.consumed_samples % self.total_samples
            perm = self._epoch_perm(epoch)
            remaining = self.total_samples - offset
            if remaining < self.global_batch_size and self.drop_last:
                self.consumed_samples += remaining  # skip tail, next epoch
                continue
            batch = perm[offset:offset + self.global_batch_size]
            self.consumed_samples += len(batch)
            # rank slice: contiguous per-rank chunk of each micro batch
            my = []
            for g in range(self.gas):
                micro = batch[g * self.micro_batch_size * self.dp_size:(g + 1) * self.micro_batch_size * self.dp_size]
                my.extend(micro[self.dp_rank * self.micro_batch_size:(self.dp_rank + 1) * self.micro_batch_size])
            yield [int(i) for i in my]

    def state_dict(self) -> Dict:
        return {
            "consumed_samples": self.consumed_samples,
            "seed": self.seed,
            "curriculum": self.curriculum.state_dict() if self.curriculum else None,
        }

    def load_state_dict(self, sd: Dict):
        self.consumed_samples = sd["consumed_samples"]
        self.seed = sd.get("seed", self.seed)
        if self.curriculum and sd.get("curriculum"):
            self.curriculum.load_state_dict(sd["curriculum"])
