"""Memory-mapped indexed dataset (Megatron ``MMIDIDX`` binary format).

Torch-free re-implementation of the reference's mmap dataset
(runtime/data_pipeline/data_sampling/indexed_dataset.py:369
``MMapIndexedDataset`` + its Index writer and ``MMapIndexedDatasetBuilder``).
The ON-DISK FORMAT is kept byte-compatible — ``<prefix>.idx``::

    9B magic "MMIDIDX\\x00\\x00" | u64 version=1 | u8 dtype-code
    | u64 num_sequences | u64 num_docs
    | int32[num_sequences] sizes | int64[num_sequences] byte pointers
    | int64[num_docs] doc offsets

with token data flat in ``<prefix>.bin`` — so corpora tokenized by
Megatron/DeepSpeed tooling load directly, and datasets built here load there.
Reads are zero-copy ``np.memmap`` views; there is no torch Dataset base —
``__getitem__``/``__len__`` duck-type for any loader, including
runtime/dataloader.py.
"""

import os
import struct
from typing import List, Optional, Sequence, Union

import numpy as np

_MAGIC = b"MMIDIDX\x00\x00"
_VERSION = 1

# dtype codes shared with the reference format (indexed_dataset.py:101)
DTYPES = {
    1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32, 5: np.int64,
    6: np.float64, 7: np.double, 8: np.uint16, 9: np.uint32, 10: np.uint64,  # dslint: disable=float64-in-compute  # on-disk dtype-code table (reference .bin format); batches cast to the compute dtype at load
}
_CODES = {np.dtype(v): k for k, v in DTYPES.items()}


def best_fitting_dtype(vocab_size: Optional[int] = None):
    """uint16 token storage for small vocabs (halves corpus bytes)."""
    if vocab_size is not None and vocab_size < 65500:
        return np.uint16
    return np.int32


def index_file_path(prefix: str) -> str:
    return prefix + ".idx"


def data_file_path(prefix: str) -> str:
    return prefix + ".bin"


def dataset_exists(prefix: str) -> bool:
    return os.path.exists(index_file_path(prefix)) and os.path.exists(data_file_path(prefix))


class MMapIndexedDataset:
    """Zero-copy reader over a (prefix.idx, prefix.bin) pair."""

    def __init__(self, prefix: str):
        self._prefix = prefix
        with open(index_file_path(prefix), "rb") as fh:
            magic = fh.read(9)
            if magic != _MAGIC:
                raise ValueError(f"{prefix}.idx is not an MMIDIDX index (bad magic)")
            (version,) = struct.unpack("<Q", fh.read(8))
            if version != _VERSION:
                raise ValueError(f"unsupported MMIDIDX version {version}")
            (code,) = struct.unpack("<B", fh.read(1))
            self._dtype = np.dtype(DTYPES[code])
            (self._len,) = struct.unpack("<Q", fh.read(8))
            (ndocs,) = struct.unpack("<Q", fh.read(8))
            offset = fh.tell()
        idx_map = np.memmap(index_file_path(prefix), mode="r")
        self._sizes = np.frombuffer(idx_map, np.int32, count=self._len, offset=offset)
        self._pointers = np.frombuffer(idx_map, np.int64, count=self._len,
                                       offset=offset + self._sizes.nbytes)
        self._doc_idx = np.frombuffer(idx_map, np.int64, count=ndocs,
                                      offset=offset + self._sizes.nbytes + self._pointers.nbytes)
        # np.memmap refuses zero-byte files; an empty dataset is still valid
        # (e.g. an idle DataAnalyzer worker's partial shard)
        if os.path.getsize(data_file_path(prefix)) == 0:
            self._data = np.zeros(0, np.uint8)
        else:
            self._data = np.memmap(data_file_path(prefix), mode="r")

    # ------------------------------------------------------------ reading
    def __len__(self) -> int:
        return self._len

    def __getitem__(self, idx: Union[int, slice]):
        if isinstance(idx, slice):
            return [self[i] for i in range(*idx.indices(self._len))]
        if idx < 0:
            idx += self._len
        if not 0 <= idx < self._len:
            raise IndexError(f"sample {idx} out of range [0, {self._len})")
        ptr, size = int(self._pointers[idx]), int(self._sizes[idx])
        return np.frombuffer(self._data, self._dtype, count=size, offset=ptr)

    def get(self, idx: int, offset: int = 0, length: Optional[int] = None) -> np.ndarray:
        """Sub-sequence read without materializing the whole sample."""
        ptr, size = int(self._pointers[idx]), int(self._sizes[idx])
        if length is None:
            length = size - offset
        if offset < 0 or length < 0 or offset + length > size:
            raise IndexError(f"window [{offset}, {offset + length}) outside sample of size {size}")
        return np.frombuffer(self._data, self._dtype, count=length,
                             offset=ptr + offset * self._dtype.itemsize)

    @property
    def sizes(self) -> np.ndarray:
        return self._sizes

    @property
    def doc_idx(self) -> np.ndarray:
        return self._doc_idx

    @property
    def dtype(self):
        return self._dtype

    def num_tokens(self, idx: int) -> int:
        return int(self._sizes[idx])


class MMapIndexedDatasetBuilder:
    """Streaming writer producing the same (idx, bin) pair."""

    def __init__(self, out_prefix_or_bin: str, dtype=np.int32):
        bin_path = (out_prefix_or_bin if out_prefix_or_bin.endswith(".bin")
                    else data_file_path(out_prefix_or_bin))
        self._bin_path = bin_path
        self._file = open(bin_path, "wb")
        self._dtype = np.dtype(dtype)
        if self._dtype not in _CODES:
            raise ValueError(f"unsupported dtype {dtype}")
        self._sizes: List[int] = []
        self._doc_idx: List[int] = [0]

    def add_item(self, tokens) -> None:
        arr = np.asarray(tokens, dtype=self._dtype)
        self._file.write(arr.tobytes(order="C"))
        self._sizes.append(arr.size)

    def end_document(self) -> None:
        self._doc_idx.append(len(self._sizes))

    def merge_file_(self, other_prefix: str) -> None:
        """Append another dataset built with the same dtype (reference
        merge_file_:293 — multi-worker corpus shards concatenated)."""
        other = MMapIndexedDataset(other_prefix)
        if other.dtype != self._dtype:
            raise ValueError(f"dtype mismatch: {other.dtype} vs {self._dtype}")
        base_docs = len(self._sizes)
        self._sizes.extend(int(s) for s in other.sizes)
        self._doc_idx.extend(base_docs + int(d) for d in other.doc_idx[1:])
        with open(data_file_path(other_prefix), "rb") as fh:
            while True:
                chunk = fh.read(1 << 24)
                if not chunk:
                    break
                self._file.write(chunk)

    def finalize(self, index_path: Optional[str] = None) -> None:
        self._file.close()
        if index_path is None:
            index_path = self._bin_path[:-4] + ".idx"
        sizes = np.asarray(self._sizes, np.int32)
        pointers = np.zeros(len(sizes), np.int64)
        if len(sizes) > 1:
            # int64 accumulate — int32 sizes * itemsize overflows past 2 GiB
            np.cumsum(sizes[:-1].astype(np.int64) * self._dtype.itemsize, out=pointers[1:])
        with open(index_path, "wb") as fh:
            fh.write(_MAGIC)
            fh.write(struct.pack("<Q", _VERSION))
            fh.write(struct.pack("<B", _CODES[self._dtype]))
            fh.write(struct.pack("<Q", len(sizes)))
            fh.write(struct.pack("<Q", len(self._doc_idx)))
            fh.write(sizes.tobytes(order="C"))
            fh.write(pointers.tobytes(order="C"))
            fh.write(np.asarray(self._doc_idx, np.int64).tobytes(order="C"))
