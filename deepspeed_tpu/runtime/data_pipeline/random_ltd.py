"""Random layerwise token dropping (random-LTD).

Analog of the reference random-LTD (runtime/data_pipeline/data_routing/
basic_layer.py + scheduler.py:38, csrc/random_ltd token_sort/gather kernels):
middle layers process a random SUBSET of tokens; dropped tokens bypass the
layer and are scattered back, cutting attention cost quadratically while the
kept-token budget ramps up on a schedule.  The CUDA token_sort/gather kernels
become jnp.take/scatter (XLA fuses the gathers).
"""

from typing import Dict, Tuple

import jax
import jax.numpy as jnp


class RandomLTDScheduler:
    """Token-budget ramp (reference scheduler.py:38): linear increase of kept
    tokens from min_value to max_value over schedule steps."""

    def __init__(self, config: Dict):
        ltd = config.get("random_ltd", config)
        self.min_tokens = int(ltd.get("random_ltd_schedule", {}).get("min_value", ltd.get("min_value", 128)))
        self.max_tokens = int(ltd.get("random_ltd_schedule", {}).get("max_value", ltd.get("max_value", 512)))
        sched = ltd.get("random_ltd_schedule", ltd)
        self.step_size = int(sched.get("schedule_config", sched).get("seq_per_step", 16))
        self.total_steps = int(sched.get("schedule_config", sched).get("require_steps", 1000))
        self.current_tokens = self.min_tokens

    def update_seq(self, global_step: int) -> int:
        frac = min(1.0, global_step / max(self.total_steps, 1))
        tokens = self.min_tokens + frac * (self.max_tokens - self.min_tokens)
        tokens = int(tokens // self.step_size * self.step_size)
        self.current_tokens = max(self.min_tokens, min(self.max_tokens, tokens))
        return self.current_tokens

    def state_dict(self):
        return {"current_tokens": self.current_tokens}

    def load_state_dict(self, sd):
        self.current_tokens = sd.get("current_tokens", self.min_tokens)


def sample_token_indices(rng, seq_len: int, keep: int) -> jnp.ndarray:
    """Sorted random subset of token positions (token_sort.cu analog)."""
    keep = min(keep, seq_len)
    perm = jax.random.permutation(rng, seq_len)
    return jnp.sort(perm[:keep])


def gather_tokens(x: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """x [B, S, D] -> kept tokens [B, K, D] (gather_scatter.cu analog)."""
    return jnp.take(x, idx, axis=1)


def scatter_tokens(full: jnp.ndarray, kept: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """Write processed kept tokens back into the full sequence."""
    return full.at[:, idx].set(kept)


def random_ltd_layer(layer_fn, x: jnp.ndarray, rng, keep: int) -> jnp.ndarray:
    """Apply ``layer_fn`` to a random token subset; dropped tokens skip the
    layer (residual identity), mirroring basic_layer.py forward."""
    idx = sample_token_indices(rng, x.shape[1], keep)
    kept = gather_tokens(x, idx)
    processed = layer_fn(kept)
    return scatter_tokens(x, processed, idx)
