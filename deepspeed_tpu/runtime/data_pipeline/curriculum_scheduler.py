"""Curriculum learning scheduler.

Analog of the reference CurriculumScheduler
(runtime/data_pipeline/data_sampling/curriculum_scheduler.py:11): maps the
global step to a difficulty value (e.g. sequence length) under
fixed_linear / fixed_root / fixed_discrete / custom schedules, with the same
config keys (schedule_type, min/max difficulty, total_curriculum_step,
difficulty_step rounding, root_degree).
"""

import math
from typing import Callable, Dict, Optional


FIXED_LINEAR = "fixed_linear"
FIXED_ROOT = "fixed_root"
FIXED_DISCRETE = "fixed_discrete"
CUSTOM = "custom"


class CurriculumScheduler:

    def __init__(self, config: Dict):
        self.state: Dict = {}
        assert "curriculum_type" in config or "schedule_type" in config, \
            "curriculum config needs schedule_type"
        self.schedule_type = config.get("schedule_type", config.get("curriculum_type"))
        self.min_difficulty = config.get("min_difficulty", 1)
        self.max_difficulty = config.get("max_difficulty", 1)
        cfg = config.get("schedule_config", config)
        self.total_step = cfg.get("total_curriculum_step", 1)
        self.difficulty_step = cfg.get("difficulty_step", 1)
        self.root_degree = cfg.get("root_degree", 2)
        self.difficulties = cfg.get("difficulty", [])
        self.max_steps = cfg.get("max_step", [])
        self._custom: Optional[Callable[[int], int]] = None
        self.current_difficulty = self.min_difficulty

    def set_custom_get_difficulty(self, fn: Callable[[int], int]):
        self._custom = fn

    def get_difficulty(self, global_step: int) -> int:
        if self.schedule_type == CUSTOM:
            assert self._custom is not None, "set_custom_get_difficulty first"
            return self._custom(global_step)
        if self.schedule_type == FIXED_DISCRETE:
            for difficulty, until in zip(self.difficulties, self.max_steps):
                if global_step <= until:
                    return difficulty
            return self.difficulties[-1]
        if self.schedule_type == FIXED_LINEAR:
            frac = min(1.0, global_step / max(self.total_step, 1))
        elif self.schedule_type == FIXED_ROOT:
            frac = min(1.0, (global_step / max(self.total_step, 1))**(1.0 / self.root_degree))
        else:
            raise ValueError(f"unknown curriculum schedule '{self.schedule_type}'")
        diff = self.min_difficulty + frac * (self.max_difficulty - self.min_difficulty)
        diff = int(diff // self.difficulty_step * self.difficulty_step)
        return max(self.min_difficulty, min(self.max_difficulty, diff))

    def update_difficulty(self, global_step: int) -> int:
        self.current_difficulty = self.get_difficulty(global_step)
        return self.current_difficulty

    def state_dict(self) -> Dict:
        return {"current_difficulty": self.current_difficulty}

    def load_state_dict(self, sd: Dict):
        self.current_difficulty = sd.get("current_difficulty", self.min_difficulty)
