"""Offline data analysis for curriculum learning.

Analog of the reference ``DataAnalyzer`` (runtime/data_pipeline/data_sampling/
data_analyzer.py:20): a map/reduce over the corpus that computes per-sample
difficulty metrics (e.g. sequence length, vocabulary rarity) and writes the
index files the curriculum sampler consumes:

* ``<metric>_sample_to_metric`` — metric value per global sample index
  (an MMapIndexedDataset, one scalar per sample);
* ``<metric>_metric_to_sample`` — for each distinct metric value, the sample
  indices holding it (dict in an ``.npz``), enabling difficulty-bucketed
  sampling;
* ``<metric>_sum`` for ``accumulate_value_over_samples`` metrics (corpus-wide
  reductions such as total tokens).

``run_map`` shards the dataset over (num_workers, worker_id) so analysis
parallelizes across hosts exactly like the reference; ``run_reduce`` merges
the per-worker partials.  No torch/mpi — partials are files, the reduce is a
second invocation, matching the reference's file-based merge
(data_analyzer.py:260 ``merge_map_results``).
"""

import json
import os
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ...utils.logging import logger
from .indexed_dataset import MMapIndexedDataset, MMapIndexedDatasetBuilder

SINGLE_VALUE = "single_value_per_sample"
ACCUMULATE = "accumulate_value_over_samples"


class DataAnalyzer:

    def __init__(self, dataset, metric_names: Sequence[str],
                 metric_functions: Sequence[Callable], metric_types: Sequence[str],
                 save_path: str, num_workers: int = 1, worker_id: int = 0,
                 batch_size: int = 1024):
        if not (len(metric_names) == len(metric_functions) == len(metric_types)):
            raise ValueError("metric_names/functions/types must align")
        for t in metric_types:
            if t not in (SINGLE_VALUE, ACCUMULATE):
                raise ValueError(f"unknown metric type {t!r}")
        self.dataset = dataset
        self.metric_names = list(metric_names)
        self.metric_functions = list(metric_functions)
        self.metric_types = list(metric_types)
        self.save_path = save_path
        self.num_workers = num_workers
        self.worker_id = worker_id
        self.batch_size = batch_size
        os.makedirs(save_path, exist_ok=True)

    # ----------------------------------------------------------------- map
    def _worker_range(self):
        n = len(self.dataset)
        per = -(-n // self.num_workers)
        lo = self.worker_id * per
        return lo, min(lo + per, n)

    def _partial_prefix(self, name: str, worker: int) -> str:
        return os.path.join(self.save_path, f"{name}.worker{worker}")

    def run_map(self) -> None:
        """Compute this worker's shard of every metric and persist partials."""
        lo, hi = self._worker_range()
        logger.info(f"DataAnalyzer map: worker {self.worker_id}/{self.num_workers} "
                    f"samples [{lo}, {hi})")
        singles: Dict[str, List[float]] = {n: [] for n, t in
                                           zip(self.metric_names, self.metric_types)
                                           if t == SINGLE_VALUE}
        sums: Dict[str, float] = {n: 0.0 for n, t in
                                  zip(self.metric_names, self.metric_types)
                                  if t == ACCUMULATE}
        for i in range(lo, hi):
            sample = self.dataset[i]
            for name, fn, mtype in zip(self.metric_names, self.metric_functions,
                                       self.metric_types):
                val = fn(sample)
                if mtype == SINGLE_VALUE:
                    fv = float(val)
                    if fv != int(fv):
                        # match the reference's guard (data_analyzer.py asserts
                        # float metrics unsupported) — silent int() truncation
                        # would collapse fractional difficulties into one bucket
                        raise ValueError(
                            f"metric {name!r} produced non-integral value {fv}; "
                            f"single_value_per_sample metrics must be integers "
                            f"(quantize the metric, e.g. round(100*x))")
                    singles[name].append(fv)
                else:
                    sums[name] += float(val)
        for name, vals in singles.items():
            b = MMapIndexedDatasetBuilder(self._partial_prefix(name, self.worker_id),
                                          dtype=np.int64)
            for v in vals:
                b.add_item([int(v)])
            b.end_document()
            b.finalize()
        meta = {"range": [lo, hi], "sums": sums}
        with open(os.path.join(self.save_path,
                               f"meta.worker{self.worker_id}.json"), "w") as fh:
            json.dump(meta, fh)

    # -------------------------------------------------------------- reduce
    def _out_prefix(self, name: str, kind: str) -> str:
        return os.path.join(self.save_path, f"{name}_{kind}")

    def run_reduce(self) -> None:
        """Merge all workers' partials into the final index files."""
        metas = []
        for w in range(self.num_workers):
            with open(os.path.join(self.save_path, f"meta.worker{w}.json")) as fh:
                metas.append(json.load(fh))
        for name, mtype in zip(self.metric_names, self.metric_types):
            if mtype == ACCUMULATE:
                total = sum(m["sums"][name] for m in metas)
                with open(self._out_prefix(name, "sum") + ".json", "w") as fh:
                    json.dump({"sum": total}, fh)
                continue
            # chunked byte-level merge (merge_file_), not per-sample python
            out_prefix = self._out_prefix(name, "sample_to_metric")
            builder = MMapIndexedDatasetBuilder(out_prefix, dtype=np.int64)
            for w in range(self.num_workers):
                builder.merge_file_(self._partial_prefix(name, w))
            builder.finalize()
            merged = MMapIndexedDataset(out_prefix)
            # every sample is one scalar -> the .bin IS the flat value array
            flat = np.frombuffer(merged._data, np.int64, count=len(merged))
            # vectorized inverse index: one stable argsort, split at value runs
            order = np.argsort(flat, kind="stable")
            vals, starts = np.unique(flat[order], return_index=True)
            bounds = np.append(starts, len(order))
            np.savez(self._out_prefix(name, "metric_to_sample") + ".npz",
                     **{str(int(v)): order[bounds[i]:bounds[i + 1]].astype(np.int64)
                        for i, v in enumerate(vals)})
        logger.info(f"DataAnalyzer reduce: wrote index files to {self.save_path}")

    # ------------------------------------------------------------- loading
    @staticmethod
    def load_sample_to_metric(save_path: str, metric_name: str) -> np.ndarray:
        ds = MMapIndexedDataset(os.path.join(save_path, f"{metric_name}_sample_to_metric"))
        # one scalar per sample: the data buffer is the value array
        return np.frombuffer(ds._data, np.int64, count=len(ds)).copy()

    @staticmethod
    def load_metric_to_sample(save_path: str, metric_name: str) -> Dict[int, np.ndarray]:
        z = np.load(os.path.join(save_path, f"{metric_name}_metric_to_sample.npz"))
        return {int(k): z[k] for k in z.files}

    @staticmethod
    def get_metric_percentiles(save_path: str, metric_name: str,
                               percentiles: Sequence[float]) -> Dict[float, float]:
        """Difficulty thresholds for curriculum schedules (reference
        get_metric_value_percentiles:199)."""
        vals = DataAnalyzer.load_sample_to_metric(save_path, metric_name)
        return {p: float(np.percentile(vals, p)) for p in percentiles}
