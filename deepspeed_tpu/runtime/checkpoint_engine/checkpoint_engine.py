"""Checkpoint engine plug-ins.

Analog of the reference's checkpoint-engine abstraction
(runtime/checkpoint_engine/checkpoint_engine.py — CheckpointEngine ABC,
TorchCheckpointEngine, async NebulaCheckpointEngine:20): an engine owns how
leaf arrays get persisted.  The native engine writes .npy files; the async
engine stages host copies and writes on a background thread so the train loop
isn't blocked on disk (the Nebula tier-1 behavior).

Save protocol contract (runtime/checkpointing.save_checkpoint_dir): leaves are
written via ``save()``/streaming, then ``flush()`` must make every pending
write visible (async engines drain their queue here), then — after the staging
dir has been atomically renamed to its final tag — ``commit(tag)`` marks the
tag durable.  ``commit`` therefore always sees a complete, manifest-bearing
checkpoint directory.
"""

import os
import queue
import threading
from typing import Optional

import numpy as np

from ...utils.logging import log_dist, logger


class CheckpointEngine:
    """Persistence strategy for checkpoint leaves."""

    # file-backed engines persisting plain .npy at the target path can accept
    # shard-streamed writes (checkpointing._write_leaf_streaming fills the
    # file via memmap, synchronously) — plug-in engines with their own storage
    # keep this False and receive gathered arrays through save()
    supports_streaming_save = False

    def makedirs(self, path: str):
        os.makedirs(path, exist_ok=True)

    def save(self, arr: np.ndarray, path: str) -> None:
        raise NotImplementedError

    def load(self, path: str) -> np.ndarray:
        raise NotImplementedError

    def flush(self) -> None:
        """Make every ``save()`` issued so far visible on disk (barrier before
        the manifest is written and the staging dir renamed).  Synchronous
        engines are already flushed; async engines drain their queue."""

    def commit(self, tag: str) -> bool:
        """Mark ``tag`` durable; called after the checkpoint dir is complete
        (leaves + metadata.json in final position).  Returns True when durable."""
        return True


class NativeCheckpointEngine(CheckpointEngine):
    """Synchronous .npy writer (TorchCheckpointEngine analog)."""

    supports_streaming_save = True

    def save(self, arr: np.ndarray, path: str) -> None:
        np.save(path, arr)

    def load(self, path: str) -> np.ndarray:
        return np.load(path)


class AsyncCheckpointEngine(CheckpointEngine):
    """Background-thread writer (NebulaCheckpointEngine analog): save() enqueues
    an already-host-resident array and returns immediately; flush()/commit()
    drain the queue.  One writer thread preserves write order."""

    supports_streaming_save = True  # same .npy-at-path layout; the streamed
    # write is synchronous, trading this leaf's async for the memory bound

    def __init__(self, max_queue: int = 64):
        self._queue: "queue.Queue" = queue.Queue(maxsize=max_queue)
        # _error crosses the worker/caller boundary: written by the worker on
        # a failed write, swapped out by _raise_pending() on the caller side.
        # Both sides hold _error_lock — an unlocked version loses the error
        # when the swap interleaves with a concurrent worker store.
        self._error_lock = threading.Lock()
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def _worker(self):
        while True:
            item = self._queue.get()
            if item is None:
                return
            arr, path = item
            try:
                np.save(path, arr)
            except BaseException as exc:  # surfaced at flush()/commit()
                with self._error_lock:
                    self._error = exc
            finally:
                self._queue.task_done()

    def _raise_pending(self):
        """Re-raise the writer thread's failure with its ORIGINAL type (an
        OSError from a flaky mount stays an OSError, so the checkpoint retry
        loop can recognize it as transient) and clear it so a retried save
        starts clean."""
        with self._error_lock:
            exc, self._error = self._error, None
        if exc is not None:
            raise exc

    def save(self, arr: np.ndarray, path: str) -> None:
        self._raise_pending()
        self._queue.put((np.asarray(arr), path))

    def load(self, path: str) -> np.ndarray:
        return np.load(path)

    def flush(self) -> None:
        self._queue.join()
        self._raise_pending()

    def commit(self, tag: str) -> bool:
        self.flush()
        return True

    def close(self):
        self._queue.join()
        self._queue.put(None)
        self._thread.join()


def build_checkpoint_engine(kind: str = "native", max_queue: int = 64) -> CheckpointEngine:
    if kind in ("native", "torch"):
        return NativeCheckpointEngine()
    if kind in ("async", "nebula"):
        return AsyncCheckpointEngine(max_queue=max_queue)
    raise ValueError(f"unknown checkpoint engine '{kind}' (native|async)")
