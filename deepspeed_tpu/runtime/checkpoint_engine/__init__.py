"""Checkpoint engine plug-ins (reference runtime/checkpoint_engine/)."""
from .checkpoint_engine import (AsyncCheckpointEngine, CheckpointEngine, NativeCheckpointEngine,
                                build_checkpoint_engine)
