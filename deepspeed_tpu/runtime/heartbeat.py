"""Per-rank heartbeat seam for elastic fault tolerance.

The dominant distributed failure mode is not a worker that *exits* — it is a
worker that *stops* (stuck in a collective while every peer waits, wedged on a
flaky storage mount, spinning in a data-loader).  A polling supervisor that
only watches exit codes deadlocks with the job.  The fix is a liveness
channel the supervisor can read without touching the workers: each rank
stamps ``step + wall-clock + last-entered-collective`` to a tiny per-rank
file, and the elastic agent (elasticity/elastic_agent.py) treats a stale
stamp as a failure — kill, diagnose, restart.

Design constraints (the reason this is its own module):

- **Zero device syncs.**  A stamp writes only values the host already owns:
  the engine's python-int step counter, ``time.time()``, and the collective
  name a wrapper pushed before blocking.  Nothing here may call ``float()``
  on a device value, ``.item()``, ``np.asarray``, ``jax.device_get`` or
  ``block_until_ready``.  dslint's host-sync rule scans this WHOLE file
  (tools/staticcheck/rules.py HEARTBEAT_PATH_FRAGMENT) for the explicit
  fetch forms — ``.item``/``np.asarray``/``np.array``/``device_get``/
  ``block_until_ready`` — so sneaking one in is a lint error, not a silent
  per-step stall.  ``float()`` on a device value is NOT statically separable
  from the host config parsing this module legitimately does, so that half
  of the contract rides on review, not the linter.
- **Crash-consistent.**  Stamps are written tmp-then-``os.replace`` so the
  agent never reads a torn file; a reader treats unparseable/missing files
  as "no heartbeat yet", never as an exception.
- **Throttled.**  ``stamp()`` is called from the train hot loop; it early-outs
  on a monotonic-clock interval check (two float compares) unless forced, so
  the file write amortizes to ~1/interval regardless of step rate.

Activation is either config (``fault_tolerance.heartbeat`` section) or
environment — the elastic agent exports ``DSTPU_HEARTBEAT_DIR`` (+ ``RANK``)
to its workers, and the engine arms a writer automatically, so supervision
needs no config plumbing through user training scripts.

Reader-side helpers (used by the agent, host-only):
``read_heartbeats`` / ``stale_ranks`` / ``straggler_ranks`` /
``format_hang_report`` — the last renders the cross-rank snapshot that turns
"the job hung" into "ranks 1,3 sat in all_reduce at step 41 while rank 2
never entered it" (the mismatched-collective deadlock diagnosis).
"""

import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence

from ..utils.env import env_float
from ..utils.logging import logger, warning_once

HEARTBEAT_DIR_ENV = "DSTPU_HEARTBEAT_DIR"
HEARTBEAT_INTERVAL_ENV = "DSTPU_HEARTBEAT_INTERVAL_S"
# the rest of the agent->worker env contract lives here too (comm and the
# elasticity package both import it from runtime, never the reverse):
# the consensus resume tag the agent pins for each restarted generation
# (engine.load_checkpoint honors it when no explicit tag is passed), the
# collective wall-clock bound, and the process-group setup retry knobs
RESUME_TAG_ENV = "DSTPU_RESUME_TAG"
RESUME_DIR_ENV = "DSTPU_RESUME_DIR"
COLLECTIVE_TIMEOUT_ENV = "DSTPU_COLLECTIVE_TIMEOUT_S"
INIT_RETRIES_ENV = "DSTPU_INIT_RETRIES"
INIT_RETRY_BACKOFF_ENV = "DSTPU_INIT_RETRY_BACKOFF_S"
# ServingSupervisor -> serving-worker contract (inference/v2/supervisor.py):
# the durable request-journal path, the generation ordinal of the current
# restart, and the drain-only flag the supervisor raises once the restart
# budget is exhausted (workers shed new admissions and only finish journaled
# work).  Same placement rationale as the training contract above.
SERVING_JOURNAL_ENV = "DSTPU_SERVING_JOURNAL"
SERVING_FSYNC_ENV = "DSTPU_SERVING_FSYNC_EVERY"
SERVING_GENERATION_ENV = "DSTPU_SERVING_GENERATION"
SERVING_DRAIN_ENV = "DSTPU_SERVING_DRAIN"
# ops-plane exchange dir (monitor/ops_server.py): the elastic agent and the
# ServingSupervisor export it so every supervised worker publishes per-rank
# metrics snapshots/textfiles the supervisor merges into one fleet endpoint
# (env wins over the ops_server.textfile_dir config, same as the rest of the
# contract above)
OPS_DIR_ENV = "DSTPU_OPS_DIR"
_FILE_PREFIX = "hb.rank"


def heartbeat_path(directory: str, rank: int) -> str:
    return os.path.join(directory, f"{_FILE_PREFIX}{int(rank)}.json")


class HeartbeatWriter:
    """Stamps this rank's liveness to ``<dir>/hb.rank<R>.json``.

    All values host-native (see module docstring); writes are atomic
    (tmp + ``os.replace``) and throttled to one per ``interval_s`` unless
    ``force=True`` (collective entry/exit and terminal stamps force).  A
    failed write keeps the throttle cadence (a broken dir must not turn
    every hot-loop stamp into a fresh syscall + exception), and after
    ``MAX_WRITE_FAILURES`` consecutive failures the writer disables itself —
    degrade supervision, never training.
    """

    MAX_WRITE_FAILURES = 10

    def __init__(self, directory: str, rank: int, *, interval_s: float = 1.0,
                 generation: int = 0, clock=time.time, monotonic=time.monotonic):
        self.directory = directory
        self.rank = int(rank)
        self.interval_s = max(float(interval_s), 0.0)
        self.generation = int(generation)
        self.enabled = True
        self._clock = clock
        self._monotonic = monotonic
        self._path = heartbeat_path(directory, rank)
        self._tmp = self._path + ".tmp"
        self._last_stamp_mono = -float("inf")
        self._write_failures = 0
        self._last_step = 0
        self._collective: Optional[str] = None
        self._collective_t: Optional[float] = None
        self.stamps_written = 0
        try:
            os.makedirs(directory, exist_ok=True)
        except OSError as exc:
            # a broken heartbeat dir must degrade supervision, never training
            warning_once(f"heartbeat: cannot create {directory!r} ({exc}); "
                         f"liveness stamps disabled for rank {rank}")
            self.enabled = False

    # ------------------------------------------------------------------ stamps
    def stamp(self, step: int, *, phase: Optional[str] = None, force: bool = False) -> bool:
        """Record liveness at host step ``step``.  Returns True when a file
        write actually happened (throttle/disable make it False)."""
        if not self.enabled:
            return False
        now_mono = self._monotonic()
        self._last_step = int(step)
        if not force and (now_mono - self._last_stamp_mono) < self.interval_s:
            return False
        record = {
            "rank": self.rank,
            "pid": os.getpid(),
            "step": int(step),
            "time": self._clock(),
            "generation": self.generation,
            "collective": self._collective,
            "collective_t": self._collective_t,
        }
        if phase is not None:
            record["phase"] = phase
        try:
            with open(self._tmp, "w") as fh:
                fh.write(json.dumps(record))
            os.replace(self._tmp, self._path)
        except OSError as exc:
            self._last_stamp_mono = now_mono  # keep the throttle cadence
            self._write_failures += 1
            if self._write_failures >= self.MAX_WRITE_FAILURES:
                self.enabled = False
                warning_once(f"heartbeat: {self._write_failures} consecutive "
                             f"stamp failures to {self._path!r} (last: {exc}); "
                             f"liveness stamps disabled for rank {self.rank} — "
                             f"the agent will see this rank as stale")
            else:
                warning_once(f"heartbeat: stamp to {self._path!r} failed ({exc}); "
                             f"the agent may see this rank as stale")
            return False
        self._last_stamp_mono = now_mono
        self._write_failures = 0
        self.stamps_written += 1
        return True

    # ------------------------------------------------------------ collectives
    def enter_collective(self, name: str) -> None:
        """Stamp 'about to block in ``name``' — called by comm wrappers BEFORE
        the blocking wait, so a hang inside the collective leaves its name on
        disk for the agent's cross-rank dump."""
        self._collective = str(name)
        self._collective_t = self._clock()
        self.stamp(self._last_step, force=True)

    def exit_collective(self) -> None:
        self._collective = None
        self._collective_t = None
        self.stamp(self._last_step, force=True)

    def close(self) -> None:
        """Terminal stamp (clean shutdown) then stop writing."""
        if self.enabled:
            self.stamp(self._last_step, phase="closed", force=True)
        self.enabled = False


class _NullHeartbeat:
    """Disabled writer: every call a cheap no-op so call sites never branch."""
    enabled = False
    rank = -1
    stamps_written = 0

    def stamp(self, step, phase=None, force=False):
        return False

    def enter_collective(self, name):
        return None

    def exit_collective(self):
        return None

    def close(self):
        return None


NULL_HEARTBEAT = _NullHeartbeat()

# process-global writer so the comm layer can stamp collective entry/exit
# without threading a handle through every call site (mirrors the comms
# logger's module-global pattern in utils/comms_logging.py)
_WRITER: Any = NULL_HEARTBEAT


def get_heartbeat():
    return _WRITER


def set_heartbeat(writer) -> None:
    global _WRITER
    _WRITER = writer if writer is not None else NULL_HEARTBEAT


def build_heartbeat(ft_config=None, *, rank: Optional[int] = None,
                    register_global: bool = True):
    """Resolve a writer from the ``fault_tolerance`` config section and/or the
    agent-exported environment.  Env wins on the *directory* (the agent owns
    placement); config wins on the interval unless the env pins one.  Returns
    the NULL writer when neither enables heartbeats."""
    env_dir = os.environ.get(HEARTBEAT_DIR_ENV)
    cfg_enabled = bool(ft_config is not None and ft_config.heartbeat)
    directory = env_dir or (ft_config.heartbeat_dir if cfg_enabled and ft_config.heartbeat_dir else None)
    if directory is None:
        if register_global:
            # one engine's writer must not leak into the next: a later
            # heartbeat-less engine would otherwise keep stamping comm
            # collectives into the previous engine's (possibly swept) dir
            set_heartbeat(NULL_HEARTBEAT)
        return NULL_HEARTBEAT
    interval = float(ft_config.heartbeat_interval_s) if ft_config is not None else 1.0
    interval = env_float(HEARTBEAT_INTERVAL_ENV, interval)
    if rank is None:
        rank = int(os.environ.get("RANK", "0") or 0)
    generation = int(os.environ.get("DSTPU_ELASTIC_RESTART", "0") or 0)
    writer = HeartbeatWriter(directory, rank, interval_s=interval, generation=generation)
    if register_global:
        set_heartbeat(writer)
    logger.info(f"heartbeat: rank {rank} stamping to {directory} "
                f"every {interval}s (generation {generation})")
    return writer


# ==========================================================================
# Reader side (agent / supervisor — host-only, tolerant of torn state)
# ==========================================================================

def read_heartbeats(directory: str) -> Dict[int, Dict[str, Any]]:
    """All parseable per-rank heartbeat records under ``directory``.  Missing
    dir, missing files, and half-written JSON all read as 'absent' — the
    agent distinguishes 'never stamped' from 'stale' itself."""
    out: Dict[int, Dict[str, Any]] = {}
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for name in names:
        if not (name.startswith(_FILE_PREFIX) and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(directory, name)) as fh:
                record = json.load(fh)
            rank = int(record["rank"])
        except (OSError, ValueError, KeyError, TypeError):
            continue  # torn write or foreign file: absent this poll, not fatal
        out[rank] = record
    return out


def heartbeat_age(record: Dict[str, Any], now: Optional[float] = None) -> float:
    now = time.time() if now is None else now
    return max(now - float(record.get("time", 0.0)), 0.0)


def stale_ranks(heartbeats: Dict[int, Dict[str, Any]], ranks: Sequence[int],
                timeout_s: float, now: Optional[float] = None) -> List[int]:
    """Ranks whose newest stamp is older than ``timeout_s`` (or that never
    stamped at all) — the liveness failure set."""
    now = time.time() if now is None else now
    out = []
    for rank in ranks:
        record = heartbeats.get(rank)
        if record is None or heartbeat_age(record, now) > timeout_s:
            out.append(rank)
    return sorted(out)


def straggler_ranks(heartbeats: Dict[int, Dict[str, Any]],
                    lag_steps: int) -> List[int]:
    """Ranks whose stamped step trails the group median by more than
    ``lag_steps`` — alive but slow (flagged, not killed)."""
    steps = sorted(int(r.get("step", 0)) for r in heartbeats.values())
    if len(steps) < 2:
        return []
    median = steps[len(steps) // 2]
    return sorted(rank for rank, r in heartbeats.items()
                  if median - int(r.get("step", 0)) > lag_steps)


def format_hang_report(heartbeats: Dict[int, Dict[str, Any]], ranks: Sequence[int],
                       timeout_s: float, now: Optional[float] = None) -> str:
    """Cross-rank snapshot for the hang postmortem: one line per rank with
    step, stamp age, and the collective it last entered (if any) — the
    mismatched-collective deadlock shows up as different collective names (or
    one rank absent from the collective every peer is waiting in)."""
    now = time.time() if now is None else now
    stale = set(stale_ranks(heartbeats, ranks, timeout_s, now))
    lines = [f"cross-rank hang snapshot (heartbeat timeout {timeout_s:.1f}s):"]
    for rank in sorted(ranks):
        record = heartbeats.get(rank)
        if record is None:
            lines.append(f"  rank {rank}: NO HEARTBEAT ever written — worker "
                         f"wedged before its first stamp (or heartbeat dir torn)")
            continue
        age = heartbeat_age(record, now)
        state = "STALE" if rank in stale else "alive"
        coll = record.get("collective")
        if coll:
            coll_age = now - float(record.get("collective_t") or record.get("time", now))
            where = f"blocked in collective '{coll}' for {coll_age:.1f}s"
        else:
            where = "not in a collective"
        lines.append(f"  rank {rank}: {state}, step {record.get('step', '?')}, "
                     f"last stamp {age:.1f}s ago, {where}"
                     + (f" [{record['phase']}]" if record.get("phase") else ""))
    stuck = {r: heartbeats[r].get("collective") for r in stale if r in heartbeats}
    named = sorted({c for c in stuck.values() if c})
    if named:
        lines.append(f"  diagnosis: stale rank(s) {sorted(stuck)} inside "
                     f"collective(s) {named} — peers waiting on a collective "
                     f"the stuck rank(s) never completed")
    return "\n".join(lines)
