"""Shared micro-batch gradient accumulation scan.

One implementation used by both the GSPMD train step (engine.py) and the
explicit-collective qgZ path (zero/quantized.py) so the two stay numerically
identical — the analog of the reference's single backward/IPG pipeline feeding
both the plain and quantized reduction paths (stage_1_and_2.py:910).
"""

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp


def accumulate_micro_grads(loss_fn: Callable, params16, batch, micro_rngs,
                           scale) -> Tuple[Any, jnp.ndarray]:
    """lax.scan over gradient-accumulation micro-batches.

    batch leaves are [gas, ...]; returns (summed fp32 grads, summed unscaled
    loss).  ``scale`` is the fp16 loss scale (1.0 for bf16).
    """

    def micro(carry, micro_batch_and_rng):
        grads_acc, loss_acc = carry
        micro_batch, mrng = micro_batch_and_rng

        def scaled_loss(p16):
            out = loss_fn(p16, micro_batch, mrng)
            loss = out[0] if isinstance(out, tuple) else out
            return loss.astype(jnp.float32) * scale

        loss, grads = jax.value_and_grad(scaled_loss)(params16)
        grads = jax.tree_util.tree_map(lambda a, g: a + g.astype(jnp.float32), grads_acc, grads)
        return (grads, loss_acc + loss / scale), None

    zero_grads = jax.tree_util.tree_map(lambda p: jnp.zeros(p.shape, jnp.float32), params16)
    (grads, loss_sum), _ = jax.lax.scan(micro, (zero_grads, jnp.float32(0.0)), (batch, micro_rngs))
    return grads, loss_sum
