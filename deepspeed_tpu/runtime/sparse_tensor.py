"""Sparse gradient reduction for embedding tables.

Analog of the reference ``SparseTensor`` (runtime/sparse_tensor.py:12) and the
engine's ``sparse_allreduce_bucket`` (engine.py:2462): embedding gradients are
nonzero only on the rows a batch touched, so the reference reduces
(indices, values) pairs with an allgather instead of a dense allreduce.

TPU-native shape: a ``SparseTensor`` pytree of (indices [N], values [N, D],
dense row count), and ``sparse_all_reduce`` — inside shard_map — allgathers
both over the dp axis; the concatenation IS the sum, since scatter-add of the
combined pairs equals adding the per-rank dense grads (the reference relies
on the same identity, engine.py:2520 csr concat).  ``to_dense`` materializes
via segment_sum.  Useful when batch-rows << vocab-rows; otherwise XLA's dense
psum wins.
"""

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..parallel.mesh import DATA_AXIS


@jax.tree_util.register_pytree_node_class
class SparseTensor:
    """COO-ish rows-only sparse gradient: values[i] belongs to row indices[i]."""

    def __init__(self, indices, values, dense_rows: int):
        self.indices = indices
        self.values = values
        self.dense_rows = int(dense_rows)

    def tree_flatten(self):
        return (self.indices, self.values), (self.dense_rows,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0])

    @classmethod
    def from_dense_rows(cls, grad: jnp.ndarray, indices: jnp.ndarray) -> "SparseTensor":
        """Select the touched rows of a dense grad (the embedding-bwd output
        already scattered; batches know their token ids)."""
        return cls(indices, jnp.take(grad, indices, axis=0), grad.shape[0])

    def to_dense(self) -> jnp.ndarray:
        """Scatter-add duplicate rows back to dense [rows, D]."""
        return jax.ops.segment_sum(self.values, self.indices,
                                   num_segments=self.dense_rows)

    def nbytes(self) -> int:
        return int(self.indices.size * 4 + self.values.size * self.values.dtype.itemsize)


def sparse_all_reduce(st: SparseTensor, axis_name: str = DATA_AXIS) -> SparseTensor:
    """Reduce a SparseTensor across ``axis_name`` (call inside shard_map):
    allgather indices+values; concatenated pairs sum to the dense total on
    every rank (reference sparse_allreduce:2462 allgather path)."""
    idx = lax.all_gather(st.indices, axis_name, tiled=True)
    vals = lax.all_gather(st.values, axis_name, tiled=True)
    return SparseTensor(idx, vals, st.dense_rows)


def embedding_grad_sparse(embed: jnp.ndarray, token_ids: jnp.ndarray,
                          dout: jnp.ndarray) -> SparseTensor:
    """Build the sparse gradient of an embedding lookup directly:
    d(embed)[ids[i]] += dout[i].  ids [T], dout [T, D]."""
    return SparseTensor(token_ids.reshape(-1), dout.reshape(-1, dout.shape[-1]),
                        embed.shape[0])
