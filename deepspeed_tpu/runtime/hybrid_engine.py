"""Hybrid engine — RLHF train ↔ generate flips.

Analog of DeepSpeedHybridEngine (runtime/hybrid_engine.py:32): the reference
flips a ZeRO-3 training model into inference-kernel mode for rollout
generation (generate:174, _zero3_forward:363).  Here the flip is a dtype cast
+ resharding of the CURRENT master params into the v1 inference engine's
jitted prefill/decode programs — compiled once, re-fed fresh weights each
rollout (weight swap is a device-side cast, no recompilation).
"""

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..inference.engine import InferenceEngine
from ..utils.logging import log_dist
from .engine import Engine


class DeepSpeedHybridEngine(Engine):
    """Training engine + in-loop generation over the same weights.

    Extra ctor args: ``model_module`` (models.llama-style: forward_with_cache,
    init_cache) and ``model_config``; ``loss_fn`` still drives training.
    """

    def __init__(self, *args, model_module=None, model_config=None,
                 inference_config: Optional[Dict] = None, lora_params=None, **kwargs):
        super().__init__(*args, **kwargs)
        if model_module is None:
            raise ValueError("DeepSpeedHybridEngine needs model_module (and model_config)")
        self.model_module = model_module
        self.model_config = model_config
        self._inf_cfg = dict(inference_config or {})
        self._inf_cfg.setdefault("dtype", "bfloat16" if self.compute_dtype == jnp.bfloat16 else "float32")
        self._inf_engine: Optional[InferenceEngine] = None
        self._params_version = -1
        self._lora = None
        self._lora_fused = False
        if lora_params is not None:
            self.set_lora(lora_params)  # validated, same as the post-init path
        log_dist("HybridEngine: training + rollout generation enabled", ranks=[0])

    # --------------------------------------------------------------- LoRA
    def set_lora(self, lora_params) -> None:
        """Attach LoRA adapters (reference hybrid_engine.py:138-158 fuse/unfuse).

        ``lora_params`` mirrors the base param tree on the adapted subset; each
        adapted leaf is ``{"a": [..., in, r], "b": [..., r, out], "alpha": s}``
        (stacked-layer leaves carry the leading L dim on a/b too).  Generation
        serves ``W + (alpha/r) a @ b`` — fused on device into the SAME compiled
        prefill/decode programs (shapes unchanged, so no recompilation); the
        train step keeps seeing the unfused base params.
        """
        if lora_params is not None:
            base = (self.state.params if self.state is not None
                    else getattr(self, "_compute_params", None))
            if base is None:
                raise ValueError("hybrid engine generation/LoRA is not available on the "
                                 "offload_param:nvme streaming path (no resident params)")
            self._validate_lora(base, lora_params)
        self._lora = lora_params
        self._lora_fused = lora_params is not None
        self._params_version = -1  # force a weight refresh on next generate

    @classmethod
    def _validate_lora(cls, params, lora, path=""):
        """Reject adapters whose paths don't exist in the base tree — a typo'd
        key would otherwise fuse as a silent no-op and rollouts would serve the
        unadapted policy."""
        if lora is None:
            return
        if isinstance(lora, dict) and "a" in lora and "b" in lora:
            if not hasattr(params, "shape"):
                raise ValueError(f"LoRA adapter at {path or '<root>'} targets a non-leaf")
            a, b = jnp.shape(lora["a"]), jnp.shape(lora["b"])
            w = jnp.shape(params)
            ok = len(a) >= 2 and len(b) >= 2 and len(w) >= 2 \
                and a[-1] == b[-2] and a[-2] == w[-2] and b[-1] == w[-1]
            if ok:
                try:  # batch dims may broadcast (shared adapter over stacked layers)
                    ok = np.broadcast_shapes(a[:-2], b[:-2], w[:-2]) == w[:-2]
                except ValueError:
                    ok = False
            if not ok:
                raise ValueError(f"LoRA shapes at {path}: a{a} @ b{b} does not match W{w}")
            return
        if not isinstance(lora, dict) or not isinstance(params, dict):
            raise ValueError(f"LoRA adapter at {path or '<root>'}: expected a dict mirroring "
                             f"the param tree (leaves = {{'a','b','alpha'}})")
        unknown = set(lora) - set(params)
        if unknown:
            raise ValueError(f"LoRA adapter keys {sorted(unknown)} at {path or '<root>'} "
                             f"not in base params (have: {sorted(params)})")
        for k, v in lora.items():
            cls._validate_lora(params[k], v, f"{path}.{k}" if path else k)

    def fuse_lora_weight(self) -> None:
        """API parity with the reference's explicit fuse (hybrid_engine.py:145)."""
        self._ensure_lora_toggle(True)

    def unfuse_lora_weight(self) -> None:
        """Serve the base weights again (reference :152)."""
        self._ensure_lora_toggle(False)

    def _ensure_lora_toggle(self, fused: bool):
        if self._lora is None:
            raise ValueError("no LoRA adapters attached — call set_lora first")
        if self._lora_fused != fused:
            self._lora_fused = fused
            self._params_version = -1

    @staticmethod
    def _fuse_lora_tree(params, lora):
        """Return params with ``W + (alpha/r) a @ b`` applied on the adapted
        subset (functional: the base tree is never mutated, so 'unfuse' is
        simply serving the originals)."""
        def fuse(p, l):
            if l is None:
                return p
            if isinstance(l, dict) and "a" in l and "b" in l:
                a = jnp.asarray(l["a"], p.dtype)
                b = jnp.asarray(l["b"], p.dtype)
                scale = jnp.asarray(float(l.get("alpha", a.shape[-1])) / a.shape[-1], p.dtype)
                return p + jnp.einsum("...ir,...ro->...io", a, b) * scale
            if isinstance(l, dict):
                return {k: fuse(p[k], l.get(k)) for k in p} if isinstance(p, dict) else p
            return p
        return fuse(params, lora)

    # ------------------------------------------------------------- the flip
    def _current_params16(self):
        if self.offload_device is not None:
            params = self._compute_params
        else:
            params = jax.tree_util.tree_map(lambda x: x.astype(self.compute_dtype),
                                            self.state.params)
        if self._lora is not None and self._lora_fused:
            params = self._fuse_lora_tree(params, self._lora)
        return params

    def _refresh_inference(self):
        if self._inf_engine is None:
            self._inf_engine = InferenceEngine(self.model_module, self.model_config,
                                               self._current_params16(),
                                               config=self._inf_cfg,
                                               topology=self.topology)
        elif self._params_version != self.global_steps:
            # weight swap only: keep the compiled prefill/decode programs
            self._inf_engine.params = self._inf_engine._shard_params(self._current_params16())
        self._params_version = self.global_steps

    # ------------------------------------------------------------ public API
    def generate(self, input_ids, **kwargs) -> np.ndarray:
        """Rollout generation from the CURRENT training weights
        (reference generate:174)."""
        self._refresh_inference()
        return self._inf_engine.generate(input_ids, **kwargs)

    def eval_forward(self, input_ids):
        """Logits from current weights (scoring rollouts / reward model)."""
        self._refresh_inference()
        return self._inf_engine.forward(input_ids)
