"""Hybrid engine — RLHF train ↔ generate flips.

Analog of DeepSpeedHybridEngine (runtime/hybrid_engine.py:32): the reference
flips a ZeRO-3 training model into inference-kernel mode for rollout
generation (generate:174, _zero3_forward:363).  Here the flip is a dtype cast
+ resharding of the CURRENT master params into the v1 inference engine's
jitted prefill/decode programs — compiled once, re-fed fresh weights each
rollout (weight swap is a device-side cast, no recompilation).
"""

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..inference.engine import InferenceEngine
from ..utils.logging import log_dist
from .engine import Engine


class DeepSpeedHybridEngine(Engine):
    """Training engine + in-loop generation over the same weights.

    Extra ctor args: ``model_module`` (models.llama-style: forward_with_cache,
    init_cache) and ``model_config``; ``loss_fn`` still drives training.
    """

    def __init__(self, *args, model_module=None, model_config=None,
                 inference_config: Optional[Dict] = None, **kwargs):
        super().__init__(*args, **kwargs)
        if model_module is None:
            raise ValueError("DeepSpeedHybridEngine needs model_module (and model_config)")
        self.model_module = model_module
        self.model_config = model_config
        self._inf_cfg = dict(inference_config or {})
        self._inf_cfg.setdefault("dtype", "bfloat16" if self.compute_dtype == jnp.bfloat16 else "float32")
        self._inf_engine: Optional[InferenceEngine] = None
        self._params_version = -1
        log_dist("HybridEngine: training + rollout generation enabled", ranks=[0])

    # ------------------------------------------------------------- the flip
    def _current_params16(self):
        if self.offload_device is not None:
            return self._compute_params
        cast = jax.tree_util.tree_map(lambda x: x.astype(self.compute_dtype), self.state.params)
        return cast

    def _refresh_inference(self):
        if self._inf_engine is None:
            self._inf_engine = InferenceEngine(self.model_module, self.model_config,
                                               self._current_params16(),
                                               config=self._inf_cfg,
                                               topology=self.topology)
        elif self._params_version != self.global_steps:
            # weight swap only: keep the compiled prefill/decode programs
            self._inf_engine.params = self._inf_engine._shard_params(self._current_params16())
        self._params_version = self.global_steps

    # ------------------------------------------------------------ public API
    def generate(self, input_ids, **kwargs) -> np.ndarray:
        """Rollout generation from the CURRENT training weights
        (reference generate:174)."""
        self._refresh_inference()
        return self._inf_engine.generate(input_ids, **kwargs)

    def eval_forward(self, input_ids):
        """Logits from current weights (scoring rollouts / reward model)."""
        self._refresh_inference()
        return self._inf_engine.forward(input_ids)
