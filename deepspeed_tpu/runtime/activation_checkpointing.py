"""Activation checkpointing (remat) subsystem.

Analog of the reference's activation_checkpointing/checkpointing.py: the
reference wraps module calls in CheckpointFunction (:484) and offers two memory
levers beyond plain recompute — ``partition_activations`` (:373, saved
activations sharded over model-parallel ranks) and ``cpu_checkpointing`` (:470,
saved activations moved to host RAM).  Under XLA the first is what GSPMD
already does to saved residuals of sharded activations; the second maps to
JAX's offload remat policies, which annotate chosen residuals to live in
``pinned_host`` memory between forward and backward (the Infinity-style
HBM-relief lever).

Policies by name (model configs carry a string; see models/llama.py
``remat_policy``):

  everything_saveable / nothing_saveable / dots_saveable /
  dots_with_no_batch_dims_saveable        jax built-ins (recompute trade-offs)
  offload_dot                             matmul outputs offloaded to host
  offload_residuals / cpu_checkpointing   the named residual stream offloaded
                                          to host; everything else recomputed

Residual names are planted with ``checkpoint_name`` in the model layers
(identity unless a naming policy is active) — llama tags its two residual-add
outputs ``attn_resid`` / ``mlp_resid``.

Composition caveat: the offload policies annotate buffers with
``annotate_device_placement`` custom calls that (as of jax 0.9) carry no
sharding metadata, so the GSPMD partitioner rejects them inside a multi-device
jit.  Use them as a per-device HBM lever (single-chip or under shard_map where
the annotated values are replicated); the plain recompute policies compose
with every mesh.
"""

from typing import Iterable, Optional

import jax
from jax.ad_checkpoint import checkpoint_name  # re-export for models

# Residual-stream names models plant; the offload policy targets these.
RESIDUAL_NAMES = ("attn_resid", "mlp_resid")


def resolve_policy(name: Optional[str], offload_names: Iterable[str] = RESIDUAL_NAMES,
                   offload_dst: str = "pinned_host"):
    """Map a config policy name to a jax.checkpoint policy.

    None/"" -> None, which under jax.checkpoint means FULL recompute (save
    nothing) — jax's default; "everything_saveable" resolves to the real
    save-all policy via getattr below."""
    if name in (None, ""):
        return None
    if name == "offload_dot":
        return jax.checkpoint_policies.offload_dot_with_no_batch_dims("device", offload_dst)
    if name in ("offload_residuals", "cpu_checkpointing"):
        return jax.checkpoint_policies.save_and_offload_only_these_names(
            names_which_can_be_saved=[],
            names_which_can_be_offloaded=list(offload_names),
            offload_src="device", offload_dst=offload_dst)
    if name == "save_anything_except_these_names":
        # factory name from the config surface: except the planted residuals
        return jax.checkpoint_policies.save_anything_except_these_names(*offload_names)
    # only true policies may fall through — the other jax.checkpoint_policies
    # attributes are FACTORIES, which jax.checkpoint would silently accept and
    # then treat every primitive as saveable (remat disabled)
    direct = ("everything_saveable", "nothing_saveable", "dots_saveable",
              "dots_with_no_batch_dims_saveable", "checkpoint_dots",
              "checkpoint_dots_with_no_batch_dims")
    if name in direct:
        return getattr(jax.checkpoint_policies, name)
    raise ValueError(f"unknown remat policy {name!r}; known: {', '.join(direct)}, "
                     f"offload_dot, offload_residuals, save_anything_except_these_names")


def policy_from_config(cfg) -> Optional[object]:
    """ActivationCheckpointingConfig -> policy; ``cpu_checkpointing: true``
    selects the host-offload policy exactly like the reference's config gate
    (checkpointing.py:470 + config key)."""
    if cfg.cpu_checkpointing:
        return resolve_policy("offload_residuals")
    return resolve_policy(cfg.policy)


def checkpoint(fn, policy_name: Optional[str] = "nothing_saveable", **kwargs):
    """jax.checkpoint with a by-name policy (CheckpointFunction analog)."""
    return jax.checkpoint(fn, policy=resolve_policy(policy_name), **kwargs)
