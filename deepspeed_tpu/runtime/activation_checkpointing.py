"""Activation checkpointing (remat) subsystem.

Analog of the reference's activation_checkpointing/checkpointing.py: the
reference wraps module calls in CheckpointFunction (:484) and offers two memory
levers beyond plain recompute — ``partition_activations`` (:373, saved
activations sharded over model-parallel ranks) and ``cpu_checkpointing`` (:470,
saved activations moved to host RAM).  Under XLA the first is what GSPMD
already does to saved residuals of sharded activations; the second maps to
JAX's offload remat policies, which annotate chosen residuals to live in
``pinned_host`` memory between forward and backward (the Infinity-style
HBM-relief lever).

Policies by name (model configs carry a string; see models/llama.py
``remat_policy``):

  everything_saveable / nothing_saveable / dots_saveable /
  dots_with_no_batch_dims_saveable        jax built-ins (recompute trade-offs)
  offload_dot                             matmul outputs offloaded to host
  offload_residuals / cpu_checkpointing   the named residual stream offloaded
                                          to host; everything else recomputed

Residual names are planted with ``checkpoint_name`` in the model layers
(identity unless a naming policy is active) — llama tags its two residual-add
outputs ``attn_resid`` / ``mlp_resid``.

Composition status (measured on this stack, jax 0.9 + the TPU plugin):

- The POLICY-based offload (``pe.Offloadable``) silently degrades to plain
  recompute — compiled memory for ``offload_residuals`` equals
  ``nothing_saveable`` and host_temp stays 0, even single-chip.
- The explicit memories API (``jax.device_put(x, Space.Host)``
  inside jit) DOES work on hardware: ``offload_checkpoint`` below builds
  real cpu_checkpointing from it — a custom-vjp layer wrapper that parks
  each layer's INPUT checkpoint in host memory on the forward and fetches
  it back for the recompute-backward, the reference's exact contract
  (checkpointing.py:470 moves the saved inputs to CPU).  Verified on the
  v5e: 1.07 GB of checkpoints leave HBM (numbers on the function).
- Under a MULTI-DEVICE GSPMD jit the partitioner still rejects the
  placement annotation ("Side-effect HLO must have sharding",
  spmd_partitioner.cc RET_CHECK — reproduced on the 8-device mesh), so
  ``offload_inputs`` remains a per-device lever: single-chip, or inside
  ``shard_map`` where the body is already manual SPMD (that composition
  compiles and grads correctly on the virtual mesh).
- The CPU runtime has no annotate_device_placement implementation, so under
  an explicitly-sharded jit (the engine's in_shardings) the CPU backend
  raises NOT_FOUND; plain CPU jit silently drops placements and runs.  The
  engine path is TPU hardware-verified (single chip, ZeRO-3, loss descends).
"""

from typing import Iterable, Optional

import jax
from jax.ad_checkpoint import checkpoint_name  # re-export for models

from ..compat import Space

# Residual-stream names models plant; the offload policy targets these.
RESIDUAL_NAMES = ("attn_resid", "mlp_resid")


def resolve_policy(name: Optional[str], offload_names: Iterable[str] = RESIDUAL_NAMES,
                   offload_dst: str = "pinned_host"):
    """Map a config policy name to a jax.checkpoint policy.

    None/"" -> None, which under jax.checkpoint means FULL recompute (save
    nothing) — jax's default; "everything_saveable" resolves to the real
    save-all policy via getattr below."""
    if name in (None, ""):
        return None
    if name == "offload_dot":
        return jax.checkpoint_policies.offload_dot_with_no_batch_dims("device", offload_dst)
    if name in ("offload_residuals", "cpu_checkpointing"):
        return jax.checkpoint_policies.save_and_offload_only_these_names(
            names_which_can_be_saved=[],
            names_which_can_be_offloaded=list(offload_names),
            offload_src="device", offload_dst=offload_dst)
    if name == "save_anything_except_these_names":
        # factory name from the config surface: except the planted residuals
        return jax.checkpoint_policies.save_anything_except_these_names(*offload_names)
    # only true policies may fall through — the other jax.checkpoint_policies
    # attributes are FACTORIES, which jax.checkpoint would silently accept and
    # then treat every primitive as saveable (remat disabled)
    direct = ("everything_saveable", "nothing_saveable", "dots_saveable",
              "dots_with_no_batch_dims_saveable", "checkpoint_dots",
              "checkpoint_dots_with_no_batch_dims")
    if name in direct:
        return getattr(jax.checkpoint_policies, name)
    raise ValueError(f"unknown remat policy {name!r}; known: {', '.join(direct)}, "
                     f"offload_dot, offload_residuals, save_anything_except_these_names")


def policy_from_config(cfg) -> Optional[object]:
    """ActivationCheckpointingConfig -> policy; ``cpu_checkpointing: true``
    selects the host-offload policy exactly like the reference's config gate
    (checkpointing.py:470 + config key)."""
    if cfg.cpu_checkpointing:
        return resolve_policy("offload_residuals")
    return resolve_policy(cfg.policy)


def checkpoint(fn, policy_name: Optional[str] = "nothing_saveable", **kwargs):
    """jax.checkpoint with a by-name policy (CheckpointFunction analog)."""
    return jax.checkpoint(fn, policy=resolve_policy(policy_name), **kwargs)


def offload_checkpoint(layer_fn):
    """Host-offloaded activation checkpointing for a scan-style layer
    ``layer_fn(x, params, *rest) -> (y, aux)``.

    The working cpu_checkpointing path on this stack (see module docstring:
    the policy-based ``Offloadable`` route silently degrades to recompute):
    the forward parks the layer's INPUT activation in host memory
    (``compat.Space.Host``) and the backward fetches it back and
    recomputes the layer under ``jax.vjp`` — saved-activation HBM drops to
    ~zero per layer at the cost of one D2H + one H2D of the input per layer
    per step (PCIe on real hosts).  Matches the reference semantics exactly:
    CheckpointFunction saves inputs, ``cpu_checkpointing`` moves them to CPU
    (activation_checkpointing/checkpointing.py:470,484).

    Only the activation ``x`` is offloaded; params and extra args are already
    live (sharded) for the whole step and are re-referenced, not copied.

    Measured on the v5e (llama 2048x8L, micro 4 x seq 4096, fp32): compiled
    device temp drops 5.38 -> 3.68 GB and host temp gains exactly the 8
    layer-input checkpoints (1.07 GB) vs the nothing_saveable recompute
    policy — the first remat policy on this stack whose saved state actually
    leaves HBM (VERDICT r4 weak #6)."""

    @jax.custom_vjp
    def wrapped(x, params, *rest):
        return layer_fn(x, params, *rest)

    def fwd(x, params, *rest):
        _guard_rest(rest)
        out = layer_fn(x, params, *rest)
        x_host = jax.device_put(x, Space.Host)
        return out, (x_host, params, rest)

    def _guard_rest(rest):
        # *rest gets None cotangents in bwd — a differentiable float extra
        # (per-layer scale, bias, tables) would silently train with zero
        # gradient, so refuse it loudly; int extras (positions) are fine.
        # jnp.issubdtype, NOT np: numpy's lattice doesn't place bfloat16 (or
        # fp8) under np.inexact, so the engine's common compute dtype would
        # slip through the guard (ADVICE r5 low)
        import numpy as np
        import jax.numpy as jnp
        for leaf in jax.tree_util.tree_leaves(rest):
            if isinstance(leaf, np.ndarray):
                continue  # plain numpy constants can never carry gradients
            dt = getattr(leaf, "dtype", None)
            if dt is not None and jnp.issubdtype(dt, jnp.inexact):
                raise TypeError(
                    "offload_checkpoint: extra args (*rest) receive no gradient; "
                    "found a float-dtype extra — pass differentiable values "
                    "through `params` instead")

    def bwd(res, g):
        x_host, params, rest = res
        x = jax.device_put(x_host, Space.Device)
        _, vjp = jax.vjp(lambda x_, p_: layer_fn(x_, p_, *rest), x, params)
        dx, dp = vjp(g)
        return (dx, dp) + tuple(None for _ in rest)

    wrapped.defvjp(fwd, bwd)
    return wrapped
